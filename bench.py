"""Benchmark: training throughput + MFU on real TPU hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline: flagship-family (openwebtext_xl: D=2048, H=16, C=128, T=1024 —
the 1.5B per-layer compute shape, depth-scaled to fit one chip) training
MFU, compared against the reference's published 47.8% MFU for the SAME
model family (1.5B on v3-128, /root/reference/README.md:55 — its only
published efficiency number; see BASELINE.md "north star"). MFU is
per-FLOP, so the depth-scaled number tracks the full-depth one; the
1.5B's smaller embed/head FLOP share makes it conservative if anything.

Auxiliary rungs:
- gpt2s_*: GPT-2-small (124M, openwebtext config) MFU — a stricter shape
  for this hardware (768/64 projections half-fill the MXU; see PERF.md
  "measured ceilings"), tracked across rounds.
- llama_*: llama_7b-family per-layer shape (D=4096, H=32/Hkv=8 GQA,
  SwiGLU, C=128, T=2048), depth-scaled to one chip (r3).
- decode_*: serving — prefill + KV-cached decode tok/s (r3; skipped if
  the training rungs consumed most of the driver budget).
"""

from __future__ import annotations

import dataclasses
import gc
import json
import os
import time

import jax
import numpy as np

BASELINE_MFU = 0.478  # reference 1.5B on TPU v3-128 (README.md:55)

# bench-run flight recorder (midgpt_tpu.train_telemetry): main() parks
# the telemetry object here so the watchdog threads can dump the rung
# timeline best-effort when the relay wedges — a watchdog/error row then
# carries its flight-dump path IN-BAND, like bench_serving's rows do
# (the r4/r5 wedged-run lesson applied to the training bench).
_FLIGHT = {"tele": None, "dir": None}


def _flight_dump(reason: str):
    """Dump the rung-lifecycle flight record (None when telemetry never
    armed or the dump fails — a dump must never mask the JSON row).
    The filename carries the reason, so a mid-run watchdog dump and a
    later error dump never overwrite each other's in-band paths."""
    tele = _FLIGHT.get("tele")
    if tele is None:
        return None
    try:
        d = _FLIGHT.get("dir") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "artifacts"
        )
        name = "bench_flight_" + reason.replace(":", "_") + ".json"
        return tele.flight_dump(reason, path=os.path.join(d, name))["path"]
    except Exception:  # noqa: BLE001 — best-effort by design
        return None


def _train_attainment(cfg, n_dev: int, step_ms: float, prefix: str = ""):
    """Roofline keys for one measured training rung: the static
    compute/HBM floors (utils.metrics.train_floor — the SAME wiring
    MetricLogger's logged series uses, so bench rows and training logs
    can never disagree on the floor arithmetic) and attainment =
    floor / measured, emitted next to the rung's MFU so BENCH_r*.json
    rows read against the hardware ceiling without hand arithmetic.
    Empty when the analytic floor doesn't cover the config
    (best-effort, like the comms summary)."""
    try:
        from midgpt_tpu.utils.metrics import train_floor

        fl = train_floor(cfg, n_dev)
        if fl is None:
            return {}
        return {
            prefix + "train_compute_floor_ms": fl["train_compute_floor_ms"],
            prefix + "train_hbm_floor_ms": fl["train_hbm_floor_ms"],
            prefix + "train_attainment_frac": (
                # significant digits: CPU attainment is ~1e-8 and must
                # not round to a hard zero
                float(f"{fl['train_floor_ms_per_step'] / step_ms:.3g}")
                if step_ms > 0 else None
            ),
        }
    except Exception:  # noqa: BLE001 — attainment is best-effort
        return {}

# steps per timing sample: the scan-mode long chain fuses _SCAN_STEPS + 1
# optimizer steps into one dispatch (train.make_train_window)
_SCAN_STEPS = 10


def _fused_len(mode: str, n_steps: int = _SCAN_STEPS) -> int:
    """Optimizer steps fused per dispatch of the program _rung_measure
    timed: the scan path's long sample compiles make_scan(n_steps + 1)
    (the trainer's steps_per_dispatch knob); chained fallback is one
    step per dispatch. Single source of truth for the JSON record —
    must mirror _measure_scan's n-vs-(n+1) construction."""
    return n_steps + 1 if mode == "scan" else 1


def _run_config(
    remat: str, batch: int, base: str = "openwebtext", n_layer=None,
    loss_chunk: int = 256, block_size=None, unroll=None,
):
    """Build state + step for one candidate config; returns a timing
    closure. Raises on compile/alloc failure (caller falls back)."""
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.config import MeshConfig, get_config
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step

    cfg = get_config(base)
    if n_layer is not None:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, n_layer=n_layer)
        )
    if block_size is not None:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, block_size=block_size)
        )
    cfg = dataclasses.replace(
        cfg,
        batch_size=batch,
        g_accum_iters=1,
        # scan_unroll = n_layer: profiling showed the rolled lax.scan costs
        # ~40% of the step in dynamic-update-slice stacking + XLA's
        # memory-pressure remat/compression copies of the carried
        # activations; fully unrolling removed 58 ms/step of 'data
        # formatting' + most loop-fusion overhead (15.2% -> ~40% MFU)
        model=dataclasses.replace(
            cfg.model, attn_impl="auto", remat=remat,
            scan_unroll=cfg.model.n_layer if unroll is None else unroll,
        ),
        mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1),
        # head+xent computed T-chunk-wise: the [B,T,V] f32 logits (3.3 GB
        # at this config) never materialize, which is what makes the
        # remat='none' rung fit in HBM; unrolled chunk loop measured
        # slightly faster than the while-loop scan (PERF.md r2 sweep)
        loss_chunk=loss_chunk,
        loss_chunk_unroll=True,
    )

    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    train_step = make_train_step(cfg, tx, mesh)

    t = cfg.model.block_size
    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.model.vocab_size, size=(1, batch, t), dtype=np.int32)
    y = rng.integers(0, cfg.model.vocab_size, size=(1, batch, t), dtype=np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg = make_global_array(x, mesh, spec)
    yg = make_global_array(y, mesh, spec)
    key = jax.random.PRNGKey(1)

    def chain(state, n):
        # n chained steps + ONE host sync. Under the axon relay a host
        # transfer costs ~70ms RTT and block_until_ready alone is
        # unreliable, so true step time = delta between chain lengths.
        start = time.perf_counter()
        loss = None
        for _ in range(n):
            state, loss = train_step(state, xg, yg, key)
        _ = float(loss)
        return time.perf_counter() - start, state

    def make_scan(n: int):
        # n steps inside ONE dispatch (see _measure_scan) — the SAME fused
        # window program the trainer ships (train.make_train_window with
        # steps_per_dispatch=n), not a parallel hand-rolled scan: what
        # bench times is the program train() launches. The window consumes
        # an [n, G, B, T] device-resident batch window; bench replicates
        # one batch n times (timing, not training).
        from midgpt_tpu.train import make_train_window

        window = make_train_window(cfg, tx, mesh, n)
        wspec = P(None, *spec)
        xs = make_global_array(
            np.ascontiguousarray(np.broadcast_to(x, (n,) + x.shape)),
            mesh, wspec,
        )
        ys = make_global_array(
            np.ascontiguousarray(np.broadcast_to(y, (n,) + y.shape)),
            mesh, wspec,
        )

        def multi(state):
            state, out = window(state, xs, ys, key)
            return state, out["loss"][-1]

        return jax.jit(multi, donate_argnums=(0,))

    return cfg, state, chain, make_scan


def _measure(cfg, state, chain, n_steps: int = _SCAN_STEPS, repeats: int = 3):
    """(tokens/sec, step_ms) from chained-steps deltas; median of
    ``repeats`` measures (single measures spread ~2% run-to-run on this
    chip — relay jitter + clock variation).

    Caveat (measured r5): the per-call deltas cancel RTT but NOT a fixed
    per-dispatch latency — when the relay serializes dispatches, every
    step inherits it (+25-50 ms/step uniformly across rungs on a bad
    relay day). _measure_scan below is the latency-immune variant."""
    rates = []
    for _ in range(repeats):
        t_1, state = chain(state, 1)  # RTT + 1 step
        t_n, state = chain(state, n_steps + 1)
        rates.append((t_n - t_1) / n_steps)
    step_s = sorted(rates)[len(rates) // 2]
    tokens_per_sec = cfg.batch_size * cfg.model.block_size / step_s
    return tokens_per_sec, 1e3 * step_s, state


def _measure_scan(
    cfg, state, make_scan, n_steps: int = _SCAN_STEPS, repeats: int = 3
):
    """(tokens/sec, step_ms) like _measure, but each timing sample runs
    its steps inside ONE ``lax.scan`` dispatch, so per-dispatch relay
    latency appears once per sample and cancels in the 1-vs-(n+1) delta
    instead of accruing per step. Raises on compile failure — the caller
    falls back to the chained path."""
    # AOT-compile both before dispatching anything: a compile failure must
    # leave ``state`` untouched so the caller can fall back to the chained
    # path (the first scan dispatch donates the state buffers)
    m_1 = make_scan(1).lower(state).compile()
    m_n = make_scan(n_steps + 1).lower(state).compile()
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        state, loss = m_1(state)
        _ = float(loss)
        t_1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        state, loss = m_n(state)
        _ = float(loss)
        t_n = time.perf_counter() - t0
        rates.append((t_n - t_1) / n_steps)
    step_s = sorted(rates)[len(rates) // 2]
    tokens_per_sec = cfg.batch_size * cfg.model.block_size / step_s
    return tokens_per_sec, 1e3 * step_s, state


def _rung_measure(cfg, state, chain, make_scan):
    """Measure one rung: scan-based (dispatch-latency-immune) when the
    scan program compiles, chained-deltas otherwise. Returns
    (tokens_per_sec, step_ms, state, mode).

    The chained fallback only runs while ``state`` is still live: the
    scan path AOT-compiles before dispatching, so a compile failure
    leaves the buffers intact — but a RUNTIME failure after the first
    scan dispatch has already donated them, and the fallback would die
    on deleted arrays with a misleading error (code review r5)."""
    try:
        tps, step_ms, state = _measure_scan(cfg, state, make_scan)
        return tps, step_ms, state, "scan"
    except Exception:  # noqa: BLE001 — fallback gated on liveness below
        state_alive = not any(
            getattr(a, "is_deleted", lambda: False)()
            for a in jax.tree.leaves(state)
        )
        if not state_alive:
            raise
        _, state = chain(state, 1)  # compile + 1 step
        tps, step_ms, state = _measure(cfg, state, chain)
        return tps, step_ms, state, "chained"


def _emit_bench_error(msg: str, status: str = "error") -> None:
    """The driver parses bench output mechanically — every failure mode
    must still print the one-JSON-line contract. ``status`` makes the
    failure MODE machine-readable: "watchdog" rows are hardware wedges
    (the r4/r5 BENCH rows — a stuck TPU relay, not a regression);
    "error" rows are real failures. Trajectory tooling reading
    BENCH_r*.json (analysis/ledger.py) can then separate the two
    instead of treating every bad round as a perf cliff. The row
    carries the rung-lifecycle flight-dump path in-band when telemetry
    was armed — a wedge yields a timeline, not a bare error string."""
    row = {
        "metric": "bench_error", "value": 0, "unit": "none",
        "vs_baseline": 0, "status": status, "error": msg[:400],
    }
    dump = _flight_dump(f"bench:{status}")
    if dump:
        row["flight_recorder"] = [dump]
    print(json.dumps(row), flush=True)


def _backend_watchdog(timeout_s: float = 600.0):
    """Fail LOUDLY if backend init hangs (a wedged axon relay blocks
    inside the C++ client forever — r4 post-mortem; a hung bench run is
    worse for the driver than a failed one). Cancelled once devices are
    visible.

    Tradeoff, explicit: exiting tears down a possibly-in-flight relay RPC,
    which the r3/r4 post-mortems show can wedge the relay for the rest of
    the round. Accepted here because (a) normal init is 20-40 s and the
    timeout is 600 s — a healthy-but-slow init never triggers it, and
    (b) the alternative is the driver's whole bench stage hanging on a
    relay that is already gone."""
    import os
    import sys
    import threading

    done = threading.Event()

    def watch():
        if not done.wait(timeout_s):
            if done.is_set():  # init finished right at the boundary: the
                return  # main thread owns the output line (ADVICE r4)
            _emit_bench_error(
                f"backend init exceeded {timeout_s:.0f}s (wedged TPU relay?)",
                status="watchdog",
            )
            sys.stderr.write("bench watchdog: backend init hung; exiting\n")
            os._exit(3)

    threading.Thread(target=watch, daemon=True).start()
    return done


def _progress_watchdog(record: dict, done, deadline_s: float = 900.0):
    """Salvage partial results if the relay wedges MID-run (r5: a wedge
    after the headline rung would otherwise hang bench forever and hand
    the driver nothing — the r4 failure mode, one stage later). At the
    deadline: if a headline was measured, print the partial record as the
    one JSON line and exit 0; else emit bench_error."""
    import os
    import sys
    import threading

    def watch():
        if done.wait(deadline_s) or done.is_set():
            return  # normal completion owns the output line
        if "value" in record:
            record["partial"] = True
            record["status"] = "watchdog"
            dump = _flight_dump("bench:watchdog")
            if dump:
                record["flight_recorder"] = [dump]
            print(json.dumps(record), flush=True)
            sys.stderr.write(
                "bench watchdog: mid-run hang; emitted partial record\n"
            )
            os._exit(0)
        _emit_bench_error(
            f"no rung completed within {deadline_s:.0f}s (relay wedge?)",
            status="watchdog",
        )
        os._exit(4)

    threading.Thread(target=watch, daemon=True).start()


def main() -> None:
    from midgpt_tpu.utils.metrics import flops_per_token, mfu

    t_start = time.perf_counter()

    # rung-lifecycle flight recorder (midgpt_tpu.train_telemetry): armed
    # BEFORE backend init, so even an init wedge dumps a timeline next
    # to its watchdog row — jax-free construction, nothing touches the
    # backend until the rungs run
    from midgpt_tpu.train_telemetry import TrainTelemetry

    tele = TrainTelemetry()
    _FLIGHT["tele"] = tele
    _rung = {"i": 0}

    def _rev(kind: str, **data) -> None:
        tele.emit(kind, step=_rung["i"], t=time.perf_counter(), **data)

    _init_done = _backend_watchdog()

    # persistent executable cache: repeat runs (and the fallback ladder)
    # skip recompiles
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    try:
        n_dev = jax.device_count()
    except Exception as e:  # relay dead: fail fast WITH the JSON contract
        _init_done.set()
        _emit_bench_error(f"backend init failed: {e}")
        raise SystemExit(3)
    _init_done.set()  # devices visible — cancel the init watchdog

    import threading as _threading

    _all_done = _threading.Event()

    # --- headline: flagship-family (openwebtext_xl per-layer shape) ------
    # ladder fastest-measured first (PERF.md r3 with the combined-backward
    # kernels: L6 B=20 68.8%, L8 B=12 68.5%, L6 B=16 66.8%; B=22/24 regress
    # — HBM compression returns); fall back if the compiler rejects a rung
    record = {}
    _progress_watchdog(record, _all_done)
    last_err = None
    # ladder note (r5): the old best rung L6 B=20 (68.8% in r3) is OUT —
    # its compile crashed the relay's remote compile helper 3/3 times on
    # 2026-07-31 (HTTP 500, then a full relay wedge on resubmission); the
    # next-best L8 B=12 (68.5% in r2) compiles reliably
    for xl_layers, xl_batch in (
        (8, 12 * n_dev), (6, 16 * n_dev), (8, 8 * n_dev),
    ):
        try:
            _rung["i"] += 1
            _rev("rung_start", rung=f"xl_L{xl_layers}_B{xl_batch}")
            xcfg, xstate, xchain, xmk = _run_config(
                "none", xl_batch, base="openwebtext_xl", n_layer=xl_layers,
                loss_chunk=512,
            )
            xtps, xstep_ms, xstate, xmode = _rung_measure(
                xcfg, xstate, xchain, xmk
            )
            _rev("rung_ok", rung=f"xl_L{xl_layers}_B{xl_batch}")
            xmfu = mfu(xtps, xcfg.model, n_dev)
            # mutate IN PLACE: _progress_watchdog holds this dict
            record.clear()
            record.update({
                "metric": f"openwebtext_xl_family_L{xl_layers}_train_mfu",
                "value": round(xmfu, 4),
                "unit": "fraction_of_peak",
                "vs_baseline": round(xmfu / BASELINE_MFU, 4),
                "tokens_per_sec_per_chip": round(xtps / n_dev, 1),
                "step_ms": round(xstep_ms, 1),
                "device": jax.devices()[0].device_kind,
                "n_devices": n_dev,
                "batch_per_chip": xcfg.batch_size // n_dev,
                "model_flops_per_token": flops_per_token(xcfg.model),
                "measure": xmode,
                # fused dispatch length of the measured program (the
                # trainer's steps_per_dispatch knob; 1 = chained fallback)
                "steps_per_dispatch": _fused_len(xmode),
            })
            # roofline attainment next to the MFU headline: the static
            # compute/HBM floors + floor/measured (analysis/traffic)
            record.update(_train_attainment(xcfg, n_dev, xstep_ms))
            del xstate, xchain
            gc.collect()
            break
        except Exception as exc:  # noqa: BLE001 — any compile/OOM falls through
            # keep the message but drop the traceback: its frames pin the
            # failed rung's device arrays (params + Adam moments) in HBM,
            # which would shrink the next rung's headroom
            exc.__traceback__ = None
            _rev("rung_error", rung=f"xl_L{xl_layers}_B{xl_batch}")
            last_err = exc
            xcfg = xstate = xchain = None
            gc.collect()
    else:
        # every XL rung failed (e.g. a smaller-HBM chip): fall through so
        # the 124M rung below becomes the headline — the contract is ONE
        # JSON line no matter what ran
        record["xl_error"] = repr(last_err)[:120]

    # --- auxiliary rung: 124M (GPT-2-small shape) ------------------------
    for remat, batch in (
        ("none", 24 * n_dev),
        ("none", 16 * n_dev),
        ("full", 16 * n_dev),
    ):
        try:
            _rung["i"] += 1
            _rev("rung_start", rung=f"gpt2s_{remat}_B{batch}")
            cfg, state, chain, mk = _run_config(remat, batch)
            tps, step_ms, state, _mode = _rung_measure(cfg, state, chain, mk)
            _rev("rung_ok", rung=f"gpt2s_{remat}_B{batch}")
            small_mfu = mfu(tps, cfg.model, n_dev)
            record.update(
                {
                    "gpt2s_metric": "openwebtext_124m_train_mfu",
                    "gpt2s_mfu": round(small_mfu, 4),
                    "gpt2s_vs_baseline": round(small_mfu / BASELINE_MFU, 4),
                    "gpt2s_tokens_per_sec_per_chip": round(tps / n_dev, 1),
                    "gpt2s_step_ms": round(step_ms, 1),
                    "gpt2s_remat": cfg.model.remat,
                    **_train_attainment(cfg, n_dev, step_ms, "gpt2s_"),
                }
            )
            if "value" not in record:  # XL never ran: promote to headline
                record.update(
                    {
                        "metric": "openwebtext_124m_train_mfu",
                        "value": round(small_mfu, 4),
                        "unit": "fraction_of_peak",
                        "vs_baseline": round(small_mfu / BASELINE_MFU, 4),
                        "tokens_per_sec_per_chip": round(tps / n_dev, 1),
                        "step_ms": round(step_ms, 1),
                        "device": jax.devices()[0].device_kind,
                        "n_devices": n_dev,
                        "model_flops_per_token": flops_per_token(cfg.model),
                    }
                )
            record.pop("gpt2s_error", None)  # a later rung succeeded
            del state, chain
            gc.collect()
            break
        except Exception as exc:  # noqa: BLE001 — aux rung is best-effort
            exc.__traceback__ = None
            _rev("rung_error", rung=f"gpt2s_{remat}_B{batch}")
            record["gpt2s_error"] = repr(exc)[:120]
            cfg = state = chain = None
            gc.collect()

    # --- auxiliary rung: llama family (GQA + SwiGLU, C=128, T=2048) ------
    # depth-scaled like the XL headline: the 7B per-layer compute shape
    # (D=4096, H=32/Hkv=8, SwiGLU) at the depth that fits one chip with
    # f32 params + Adam state (~770M params at L=2 incl. the 50304 embed)
    for ll_layers, ll_batch in ((2, 8 * n_dev), (2, 4 * n_dev)):
        try:
            lcfg, lstate, lchain, lmk = _run_config(
                "none", ll_batch, base="llama_7b", n_layer=ll_layers,
                loss_chunk=512,
            )
            ltps, lstep_ms, lstate, _lmode = _rung_measure(
                lcfg, lstate, lchain, lmk
            )
            lmfu = mfu(ltps, lcfg.model, n_dev)
            record.update(
                {
                    "llama_metric": f"llama_7b_family_L{ll_layers}_train_mfu",
                    "llama_mfu": round(lmfu, 4),
                    "llama_vs_baseline": round(lmfu / BASELINE_MFU, 4),
                    "llama_tokens_per_sec_per_chip": round(ltps / n_dev, 1),
                    "llama_step_ms": round(lstep_ms, 1),
                    "llama_batch_per_chip": lcfg.batch_size // n_dev,
                }
            )
            record.pop("llama_error", None)
            del lstate, lchain
            gc.collect()
            break
        except Exception as exc:  # noqa: BLE001 — aux rung is best-effort
            exc.__traceback__ = None
            record["llama_error"] = repr(exc)[:120]
            lcfg = lstate = lchain = None
            gc.collect()

    # --- auxiliary rung: serving (prefill + KV-cached decode) ------------
    # skipped when the training rungs already consumed most of the driver
    # budget (the relay post-mortem in PERF.md: never run into the timeout)
    if time.perf_counter() - t_start < 300:
        try:
            from scripts.bench_decode import measure_decode

            record.update(measure_decode())
            # decode roofline attainment: the recorded HBM floor over
            # the measured per-token latency (1.0 = bandwidth-bound
            # perfection; decode_vs_floor is the same ratio inverted)
            if record.get("decode_ms_per_tok") and record.get(
                "decode_hbm_floor_ms"
            ):
                record["decode_attainment_frac"] = round(
                    record["decode_hbm_floor_ms"]
                    / record["decode_ms_per_tok"], 4,
                )
        except Exception as exc:  # noqa: BLE001 — aux rung is best-effort
            exc.__traceback__ = None
            record["decode_error"] = repr(exc)[:120]
            gc.collect()

    # --- auxiliary rung: long context (T=4096/8192, 124M family) ---------
    # flash + chunked loss at T >> the kernels' 1024 block cap: exercises
    # the multi-block backward path and the O(T) activation story that
    # ring attention + chunked xent exist for (VERDICT r4 Next #5). The
    # 8192 attempt is budget-gated like decode.
    for lc_t, lc_batch, lc_remat in (
        (4096, 4 * n_dev, "none"),
        (4096, 2 * n_dev, "none"),
        (4096, 4 * n_dev, "full"),
    ):
        if time.perf_counter() - t_start > 420:
            record.setdefault("long_ctx_error", "skipped: bench budget")
            break
        try:
            ccfg, cstate, cchain, cmk = _run_config(
                lc_remat, lc_batch, base="openwebtext",
                block_size=lc_t, loss_chunk=512,
            )
            ctps, cstep_ms, cstate, _cmode = _rung_measure(
                ccfg, cstate, cchain, cmk
            )
            cmfu = mfu(ctps, ccfg.model, n_dev)
            record.update(
                {
                    "long_ctx_metric": f"openwebtext_124m_T{lc_t}_train_mfu",
                    "long_ctx_mfu": round(cmfu, 4),
                    "long_ctx_t": lc_t,
                    "long_ctx_tokens_per_sec_per_chip": round(ctps / n_dev, 1),
                    "long_ctx_step_ms": round(cstep_ms, 1),
                    "long_ctx_remat": lc_remat,
                    "long_ctx_batch_per_chip": ccfg.batch_size // n_dev,
                }
            )
            record.pop("long_ctx_error", None)
            del cstate, cchain
            gc.collect()
            break
        except Exception as exc:  # noqa: BLE001 — aux rung is best-effort
            exc.__traceback__ = None
            record["long_ctx_error"] = repr(exc)[:120]
            ccfg = cstate = cchain = None
            gc.collect()

    if time.perf_counter() - t_start < 480 and "long_ctx_mfu" in record:
        try:
            ccfg, cstate, cchain, cmk = _run_config(
                "none", 1 * n_dev, base="openwebtext",
                block_size=8192, loss_chunk=512,
            )
            ctps, cstep_ms, cstate, _cmode = _rung_measure(
                ccfg, cstate, cchain, cmk
            )
            record.update(
                {
                    "long_ctx8k_mfu": round(mfu(ctps, ccfg.model, n_dev), 4),
                    "long_ctx8k_tokens_per_sec_per_chip": round(
                        ctps / n_dev, 1
                    ),
                    "long_ctx8k_step_ms": round(cstep_ms, 1),
                }
            )
            del cstate, cchain
            gc.collect()
        except Exception as exc:  # noqa: BLE001
            exc.__traceback__ = None
            record["long_ctx8k_error"] = repr(exc)[:120]
            ccfg = cstate = cchain = None
            gc.collect()


    # --- comms audit: static per-step wire traffic of the headline -------
    # config (midgpt_tpu.analysis). Recompiling the measured program is an
    # executable-cache hit right after its rung ran; the scalar split
    # (ICI / DCN bytes per axis, collective count) rides the BENCH_*.json
    # record so the trajectory tracks comms alongside MFU. window_steps
    # makes the audit compile the SAME fused K-step window the headline
    # rung dispatched (scan mode fuses _SCAN_STEPS+1 steps), not a K=1
    # program the trainer never launched.
    audit_cfg = xcfg if xcfg is not None else cfg
    if audit_cfg is not None and time.perf_counter() - t_start < 540:
        try:
            from midgpt_tpu.analysis.harness import train_step_comms_summary

            record.update(train_step_comms_summary(
                audit_cfg,
                window_steps=record.get("steps_per_dispatch", 1),
            ))
        except Exception as exc:  # noqa: BLE001 — audit rung is best-effort
            exc.__traceback__ = None
            record["comms_error"] = repr(exc)[:120]
            gc.collect()

    _all_done.set()  # cancel the mid-run watchdog: main owns the output
    if "value" not in record:
        raise RuntimeError(f"no bench config ran: {record}")
    record.setdefault("status", "ok")
    print(json.dumps(record))


if __name__ == "__main__":
    main()
