"""Benchmark: training throughput + MFU on real TPU hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline: GPT-2-small (124M, openwebtext config) training MFU on the
available chip(s), compared against the reference's published 47.8% MFU
(1.5B on v3-128, /root/reference/README.md:55 — the only published
efficiency number; see BASELINE.md)."""

from __future__ import annotations

import dataclasses
import json
import time

import jax
import numpy as np

BASELINE_MFU = 0.478  # reference 1.5B on TPU v3-128 (README.md:55)


def _run_config(remat: str, batch: int, base: str = "openwebtext", n_layer=None):
    """Build state + step for one candidate config; returns a timing
    closure. Raises on compile/alloc failure (caller falls back)."""
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.config import MeshConfig, get_config
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step

    cfg = get_config(base)
    if n_layer is not None:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, n_layer=n_layer)
        )
    cfg = dataclasses.replace(
        cfg,
        batch_size=batch,
        g_accum_iters=1,
        # scan_unroll = n_layer: profiling showed the rolled lax.scan costs
        # ~40% of the step in dynamic-update-slice stacking + XLA's
        # memory-pressure remat/compression copies of the carried
        # activations; fully unrolling removed 58 ms/step of 'data
        # formatting' + most loop-fusion overhead (15.2% -> ~40% MFU)
        model=dataclasses.replace(
            cfg.model, attn_impl="auto", remat=remat, scan_unroll=cfg.model.n_layer
        ),
        mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1),
        # head+xent computed T-chunk-wise: the [B,T,V] f32 logits (3.3 GB
        # at this config) never materialize, which is what makes the
        # remat='none' rung fit in HBM
        loss_chunk=256,
    )

    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    train_step = make_train_step(cfg, tx, mesh)

    t = cfg.model.block_size
    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.model.vocab_size, size=(1, batch, t), dtype=np.int32)
    y = rng.integers(0, cfg.model.vocab_size, size=(1, batch, t), dtype=np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg = make_global_array(x, mesh, spec)
    yg = make_global_array(y, mesh, spec)
    key = jax.random.PRNGKey(1)

    def chain(state, n):
        # n chained steps + ONE host sync. Under the axon relay a host
        # transfer costs ~70ms RTT and block_until_ready alone is
        # unreliable, so true step time = delta between chain lengths.
        start = time.perf_counter()
        loss = None
        for _ in range(n):
            state, loss = train_step(state, xg, yg, key)
        _ = float(loss)
        return time.perf_counter() - start, state

    return cfg, state, chain


def main() -> None:
    from midgpt_tpu.utils.metrics import flops_per_token, mfu

    # persistent executable cache: repeat runs (and the fallback ladder)
    # skip recompiles
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    n_dev = jax.device_count()
    # candidate ladder, fastest-measured first (see PERF.md r2 sweep:
    # B=24 remat=none 40.1%, B=16 none 39.9%, dots/full B=32 ~33%); fall
    # back if the compiler/allocator rejects a rung on this chip
    last_err = None
    for remat, batch in (
        ("none", 24 * n_dev),
        ("none", 16 * n_dev),
        ("full", 16 * n_dev),
    ):
        try:
            cfg, state, chain = _run_config(remat, batch)
            _, state = chain(state, 1)  # compile + 1 step
            break
        except Exception as exc:  # noqa: BLE001 — any compile/OOM falls through
            # keep the message but drop the traceback: its frames pin the
            # failed rung's device arrays (params + Adam moments) in HBM,
            # which would shrink the next rung's headroom
            exc.__traceback__ = None
            last_err = exc
            cfg = state = chain = None
    else:
        raise RuntimeError(f"no bench config ran: {last_err}")

    batch = cfg.batch_size
    t = cfg.model.block_size
    t_1, state = chain(state, 1)  # RTT + 1 step
    n_steps = 10
    t_n, state = chain(state, n_steps + 1)
    elapsed = t_n - t_1

    tokens_per_sec = batch * t * n_steps / elapsed
    achieved_mfu = mfu(tokens_per_sec, cfg.model, n_dev)
    record = {
        "metric": "openwebtext_124m_train_mfu",
        "value": round(achieved_mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(achieved_mfu / BASELINE_MFU, 4),
        "tokens_per_sec_per_chip": round(tokens_per_sec / n_dev, 1),
        "step_ms": round(1e3 * elapsed / n_steps, 1),
        "device": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "remat": cfg.model.remat,
        "model_flops_per_token": flops_per_token(cfg.model),
    }

    # flagship-family rung (BASELINE.md north star tracks the 1.5B
    # openwebtext_xl shape): same D=2048/H=16/C=128 per-layer compute,
    # depth scaled to fit one chip's HBM with full params + Adam state.
    # MFU is per-FLOP, so the depth-scaled number tracks the full-depth
    # one (the 1.5B head/embed share is slightly smaller -> reported
    # number is, if anything, conservative).
    del state, chain
    import gc

    gc.collect()
    for xl_layers, xl_batch in ((6, 16 * n_dev), (6, 8 * n_dev)):
        try:
            xcfg, xstate, xchain = _run_config(
                "none", xl_batch, base="openwebtext_xl", n_layer=xl_layers
            )
            _, xstate = xchain(xstate, 1)
            xt_1, xstate = xchain(xstate, 1)
            xt_n, xstate = xchain(xstate, n_steps + 1)
            xelapsed = xt_n - xt_1
            xtps = xcfg.batch_size * xcfg.model.block_size * n_steps / xelapsed
            xmfu = mfu(xtps, xcfg.model, n_dev)
            record.update(
                {
                    "xl_metric": f"openwebtext_xl_L{xl_layers}_train_mfu",
                    "xl_mfu": round(xmfu, 4),
                    "xl_vs_baseline": round(xmfu / BASELINE_MFU, 4),
                    "xl_tokens_per_sec_per_chip": round(xtps / n_dev, 1),
                    "xl_step_ms": round(1e3 * xelapsed / n_steps, 1),
                    "xl_batch_per_chip": xcfg.batch_size // n_dev,
                }
            )
            del xstate, xchain
            gc.collect()
            break
        except Exception as exc:  # noqa: BLE001 — xl rung is best-effort
            exc.__traceback__ = None
            record["xl_error"] = repr(exc)[:120]
            # release the failed rung's device state before the fallback
            xcfg = xstate = xchain = None
            gc.collect()

    print(json.dumps(record))


if __name__ == "__main__":
    main()
