"""Prepare the char-level tinyshakespeare dataset (parity:
/root/reference/data/shakespeare_char/prepare.py): download input.txt,
build the char vocab, 90/10 split, write train.bin/val.bin (uint16) +
meta.pkl with stoi/itos.

Offline environments: pass --input=<path> to use a local text file, or
--synthetic to generate a deterministic synthetic corpus (for smoke runs
only — golden val-loss numbers require the real dataset)."""

import argparse
import os
import pickle

import numpy as np

URL = "https://raw.githubusercontent.com/karpathy/char-rnn/master/data/tinyshakespeare/input.txt"


def synthetic_corpus(n_chars: int = 1_000_000, seed: int = 0) -> str:
    """Deterministic char-level corpus with word/sentence structure —
    enough statistical signal for a tiny model to fit, zero downloads."""
    rng = np.random.default_rng(seed)
    words = [
        "the", "lord", "king", "and", "to", "of", "thou", "thy", "with",
        "love", "death", "night", "day", "sword", "crown", "blood", "heart",
        "speak", "come", "good", "my", "what", "shall", "is", "not", "that",
    ]
    names = ["ROMEO", "JULIET", "HAMLET", "MACBETH", "OTHELLO", "KING LEAR"]
    parts = []
    total = 0
    while total < n_chars:
        name = names[rng.integers(len(names))]
        n_words = int(rng.integers(4, 12))
        sent = " ".join(words[rng.integers(len(words))] for _ in range(n_words))
        line = f"{name}:\n{sent.capitalize()}.\n\n"
        parts.append(line)
        total += len(line)
    return "".join(parts)[:n_chars]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", default=None, help="local input.txt path")
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--out_dir", default=os.path.dirname(__file__) or ".")
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    if args.synthetic:
        text = synthetic_corpus()
    elif args.input:
        with open(args.input) as f:
            text = f.read()
    else:
        import requests

        path = os.path.join(args.out_dir, "input.txt")
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write(requests.get(URL, timeout=60).text)
        with open(path) as f:
            text = f.read()

    chars = sorted(set(text))
    stoi = {ch: i for i, ch in enumerate(chars)}
    itos = {i: ch for i, ch in enumerate(chars)}
    print(f"corpus: {len(text):,} chars, vocab {len(chars)}")

    data = np.array([stoi[c] for c in text], dtype=np.uint16)
    n = len(data)
    train, val = data[: int(n * 0.9)], data[int(n * 0.9) :]
    train.tofile(os.path.join(args.out_dir, "train.bin"))
    val.tofile(os.path.join(args.out_dir, "val.bin"))
    with open(os.path.join(args.out_dir, "meta.pkl"), "wb") as f:
        pickle.dump({"vocab_size": len(chars), "stoi": stoi, "itos": itos}, f)
    print(f"train {len(train):,} tokens / val {len(val):,} tokens")


if __name__ == "__main__":
    main()
