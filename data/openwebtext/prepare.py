"""Prepare OpenWebText as GPT-2 BPE uint16 token streams (parity:
/root/reference/data/openwebtext/prepare.py): HF load_dataset, 0.05% val
split (seed 2357), tiktoken GPT-2 encode_ordinary + EOT append, parallel
map, concat to memmapped train.bin/val.bin (~9.04B / ~4.4M tokens).

Requires network + disk; run on a CPU host, not the TPU workers."""

import argparse
import os

import numpy as np

NUM_PROC = max(os.cpu_count() // 2, 1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out_dir", default=os.path.dirname(__file__) or ".")
    ap.add_argument("--num_proc", type=int, default=NUM_PROC)
    args = ap.parse_args()

    import tiktoken
    from datasets import load_dataset

    enc = tiktoken.get_encoding("gpt2")

    dataset = load_dataset("openwebtext", num_proc=args.num_proc)
    split = dataset["train"].train_test_split(
        test_size=0.0005, seed=2357, shuffle=True
    )
    split["val"] = split.pop("test")

    def process(example):
        ids = enc.encode_ordinary(example["text"])
        ids.append(enc.eot_token)
        return {"ids": ids, "len": len(ids)}

    tokenized = split.map(
        process,
        remove_columns=["text"],
        desc="tokenizing",
        num_proc=args.num_proc,
    )

    for name, dset in tokenized.items():
        total = np.sum(dset["len"], dtype=np.uint64)
        path = os.path.join(args.out_dir, f"{name}.bin")
        arr = np.memmap(path, dtype=np.uint16, mode="w+", shape=(int(total),))
        idx = 0
        n_shards = 1024
        for shard_idx in range(n_shards):
            shard = dset.shard(
                num_shards=n_shards, index=shard_idx, contiguous=True
            ).with_format("numpy")
            batch = np.concatenate(shard["ids"])
            arr[idx : idx + len(batch)] = batch
            idx += len(batch)
        arr.flush()
        print(f"{name}: {int(total):,} tokens -> {path}")


if __name__ == "__main__":
    main()
