"""Training-loop telemetry: lifecycle tracing, prefetch-starvation
accounting, and deterministic step-keyed anomaly monitors.

PR 12 gave the *serving* stack lifecycle tracing and a flight recorder;
the training loop — the half of the codebase the paper is about — was
still observed through wandb scalars alone. This module is the training
specialization of the shared substrate (:mod:`midgpt_tpu.telemetry`):

1. **Lifecycle tracing** (:class:`TrainTelemetry`,
   ``ExperimentConfig(train_telemetry=True)``): typed events keyed to
   the *optimizer-step window index* — ``window_launch`` /
   ``window_harvest`` around each fused dispatch, ``prefetch_wait``
   spans (with a starvation counter when the loop blocked on the
   loader), ``eval_pause``, ``ckpt_save``/``ckpt_wait``, ``resume`` —
   with wall clock stamped ONLY at host reads the loop already performs
   (the prefetch queue get, the logging-window ``np.asarray`` harvest,
   the eval ``float()``, the checkpoint call boundaries). Tracing is
   not a parameter of any program factory: the jitted train window is
   resolved through :func:`midgpt_tpu.train.get_train_window`'s
   module-level cache, so telemetry on/off selects the ``is``-identical
   callable and the loss sequence is bitwise unchanged
   (tests/test_train_telemetry.py — the serving inertness contract,
   mirrored exactly).

2. **Timeline export**: :func:`chrome_trace_train` renders the loop as
   Perfetto-loadable lanes (prefetch / train-window / eval / checkpoint
   spans + anomaly and starvation instants).

3. **Anomaly monitors** (:class:`AnomalyMonitors`, always on — they
   only read scalars the logging path already pulled to the host):
   a NaN sentinel, EWMA loss-spike and grad-norm-spike detectors, and
   a throughput-drop detector. The loss/grad/NaN monitors are
   *deterministic and step-keyed* — their decisions are a pure function
   of the (step, value) series the fused window emits, so a replayed
   run trips at the identical step. The throughput monitor consumes a
   wall-clock-derived rate and is the one monitor that is
   hardware-informed by construction (it exists for the r4/r5 wedge
   class: a run that silently slows to a crawl). On trip: a structured
   flight record (recent value history + the event/dispatch rings) is
   dumped to the rundir — the wedged-run lesson applied to training.

Window-granularity honesty: the fused K-step dispatch crosses to the
host once per *logging window*, so ``train_window`` spans exist only
for windows that logged (their ``dur`` runs launch -> the existing
harvest read; non-logging windows launch asynchronously and are never
synced on). Nothing here adds a device round-trip.
"""

from __future__ import annotations

import math
import os
import typing as tp

from midgpt_tpu.telemetry import (
    MetricsRegistry,
    TelemetryLog,
    write_json,
)

__all__ = [
    "AnomalyMonitors",
    "TRAIN_EVENT_KINDS",
    "TRAIN_SPAN_KINDS",
    "TrainTelemetry",
    "chrome_trace_train",
]


#: Point events (``TrainTelemetry.emit``). ``window_launch`` fires when
#: a fused dispatch is enqueued (host-side clock read, no sync);
#: ``window_harvest`` fires at the logging window's existing
#: device->host read; ``prefetch_starved`` marks a prefetch wait above
#: the starvation threshold; ``anomaly`` is a monitor trip.
TRAIN_EVENT_KINDS: tp.Tuple[str, ...] = (
    "run_start",
    "resume",
    "window_launch",
    "window_harvest",
    "prefetch_starved",
    "anomaly",
    "interrupt",
    "run_end",
    # bench.py's rung-ladder lifecycle (its flight recorder is this
    # module too — a wedged BENCH round dumps which rung it died in)
    "rung_start",
    "rung_ok",
    "rung_error",
)

#: Span records (``TrainTelemetry.span`` -> the dispatch ring).
TRAIN_SPAN_KINDS: tp.Tuple[str, ...] = (
    "prefetch_wait",
    "train_window",
    "eval_pause",
    "ckpt_save",
    "ckpt_wait",
)

#: Registry counters every TrainTelemetry carries (the train analogue of
#: the engine's ``_ENGINE_COUNTERS`` — pinned by test so the Prometheus
#: exporter and the ledger can rely on the inventory).
TRAIN_COUNTERS: tp.Tuple[str, ...] = (
    "windows_dispatched",
    "steps_completed",
    "prefetch_waits",
    "prefetch_starved",
    "evals",
    "ckpt_saves",
    "anomalies_tripped",
)


class TrainTelemetry(TelemetryLog):
    """Event log + metrics registry for one training run.

    ``step`` on every event/span is the absolute optimizer step the
    window starts at (the window index times K, plus resume offset) —
    the training analogue of the engine-local scheduler step, and like
    it fully deterministic. ``starvation_s`` sets the prefetch-wait
    threshold above which the loop counts itself loader-starved (the
    queue get is a host block either way; the threshold only
    classifies it)."""

    event_kinds = TRAIN_EVENT_KINDS

    def __init__(self, *, starvation_s: float = 0.05, **kw):
        super().__init__(**kw)
        self.starvation_s = starvation_s
        self.metrics = MetricsRegistry()
        for name in TRAIN_COUNTERS:
            self.metrics.counter(name)
        self.metrics.histogram("prefetch_wait_s")
        self.metrics.histogram("train_window_s")
        self.metrics.histogram("eval_pause_s")
        self.metrics.histogram("ckpt_save_s")

    # -- recording ---------------------------------------------------------

    def span(
        self, kind: str, *, step: int, t: float, dur: float, **data
    ) -> None:
        """One timed loop phase onto the dispatch ring (+ its latency
        histogram). ``data`` must stay deterministic — wall clock rides
        only in ``t``/``dur``."""
        assert kind in TRAIN_SPAN_KINDS, kind
        self.record_dispatch(
            kind, step=step, t=t, dur=dur, rids=(), tokens=0, **data
        )
        h = self.metrics.histograms.get(f"{kind}_s")
        if h is not None:
            h.observe(dur)

    def prefetch_wait(self, *, step: int, t: float, dur: float) -> None:
        """The loop blocked ``dur`` seconds on ``prefetch.next()``.
        Above ``starvation_s`` the wait counts as loader starvation —
        the input pipeline, not the device, owned the critical path."""
        self.metrics.counter("prefetch_waits").inc()
        self.span("prefetch_wait", step=step, t=t, dur=dur)
        if dur > self.starvation_s:
            self.metrics.counter("prefetch_starved").inc()
            self.emit("prefetch_starved", step=step, t=t + dur)

    def metrics_snapshot(self) -> tp.Dict[str, tp.Any]:
        """The registry view (counters + histograms) — same shape as
        ``ServingEngine.metrics_snapshot()``, so
        :func:`midgpt_tpu.telemetry.prometheus_text` exports it
        directly."""
        return self.metrics.snapshot()

    def flight_dump(
        self,
        reason: str,
        path: tp.Optional[str] = None,
        extra: tp.Optional[tp.Dict[str, tp.Any]] = None,
    ) -> tp.Dict[str, tp.Any]:
        """The flight-recorder artifact: metrics snapshot + the bounded
        event/span rings, as one JSON-able record (written to ``path``
        when given). Reads host-side state only — safe best-effort from
        a watchdog thread, like the serving twin."""
        rec: tp.Dict[str, tp.Any] = {
            "reason": reason,
            "metrics": self.metrics_snapshot(),
            "telemetry": self.flight_payload(),
        }
        if extra:
            rec.update(extra)
        if path is not None:
            rec["path"] = os.path.abspath(path)
            write_json(path, rec)
        return rec


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------

_TRAIN_PID = 1
_TRAIN_LANES = {
    "prefetch_wait": 0,
    "train_window": 1,
    "eval_pause": 2,
    "ckpt_save": 3,
    "ckpt_wait": 4,
}
_TRAIN_INSTANTS = (
    "run_start", "resume", "prefetch_starved", "anomaly", "interrupt",
    "run_end",
)


def chrome_trace_train(tele: TrainTelemetry) -> tp.Dict[str, tp.Any]:
    """Export a training telemetry log as a Chrome trace-event JSON
    object: one process with a lane per loop phase (spans from the
    dispatch ring) plus an events lane (anomalies, starvation, resume
    markers as instants). Timestamps are microseconds relative to the
    earliest recorded event."""
    all_ts = [d.t for d in tele.dispatches] + [ev.t for ev in tele.events]
    base = min(all_ts) if all_ts else 0.0
    events: tp.List[tp.Dict[str, tp.Any]] = [{
        "ph": "M", "pid": _TRAIN_PID, "name": "process_name",
        "args": {"name": "train-loop"},
    }]
    for kind, tid in _TRAIN_LANES.items():
        events.append({
            "ph": "M", "pid": _TRAIN_PID, "tid": tid,
            "name": "thread_name", "args": {"name": kind},
        })
    ev_lane = len(_TRAIN_LANES)
    events.append({
        "ph": "M", "pid": _TRAIN_PID, "tid": ev_lane,
        "name": "thread_name", "args": {"name": "events"},
    })
    for d in tele.dispatches:
        events.append({
            "name": d.kind,
            "ph": "X",
            "pid": _TRAIN_PID,
            "tid": _TRAIN_LANES.get(d.kind, ev_lane),
            "ts": (d.t - base) * 1e6,
            "dur": max(0.0, d.dur) * 1e6,
            "args": dict(d.data, step=d.step),
        })
    for ev in tele.events:
        if ev.kind not in _TRAIN_INSTANTS:
            continue
        events.append({
            "name": ev.kind,
            "ph": "i",
            "s": "p",
            "pid": _TRAIN_PID,
            "tid": ev_lane,
            "ts": (ev.t - base) * 1e6,
            "args": dict(ev.data, step=ev.step),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Anomaly monitors
# ---------------------------------------------------------------------------


class _EwmaSpike:
    """Deterministic EWMA mean/variance spike detector: trips when an
    observation exceeds the running mean by ``z`` standard deviations
    (with a relative floor so a flat series doesn't trip on noise).
    Statistics update AFTER the check, so a spike cannot absorb
    itself."""

    def __init__(
        self, *, alpha: float = 0.05, z: float = 8.0, warmup: int = 20,
        rel_floor: float = 0.25,
    ):
        self.alpha = alpha
        self.z = z
        self.warmup = warmup
        self.rel_floor = rel_floor
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def observe(self, x: float) -> tp.Optional[tp.Dict[str, float]]:
        if self.n == 0:
            # seed from the first observation: starting the mean at 0
            # would make the whole warmup period one giant "spike" that
            # inflates the variance estimate for hundreds of steps
            self.mean = x
            self.n = 1
            return None
        trip = None
        if self.n >= self.warmup:
            threshold = self.mean + max(
                self.z * math.sqrt(max(self.var, 0.0)),
                self.rel_floor * abs(self.mean),
            )
            if x > threshold:
                trip = {"value": x, "threshold": threshold,
                        "ewma": self.mean}
        delta = x - self.mean
        self.mean += self.alpha * delta
        self.var = (1.0 - self.alpha) * (
            self.var + self.alpha * delta * delta
        )
        self.n += 1
        return trip


class AnomalyMonitors:
    """Step-keyed training-health monitors with flight-record dumps.

    ``observe_step(step, loss, grad_norm)`` runs the deterministic
    monitors (NaN sentinel first — a non-finite value trips regardless
    of warmup — then the EWMA loss and grad-norm spike detectors);
    ``observe_throughput(step, tokens_per_sec)`` runs the wall-informed
    throughput-drop detector (trips when the rate falls below
    ``tps_drop_frac`` of its EWMA). Every trip increments the attached
    telemetry's ``anomalies_tripped`` counter, emits an ``anomaly``
    event, and (up to ``max_dumps`` times) writes a flight record to
    ``flight_dir`` carrying the recent value history and the telemetry
    rings — so a diverging or wedging run leaves a timeline, not just a
    broken loss curve. Trips never raise: the monitors observe, the
    operator decides.
    """

    def __init__(
        self,
        *,
        telemetry: tp.Optional[TrainTelemetry] = None,
        flight_dir: tp.Optional[str] = None,
        loss_z: float = 8.0,
        grad_z: float = 10.0,
        warmup: int = 20,
        tps_drop_frac: float = 0.5,
        tps_warmup: int = 3,
        max_dumps: int = 4,
        history: int = 256,
    ):
        self.telemetry = telemetry
        self.flight_dir = flight_dir
        self._loss = _EwmaSpike(z=loss_z, warmup=warmup)
        self._grad = _EwmaSpike(z=grad_z, warmup=warmup)
        self._tps_ewma = 0.0
        self._tps_n = 0
        self._tps_drop_frac = tps_drop_frac
        self._tps_warmup = tps_warmup
        self.max_dumps = max_dumps
        self.trips: tp.List[tp.Dict[str, tp.Any]] = []
        self.dump_paths: tp.List[str] = []
        import collections

        self._history: tp.Deque[tp.Tuple[int, float, float]] = (
            collections.deque(maxlen=history)
        )

    # -- observation -------------------------------------------------------

    def observe_step(
        self, step: int, loss: float,
        grad_norm: tp.Optional[float] = None, *, t: float = 0.0,
    ) -> tp.List[tp.Dict[str, tp.Any]]:
        """Feed one optimizer step's host-read scalars; returns the
        trips (possibly empty). Deterministic: same (step, loss,
        grad_norm) series -> same trips at the same steps.
        ``grad_norm=None`` (the K=1 loop, which logs no grad norm)
        skips the grad-norm detectors."""
        gn = float(grad_norm) if grad_norm is not None else 0.0
        self._history.append((step, float(loss), gn))
        out = []
        if not math.isfinite(loss) or (
            grad_norm is not None and not math.isfinite(grad_norm)
        ):
            out.append(self._trip(
                "nan", step, t=t,
                detail={"loss": float(loss), "grad_norm": gn},
            ))
            return out  # non-finite values must not poison the EWMAs
        d = self._loss.observe(float(loss))
        if d is not None:
            out.append(self._trip("loss_spike", step, t=t, detail=d))
        if grad_norm is not None:
            d = self._grad.observe(float(grad_norm))
            if d is not None:
                out.append(
                    self._trip("grad_norm_spike", step, t=t, detail=d)
                )
        return out

    def observe_throughput(
        self, step: int, tokens_per_sec: float, *, t: float = 0.0
    ) -> tp.List[tp.Dict[str, tp.Any]]:
        """Feed one logging window's host-clocked rate. Wall-informed by
        construction (this is the monitor that catches the r4/r5 wedge
        class: the device silently slowing down)."""
        out = []
        if self._tps_n >= self._tps_warmup and tokens_per_sec < (
            self._tps_drop_frac * self._tps_ewma
        ):
            out.append(self._trip(
                "throughput_drop", step, t=t,
                detail={"tokens_per_sec": tokens_per_sec,
                        "ewma": self._tps_ewma},
            ))
        alpha = 0.3
        self._tps_ewma = (
            tokens_per_sec if self._tps_n == 0
            else (1 - alpha) * self._tps_ewma + alpha * tokens_per_sec
        )
        self._tps_n += 1
        return out

    # -- trip handling -----------------------------------------------------

    def _trip(
        self, kind: str, step: int, *, t: float,
        detail: tp.Dict[str, float],
    ) -> tp.Dict[str, tp.Any]:
        trip = {"kind": kind, "step": step, "detail": detail}
        self.trips.append(trip)
        tele = self.telemetry
        if tele is not None:
            tele.metrics.counter("anomalies_tripped").inc()
            # detail values are step-keyed scalars (the throughput rate
            # being the documented wall-informed exception), so they may
            # ride the deterministic data fields
            tele.emit("anomaly", step=step, t=t, kind_detail=kind)
        if self.flight_dir is not None and len(
            self.dump_paths
        ) < self.max_dumps:
            path = os.path.join(
                self.flight_dir, f"anomaly_{kind}_step{step}.json"
            )
            payload = {
                "reason": f"anomaly:{kind}",
                "step": step,
                "detail": detail,
                "history": [
                    {"step": s, "loss": lo, "grad_norm": gn}
                    for s, lo, gn in list(self._history)
                ],
                "telemetry": (
                    tele.flight_payload() if tele is not None else None
                ),
            }
            try:
                trip["flight_dump"] = write_json(path, payload)
                self.dump_paths.append(trip["flight_dump"])
            except OSError:  # a dump must never kill the training loop
                pass
        return trip
