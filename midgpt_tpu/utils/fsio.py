"""Uniform local/GCS file access.

The reference hand-rolls a gs://-vs-local branch at every site that touches
a rundir file (/root/reference/launch.py:43-53, sample.py:39-46,
launch.py:60-67 for the wandb id). We keep one helper instead; every
rundir-file consumer (launch.py, sample.py, utils/metrics.py) routes
through it, so auth/retry changes happen in one place.
"""

from __future__ import annotations

import os


def open_path(path: str, mode: str = "r"):
    """open() that understands gs:// (via gcsfs). Creates parent dirs for
    local writes; gcsfs handles bucket "dirs" implicitly."""
    if path.startswith("gs://"):
        import gcsfs

        return gcsfs.GCSFileSystem().open(path, mode)
    if "w" in mode or "a" in mode:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
    return open(path, mode)


def path_exists(path: str) -> bool:
    if path.startswith("gs://"):
        import gcsfs

        return gcsfs.GCSFileSystem().exists(path)
    return os.path.exists(path)
