"""Backend probes shared by the kernel dispatch sites."""

from __future__ import annotations

import jax


def is_tpu_backend() -> bool:
    """True when the default backend is a TPU. The platform string is
    "tpu" natively but e.g. "axon" through a tunnel, where only the
    device_kind ("TPU v5 lite", ...) gives it away — hence the combined
    probe."""
    return any(
        "tpu" in f"{d.platform} {d.device_kind}".lower() for d in jax.devices()
    )
