"""In-process JAX backend pinning shared by the CLIs.

On hosts whose site config pins a hardware platform (this container's
sitecustomize re-pins axon), ``JAX_PLATFORMS`` in the environment is
IGNORED — the only working override is ``jax.config.update`` in-process,
before any backend-initializing call. launch.py, sample.py, and
scripts/check_reference_parity.py all share this helper so the semantics
can't drift."""

from __future__ import annotations

HELP = (
    "force the JAX backend in-process (JAX_PLATFORMS in the environment "
    "is ignored on hosts whose site config pins a platform; pair cpu "
    "with XLA_FLAGS=--xla_force_host_platform_device_count=N for "
    "CPU-mesh smoke runs)"
)


def add_platform_arg(parser) -> None:
    parser.add_argument(
        "--platform", default=None, choices=("cpu", "tpu"), help=HELP
    )


def apply_platform(platform) -> None:
    """Pin the backend; must run before any backend-initializing call."""
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
