"""Observability: throughput/MFU computed in-train + structured logging.

The reference computes throughput from the tqdm rate and MFU offline
(SURVEY.md 5.1, 5.5); here both are first-class: model FLOPs from the
config, per-device peak FLOPs from the device kind, metrics appended to a
JSONL file in the rundir (wandb optional, gated on import)."""

from __future__ import annotations

import json
import os
import time
import typing as tp

import jax

from midgpt_tpu.config import ExperimentConfig, ModelConfig, to_dict

# bf16 peak FLOPs/s per chip by device kind substring
_PEAK_FLOPS = {
    "v6": 918e12,
    "v5p": 459e12,
    "v5": 197e12,  # v5e / v5 lite
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def device_peak_flops(device: tp.Optional[jax.Device] = None) -> float:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key, val in _PEAK_FLOPS.items():
        if key in kind:
            return val
    return 197e12  # assume v5e-class if unknown


def flops_per_token(model: ModelConfig, seq_len: tp.Optional[int] = None) -> float:
    """Training FLOPs/token (fwd+bwd), PaLM-style 6N + attention term."""
    t = seq_len or model.block_size
    d = model.n_embd
    c = model.head_dim
    from midgpt_tpu.models.gpt import mlp_hidden_dim

    f = mlp_hidden_dim(model)
    # parameter FLOPs (2 per MAC, x3 for fwd+bwd)
    qkv = d * (model.n_head + 2 * model.kv_heads) * c
    proj = model.n_head * c * d
    mlp = (3 if model.mlp == "swiglu" else 2) * d * f
    per_layer = qkv + proj + mlp
    # + the lm-head projection only: the token embedding is a gather (or a
    # one-hot contraction of the same cost class under TP), not counted
    n_matmul = model.n_layer * per_layer + d * model.vocab_size
    param_flops = 6 * n_matmul
    # attention score/value FLOPs: 2 matmuls of T x C per head, causal ~1/2
    attn_flops = 6 * 2 * model.n_layer * model.n_head * c * t  # per token
    attn_flops = attn_flops / 2  # causal
    return param_flops + attn_flops


def mfu(tokens_per_sec: float, model: ModelConfig, n_devices: int) -> float:
    achieved = tokens_per_sec * flops_per_token(model)
    peak = device_peak_flops() * n_devices
    return achieved / peak


def decode_flops_per_token(
    model: ModelConfig, live_tokens: tp.Optional[float] = None
) -> float:
    """Inference FLOPs per generated token (forward only): 2 FLOPs per
    parameter MAC plus the attention score/value term over the live KV
    context — the numerator of serving MFU, which bench_serving records
    next to the HBM-floor attainment so the compute-vs-bandwidth split
    of a decode step is visible in one row."""
    live = float(model.block_size if live_tokens is None else live_tokens)
    d = model.n_embd
    c = model.head_dim
    from midgpt_tpu.models.gpt import mlp_hidden_dim

    f = mlp_hidden_dim(model)
    qkv = d * (model.n_head + 2 * model.kv_heads) * c
    proj = model.n_head * c * d
    mlp = (3 if model.mlp == "swiglu" else 2) * d * f
    n_matmul = model.n_layer * (qkv + proj + mlp) + d * model.vocab_size
    # scores + value sum: 2 matmuls of live x C per head, 2 FLOPs/MAC
    attn = 4 * model.n_layer * model.n_head * c * live
    return 2 * n_matmul + attn


def train_floor(
    cfg: ExperimentConfig, n_devices: int
) -> tp.Optional[tp.Dict[str, tp.Any]]:
    """The training-step roofline context MetricLogger attaches to every
    logging step (analysis/traffic.train_floor_decomposition, wired to
    this device's peak FLOPs): compute + HBM floors and the
    tokens-per-step needed to turn a measured tokens_per_sec into
    step_ms and an attainment fraction. None when the analytic floor
    doesn't cover the config (e.g. MoE) — logging then proceeds without
    the attainment keys rather than with wrong ones."""
    from midgpt_tpu.analysis.traffic import train_floor_decomposition

    try:
        return train_floor_decomposition(
            cfg.model,
            batch_size=cfg.batch_size,
            n_devices=n_devices,
            flops_per_token=flops_per_token(cfg.model),
            peak_flops_per_device=device_peak_flops(),
        )
    except AssertionError:
        return None


def moe_router_metrics(stats: tp.Mapping[str, tp.Any]) -> tp.Dict[str, float]:
    """Schema for the per-eval-interval MoE router telemetry (VERDICT r5
    Next #7): ``moe/aux`` (load-balance aux, 1.0 = perfectly balanced,
    summed over layers like the training loss term) and
    ``moe/dropped_frac`` (fraction of routing claims past expert
    capacity — the silent failure mode: dropped tokens ride the residual
    and never show in the loss curve). ``stats`` is
    ``models.gpt.GPT.moe_stats``'s output."""
    return {
        "moe/aux": float(stats["aux"]),
        "moe/dropped_frac": float(stats["dropped_frac"]),
    }


def _load_or_create_wandb_id(rundir: str, wandb_mod) -> tp.Optional[str]:
    """Read rundir/wandb_id.txt, creating it with a fresh id on first run
    (parity: /root/reference/launch.py:60-67). Returns None when the rundir
    isn't a writable local path (wandb then picks its own id)."""
    if not rundir:
        return None
    from midgpt_tpu.utils.fsio import open_path, path_exists

    path = os.path.join(rundir, "wandb_id.txt")
    try:
        if path_exists(path):
            with open_path(path) as f:
                return f.read().strip()
        run_id = wandb_mod.util.generate_id()
        with open_path(path, "w") as f:
            f.write(run_id)
        return run_id
    except Exception:
        return None


class MetricLogger:
    """JSONL metrics + optional wandb, process-0 only (parity:
    launch.py:38-68 / train.py:212-213 wandb logging).

    ``floor`` (a ``train_floor`` dict) arms roofline attainment: any
    logged metrics dict carrying ``tokens_per_sec`` is augmented with
    ``step_ms`` (tokens_per_step / rate), the static
    ``train_hbm_floor_ms`` / ``train_compute_floor_ms`` decomposition,
    and ``train_attainment_frac = floor / measured`` — so the logged
    series reads against the hardware ceiling next to MFU instead of
    requiring hand arithmetic in PERF.md."""

    def __init__(
        self, rundir: str, config: ExperimentConfig,
        use_wandb: bool = False,
        floor: tp.Optional[tp.Mapping[str, tp.Any]] = None,
    ):
        self.is_main = jax.process_index() == 0
        self.floor = floor
        self._file = None
        self._wandb = None
        if not self.is_main:
            return
        if rundir and not rundir.startswith("gs://"):
            os.makedirs(rundir, exist_ok=True)
            self._file = open(os.path.join(rundir, "metrics.jsonl"), "a")
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb
                # persist the run id in the rundir so a resumed run
                # continues the same wandb run instead of forking a new one
                # (parity: /root/reference/launch.py:60-67)
                run_id = _load_or_create_wandb_id(rundir, wandb)
                wandb.init(
                    dir=rundir or None,
                    config=to_dict(config),
                    id=run_id,
                    resume="allow",
                )
            except Exception:
                self._wandb = None

    def attainment(
        self, tokens_per_sec: float
    ) -> tp.Dict[str, float]:
        """The roofline keys for one measured rate (empty without a
        floor context): measured step_ms, the two static floors, and
        attainment = floor / measured."""
        fl = self.floor
        if not fl or tokens_per_sec <= 0:
            return {}
        step_ms = fl["tokens_per_step"] / tokens_per_sec * 1e3
        return {
            "step_ms": round(step_ms, 3),
            "train_hbm_floor_ms": fl["train_hbm_floor_ms"],
            "train_compute_floor_ms": fl["train_compute_floor_ms"],
            # significant digits, not decimals: CPU attainment is ~1e-8
            # and must not round to a hard zero
            "train_attainment_frac": float(
                f"{fl['train_floor_ms_per_step'] / step_ms:.3g}"
            ),
        }

    def log(self, step: int, metrics: tp.Mapping[str, float]) -> None:
        if not self.is_main:
            return
        if "tokens_per_sec" in metrics:
            metrics = {
                **metrics, **self.attainment(metrics["tokens_per_sec"])
            }
        rec = {"step": step, "time": time.time(), **metrics}
        if self._file is not None:
            self._file.write(json.dumps(rec) + "\n")
            self._file.flush()
        if self._wandb is not None:
            self._wandb.log(metrics, step=step)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
        if self._wandb is not None:
            self._wandb.finish()
