"""Observability: throughput/MFU computed in-train + structured logging.

The reference computes throughput from the tqdm rate and MFU offline
(SURVEY.md 5.1, 5.5); here both are first-class: model FLOPs from the
config, per-device peak FLOPs from the device kind, metrics appended to a
JSONL file in the rundir (wandb optional, gated on import)."""

from __future__ import annotations

import json
import os
import time
import typing as tp

import jax

from midgpt_tpu.config import ExperimentConfig, ModelConfig, to_dict

# bf16 peak FLOPs/s per chip by device kind substring
_PEAK_FLOPS = {
    "v6": 918e12,
    "v5p": 459e12,
    "v5": 197e12,  # v5e / v5 lite
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def device_peak_flops(device: tp.Optional[jax.Device] = None) -> float:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key, val in _PEAK_FLOPS.items():
        if key in kind:
            return val
    return 197e12  # assume v5e-class if unknown


def flops_per_token(model: ModelConfig, seq_len: tp.Optional[int] = None) -> float:
    """Training FLOPs/token (fwd+bwd), PaLM-style 6N + attention term."""
    t = seq_len or model.block_size
    d = model.n_embd
    c = model.head_dim
    from midgpt_tpu.models.gpt import mlp_hidden_dim

    f = mlp_hidden_dim(model)
    # parameter FLOPs (2 per MAC, x3 for fwd+bwd)
    qkv = d * (model.n_head + 2 * model.kv_heads) * c
    proj = model.n_head * c * d
    mlp = (3 if model.mlp == "swiglu" else 2) * d * f
    per_layer = qkv + proj + mlp
    # + the lm-head projection only: the token embedding is a gather (or a
    # one-hot contraction of the same cost class under TP), not counted
    n_matmul = model.n_layer * per_layer + d * model.vocab_size
    param_flops = 6 * n_matmul
    # attention score/value FLOPs: 2 matmuls of T x C per head, causal ~1/2
    attn_flops = 6 * 2 * model.n_layer * model.n_head * c * t  # per token
    attn_flops = attn_flops / 2  # causal
    return param_flops + attn_flops


def mfu(tokens_per_sec: float, model: ModelConfig, n_devices: int) -> float:
    achieved = tokens_per_sec * flops_per_token(model)
    peak = device_peak_flops() * n_devices
    return achieved / peak


def moe_router_metrics(stats: tp.Mapping[str, tp.Any]) -> tp.Dict[str, float]:
    """Schema for the per-eval-interval MoE router telemetry (VERDICT r5
    Next #7): ``moe/aux`` (load-balance aux, 1.0 = perfectly balanced,
    summed over layers like the training loss term) and
    ``moe/dropped_frac`` (fraction of routing claims past expert
    capacity — the silent failure mode: dropped tokens ride the residual
    and never show in the loss curve). ``stats`` is
    ``models.gpt.GPT.moe_stats``'s output."""
    return {
        "moe/aux": float(stats["aux"]),
        "moe/dropped_frac": float(stats["dropped_frac"]),
    }


def _load_or_create_wandb_id(rundir: str, wandb_mod) -> tp.Optional[str]:
    """Read rundir/wandb_id.txt, creating it with a fresh id on first run
    (parity: /root/reference/launch.py:60-67). Returns None when the rundir
    isn't a writable local path (wandb then picks its own id)."""
    if not rundir:
        return None
    from midgpt_tpu.utils.fsio import open_path, path_exists

    path = os.path.join(rundir, "wandb_id.txt")
    try:
        if path_exists(path):
            with open_path(path) as f:
                return f.read().strip()
        run_id = wandb_mod.util.generate_id()
        with open_path(path, "w") as f:
            f.write(run_id)
        return run_id
    except Exception:
        return None


class MetricLogger:
    """JSONL metrics + optional wandb, process-0 only (parity:
    launch.py:38-68 / train.py:212-213 wandb logging)."""

    def __init__(self, rundir: str, config: ExperimentConfig, use_wandb: bool = False):
        self.is_main = jax.process_index() == 0
        self._file = None
        self._wandb = None
        if not self.is_main:
            return
        if rundir and not rundir.startswith("gs://"):
            os.makedirs(rundir, exist_ok=True)
            self._file = open(os.path.join(rundir, "metrics.jsonl"), "a")
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb
                # persist the run id in the rundir so a resumed run
                # continues the same wandb run instead of forking a new one
                # (parity: /root/reference/launch.py:60-67)
                run_id = _load_or_create_wandb_id(rundir, wandb)
                wandb.init(
                    dir=rundir or None,
                    config=to_dict(config),
                    id=run_id,
                    resume="allow",
                )
            except Exception:
                self._wandb = None

    def log(self, step: int, metrics: tp.Mapping[str, float]) -> None:
        if not self.is_main:
            return
        rec = {"step": step, "time": time.time(), **metrics}
        if self._file is not None:
            self._file.write(json.dumps(rec) + "\n")
            self._file.flush()
        if self._wandb is not None:
            self._wandb.log(metrics, step=step)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
        if self._wandb is not None:
            self._wandb.finish()
