"""KV-cached autoregressive generation.

Replaces the reference sampler's pad-to-block_size full re-forward per token
(/root/reference/sample.py:68-95) with prefill + incremental decode under
``lax.scan`` — one compiled program, O(T) per token, static shapes.
Capability parity: temperature-scaled categorical sampling; adds greedy
(temperature=0) and top-k."""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.models.gpt import GPT, KVCache, decode_step, prefill

Array = jax.Array


def _sample_token(logits: Array, key: Array, temperature: float, top_k: tp.Optional[int]) -> Array:
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        assert top_k > 0, f"top_k must be positive, got {top_k}"
        top_k = min(top_k, logits.shape[-1])  # clamp to vocab
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(
    model: GPT,
    prompt: Array,  # [B, P] int32
    max_new_tokens: int,
    *,
    key: Array,
    temperature: float = 1.0,
    top_k: tp.Optional[int] = None,
    cache_dtype=jnp.bfloat16,
    sliding: str = "exact",
) -> Array:
    """Returns [B, max_new_tokens] sampled continuations (parity:
    sample.py:68-95 generate, temperature semantics sample.py:88-92).

    Up to ``block_size`` total tokens, decoding is KV-cached (O(W)/token vs
    the reference's full re-forward per token). Past ``block_size`` the
    window must slide (sample.py:74 ``idx[:, -block_size:]``) and two
    semantics are offered:

    - ``sliding="exact"`` (default): re-run the cropped-window full forward
      per token — bit-parity with the reference, which *recomputes the
      hidden states of past tokens under the shrunken context* each step.
      Same O(W * fwd)/token cost the reference always pays.
    - ``sliding="kv"``: ring-buffer cache, evict-oldest. Past tokens keep
      the hidden states they were computed with (standard sliding-window
      KV decoding, O(W)/token). Diverges from the reference once the
      window slides — fast mode, not a parity mode.
    """
    assert sliding in ("exact", "kv"), f"unknown sliding mode {sliding!r}"
    b, p = prompt.shape
    cfg = model.config
    if p > cfg.block_size:
        # reference conditions on the last block_size tokens (sample.py:74)
        prompt = prompt[:, -cfg.block_size :]
        p = cfg.block_size
    total = p + max_new_tokens
    w = min(total, cfg.block_size)
    cache = KVCache.init(cfg, b, w, dtype=cache_dtype)
    logits, cache = prefill(model, prompt, cache)

    def body(carry, _):
        logits, pos, cache, k = carry
        k, sub = jax.random.split(k)
        tok = _sample_token(logits, sub, temperature, top_k)
        new_logits, cache = decode_step(model, tok, pos, cache, rope_len=total)
        return (new_logits, pos + 1, cache, k), tok

    n1 = w - p  # tokens decodable before the window would slide
    (logits, _, cache, key), toks1 = jax.lax.scan(
        body, (logits, jnp.asarray(p, jnp.int32), cache, key), None, length=n1
    )
    toks1 = jnp.transpose(toks1)  # [B, n1]
    if total <= w:
        return toks1

    n2 = total - w
    if sliding == "kv":
        # same decode body; pos continues from w, evicting the oldest slot
        (_, _, _, _), toks2 = jax.lax.scan(
            body, (logits, jnp.asarray(w, jnp.int32), cache, key), None,
            length=n2,
        )
    else:  # exact
        window = jnp.concatenate([prompt, toks1], axis=1)  # [B, W]
        # single-chip full forward: ring needs a live mesh and an explicit
        # 'flash' may not divide W — same impl fallback prefill uses
        # (models/gpt.py prefill)
        impl = "auto" if cfg.attn_impl in ("ring", "flash", "fused") else cfg.attn_impl

        def body2(carry, _):
            logits, window, k = carry
            k, sub = jax.random.split(k)
            tok = _sample_token(logits, sub, temperature, top_k)
            window = jnp.concatenate([window[:, 1:], tok[:, None]], axis=1)
            new_logits = model(window, attn_impl=impl)[:, -1, :]
            return (new_logits, window, k), tok

        (_, _, _), toks2 = jax.lax.scan(
            body2, (logits, window, key), None, length=n2
        )
    return jnp.concatenate([toks1, jnp.transpose(toks2)], axis=1)


def make_sampler(
    max_new_tokens: int,
    *,
    mesh=None,
    temperature: float = 1.0,
    top_k: tp.Optional[int] = None,
    cache_dtype=jnp.bfloat16,
    sliding: str = "exact",
):
    """A jitted ``(model, prompt, key) -> tokens`` sampler.

    With ``mesh``, generation runs under the mesh's axis rules: restored
    params keep their TP/FSDP shardings and GSPMD distributes the decode
    matmuls + KV cache — the multi-chip serving path for models too big
    for one chip (absent from the reference, whose sampler is strictly
    single-process full-replication, sample.py:177-182)."""
    from midgpt_tpu.parallel.sharding import axis_rules

    def fn(model: GPT, prompt: Array, key: Array) -> Array:
        with axis_rules(mesh):  # axis_rules(None) is an explicit no-op scope
            return generate(
                model,
                prompt,
                max_new_tokens,
                key=key,
                temperature=temperature,
                top_k=top_k,
                cache_dtype=cache_dtype,
                sliding=sliding,
            )

    return jax.jit(fn)
