"""KV-cached autoregressive generation (fixed batch).

Replaces the reference sampler's pad-to-block_size full re-forward per token
(/root/reference/sample.py:68-95) with prefill + incremental decode under
``lax.scan`` — one compiled program, O(T) per token, static shapes.
Capability parity: temperature-scaled categorical sampling; adds greedy
(temperature=0) and top-k.

This module is the FIXED-BATCH path (one ring cache sized for the batch,
all requests start and stop together) and the exact-parity oracle the
serving tests compare against. Under real traffic — requests arriving and
finishing independently — route through ``midgpt_tpu.serving`` instead:
paged KV pool, continuous batching, and K decode steps fused per XLA
dispatch (``serving.generate_served`` is the drop-in batch entry point;
``sample.py --serve`` uses it)."""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.models.gpt import (
    GPT,
    KVCache,
    decode_step_recent,
    merge_recent,
    prefill,
)

Array = jax.Array


def _pin_cache_layout(cache: KVCache) -> KVCache:
    """Constrain the ring cache to the standard streaming layout (W minor).

    Without this, XLA's layout assignment sees the bulk merge writes and
    may flip the cache to a write-friendly C-minor layout that pads C=64
    lanes to 128 — halving read bandwidth on the decode hot loop (measured
    on v5e, PERF.md r4 'Serving'). Single-device TPU only: under a mesh
    GSPMD owns layouts, and on CPU it's moot."""
    if jax.default_backend() != "tpu":
        return cache
    from midgpt_tpu.parallel.sharding import current_mesh

    if current_mesh() is not None:
        return cache
    from jax.experimental.layout import Layout, with_layout_constraint

    lay = Layout(tuple(range(cache.k.ndim)))
    return KVCache(
        k=with_layout_constraint(cache.k, lay),
        v=with_layout_constraint(cache.v, lay),
    )


def _scaled_masked(
    logits: Array, temperature: float, top_k: tp.Optional[int]
) -> Array:
    """Temperature-scale and top-k-mask ``logits`` — the pre-sampling
    arithmetic SHARED by :func:`sample_token` (which feeds the result to
    a key-derived categorical) and :func:`target_probs` (which softmaxes
    it into the acceptance distribution of the sampled verify program).
    One body on purpose: the choreo prover compares the two call sites
    op for op, so the tempering/masking arithmetic must literally be the
    same code, not two copies that could drift."""
    logits = logits / temperature
    if top_k is not None:
        assert top_k > 0, f"top_k must be positive, got {top_k}"
        top_k = min(top_k, logits.shape[-1])  # clamp to vocab
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return logits


def sample_token(
    logits: Array, key: Array, temperature: float, top_k: tp.Optional[int]
) -> Array:
    """One sampling decision: greedy argmax at ``temperature == 0``,
    temperature-scaled (optionally top-k-filtered) categorical otherwise.
    Shared by the fixed-batch sampler below and the serving engine's
    decode window AND verify program: at ``temperature == 0`` the verify
    program's acceptance check is this function's argmax branch applied
    per candidate row (greedy speculation is exactly greedy-equivalent);
    at ``temperature > 0`` the verify program's row-0 draw is this very
    function under the same (seed, token-index) derived key, and its
    rejection-sampling acceptance threshold is :func:`target_probs` —
    the softmax of the SAME tempered/masked logits this function draws
    from.

    Under a tensor-parallel serving mesh ``logits`` arrives
    VOCAB-SHARDED: the greedy branch partitions cleanly (per-shard
    argmax + a [B, tp]-sized combiner gather — the only thing that ever
    crosses chips is one (value, index) pair per shard, never the row).
    The temperature branch's top-k sort and categorical draw may gather
    the row per slot — correct, but the gathered-row-free contract is
    greedy-only (the sharded-serving audits gate the greedy programs)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, _scaled_masked(logits, temperature, top_k), axis=-1
    ).astype(jnp.int32)


_sample_token = sample_token  # back-compat alias (pre-PR 5 private name)


def derive_request_key(key: Array, seed: Array, token_index: Array) -> Array:
    """The per-request, per-position sampling key:
    ``fold_in(fold_in(key, request_seed), token_index)``. This is the
    serving determinism contract in one place — a request's stream
    position ``i`` is always drawn with this key, whether the draw
    happens in a decode window step, as the sampled verify program's
    row 0, or as the residual resample after a rejected draft (the
    verify program carries the residual as logits, so the NEXT
    dispatch's row-0 draw at this very key IS the residual draw). Keys
    are a function of (request seed, stream position) only — never slot
    index, window size, batch composition, or chunking — which is what
    makes sampled streams bitwise scheduling-invariant."""
    return jax.random.fold_in(jax.random.fold_in(key, seed), token_index)


# Salt folded into a position's derived key to produce the ACCEPTANCE
# uniform for that position (speculative rejection sampling). A salted
# substream, not the categorical stream itself: position i's categorical
# key must stay untouched so a rejection at i resamples with exactly the
# key the non-speculative engine would have used there.
SPEC_ACCEPT_SALT = 0x5BEC


def target_probs(
    logits: Array, temperature: float, top_k: tp.Optional[int]
) -> Array:
    """The model's sampling distribution as probabilities, in f32:
    ``softmax(_scaled_masked(logits))``. This is BY CONSTRUCTION the
    distribution :func:`sample_token` draws from at the same
    ``(temperature, top_k)`` — the verify program's acceptance test
    ``u * q(t) <= p(t)`` and residual ``max(p - q, 0)`` use it, so
    accepted drafts are distributed exactly like decode-window draws
    (standard speculative-sampling exactness). f32 throughout: the
    acceptance compare is the new near-tie surface (the same bug class
    the PR 4/5 dtype drifts hit), and the choreo prover pins it."""
    return jax.nn.softmax(
        _scaled_masked(logits.astype(jnp.float32), temperature, top_k),
        axis=-1,
    )


def acceptance_mask(u: Array, q_sel: Array, p_sel: Array) -> Array:
    """Rejection-sampling acceptance: accept a drafted token ``t`` iff
    ``u * q(t) <= p(t)`` — the multiplied form of ``u <= p(t)/q(t)``
    (no division, so a zero draft probability cannot produce inf/nan;
    ``q(t) = 0`` accepts always, which is the correct limit: the draft
    distribution then carries no mass to reject against). For one-hot
    n-gram drafts ``q(t) = 1`` and this degenerates to ``u <= p(t)``.

    A named module-level seam on purpose: the acceptance compare is
    where a dtype drift would silently skew the sampled distribution
    (bf16 rounds p near ulp boundaries), so the choreo prover proves its
    operands are f32 and the fault-injection test monkeypatches THIS
    function with a drifted-dtype variant to prove exactly that clause
    fails."""
    return (u * q_sel) <= p_sel


def residual_logits(
    p: Array, q: Array, temperature: float
) -> tp.Tuple[Array, Array]:
    """Logits whose :func:`sample_token` draw IS the rejection-sampling
    residual draw: ``temperature * log(normalize(max(p - q, 0)))``, plus
    the residual mass ``sum(max(p - q, 0))`` (callers fall back to the
    raw logits row when the mass is 0 — a float-exactness corner where
    ``p <= q`` everywhere, meaning the acceptance test could not have
    rejected except at an exact boundary).

    Why this shape: the verify program does not draw the resample token
    in-dispatch (the rejected row's K/V was computed for the DRAFT
    token, so an in-dispatch resample would need pending-token replumb
    of the pool write path). Instead it CARRIES these logits out, and
    the next dispatch's ordinary row-0 ``sample_token`` at the position's
    derived key performs the draw: the temperature division cancels the
    ``temperature *`` here, top-k masking is a no-op on a <= top_k
    support vector (the kth-largest of a shorter-support row sorts to
    -inf, and nothing compares below -inf), and the categorical's
    gumbel-argmax is shift-invariant — so the draw is exactly
    ``categorical(residual)`` with zero special cases in the sampler.
    (Exact float ties inside ``_scaled_masked``'s kth threshold can
    widen p's support past top_k; the carried draw then re-applies
    top-k on the residual — a measure-zero corner that keeps streams
    deterministic either way.)"""
    resid = jnp.maximum(p - q, 0.0)
    mass = jnp.sum(resid, axis=-1)
    denom = jnp.where(mass > 0.0, mass, 1.0)[..., None]
    norm = jnp.where(resid > 0.0, resid / denom, 1.0)
    out = jnp.where(
        resid > 0.0, temperature * jnp.log(norm), -jnp.inf
    )
    return out, mass


def generate(
    model: GPT,
    prompt: Array,  # [B, P] int32
    max_new_tokens: int,
    *,
    key: Array,
    temperature: float = 1.0,
    top_k: tp.Optional[int] = None,
    cache_dtype=jnp.bfloat16,
    sliding: str = "exact",
    chunk_len: int = 64,
) -> Array:
    """Returns [B, max_new_tokens] sampled continuations (parity:
    sample.py:68-95 generate, temperature semantics sample.py:88-92).

    Up to ``block_size`` total tokens, decoding is KV-cached (O(W)/token vs
    the reference's full re-forward per token). Past ``block_size`` the
    window must slide (sample.py:74 ``idx[:, -block_size:]``) and two
    semantics are offered:

    - ``sliding="exact"`` (default): re-run the cropped-window full forward
      per token — bit-parity with the reference, which *recomputes the
      hidden states of past tokens under the shrunken context* each step.
      Same O(W * fwd)/token cost the reference always pays.
    - ``sliding="kv"``: ring-buffer cache, evict-oldest. Past tokens keep
      the hidden states they were computed with (standard sliding-window
      KV decoding, O(W)/token). Diverges from the reference once the
      window slides — fast mode, not a parity mode.

    Decoding runs in chunks of ``chunk_len`` tokens through a small
    write-combining recent-KV buffer (gpt.decode_step_recent) so the ring
    cache stays read-only between bulk merges — the layout-friendly shape
    of KV decode on TPU (PERF.md r4). The joint softmax over both parts is
    exact; chunking changes performance, not semantics."""
    assert sliding in ("exact", "kv"), f"unknown sliding mode {sliding!r}"
    assert chunk_len >= 1, f"chunk_len must be >= 1, got {chunk_len}"
    b, p = prompt.shape
    cfg = model.config
    if p > cfg.block_size:
        # reference conditions on the last block_size tokens (sample.py:74)
        prompt = prompt[:, -cfg.block_size :]
        p = cfg.block_size
    total = p + max_new_tokens
    w = min(total, cfg.block_size)  # sliding-window size (semantics)
    # a chunk longer than the window wastes recent-buffer reads (its
    # oldest rows are evicted mid-chunk; decode_step_recent masks them)
    r_len = min(chunk_len, w)
    wp = -(-w // r_len) * r_len  # ring slots, padded so merges never wrap
    cache = KVCache.init(cfg, b, wp, dtype=cache_dtype)
    logits, cache = prefill(model, prompt, cache)
    cache = _pin_cache_layout(cache)

    rshape = (cfg.n_layer, b, cfg.kv_heads, r_len, cfg.head_dim)

    def one_chunk(logits, key, cache, base, clen: int):
        """clen decode steps from traced base; returns toks [clen, B].
        base is a TRACED scalar so every full-length chunk shares one
        compiled body (baking it in statically made trace/compile size grow
        linearly with max_new_tokens/chunk_len)."""
        rk = jnp.zeros(rshape, cache.k.dtype)
        rv = jnp.zeros(rshape, cache.k.dtype)

        def body(carry, _):
            logits, r, rk, rv, k = carry
            k, sub = jax.random.split(k)
            tok = sample_token(logits, sub, temperature, top_k)
            new_logits, rk, rv = decode_step_recent(
                model, tok, base + r, cache, rk, rv, r, base, w, total
            )
            return (new_logits, r + 1, rk, rv, k), tok

        (logits, _, rk, rv, key), toks = jax.lax.scan(
            body,
            (logits, jnp.zeros((), jnp.int32), rk, rv, key),
            None,
            length=clen,
        )
        cache = merge_recent(cache, rk, rv, jnp.mod(base, wp), clen)
        return logits, key, _pin_cache_layout(cache), toks

    def run_chunked(logits, key, cache, start_pos: int, n_steps: int):
        """n_steps of chunked decode from absolute position start_pos.
        A partial first chunk aligns subsequent bases to r_len (merges
        never wrap the ring); the full chunks run under ONE outer scan."""
        toks_parts = []
        base, remaining = start_pos, n_steps
        l0 = min(r_len - base % r_len, remaining) if base % r_len else 0
        if l0:
            logits, key, cache, t0 = one_chunk(
                logits, key, cache, jnp.asarray(base, jnp.int32), l0
            )
            toks_parts.append(t0)
            base, remaining = base + l0, remaining - l0
        n_full = remaining // r_len
        if n_full:
            def chunk_body(carry, _):
                logits, key, cache, cur = carry
                logits, key, cache, toks = one_chunk(
                    logits, key, cache, cur, r_len
                )
                return (logits, key, cache, cur + r_len), toks

            (logits, key, cache, _), tf = jax.lax.scan(
                chunk_body,
                (logits, key, cache, jnp.asarray(base, jnp.int32)),
                None,
                length=n_full,
            )
            toks_parts.append(tf.reshape(n_full * r_len, b))
            base, remaining = base + n_full * r_len, remaining - n_full * r_len
        if remaining:
            logits, key, cache, t2 = one_chunk(
                logits, key, cache, jnp.asarray(base, jnp.int32), remaining
            )
            toks_parts.append(t2)
        toks = (
            jnp.concatenate(toks_parts, axis=0)
            if toks_parts
            else jnp.zeros((0, b), jnp.int32)
        )
        return logits, key, cache, toks

    n1 = w - p  # tokens decodable before the window would slide
    if sliding == "kv":
        # ring eviction is just the sliding-window mask in the chunked
        # step — one unified loop over all new tokens
        _, _, _, toks = run_chunked(logits, key, cache, p, max_new_tokens)
        return jnp.transpose(toks)  # [B, max_new_tokens]

    logits, key, cache, toks1 = run_chunked(logits, key, cache, p, n1)
    toks1 = jnp.transpose(toks1)  # [B, n1]
    if total <= w:
        return toks1

    # exact sliding: re-run the cropped-window full forward per token
    n2 = total - w
    window = jnp.concatenate([prompt, toks1], axis=1)  # [B, W]
    # single-chip full forward: ring needs a live mesh and an explicit
    # 'flash' may not divide W — same impl fallback prefill uses
    # (models/gpt.py prefill)
    impl = "auto" if cfg.attn_impl in ("ring", "ulysses", "flash", "fused") else cfg.attn_impl

    def body2(carry, _):
        logits, window, k = carry
        k, sub = jax.random.split(k)
        tok = sample_token(logits, sub, temperature, top_k)
        window = jnp.concatenate([window[:, 1:], tok[:, None]], axis=1)
        new_logits = model(window, attn_impl=impl)[:, -1, :]
        return (new_logits, window, k), tok

    (_, _, _), toks2 = jax.lax.scan(
        body2, (logits, window, key), None, length=n2
    )
    return jnp.concatenate([toks1, jnp.transpose(toks2)], axis=1)


def make_sampler(
    max_new_tokens: int,
    *,
    mesh=None,
    temperature: float = 1.0,
    top_k: tp.Optional[int] = None,
    cache_dtype=jnp.bfloat16,
    sliding: str = "exact",
    chunk_len: int = 64,
):
    """A jitted ``(model, prompt, key) -> tokens`` sampler.

    With ``mesh``, generation runs under the mesh's axis rules: restored
    params keep their TP/FSDP shardings and GSPMD distributes the decode
    matmuls + KV cache — the multi-chip serving path for models too big
    for one chip (absent from the reference, whose sampler is strictly
    single-process full-replication, sample.py:177-182)."""
    from midgpt_tpu.parallel.sharding import axis_rules

    def fn(model: GPT, prompt: Array, key: Array) -> Array:
        with axis_rules(mesh):  # axis_rules(None) is an explicit no-op scope
            return generate(
                model,
                prompt,
                max_new_tokens,
                key=key,
                temperature=temperature,
                top_k=top_k,
                cache_dtype=cache_dtype,
                sliding=sliding,
                chunk_len=chunk_len,
            )

    return jax.jit(fn)
