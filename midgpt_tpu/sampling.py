"""KV-cached autoregressive generation.

Replaces the reference sampler's pad-to-block_size full re-forward per token
(/root/reference/sample.py:68-95) with prefill + incremental decode under
``lax.scan`` — one compiled program, O(T) per token, static shapes.
Capability parity: temperature-scaled categorical sampling; adds greedy
(temperature=0) and top-k."""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.models.gpt import GPT, KVCache, decode_step, prefill

Array = jax.Array


def _sample_token(logits: Array, key: Array, temperature: float, top_k: tp.Optional[int]) -> Array:
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        assert top_k > 0, f"top_k must be positive, got {top_k}"
        top_k = min(top_k, logits.shape[-1])  # clamp to vocab
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(
    model: GPT,
    prompt: Array,  # [B, P] int32
    max_new_tokens: int,
    *,
    key: Array,
    temperature: float = 1.0,
    top_k: tp.Optional[int] = None,
    cache_dtype=jnp.bfloat16,
) -> Array:
    """Returns [B, max_new_tokens] sampled continuations (parity:
    sample.py:68-95 generate, temperature semantics sample.py:88-92)."""
    b, p = prompt.shape
    cfg = model.config
    total = p + max_new_tokens
    assert total <= cfg.block_size, (
        f"prompt {p} + new {max_new_tokens} exceeds block_size {cfg.block_size}"
    )
    cache = KVCache.init(cfg, b, total, dtype=cache_dtype)
    logits, cache = prefill(model, prompt, cache)

    def body(carry, _):
        logits, pos, cache, k = carry
        k, sub = jax.random.split(k)
        tok = _sample_token(logits, sub, temperature, top_k)
        new_logits, cache = decode_step(model, tok, pos, cache)
        return (new_logits, pos + 1, cache, k), tok

    (_, _, _, _), toks = jax.lax.scan(
        body,
        (logits, jnp.asarray(p, jnp.int32), cache, key),
        None,
        length=max_new_tokens,
    )
    return jnp.transpose(toks)  # [B, N]


def make_sampler(
    max_new_tokens: int,
    *,
    mesh=None,
    temperature: float = 1.0,
    top_k: tp.Optional[int] = None,
    cache_dtype=jnp.bfloat16,
):
    """A jitted ``(model, prompt, key) -> tokens`` sampler.

    With ``mesh``, generation runs under the mesh's axis rules: restored
    params keep their TP/FSDP shardings and GSPMD distributes the decode
    matmuls + KV cache — the multi-chip serving path for models too big
    for one chip (absent from the reference, whose sampler is strictly
    single-process full-replication, sample.py:177-182)."""
    from midgpt_tpu.parallel.sharding import axis_rules

    def fn(model: GPT, prompt: Array, key: Array) -> Array:
        with axis_rules(mesh):  # axis_rules(None) is an explicit no-op scope
            return generate(
                model,
                prompt,
                max_new_tokens,
                key=key,
                temperature=temperature,
                top_k=top_k,
                cache_dtype=cache_dtype,
            )

    return jax.jit(fn)
