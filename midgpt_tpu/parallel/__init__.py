from midgpt_tpu.parallel.mesh import AXIS_NAMES, BATCH_AXES, create_mesh, single_device_mesh
from midgpt_tpu.parallel.sharding import (
    DEFAULT_LOGICAL_RULES,
    axis_rules,
    constrain_params,
    make_global_array,
    param_shardings,
    replicate,
    shard_act,
)

__all__ = [
    "AXIS_NAMES",
    "BATCH_AXES",
    "create_mesh",
    "single_device_mesh",
    "DEFAULT_LOGICAL_RULES",
    "axis_rules",
    "constrain_params",
    "make_global_array",
    "param_shardings",
    "replicate",
    "shard_act",
]
