"""Ring attention: causal self-attention sharded over the mesh 'sequence'
axis (context parallelism).

Absent from the reference (SURVEY.md 5.7: full T on every device, O(T^2)
memory); this is the long-context mechanism the rebuild owes. Design:

- Q/K/V arrive as GLOBAL arrays with T sharded over the 'sequence' axis;
  RoPE was already applied upstream with global positions (GSPMD keeps that
  correct automatically).
- Inside ``jax.shard_map`` each device holds one T/s chunk. K/V chunks
  rotate around the ring with ``lax.ppermute`` (pure ICI neighbor traffic,
  no all-gather); each hop computes a chunk-pair attention and the partial
  results merge via streaming log-sum-exp — numerically identical to full
  softmax attention.
- Causality by chunk index: source chunk j contributes to query chunk i
  fully if j < i, causally-masked if j == i, not at all if j > i (the hop
  is skipped with a -inf lse so the merge ignores it).

Two per-chunk-pair backends, both differentiable end to end through
ppermute's transpose (bwd runs the ring in reverse automatically):
- naive oracle (``_chunk_attention``) — reference-parity math;
- Pallas flash (``_chunk_flash``, default on TPU) — each hop runs
  ``flash_attention_lse``; its lse output is differentiable (the
  cotangent folds into the kernel backward as ``delta - dlse``), so no
  hand-written ring VJP is needed and per-hop memory stays O(chunk).
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.compat import shard_map

Array = jax.Array

_NEG_INF = -1e30


def _chunk_attention(
    q: Array,  # [B, H, Tq, C]
    k: Array,  # [B, Hkv, Tk, C]
    v: Array,  # [B, Hkv, Tk, C]
    mode: Array,  # [] int32: 0 = skip, 1 = causal (diagonal), 2 = full
    keep: tp.Optional[float] = None,  # attention-dropout keep prob
    seed: tp.Optional[Array] = None,
    row_off: tp.Optional[Array] = None,  # global row of (0, 0)
    col_off: tp.Optional[Array] = None,
    bh_off: tp.Optional[Array] = None,  # global batch*H_total + head of (0,0)
    n_head_total: tp.Optional[int] = None,
) -> tp.Tuple[Array, Array]:
    """Attention of one (q-chunk, kv-chunk) pair -> (NORMALIZED chunk
    softmax out [B,H,Tq,C] f32, lse [B,H,Tq] f32) — the contract _merge
    consumes. Reference-parity math: scores from compute-dtype inputs, f32
    softmax with 1/sqrt(C) folded in (model.py:71-79).

    Dropout uses the flash kernels' counter hash at GLOBAL (row, col)
    coordinates (ops/flash._dropout_keep_block semantics): l/lse stay
    UNDROPPED sums so the streaming merge weights are exact, and the mask
    equals the single-device mask at the same seed."""
    b, h, tq, c = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    groups = h // hkv
    qg = q.reshape(b, hkv, groups, tq, c)
    scores = jnp.einsum(
        "bkgqc,bkjc->bkgqj", qg, k, preferred_element_type=jnp.float32
    )
    scale = 1.0 / math.sqrt(c)
    z = scores * scale  # [B, Hkv, G, Tq, Tk]

    causal = (
        jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
    )  # same-chunk relative causality
    # mode: 0 -> all masked; 1 -> causal mask; 2 -> none masked
    visible = jnp.where(
        mode == 0,
        jnp.zeros((tq, tk), bool),
        jnp.where(mode == 1, causal, jnp.ones((tq, tk), bool)),
    )
    z = jnp.where(visible, z, _NEG_INF)
    m = jnp.max(z, axis=-1)  # [B, Hkv, G, Tq]
    p = jnp.exp(z - m[..., None])
    l = jnp.sum(p, axis=-1)  # UNDROPPED (dropout hits softmax outputs only)
    p_acc = p
    if keep is not None:
        from midgpt_tpu.ops.flash import _hash_finalize, _wrap32

        rows = jnp.asarray(row_off, jnp.int32) + jnp.arange(tq, dtype=jnp.int32)
        cols = jnp.asarray(col_off, jnp.int32) + jnp.arange(tk, dtype=jnp.int32)
        x = (
            rows[:, None] * _wrap32(0x9E3779B1)
            + cols[None, :] * _wrap32(0x85EBCA77)
        )  # [Tq, Tk]
        # kernel head id = bh_off + batch * H_total + (kv * groups + g),
        # H_total = GLOBAL q-head count (local h when unsharded)
        nh = jnp.int32(n_head_total or h)
        base = jnp.int32(0) if bh_off is None else jnp.asarray(bh_off, jnp.int32)
        head_ids = (
            base
            + jnp.arange(b, dtype=jnp.int32).reshape(b, 1, 1) * nh
            + jnp.arange(h, dtype=jnp.int32).reshape(1, hkv, groups)
        )
        hx = x[None, None, None] ^ (
            jnp.asarray(seed, jnp.int32).reshape(())
            + head_ids[..., None, None] * _wrap32(0xC2B2AE35)
        )
        u24 = _hash_finalize(hx) & jnp.int32(0x00FFFFFF)
        mask = u24 < jnp.int32(int(keep * (1 << 24)))
        p_acc = jnp.where(mask, p * (1.0 / keep), 0.0)
    out = jnp.einsum(
        "bkgqj,bkjc->bkgqc", p_acc.astype(v.dtype), v
    ).astype(jnp.float32)
    # NORMALIZED chunk softmax output + its logsumexp
    out = out / jnp.maximum(l, 1e-30)[..., None]
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    # fully-masked rows: lse = -inf so the merge ignores this hop
    lse = jnp.where(m <= _NEG_INF / 2, -jnp.inf, lse)
    return out.reshape(b, h, tq, c), lse.reshape(b, h, tq)


def _merge(o1, lse1, o2, lse2):
    """Merge two normalized chunk-softmax partials: softmax-weighted average
    over their logsumexps."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(jnp.isinf(m), 0.0, m)
    w1 = jnp.where(jnp.isinf(lse1), 0.0, jnp.exp(lse1 - m_safe))
    w2 = jnp.where(jnp.isinf(lse2), 0.0, jnp.exp(lse2 - m_safe))
    denom = jnp.maximum(w1 + w2, 1e-30)
    out = (o1 * w1[..., None] + o2 * w2[..., None]) / denom[..., None]
    lse = m_safe + jnp.log(denom)
    lse = jnp.where(jnp.isinf(lse1) & jnp.isinf(lse2), -jnp.inf, lse)
    return out, lse


def _chunk_flash(
    q, k, v, causal: bool,
    keep: tp.Optional[float] = None, seed=None, row_off=None, col_off=None,
    bh_off=None, n_head_total=None,
):
    """One (q-chunk, kv-chunk) pair through the Pallas flash kernel —
    no Tq x Tk materialization, so per-hop memory stays O(chunk). Returns
    the same (normalized out f32, lse f32) contract as _chunk_attention.
    With ``keep``, runs the in-kernel-dropout entry anchored at the hop's
    GLOBAL score coordinates (ops/flash.flash_attention_dropout_lse)."""
    if keep is not None:
        from midgpt_tpu.ops.flash import flash_attention_dropout_lse

        out, lse = flash_attention_dropout_lse(
            q, k, v, seed, 1.0 - keep, causal,
            row_off=row_off, col_off=col_off,
            bh_off=bh_off, n_head_total=n_head_total,
        )
        return out.astype(jnp.float32), lse
    from midgpt_tpu.ops.flash import flash_attention_lse

    out, lse = flash_attention_lse(q, k, v, causal)
    return out.astype(jnp.float32), lse


def _ring_body(
    q, k, v, axis_name: str, use_flash: bool,
    keep: tp.Optional[float] = None, seed=None,
    bh_off=None, n_head_total=None,
):
    """Per-device program: local chunks in, attention output chunk out.

    With ``keep`` (attention dropout), every hop anchors the counter-hash
    mask at its GLOBAL (row, col) score offsets — the ring pass drops the
    exact (head, row, col) set a single-device flash_attention_dropout
    call would (each global coordinate is computed on exactly one hop, so
    no cross-hop correlation is possible)."""
    s = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % s) for i in range(s)]  # send kv to the next device
    tc = q.shape[2]
    q_off = idx * tc  # global row of this device's first query

    # hop 0: own chunk (diagonal -> causal)
    if use_flash:
        out, lse = _chunk_flash(
            q, k, v, causal=True,
            keep=keep, seed=seed, row_off=q_off, col_off=q_off,
            bh_off=bh_off, n_head_total=n_head_total,
        )
    else:
        out, lse = _chunk_attention(
            q, k, v, jnp.asarray(1, jnp.int32),
            keep=keep, seed=seed, row_off=q_off, col_off=q_off,
            bh_off=bh_off, n_head_total=n_head_total,
        )

    def hop(r, carry):
        out, lse, k, v = carry
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        src = (idx - r) % s  # chunk index now held
        k_off = src * tc  # its global column offset
        if use_flash:
            # compute the full-visibility pair, then gate the skip hops
            # (src > idx) out of the merge with lse = -inf; the flash
            # kernel's causal flag must stay static
            o_r, lse_r = _chunk_flash(
                q, k, v, causal=False,
                keep=keep, seed=seed, row_off=q_off, col_off=k_off,
                bh_off=bh_off, n_head_total=n_head_total,
            )
            vis = src < idx
            lse_r = jnp.where(vis, lse_r, -jnp.inf)
            o_r = jnp.where(vis, o_r, 0.0)
        else:
            mode = jnp.where(src < idx, 2, 0).astype(jnp.int32)  # full|skip
            o_r, lse_r = _chunk_attention(
                q, k, v, mode,
                keep=keep, seed=seed, row_off=q_off, col_off=k_off,
                bh_off=bh_off, n_head_total=n_head_total,
            )
        out, lse = _merge(out, lse, o_r, lse_r)
        return out, lse, k, v

    out, lse, _, _ = jax.lax.fori_loop(1, s, hop, (out, lse, k, v))
    return out.astype(q.dtype)  # partials merge pre-normalized


def _zigzag_pair(q, k, v, causal: bool, use_flash: bool):
    """One sub-chunk pair with a STATIC causal flag (zigzag hops only ever
    need full or diagonal-causal visibility; skips are gated by -inf lse)."""
    if use_flash:
        return _chunk_flash(q, k, v, causal=causal)
    return _chunk_attention(
        q, k, v, jnp.asarray(1 if causal else 2, jnp.int32)
    )


def _zigzag_ring_body(q, k, v, axis_name: str, use_flash: bool):
    """Zigzag-scheduled causal ring: the local T axis holds the chunk pair
    (g1=i, g2=2S-1-i) back to back. Per hop against source device j's pair:

      (q_g1, kv_g1-of-j): diagonal-causal at j==i, full at j<i, skip j>i
      (q_g1, kv_g2-of-j): never visible (g2 chunks are all later)
      (q_g2, kv_g1-of-j): always fully visible
      (q_g2, kv_g2-of-j): diagonal-causal at j==i, full at j>i, skip j<i

    => every hop costs exactly two half-chunk pairs on every device (three
    on the diagonal hop), vs the standard schedule where device S-1 does
    S times the work of device 0."""
    s = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % s) for i in range(s)]
    tc = q.shape[2] // 2
    qa, qb = q[:, :, :tc], q[:, :, tc:]

    def halves(x):
        return x[:, :, :tc], x[:, :, tc:]

    ka, kb = halves(k)
    va, vb = halves(v)

    # diagonal hop (j == i)
    oa, la = _zigzag_pair(qa, ka, va, True, use_flash)
    ob, lb = _zigzag_pair(qb, ka, va, False, use_flash)
    ob2, lb2 = _zigzag_pair(qb, kb, vb, True, use_flash)
    ob, lb = _merge(ob, lb, ob2, lb2)

    def hop(r, carry):
        oa, la, ob, lb, k, v = carry
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        j = (idx - r) % s  # source device whose pair we now hold (j != idx)
        ka, kb = halves(k)
        va, vb = halves(v)
        # (qb, kv_g1): always visible
        o2, l2 = _zigzag_pair(qb, ka, va, False, use_flash)
        ob, lb = _merge(ob, lb, o2, l2)
        # the other visible pair is (qa, kv_g1) when j < i, (qb, kv_g2)
        # when j > i — same shapes, so SELECT the operands and compute ONE
        # pair (exactly two half-pairs per hop, as the schedule promises)
        early = j < idx
        q_sel = jnp.where(early, qa, qb)
        k_sel = jnp.where(early, ka, kb)
        v_sel = jnp.where(early, va, vb)
        o_x, l_x = _zigzag_pair(q_sel, k_sel, v_sel, False, use_flash)
        oa, la = _merge(
            oa, la,
            jnp.where(early, o_x, 0.0),
            jnp.where(early, l_x, -jnp.inf),
        )
        ob, lb = _merge(
            ob, lb,
            jnp.where(early, 0.0, o_x),
            jnp.where(early, -jnp.inf, l_x),
        )
        return oa, la, ob, lb, k, v

    oa, la, ob, lb, _, _ = jax.lax.fori_loop(
        1, s, hop, (oa, la, ob, lb, k, v)
    )
    return jnp.concatenate([oa, ob], axis=2).astype(q.dtype)


def _zigzag_order(t: int, s: int):
    """Gather indices re-laying a contiguous T axis into zigzag chunk
    order [0, 2S-1, 1, 2S-2, ...] (device i holds pair (i, 2S-1-i)), and
    the inverse permutation. Oracle only: tests assert the shard-local
    ppermute relayout below equals this index permutation
    (tests/test_ring.py::test_zigzag_relayout_matches_index_oracle)."""
    import numpy as np

    tc = t // (2 * s)
    order = []
    for i in range(s):
        order += [i, 2 * s - 1 - i]
    idx = np.concatenate([np.arange(c * tc, (c + 1) * tc) for c in order])
    return idx, np.argsort(idx)


def _zigzag_relayout_in(x, axis_name: str, s: int):
    """Natural-order local rows (global half-chunks (2d, 2d+1) on device
    d) -> the zigzag pair (d, 2S-1-d), via two bijective half-chunk
    ppermutes + a parity slot-select. A global ``jnp.take`` over the
    sharded T axis did this before — GSPMD lowered it to a FULL-sequence
    all-gather of Q/K/V on every device (caught by the r4 HLO audit,
    tests/test_hlo_collectives.py), defeating ring attention's O(T/S)
    memory at its own front door. Here each device sends exactly two
    half-chunks and receives two.

    Half-chunk g's zigzag owner is t(g) = g if g < S else 2S-1-g; the two
    preimages {d, 2S-1-d} of owner d always have opposite parity, so the
    even-g halves form one device bijection and the odd-g halves another.
    The even-g arrival lands in slot 0 exactly when d is even."""
    d = jax.lax.axis_index(axis_name)
    tc = x.shape[2] // 2
    lo, hi = x[:, :, :tc], x[:, :, tc:]

    def tgt(g: int) -> int:
        return g if g < s else 2 * s - 1 - g

    a = jax.lax.ppermute(
        lo, axis_name, [(i, tgt(2 * i)) for i in range(s)]
    )  # even-g halves
    b = jax.lax.ppermute(
        hi, axis_name, [(i, tgt(2 * i + 1)) for i in range(s)]
    )  # odd-g halves
    even_first = (d % 2 == 0)
    first = jnp.where(even_first, a, b)
    second = jnp.where(even_first, b, a)
    return jnp.concatenate([first, second], axis=2)


def _zigzag_relayout_out(y, axis_name: str, s: int):
    """Inverse of ``_zigzag_relayout_in`` (the permutation transpose):
    device d holds (g=d, g=2S-1-d); the even-g half goes to device
    g_even/2's low slot, the odd-g half to (g_odd-1)/2's high slot."""
    d = jax.lax.axis_index(axis_name)
    tc = y.shape[2] // 2
    slot0, slot1 = y[:, :, :tc], y[:, :, tc:]
    even_first = (d % 2 == 0)
    even_half = jnp.where(even_first, slot0, slot1)
    odd_half = jnp.where(even_first, slot1, slot0)

    def g_even(dd: int) -> int:
        return dd if dd % 2 == 0 else 2 * s - 1 - dd

    def g_odd(dd: int) -> int:
        return dd if dd % 2 == 1 else 2 * s - 1 - dd

    c = jax.lax.ppermute(
        even_half, axis_name, [(i, g_even(i) // 2) for i in range(s)]
    )
    e = jax.lax.ppermute(
        odd_half, axis_name, [(i, (g_odd(i) - 1) // 2) for i in range(s)]
    )
    return jnp.concatenate([c, e], axis=2)


def ring_attention(
    q: Array,  # [B, H, T, C] global, T sharded over 'sequence'
    k: Array,  # [B, Hkv, T, C]
    v: Array,
    mesh: Mesh,
    *,
    axis_name: str = "sequence",
    batch_axes: tp.Tuple[str, ...] = ("replica", "fsdp"),
    head_axis: tp.Optional[str] = "tensor",
    use_flash: tp.Optional[bool] = None,
    schedule: str = "standard",
    dropout_rate: float = 0.0,
    dropout_seed: tp.Optional[Array] = None,
) -> Array:
    """Causal ring attention over the mesh. Differentiable (autodiff
    transposes the ppermute ring). T must divide by the axis size.

    use_flash: run each hop through the Pallas flash kernel (O(chunk)
    memory per hop — the true long-context path) instead of the naive
    chunk-pair math. None = auto: flash on TPU when the local chunk is
    lane-aligned.

    schedule: "standard" (device i = chunk i; devices with later chunks do
    up to S times the work of device 0) or "zigzag" (device i = chunk pair
    (i, 2S-1-i); every hop is constant work — ~2x faster at large S). The
    zigzag relayout runs INSIDE the shard_map as two half-chunk ppermutes
    each way (r4: the old global jnp.take lowered to a full-T all-gather
    of Q/K/V per device — caught by tests/test_hlo_collectives.py).

    Relayout cost, rationalized (r5, VERDICT r4 Weak #8): the in/out
    relayouts move 4 half-chunks per q/k/v/out array vs the ring's
    2(S-1) full-chunk K/V hops — ~2/(S-1) relative ICI traffic (29% at
    S=8, 13% at S=16), against ~2x better critical-path compute balance.
    Feeding data in zigzag order UPSTREAM would delete even that, but
    needs position-permuted RoPE tables and a permuted loss/target layout
    end to end through the train step — an invasive re-layout of every
    T-indexed surface for a shrinking benefit as S grows. Decision:
    keep the shard-local relayout; revisit only if a profile on real
    multi-chip hardware shows the 4 ppermutes on the critical path."""
    s = mesh.shape[axis_name]
    t = q.shape[2]
    assert t % s == 0, f"T={t} not divisible by sequence axis {s}"
    if dropout_rate > 0.0:
        assert dropout_seed is not None, "ring dropout needs dropout_seed"
        # zigzag chunks interleave two non-contiguous half-chunks, so a
        # single scalar (row, col) offset cannot anchor the in-kernel
        # hash; the standard schedule keeps chunks contiguous. Callers
        # (models/gpt.py) degrade zigzag -> standard when dropout is live
        # (dropout configs are the small shakespeare family — ring there
        # is a capability test, not a perf path).
        assert schedule == "standard", (
            "attention dropout under ring requires schedule='standard'"
        )
    if schedule == "zigzag":
        assert t % (2 * s) == 0, (
            f"zigzag needs T={t} divisible by 2*sequence ({2 * s})"
        )
    if use_flash is None:
        from midgpt_tpu.utils.platform import is_tpu_backend

        chunk = t // s if schedule == "standard" else t // (2 * s)
        # flash auto-picks a block dividing the chunk; 128 keeps a full
        # sublane-tile-aligned block available
        use_flash = is_tpu_backend() and chunk % 128 == 0

    from midgpt_tpu.parallel.sharding import fit_axes

    b_axes = fit_axes(mesh, q.shape[0], batch_axes)
    h_axes = fit_axes(mesh, k.shape[1], (head_axis,) if head_axis else ())
    spec = P(b_axes if b_axes else None, h_axes if h_axes else None, axis_name, None)

    if schedule == "zigzag":
        def zigzag_body(ql, kl, vl):
            ql = _zigzag_relayout_in(ql, axis_name, s)
            kl = _zigzag_relayout_in(kl, axis_name, s)
            vl = _zigzag_relayout_in(vl, axis_name, s)
            out = _zigzag_ring_body(
                ql, kl, vl, axis_name=axis_name, use_flash=use_flash
            )
            return _zigzag_relayout_out(out, axis_name, s)

        fn = shard_map(
            zigzag_body,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)

    assert schedule == "standard", f"unknown ring schedule {schedule!r}"
    if dropout_rate > 0.0:
        n_head_total = q.shape[1]  # GLOBAL q-head count (pre-shard_map)
        b_local = q.shape[0] // max(
            1, int(np.prod([mesh.shape[a] for a in b_axes]))
        )
        h_local = q.shape[1] // max(
            1, int(np.prod([mesh.shape[a] for a in h_axes]))
        )

        def drop_body(ql, kl, vl, sl):
            # flat shard index over the batch axes -> global batch offset;
            # same for the (q-)head axis. bh base = b_off*H_total + h_off.
            b_idx = jnp.int32(0)
            for a in b_axes:
                b_idx = b_idx * jnp.int32(mesh.shape[a]) + jax.lax.axis_index(a)
            h_idx = jnp.int32(0)
            for a in h_axes:
                h_idx = h_idx * jnp.int32(mesh.shape[a]) + jax.lax.axis_index(a)
            bh_off = (
                b_idx * jnp.int32(b_local) * jnp.int32(n_head_total)
                + h_idx * jnp.int32(h_local)
            )
            return _ring_body(
                ql, kl, vl, axis_name=axis_name, use_flash=use_flash,
                keep=1.0 - dropout_rate, seed=sl,
                bh_off=bh_off, n_head_total=n_head_total,
            )

        fn = shard_map(
            drop_body,
            mesh=mesh,
            in_specs=(spec, spec, spec, P()),
            out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v, jnp.asarray(dropout_seed, jnp.int32).reshape(()))
    fn = shard_map(
        functools.partial(
            _ring_body, axis_name=axis_name, use_flash=use_flash
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
