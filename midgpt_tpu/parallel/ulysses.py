"""All-to-all sequence parallelism (DeepSpeed-Ulysses style) — the second
context-parallel mechanism beside ring attention (parallel/ring.py).

Where the ring streams K/V chunks around the 'sequence' axis and merges
partial softmaxes, Ulysses TRADES the sharded axis: one ``all_to_all``
re-shards [B, H, T/S, C] -> [B, H/S, T, C] (heads scatter, sequence
gathers), each device then runs ordinary FULL-sequence causal attention
on its head group, and a second all_to_all restores the sequence-sharded
layout. Consequences, vs ring:

- attention math is the plain single-device kernel — no streaming-LSE
  merge, no per-hop scheduling; the flash kernel (and its in-kernel
  dropout, anchored at global (row, col, batch*H+head) coordinates via
  ops/flash._seed_vec) applies unchanged, so DROPOUT IS EXACT here with
  no schedule restrictions (ring degrades zigzag -> standard for it);
- communication is 2 all-to-alls of the full activations per call
  (O(B*H*T*C/S) per device) instead of (S-1) K/V chunk hops — cheaper
  for moderate S on all-to-all-friendly interconnects, but per-device
  attention memory is O(T) (the full sequence), so the EXTREME-context
  regime (T too big for one device even at H/S heads) still needs ring;
- requires H (and Hkv, for GQA) divisible by S.

Differentiable end to end: ``lax.all_to_all``'s transpose is the reverse
all_to_all, so autodiff derives the backward schedule. Absent from the
reference (SURVEY.md 5.7: full T everywhere); SNIPPETS/PAPERS document
the public Ulysses recipe this follows.
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.compat import shard_map

Array = jax.Array


def _local_attention(
    q: Array, k: Array, v: Array,
    use_flash: bool,
    keep: tp.Optional[float],
    seed,
    bh_off,
    n_head_total: tp.Optional[int],
) -> Array:
    """Full-sequence causal attention on the local head group."""
    if use_flash:
        if keep is not None:
            from midgpt_tpu.ops.flash import flash_attention_dropout_lse

            out, _ = flash_attention_dropout_lse(
                q, k, v, seed, 1.0 - keep, True,
                bh_off=bh_off, n_head_total=n_head_total,
            )
            return out
        from midgpt_tpu.ops.flash import flash_attention

        return flash_attention(q, k, v, causal=True)
    if keep is not None:
        # naive oracle with the kernels' counter-hash mask at global
        # (batch*H+head) coordinates — mirrors ring._chunk_attention
        import math

        from midgpt_tpu.ops.flash import _hash_finalize, _wrap32

        b, h, t, c = q.shape
        hkv = k.shape[1]
        groups = h // hkv
        qg = q.reshape(b, hkv, groups, t, c)
        z = jnp.einsum(
            "bkgqc,bkjc->bkgqj", qg, k, preferred_element_type=jnp.float32
        ) / math.sqrt(c)
        causal = jnp.tril(jnp.ones((t, t), bool))
        z = jnp.where(causal, z, -1e30)
        p = jax.nn.softmax(z, axis=-1)
        rows = jnp.arange(t, dtype=jnp.int32)
        x = (
            rows[:, None] * _wrap32(0x9E3779B1)
            + rows[None, :] * _wrap32(0x85EBCA77)
        )
        nh = jnp.int32(n_head_total or h)
        head_ids = (
            jnp.asarray(bh_off, jnp.int32)
            + jnp.arange(b, dtype=jnp.int32).reshape(b, 1, 1) * nh
            + jnp.arange(h, dtype=jnp.int32).reshape(1, hkv, groups)
        )
        hx = x[None, None, None] ^ (
            jnp.asarray(seed, jnp.int32).reshape(())
            + head_ids[..., None, None] * _wrap32(0xC2B2AE35)
        )
        u24 = _hash_finalize(hx) & jnp.int32(0x00FFFFFF)
        mask = u24 < jnp.int32(int(keep * (1 << 24)))
        p = jnp.where(mask, p * (1.0 / keep), 0.0)
        out = jnp.einsum("bkgqj,bkjc->bkgqc", p.astype(v.dtype), v)
        return out.reshape(b, h, t, c)
    from midgpt_tpu.ops.attention import naive_attention

    return naive_attention(q, k, v, causal=True)


def ulysses_attention(
    q: Array,  # [B, H, T, C] global, T sharded over 'sequence'
    k: Array,  # [B, Hkv, T, C]
    v: Array,
    mesh: Mesh,
    *,
    axis_name: str = "sequence",
    batch_axes: tp.Tuple[str, ...] = ("replica", "fsdp"),
    use_flash: tp.Optional[bool] = None,
    dropout_rate: float = 0.0,
    dropout_seed: tp.Optional[Array] = None,
) -> Array:
    """Causal attention with T sharded over ``axis_name`` via head/sequence
    all-to-alls. Requires H % S == 0 and Hkv % S == 0 (GQA) and T % S == 0.
    TP composition is out of scope v1 (the head groups the all_to_all
    forms would collide with a 'tensor' head sharding) — callers gate on
    tensor == 1 (models/gpt.py)."""
    s = mesh.shape[axis_name]
    b, h, t, c = q.shape
    hkv = k.shape[1]
    assert t % s == 0, f"T={t} not divisible by sequence axis {s}"
    assert h % s == 0 and hkv % s == 0, (
        f"ulysses needs head counts divisible by the sequence axis "
        f"(H={h}, Hkv={hkv}, S={s}); use attn_impl='ring' otherwise"
    )
    assert mesh.shape.get("tensor", 1) == 1, (
        "ulysses + tensor parallelism is unsupported (v1); use ring"
    )
    if use_flash is None:
        from midgpt_tpu.utils.platform import is_tpu_backend

        use_flash = is_tpu_backend() and t >= 128 and t % 128 == 0
    if dropout_rate > 0.0:
        assert dropout_seed is not None, "ulysses dropout needs dropout_seed"

    from midgpt_tpu.parallel.sharding import fit_axes

    b_axes = fit_axes(mesh, b, batch_axes)
    spec = P(b_axes if b_axes else None, None, axis_name, None)
    b_shards = 1
    for a in b_axes:
        b_shards *= mesh.shape[a]
    b_local = b // b_shards

    keep = None if dropout_rate == 0.0 else 1.0 - dropout_rate

    def body(ql, kl, vl, sl):
        # [B_l, H, T/S, C] -> heads scatter / sequence gather
        qh = jax.lax.all_to_all(
            ql, axis_name, split_axis=1, concat_axis=2, tiled=True
        )  # [B_l, H/S, T, C]
        kh = jax.lax.all_to_all(
            kl, axis_name, split_axis=1, concat_axis=2, tiled=True
        )
        vh = jax.lax.all_to_all(
            vl, axis_name, split_axis=1, concat_axis=2, tiled=True
        )
        bh_off = None
        if keep is not None:
            # global flat batch*H + head of this device's (0, 0): batch
            # offset from the batch shards, head offset from the sequence
            # shard's head group
            b_idx = jnp.int32(0)
            for a in b_axes:
                b_idx = b_idx * jnp.int32(mesh.shape[a]) + jax.lax.axis_index(a)
            seq_idx = jax.lax.axis_index(axis_name)
            bh_off = (
                b_idx * jnp.int32(b_local) * jnp.int32(h)
                + seq_idx * jnp.int32(h // s)
            )
        out = _local_attention(
            qh, kh, vh, use_flash, keep, sl, bh_off, n_head_total=h
        )
        # inverse: sequence scatter / heads gather
        return jax.lax.all_to_all(
            out, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    seed = (
        jnp.asarray(dropout_seed, jnp.int32).reshape(())
        if dropout_seed is not None
        else jnp.zeros((), jnp.int32)
    )
    manual = set(b_axes) | {axis_name}
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, P()),
        out_specs=spec,
        axis_names=manual,
        check_vma=False,
    )
    return fn(q, k, v, seed)
