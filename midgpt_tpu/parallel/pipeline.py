"""Pipeline parallelism: GPipe-style microbatch streaming over a
``'pipeline'`` mesh axis.

Absent from the reference (SURVEY.md 2.6: no PP anywhere); provided here as
the TPU-native building block. Design (idiomatic JAX, no hand-scheduled
backward):

- Layer-stacked params (leading ``[L, ...]`` axis, the same layout the
  scan-over-layers model already uses) are split into S contiguous stage
  chunks; inside ``jax.shard_map`` each device along the ``'pipeline'``
  axis holds ``L/S`` layers.
- Microbatches stream through the ring: one ``lax.scan`` over
  ``M + S - 1`` ticks; every tick each stage runs its layer chunk on its
  current activation and hands the result to the next stage with
  ``lax.ppermute`` (neighbor-only ICI traffic). Stage 0 injects a fresh
  microbatch per tick; the last stage banks its outputs.
- The backward pass is DERIVED BY AD: ppermute's transpose is the reverse
  permute, scan's transpose runs the ticks backwards — exactly the
  reverse-schedule GPipe backward, with whole-stage rematerialization via
  ``jax.checkpoint`` around the stage body.
- The (S-1)-tick bubble is the standard GPipe cost: utilization
  M / (M + S - 1); choose M >= 4*S to keep it small.

This module is schedule-complete and differentiable; wiring it into the
GPT trainer (embedding/head placement, composing with the fsdp/tensor
axes via partial-auto shard_map) is the integration step tracked in
SURVEY.md §7 stage extensions.
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Array = jax.Array

StageFn = tp.Callable[[tp.Any, Array], Array]
"""(stage_params, activation [Bm, ...]) -> activation [Bm, ...]; applies
one stage's worth of layers (e.g. a lax.scan over the local layer chunk)."""


def pipeline_forward(
    stacked_params: tp.Any,  # pytree, every leaf [L, ...]
    x: Array,  # [M, Bm, ...] microbatched input activations
    stage_fn: StageFn,
    mesh: Mesh,
    *,
    axis: str = "pipeline",
    remat: bool = True,
) -> Array:
    """Run ``x`` through all L layers, pipelined over the ``axis`` stages.

    Returns [M, Bm, ...] outputs (same sharding layout as ``x``).
    """
    n_stages = mesh.shape[axis]
    m = x.shape[0]
    leaves = jax.tree.leaves(stacked_params)
    assert leaves, "stacked_params must contain at least one array"
    n_layer = leaves[0].shape[0]
    assert n_layer % n_stages == 0, (
        f"n_layer {n_layer} not divisible by {n_stages} pipeline stages"
    )

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    def per_stage(params_local, x_local):
        # params_local leaves: [L/S, ...] (shard_map strips the stage dim)
        # x_local: [M, Bm, ...] (replicated across the pipeline axis)
        s_idx = jax.lax.axis_index(axis)
        n_ticks = m + n_stages - 1
        zero_act = jnp.zeros_like(x_local[0])

        def tick(carry, t):
            recv, outputs = carry
            # stage 0 pulls microbatch t (clamped; masked off after M)
            mb = jax.lax.dynamic_index_in_dim(
                x_local, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
            )
            in_act = jnp.where(s_idx == 0, mb, recv)
            # active window for this stage: t in [s_idx, s_idx + M)
            active = jnp.logical_and(t >= s_idx, t < s_idx + m)
            out_act = body(params_local, in_act)
            out_act = jnp.where(active, out_act, zero_act)
            # bank the last stage's finished microbatch (m_done = t - (S-1));
            # non-banking ticks write back the existing slot unchanged
            m_done = t - (n_stages - 1)
            is_last = s_idx == n_stages - 1
            do_bank = jnp.logical_and(is_last, m_done >= 0)
            slot = jnp.clip(m_done, 0, m - 1)
            prev = jax.lax.dynamic_index_in_dim(
                outputs, slot, axis=0, keepdims=False
            )
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(do_bank, out_act, prev), slot, axis=0
            )
            # hand activations to the next stage (ring; last->0 edge is
            # ignored because stage 0 reads the fresh microbatch instead)
            sent = jax.lax.ppermute(
                out_act,
                axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            return (sent, outputs), None

        outputs0 = jnp.zeros((m,) + x_local.shape[1:], x_local.dtype)
        (_, outputs), _ = jax.lax.scan(
            tick, (zero_act, outputs0), jnp.arange(n_ticks)
        )
        # only the last stage holds real outputs; share them around the ring
        outputs = jax.lax.psum(
            jnp.where(s_idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis,
        )
        return outputs

    in_specs = (
        jax.tree.map(lambda _: P(axis), stacked_params),  # stage dim = leading
        P(),  # input replicated over the pipeline axis
    )
    return jax.shard_map(
        per_stage,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x)


def stage_scan_fn(block_fn: tp.Callable[[tp.Any, Array], Array]) -> StageFn:
    """Lift a single-layer ``block_fn(params_1layer, x) -> x`` into a
    StageFn that scans over the stage's local layer chunk — the same
    scan-over-layers pattern the full model uses (models/gpt.py)."""

    def stage(params_local, x):
        def body(h, layer_params):
            return block_fn(layer_params, h), None

        out, _ = jax.lax.scan(body, x, params_local)
        return out

    return stage
