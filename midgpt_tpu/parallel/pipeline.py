"""Pipeline parallelism: GPipe-style microbatch streaming over a
``'pipeline'`` mesh axis.

Absent from the reference (SURVEY.md 2.6: no PP anywhere); provided here as
the TPU-native building block. Design (idiomatic JAX, no hand-scheduled
backward):

- Layer-stacked params (leading ``[L, ...]`` axis, the same layout the
  scan-over-layers model already uses) are split into S contiguous stage
  chunks; inside ``jax.shard_map`` each device along the ``'pipeline'``
  axis holds ``L/S`` layers.
- Microbatches stream through the ring: one ``lax.scan`` over
  ``M + S - 1`` ticks; every tick each stage runs its layer chunk on its
  current activation and hands the result to the next stage with
  ``lax.ppermute`` (neighbor-only ICI traffic). Stage 0 injects a fresh
  microbatch per tick; the last stage banks its outputs.
- The backward pass is DERIVED BY AD: ppermute's transpose is the reverse
  permute, scan's transpose runs the ticks backwards — exactly the
  reverse-schedule GPipe backward, with whole-stage rematerialization via
  ``jax.checkpoint`` around the stage body.
- The (S-1)-tick bubble is the standard GPipe cost: utilization
  M / (M + S - 1); choose M >= 4*S to keep it small.

This module is schedule-complete and differentiable; wiring it into the
GPT trainer (embedding/head placement, composing with the fsdp/tensor
axes via partial-auto shard_map) is the integration step tracked in
SURVEY.md §7 stage extensions.
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.compat import shard_map

Array = jax.Array


def _to_varying(x: Array, axis: str) -> Array:
    """Promote ``x`` to VARYING along the mesh axis. ``jax.lax.pcast``
    replaced ``pvary`` in newer JAX; jax before ~0.5 has neither (the
    varying-manual-axes annotation didn't exist yet), and there the
    promotion is a value-level no-op — identity keeps old pins working."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis)
    return x

StageFn = tp.Callable[..., Array]
"""(stage_params, activation [Bm, ...][, keys [L/S, 2]]) -> activation
[Bm, ...]; applies one stage's worth of layers (e.g. a lax.scan over the
local layer chunk). The keys argument is passed iff ``keys`` was given to
pipeline_forward (per-layer dropout keys for the current microbatch)."""


def pipeline_forward(
    stacked_params: tp.Any,  # pytree, every leaf [L, ...]
    x: Array,  # [M, Bm, ...] microbatched input activations
    stage_fn: StageFn,
    mesh: Mesh,
    *,
    keys: tp.Optional[Array] = None,  # [L, M, 2] uint32 per-layer/microbatch
    axis: str = "pipeline",
    remat: bool = True,
    check_vma: bool = True,
) -> Array:
    """Run ``x`` through all L layers, pipelined over the ``axis`` stages.

    Returns [M, Bm, ...] outputs (same sharding layout as ``x``).

    ``keys`` threads dropout through the tick schedule: raw uint32
    [L, M, 2] key data, split over stages on the layer axis exactly like
    the params; at tick t, stage s slices the keys of the microbatch it is
    processing (m = t - s) and hands its [L/S, 2] slab to stage_fn.
    """
    n_stages = mesh.shape[axis]
    m = x.shape[0]
    leaves = jax.tree.leaves(stacked_params)
    assert leaves, "stacked_params must contain at least one array"
    n_layer = leaves[0].shape[0]
    assert n_layer % n_stages == 0, (
        f"n_layer {n_layer} not divisible by {n_stages} pipeline stages"
    )

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    def per_stage(params_local, x_local, keys_local):
        # params_local leaves: [L/S, ...] (shard_map strips the stage dim)
        # x_local: [M, Bm, ...] (replicated across the pipeline axis).
        # Everything entering the tick carry is promoted to pipeline-VARYING
        # (pcast to='varying'): the carry mixes per-stage values (ppermute output, banked
        # activations) with broadcast inputs, and an invariant/varying mix in
        # a scan carry is unsound — it surfaced as an XLA miscompile
        # ("Invalid binary instruction opcode copy") under check_vma=False.
        x_local = _to_varying(x_local, axis)
        s_idx = jax.lax.axis_index(axis)
        n_ticks = m + n_stages - 1
        zero_act = jnp.zeros_like(x_local[0])

        def tick(carry, t):
            recv, outputs = carry
            # stage 0 pulls microbatch t (clamped; masked off after M)
            mb = jax.lax.dynamic_index_in_dim(
                x_local, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
            )
            in_act = jnp.where(s_idx == 0, mb, recv)
            # active window for this stage: t in [s_idx, s_idx + M)
            active = jnp.logical_and(t >= s_idx, t < s_idx + m)
            if keys_local is None:
                out_act = body(params_local, in_act)
            else:
                # this stage is working on microbatch t - s_idx (clamped
                # on inactive ticks, whose output is masked anyway)
                k_mb = jax.lax.dynamic_index_in_dim(
                    keys_local, jnp.clip(t - s_idx, 0, m - 1),
                    axis=1, keepdims=False,
                )  # [L/S, 2]
                out_act = body(params_local, in_act, k_mb)
            out_act = jnp.where(active, out_act, zero_act)
            # bank the last stage's finished microbatch (m_done = t - (S-1));
            # non-banking ticks write back the existing slot unchanged
            m_done = t - (n_stages - 1)
            is_last = s_idx == n_stages - 1
            do_bank = jnp.logical_and(is_last, m_done >= 0)
            slot = jnp.clip(m_done, 0, m - 1)
            prev = jax.lax.dynamic_index_in_dim(
                outputs, slot, axis=0, keepdims=False
            )
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(do_bank, out_act, prev), slot, axis=0
            )
            # hand activations to the next stage (ring; last->0 edge is
            # ignored because stage 0 reads the fresh microbatch instead)
            sent = jax.lax.ppermute(
                out_act,
                axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            return (sent, outputs), None

        outputs0 = _to_varying(
            jnp.zeros((m,) + x_local.shape[1:], x_local.dtype), axis
        )
        (_, outputs), _ = jax.lax.scan(
            tick, (zero_act, outputs0), jnp.arange(n_ticks)
        )
        # only the last stage holds real outputs; share them around the ring
        outputs = jax.lax.psum(
            jnp.where(s_idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis,
        )
        return outputs

    in_specs = (
        jax.tree.map(lambda _: P(axis), stacked_params),  # stage dim = leading
        P(),  # input replicated over the pipeline axis
        P(axis) if keys is not None else P(),  # keys split like the params
    )
    # partial-auto: only the pipeline axis is manual; any other mesh axes
    # (replica/fsdp/sequence/tensor) stay under GSPMD, so PP composes with
    # the data/tensor shardings of the surrounding train step
    return shard_map(
        per_stage,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        axis_names={axis},
        check_vma=check_vma,
    )(stacked_params, x, keys)


def stage_scan_fn(block_fn: tp.Callable[[tp.Any, Array], Array]) -> StageFn:
    """Lift a single-layer ``block_fn(params_1layer, x) -> x`` into a
    StageFn that scans over the stage's local layer chunk — the same
    scan-over-layers pattern the full model uses (models/gpt.py)."""

    def stage(params_local, x):
        def body(h, layer_params):
            return block_fn(layer_params, h), None

        out, _ = jax.lax.scan(body, x, params_local)
        return out

    return stage


def gpt_pipeline_hidden(
    model,  # midgpt_tpu.models.gpt.GPT
    tokens: Array,  # [B, T] int32
    mesh: Mesh,
    *,
    n_micro: int = 0,
    axis: str = "pipeline",
    key: tp.Optional[Array] = None,
    deterministic: bool = True,
    boundary_dtype: tp.Optional[str] = None,
) -> Array:
    """GPT forward with the block stack pipelined over ``axis``.

    The integration split (SURVEY.md 2.6 PP row): embedding runs BEFORE the
    pipeline and ln_f/lm-head AFTER it, as ordinary GSPMD ops over the full
    mesh — the natural TPU placement of the reference's stage-0-embedding /
    stage-(S-1)-head convention, since wte/ln_f/head params are not
    layer-stacked and GSPMD already shards them (fsdp/tensor). Only the
    ``blocks`` stack (leaves ``[L, ...]``, L/S layers per stage) enters
    the shard_map, which is manual ONLY over the pipeline axis — data /
    tensor sharding of the activations stays with GSPMD (partial-auto).

    Dropout threads through the tick schedule: per-(layer, microbatch)
    keys ride the stage shard_map next to the params (pipeline_forward's
    ``keys``), so dropout configs train under PP too (r3 left this
    deterministic-only). Returns ln_f-normalized hidden [B, T, D]."""
    from midgpt_tpu.models.gpt import embed_tokens
    from midgpt_tpu.models.layers import dropout as dropout_fn, rope_tables
    from midgpt_tpu.parallel.sharding import axis_rules, shard_act

    cfg = model.config
    assert cfg.attn_impl not in ("ring", "ulysses"), (
        "sequence-parallel attention (ring/ulysses) inside pipeline stages "
        "is unsupported (the sequence axis is invisible inside the "
        "pipeline's manual region)"
    )
    b, t = tokens.shape
    s = mesh.shape[axis]
    if n_micro:
        m = n_micro
        assert b % m == 0, f"batch {b} not divisible by {m} microbatches"
    else:
        # auto: aim for 2 microbatches per stage (bubble (S-1)/(M+S-1)),
        # clamped to the largest divisor of the batch — grad-accumulation
        # microsteps can hand this a batch smaller than 2*S
        m = min(2 * s, b)
        while b % m:
            m -= 1
        if m < s:
            import warnings

            warnings.warn(
                f"pipeline auto-microbatching degraded to {m} microbatches "
                f"for batch {b} over {s} stages (bubble "
                f"{(s - 1) / (m + s - 1):.0%}); pick a batch divisible by "
                f"2*pipeline or set MeshConfig.pp_microbatches",
                stacklevel=2,
            )
    sin, cos = rope_tables(cfg.head_dim, t, cfg.rope_base)
    impl = cfg.attn_impl
    has_dropout = cfg.dropout > 0.0 and not deterministic and key is not None

    drop_key, block_key = (
        jax.random.split(key) if has_dropout else (None, None)
    )
    h = embed_tokens(model.wte, tokens)  # [B, T, D]
    h = dropout_fn(h, cfg.dropout, drop_key, not has_dropout)
    h = shard_act(h, "batch", "seq", "embed")
    compute_dtype = h.dtype
    # activations cross the shard_map boundary (and the inter-stage
    # ppermutes) in float32 by default: a bf16 manual-boundary all-reduce
    # crashes XLA CPU's AllReducePromotion pass on the current pin
    # ("Invalid binary instruction opcode copy" — re-confirmed r4; the
    # same bug bit the chunked-loss shard_map, ops/loss.py). The pass is
    # CPU-backend-side, so MeshConfig.pp_boundary_dtype="bfloat16" is
    # worth trying on real TPU hardware (halves ppermute bytes).
    if boundary_dtype is not None:
        bdtype = jnp.dtype(boundary_dtype)
    else:
        bdtype = jnp.float32 if compute_dtype == jnp.bfloat16 else compute_dtype
    h = h.astype(bdtype).reshape(m, b // m, t, cfg.n_embd)

    keys = None
    if has_dropout:
        n_layer = cfg.n_layer
        keys = jax.random.split(block_key, n_layer * m).reshape(n_layer, m, 2)

    def stage_fn(params_local, x, *stage_keys):
        # one cast per stage boundary, not per layer; no activation-sharding
        # constraints inside the manual region (the pipeline axis is
        # invisible to GSPMD there; auto axes keep the inputs' shardings)
        with axis_rules(None):
            if stage_keys:
                def body(hh, layer):
                    bp, k_l = layer
                    return bp(
                        hh, sin, cos, impl=impl, key=k_l, deterministic=False
                    ), None

                y, _ = jax.lax.scan(
                    body, x.astype(compute_dtype),
                    (params_local, stage_keys[0]),
                )
            else:
                def body(hh, bp):
                    return bp(hh, sin, cos, impl=impl, deterministic=True), None

                y, _ = jax.lax.scan(body, x.astype(compute_dtype), params_local)
        return y.astype(bdtype)

    out = pipeline_forward(
        model.blocks, h, stage_fn, mesh, keys=keys, axis=axis
    )
    h = out.reshape(b, t, cfg.n_embd).astype(compute_dtype)
    h = shard_act(h, "batch", "seq", "embed")
    return model.ln_f(h)
