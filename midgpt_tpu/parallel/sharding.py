"""Declarative sharding: logical-axis activation constraints + a param
partition-rule table.

Replaces the reference's size-gated last-dim heuristic ``shard_gpt``
(/root/reference/src/model.py:167-178) and the big_vision ``reshard`` /
``get_shard_fn`` host glue (/root/reference/src/sharding.py), redesigned:

- Activations: model code tags intermediate arrays with *logical* axis names
  (``shard_act(x, 'batch', 'seq', 'embed')``); a context-scoped rule table
  maps logical names to mesh axes. No mesh leaks into model code.
- Parameters: a list of ``(path-regex, PartitionSpec)`` rules resolved
  against pytree paths gives every param an explicit NamedSharding —
  FSDP x TP is a rule-table entry, not a size heuristic.
- Host->device feed: ``make_global_array`` assembles per-process batches
  into one global jax.Array (parity: sharding.py:33-42).
"""

from __future__ import annotations

import re
import threading
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from midgpt_tpu.pytree import tree_paths

Array = jax.Array

# logical axis name -> mesh axis (str | tuple | None)
LogicalRules = tp.Mapping[str, tp.Union[str, tp.Tuple[str, ...], None]]

# Default logical->mesh mapping. 'batch' shards over both DP axes (the
# reference sharded batch over ('replica', 'data'), train.py:105).
DEFAULT_LOGICAL_RULES: LogicalRules = {
    "batch": ("replica", "fsdp"),
    "seq": "sequence",
    "embed": None,
    "heads": "tensor",
    "kv_heads": "tensor",
    "head_dim": None,
    "mlp": "tensor",
    "expert": "tensor",  # MoE expert-parallel axis (models/gpt.MoEMLP)
    "vocab": "tensor",
    "layers": None,
    # sequence-parallel row axis of the serving SP prefill program
    # (models/gpt.prefill_chunk_paged sp=True): unmapped by default —
    # only serving_logical_rules(prefill_sp="on") binds it to 'tensor',
    # so training paths and every other serving program never see it
    "sp": None,
}


class _ShardingContext(threading.local):
    def __init__(self):
        self.mesh: tp.Optional[Mesh] = None
        self.rules: tp.Optional[LogicalRules] = None


_CTX = _ShardingContext()


class axis_rules:
    """Context manager activating activation-sharding constraints.

    with axis_rules(mesh): ... # default rules
    with axis_rules(mesh, rules): ...
    with axis_rules(None): ... # explicit no-op scope
    """

    def __init__(self, mesh: tp.Optional[Mesh], rules: tp.Optional[LogicalRules] = None):
        self._new = (mesh, dict(rules) if rules is not None else dict(DEFAULT_LOGICAL_RULES))

    def __enter__(self):
        self._old = (_CTX.mesh, _CTX.rules)
        _CTX.mesh, _CTX.rules = self._new
        return self

    def __exit__(self, *exc):
        _CTX.mesh, _CTX.rules = self._old
        return False


def current_mesh() -> tp.Optional[Mesh]:
    """Mesh of the innermost active ``axis_rules`` scope, if any."""
    return _CTX.mesh


def logical_to_spec(logical_axes: tp.Sequence[tp.Optional[str]],
                    rules: tp.Optional[LogicalRules] = None) -> P:
    if rules is None:
        rules = _CTX.rules if _CTX.rules is not None else DEFAULT_LOGICAL_RULES
    for a in logical_axes:
        assert a is None or a in rules, (
            f"unknown logical axis {a!r}; rule table has {sorted(rules)}"
        )
    return P(*[rules.get(a) if a is not None else None for a in logical_axes])


def fit_axes(mesh, dim: int, axes) -> tp.Tuple[str, ...]:
    """Longest prefix of ``axes`` whose product divides ``dim`` — how the
    SP wrappers (ring/ulysses) decide which mesh axes actually shard a
    batch/head dimension."""
    kept = []
    prod = 1
    for a in axes:
        if dim % (prod * mesh.shape[a]) == 0:
            kept.append(a)
            prod *= mesh.shape[a]
    return tuple(kept)


def shard_act(x: Array, *logical_axes: tp.Optional[str]) -> Array:
    """Constrain an activation's sharding by logical axis names.

    No-op outside an ``axis_rules`` scope (single-device tests, sampling).
    """
    if _CTX.mesh is None:
        return x
    assert len(logical_axes) == x.ndim, (
        f"{len(logical_axes)} axes for rank-{x.ndim} array"
    )
    spec = logical_to_spec(logical_axes)
    return jax.lax.with_sharding_constraint(x, NamedSharding(_CTX.mesh, spec))


# ---------------------------------------------------------------------------
# Parameter partition rules
# ---------------------------------------------------------------------------

ParamRules = tp.Sequence[tp.Tuple[str, P]]


def match_param_spec(path: str, rules: ParamRules) -> P:
    """First rule whose regex matches (re.search) wins; default replicated."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return P()


def param_shardings(mesh: Mesh, tree: tp.Any, rules: ParamRules) -> tp.Any:
    """Pytree of NamedShardings matching ``tree``, resolved from ``rules``.

    Specs may have fewer entries than the array rank; they are right-padded
    with None (replicated leading axes) — this is how one rule covers both a
    stacked ``[L, D, F]`` scan param and an unstacked ``[D, F]`` one.
    """
    paths = tree_paths(tree)
    shardings = []
    for path, leaf in paths:
        spec = match_param_spec(path, rules)
        ndim = getattr(leaf, "ndim", 0)
        entries = list(spec)
        assert len(entries) <= ndim, (
            f"spec {spec} has more axes than rank-{ndim} param at {path}"
        )
        entries = [None] * (ndim - len(entries)) + entries
        shardings.append(NamedSharding(mesh, P(*entries)))
    treedef = jax.tree_util.tree_structure(tree)
    return jax.tree_util.tree_unflatten(treedef, shardings)


def constrain_params(tree: tp.Any, mesh: Mesh, rules: ParamRules) -> tp.Any:
    """with_sharding_constraint over a whole param tree (used inside jit on
    grads so accumulated grads stay sharded — parity: train.py:87)."""
    shardings = param_shardings(mesh, tree, rules)
    return jax.tree.map(jax.lax.with_sharding_constraint, tree, shardings)


# ---------------------------------------------------------------------------
# Host <-> device glue (multi-process data feed)
# ---------------------------------------------------------------------------


def make_global_array(
    local_batch: np.ndarray, mesh: Mesh, spec: P
) -> Array:
    """Assemble per-process host batches into one global jax.Array.

    Parity: /root/reference/src/sharding.py:33-42 (get_shard_fn), generalized
    to any PartitionSpec: each process holds 1/num_processes of the global
    batch along axis 0; jax.make_array_from_process_local_data computes the
    local->global mapping from the sharding itself.
    """
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, local_batch)


def replicate(tree: tp.Any, mesh: Mesh) -> tp.Any:
    """Fully replicate host-side leaves onto the mesh (parity:
    sharding.py:15-30 reshard with replicated sharding)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x), sharding), tree
    )
