"""Device mesh construction.

Replaces the reference's hardcoded ``(n_devices // 8, 8)`` 2-D mesh
(/root/reference/src/train.py:130) with an explicit 4-axis mesh
``('pipeline', 'replica', 'fsdp', 'sequence', 'tensor')`` sized from
``MeshConfig``.

- Single slice: ``mesh_utils.create_device_mesh`` lays axes out so the
  innermost (tensor) axis rides the fastest ICI links.
- Multi-slice (num_slices > 1): ``create_hybrid_device_mesh`` puts the
  outermost axes (replica) across DCN and the rest within each slice's ICI
  domain — DP-only over DCN per SURVEY.md 2.6.
"""

from __future__ import annotations

import typing as tp

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from midgpt_tpu.config import MeshConfig

AXIS_NAMES = ("pipeline", "replica", "fsdp", "sequence", "tensor")

# mesh axes a global batch is sharded over (data-parallel axes)
BATCH_AXES = ("replica", "fsdp")


def group_by_slice(
    devices: tp.Sequence, num_slices: int
) -> tp.List[tp.List]:
    """Partition devices into per-slice groups.

    Real multi-slice TPU devices carry a ``slice_index`` attribute (that is
    what ``create_hybrid_device_mesh`` keys on); grouped by it when present
    and consistent with ``num_slices``. Simulated devices (CPU, or a
    single-slice testbed standing in for N slices) have no slice_index —
    they are partitioned contiguously by listing order, which preserves the
    invariant the layout needs: each group is one DCN domain."""
    n = len(devices)
    assert n % num_slices == 0, f"{n} devices not divisible by {num_slices} slices"
    idx = {getattr(d, "slice_index", None) for d in devices}
    if None not in idx and len(idx) > 1:
        # real multi-slice DCN topology: the config MUST match it — silently
        # splitting contiguously would place ICI axes across a DCN boundary.
        # (len(idx) == 1 — all devices in one physical slice — falls through
        # to the contiguous split: that's the single-slice testbed standing
        # in for N slices.)
        assert len(idx) == num_slices, (
            f"devices report {len(idx)} physical slices {sorted(idx)} but "
            f"num_slices={num_slices}; set MeshConfig.num_slices to the "
            f"actual slice count"
        )
        groups: tp.Dict[int, tp.List] = {i: [] for i in sorted(idx)}
        for d in devices:
            groups[d.slice_index].append(d)
        out = [groups[i] for i in sorted(groups)]
        assert all(len(g) == n // num_slices for g in out), (
            f"uneven slices: {[len(g) for g in out]}"
        )
        return out
    per = n // num_slices
    return [list(devices[i * per : (i + 1) * per]) for i in range(num_slices)]


def hybrid_device_layout(
    devices: tp.Sequence, sizes: tp.Tuple[int, ...], num_slices: int
) -> np.ndarray:
    """Pure hybrid ICI/DCN mesh layout (testable without DCN hardware).

    Places the slice (DCN) dimension on the OUTERMOST positions of the
    replica axis and each slice's devices contiguously in the inner
    (fsdp, sequence, tensor) ICI axes — so only the leading ``num_slices``
    factor of 'replica' ever crosses DCN, matching the DP-only-over-DCN
    design (SURVEY.md 2.6) that ``create_hybrid_device_mesh`` produces on
    real hardware."""
    p, r, f, s, t = sizes
    assert p == 1, (
        f"pipeline axis must stay within a slice (got pipeline={p} with "
        f"num_slices={num_slices}); ppermute over DCN would serialize hops"
    )
    assert r % num_slices == 0, (
        f"replica axis {r} must be a multiple of num_slices {num_slices} "
        f"(DP-only over DCN)"
    )
    groups = group_by_slice(devices, num_slices)
    arr = np.empty((num_slices, r // num_slices, f, s, t), dtype=object)
    for i, g in enumerate(groups):
        arr[i] = np.asarray(g, dtype=object).reshape(r // num_slices, f, s, t)
    return arr.reshape(sizes)


def create_mesh(
    cfg: MeshConfig, devices: tp.Optional[tp.Sequence[jax.Device]] = None
) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    sizes = cfg.sizes(len(devices))

    if cfg.num_slices > 1:
        assert sizes[1] % cfg.num_slices == 0, (
            f"replica axis {sizes[1]} must be a multiple of num_slices "
            f"{cfg.num_slices} (DP-only over DCN)"
        )
        has_dcn = all(
            getattr(d, "slice_index", None) is not None for d in devices
        ) and len({d.slice_index for d in devices}) == cfg.num_slices
        if has_dcn:
            dcn_parallelism = (1, cfg.num_slices, 1, 1, 1)
            ici_parallelism = (
                sizes[0], sizes[1] // cfg.num_slices,
            ) + sizes[2:]
            device_array = mesh_utils.create_hybrid_device_mesh(
                ici_parallelism,
                dcn_parallelism,
                devices=devices,
                allow_split_physical_axes=True,
            )
        else:
            # simulated slices (CPU mesh / single-slice testbed): same
            # axis-split contract via the pure layout above
            device_array = hybrid_device_layout(devices, sizes, cfg.num_slices)
    else:
        try:
            device_array = mesh_utils.create_device_mesh(
                sizes, devices=devices, allow_split_physical_axes=True
            )
        except (ValueError, AssertionError, NotImplementedError):
            # CPU-simulated or irregular topologies: plain reshape
            device_array = np.asarray(devices).reshape(sizes)

    return Mesh(device_array, AXIS_NAMES)


def single_device_mesh(device: tp.Optional[jax.Device] = None) -> Mesh:
    """Degenerate 1-device mesh (all axes size 1) so the same sharded code
    path runs on one chip or CPU."""
    device = device if device is not None else jax.devices()[0]
    return Mesh(np.asarray([device]).reshape(1, 1, 1, 1, 1), AXIS_NAMES)
