"""Device mesh construction.

Replaces the reference's hardcoded ``(n_devices // 8, 8)`` 2-D mesh
(/root/reference/src/train.py:130) with an explicit 4-axis mesh
``('replica', 'fsdp', 'sequence', 'tensor')`` sized from ``MeshConfig``.

- Single slice: ``mesh_utils.create_device_mesh`` lays axes out so the
  innermost (tensor) axis rides the fastest ICI links.
- Multi-slice (num_slices > 1): ``create_hybrid_device_mesh`` puts the
  outermost axes (replica) across DCN and the rest within each slice's ICI
  domain — DP-only over DCN per SURVEY.md 2.6.
"""

from __future__ import annotations

import typing as tp

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from midgpt_tpu.config import MeshConfig

AXIS_NAMES = ("replica", "fsdp", "sequence", "tensor")

# mesh axes a global batch is sharded over (data-parallel axes)
BATCH_AXES = ("replica", "fsdp")


def create_mesh(
    cfg: MeshConfig, devices: tp.Optional[tp.Sequence[jax.Device]] = None
) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    sizes = cfg.sizes(len(devices))

    if cfg.num_slices > 1:
        assert sizes[0] % cfg.num_slices == 0, (
            f"replica axis {sizes[0]} must be a multiple of num_slices "
            f"{cfg.num_slices} (DP-only over DCN)"
        )
        dcn_parallelism = (cfg.num_slices, 1, 1, 1)
        ici_parallelism = (sizes[0] // cfg.num_slices,) + sizes[1:]
        device_array = mesh_utils.create_hybrid_device_mesh(
            ici_parallelism,
            dcn_parallelism,
            devices=devices,
            allow_split_physical_axes=True,
        )
    else:
        try:
            device_array = mesh_utils.create_device_mesh(
                sizes, devices=devices, allow_split_physical_axes=True
            )
        except (ValueError, AssertionError, NotImplementedError):
            # CPU-simulated or irregular topologies: plain reshape
            device_array = np.asarray(devices).reshape(sizes)

    return Mesh(device_array, AXIS_NAMES)


def single_device_mesh(device: tp.Optional[jax.Device] = None) -> Mesh:
    """Degenerate 1-device mesh (all axes size 1) so the same sharded code
    path runs on one chip or CPU."""
    device = device if device is not None else jax.devices()[0]
    return Mesh(np.asarray([device]).reshape(1, 1, 1, 1), AXIS_NAMES)
