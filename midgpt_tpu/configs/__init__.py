"""Named experiment configs.

Parity with /root/reference/src/configs/*.py (see SURVEY.md 2.2 config
matrix) plus the BASELINE.json additions (Llama-style 7B, multi-slice xl).
Each function returns a fresh ExperimentConfig; select by name via
``midgpt_tpu.get_config(name)``.
"""

from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig, register


@register("shakespeare_char")
def shakespeare_char() -> ExperimentConfig:
    """Char-level tiny GPT (parity: configs/shakespeare_char.py)."""
    return ExperimentConfig(
        model=ModelConfig(
            block_size=256, vocab_size=65, n_layer=6, n_head=6, n_embd=384,
            dropout=0.2,
        ),
        data_dir="data/shakespeare_char",
        learning_rate=1e-3, min_lr=1e-4, warmup_steps=100,
        lr_decay_steps=5000, max_steps=5000,
        batch_size=64, g_accum_iters=1,
        beta2=0.99, weight_decay=1e-4,
        eval_interval=2000,
    )


@register("openwebtext")
def openwebtext() -> ExperimentConfig:
    """GPT-2-small 124M single host (parity: configs/openwebtext.py)."""
    return ExperimentConfig(
        model=ModelConfig(
            block_size=1024, vocab_size=50304, n_layer=12, n_head=12,
            n_embd=768, dropout=0.0,
            # perf knobs resolved by HBM fit at train start (PERF.md r3:
            # remat=none + full unroll measured 47.9% vs ~27% MFU at the
            # remat=full defaults on one v5e chip)
            remat="auto", scan_unroll=0,
        ),
        data_dir="data/openwebtext",
        learning_rate=1e-3, min_lr=1e-5, warmup_steps=5000,
        lr_decay_steps=60000, max_steps=60000,
        # our batch_size is GLOBAL incl. accumulation; the reference's 128 x 16
        # accumulation steps (configs/openwebtext.py:18) = 2048 seqs/update
        batch_size=2048, g_accum_iters=16,
        beta2=0.95, weight_decay=1e-4,
        eval_interval=1000,
        # fixed eval sweep: same eval batches every interval -> comparable
        # curves, and the counter-based loader makes it free (VERDICT r4)
        eval_fixed=True,
        loss_chunk=256, loss_chunk_unroll=True,  # measured best (PERF.md)
    )


@register("openwebtext_mh")
def openwebtext_mh() -> ExperimentConfig:
    """124M multihost (parity: configs/openwebtext_mh.py)."""
    import dataclasses

    return dataclasses.replace(
        openwebtext(),
        batch_size=2048, g_accum_iters=1,
        data_dir="/mnt/disks/persist/openwebtext",
    )


@register("openwebtext_xl")
def openwebtext_xl() -> ExperimentConfig:
    """GPT-2-XL 1.5B, FSDP x TP mesh (parity: configs/openwebtext_xl.py +
    BASELINE.json north star: TP=4)."""
    return ExperimentConfig(
        model=ModelConfig(
            block_size=1024, vocab_size=50304, n_layer=24, n_head=16,
            n_embd=2048, dropout=0.0, attn_impl="auto",
            remat="auto", scan_unroll=0,
        ),
        data_dir="/mnt/disks/persist/openwebtext",
        learning_rate=1e-3, min_lr=1e-5, warmup_steps=2500,
        lr_decay_steps=25000, max_steps=25000,
        batch_size=1024, g_accum_iters=1,
        beta2=0.95, weight_decay=1e-4,
        eval_interval=1000,
        eval_fixed=True,
        loss_chunk=512, loss_chunk_unroll=True,  # measured best (PERF.md)
        mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=4),
    )


@register("openwebtext_xl_multislice")
def openwebtext_xl_multislice() -> ExperimentConfig:
    """1.5B on 2 slices over DCN, DP across slices (BASELINE.json config 5)."""
    import dataclasses

    return dataclasses.replace(
        openwebtext_xl(),
        mesh=MeshConfig(replica=2, fsdp=-1, sequence=1, tensor=4, num_slices=2),
    )


@register("llama_7b")
def llama_7b() -> ExperimentConfig:
    """Llama-style 7B: SwiGLU + GQA (BASELINE.json config 4)."""
    return ExperimentConfig(
        model=ModelConfig(
            block_size=2048, vocab_size=50304, n_layer=32, n_head=32,
            n_kv_head=8, n_embd=4096, dropout=0.0,
            mlp="swiglu", mlp_ratio=8 / 3,  # ~11008 hidden, Llama-style
            attn_impl="auto",
            remat="auto", scan_unroll=0,
        ),
        data_dir="/mnt/disks/persist/openwebtext",
        learning_rate=3e-4, min_lr=3e-5, warmup_steps=2000,
        lr_decay_steps=50000, max_steps=50000,
        batch_size=512, g_accum_iters=1,
        beta2=0.95, weight_decay=1e-4,
        eval_interval=1000,
        eval_fixed=True,
        loss_chunk=512, loss_chunk_unroll=True,  # measured best (PERF.md)
        mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=4),
    )


@register("openwebtext_moe")
def openwebtext_moe() -> ExperimentConfig:
    """124M-dense-equivalent Switch MoE: 8 experts per MLP (~530M params,
    ~124M active per token). Beyond the reference (dense-only MLPs);
    expert-parallel over the 'tensor' mesh axis."""
    import dataclasses

    base = openwebtext()
    return dataclasses.replace(
        base,
        model=dataclasses.replace(
            base.model, mlp="moe", moe_experts=8, moe_capacity=1.25,
        ),
        mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=4),
    )


@register("tiny")
def tiny() -> ExperimentConfig:
    """Minutes-scale config for tests and smoke runs."""
    return ExperimentConfig(
        model=ModelConfig(
            block_size=64, vocab_size=256, n_layer=2, n_head=2, n_embd=64,
            dropout=0.0, attn_impl="naive",
        ),
        data_dir="",
        learning_rate=1e-3, min_lr=1e-4, warmup_steps=10,
        lr_decay_steps=100, max_steps=100,
        batch_size=8, g_accum_iters=2,
        beta2=0.99, weight_decay=1e-4,
        eval_interval=50, eval_batches=4, log_interval=10,
    )
