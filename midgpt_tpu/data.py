"""Token-stream data pipeline.

Capability parity with the reference's loader (/root/reference/src/train.py:56-66
``get_batch`` + :122-125 per-process splitting), redesigned:

- **Deterministic + checkpointable**: the reference draws offsets from
  unseeded numpy (train.py:60), so resume changes the data order (SURVEY.md
  2.3). Here every batch is a pure function of (seed, step, process_index)
  via a counter-based Philox generator — the loader "state" checkpointed is
  just the step number, and resume is exact.
- Same throughput recipe: memmapped uint16 token file, vectorized
  ``np.take`` window gather, targets = inputs shifted by one.
- Per-process contiguous shards (equal-size, unlike the reference's
  ``int(n/p)+1`` imbalance).
"""

from __future__ import annotations

import dataclasses
import os
import typing as tp

import numpy as np


@dataclasses.dataclass(frozen=True)
class Shard:
    """A process-local contiguous view of the global token stream."""

    tokens: np.ndarray  # 1-D uint16 view (memmap-backed)
    global_len: int
    offset: int  # start of this shard in the global stream


def load_shard(
    path: str,
    process_index: int = 0,
    process_count: int = 1,
    in_memory: bool = True,
) -> Shard:
    """Memmap ``path`` and take this process's contiguous 1/process_count
    slice (parity: train.py:132-136, split_array_by_idx train.py:122-124)."""
    data = np.memmap(path, dtype=np.uint16, mode="r")
    n = len(data)
    per = n // process_count
    lo, hi = process_index * per, (process_index + 1) * per
    shard = data[lo:hi]
    if in_memory:
        shard = np.asarray(shard)  # host-RAM copy (reference .copy())
    return Shard(tokens=shard, global_len=n, offset=lo)


def _rng(seed: int, step: int, process_index: int, stream: int) -> np.random.Generator:
    """Counter-based generator: unique, reproducible per (seed, step, proc)."""
    return np.random.Generator(
        np.random.Philox(key=seed, counter=[0, stream, step, process_index])
    )


def sample_batch(
    shard: Shard,
    block_size: int,
    batch_shape: tp.Tuple[int, ...],
    seed: int,
    step: int,
    process_index: int = 0,
    stream: int = 0,
) -> tp.Tuple[np.ndarray, np.ndarray]:
    """Random block_size windows, with replacement.

    Returns (x, y) int32 arrays shaped ``batch_shape + (block_size,)``;
    y is x shifted by one (parity: train.py:56-66, incl. the
    ``(g_accum, B, T)`` reshape for microbatching).
    """
    n_seqs = int(np.prod(batch_shape))
    rng = _rng(seed, step, process_index, stream)
    offsets = rng.integers(
        0, len(shard.tokens) - block_size - 1, size=(n_seqs,)
    )
    idx = offsets[:, None] + np.arange(block_size + 1)[None, :]
    windows = np.take(shard.tokens, idx, axis=0).astype(np.int32)
    x = windows[:, :-1].reshape(*batch_shape, block_size)
    y = windows[:, 1:].reshape(*batch_shape, block_size)
    return x, y


@dataclasses.dataclass
class Loader:
    """Stateful wrapper holding the (tiny) loader state = current step.

    ``state_dict``/``load_state_dict`` round-trip through checkpoints;
    restoring the step reproduces the exact batch sequence.
    """

    shard: Shard
    block_size: int
    batch_shape: tp.Tuple[int, ...]  # e.g. (g_accum, local_batch)
    seed: int
    process_index: int = 0
    step: int = 0
    stream: int = 0

    def next(self) -> tp.Tuple[np.ndarray, np.ndarray]:
        x, y = sample_batch(
            self.shard,
            self.block_size,
            self.batch_shape,
            self.seed,
            self.step,
            self.process_index,
            self.stream,
        )
        self.step += 1
        return x, y

    def peek(self, step: int) -> tp.Tuple[np.ndarray, np.ndarray]:
        return sample_batch(
            self.shard,
            self.block_size,
            self.batch_shape,
            self.seed,
            step,
            self.process_index,
            self.stream,
        )

    def state_dict(self) -> tp.Dict[str, int]:
        return {"step": self.step, "seed": self.seed}

    def load_state_dict(self, state: tp.Mapping[str, int]) -> None:
        assert int(state["seed"]) == self.seed, (
            f"loader seed changed: ckpt {state['seed']} vs config {self.seed}"
        )
        self.step = int(state["step"])


def write_tokens(path: str, tokens: np.ndarray) -> None:
    """Write a uint16 token stream the way the prep scripts do
    (parity: data/shakespeare_char/prepare.py:54-61 .tofile)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.asarray(tokens, dtype=np.uint16).tofile(path)
