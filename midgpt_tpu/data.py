"""Token-stream data pipeline.

Capability parity with the reference's loader (/root/reference/src/train.py:56-66
``get_batch`` + :122-125 per-process splitting), redesigned:

- **Deterministic + checkpointable**: the reference draws offsets from
  unseeded numpy (train.py:60), so resume changes the data order (SURVEY.md
  2.3). Here every batch is a pure function of (seed, step, process_index)
  via a counter-based Philox generator — the loader "state" checkpointed is
  just the step number, and resume is exact.
- Same throughput recipe: memmapped uint16 token file, windows gathered by
  the native multi-threaded C++ gather (midgpt_tpu.native, numpy fallback),
  targets = inputs shifted by one.
- Per-process contiguous shards (equal-size, unlike the reference's
  ``int(n/p)+1`` imbalance).
- ``PrefetchLoader`` overlaps next-batch assembly (gather + host->device
  transfer) with the device step on a background thread.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import typing as tp

import numpy as np

from midgpt_tpu.native import gather_windows


@dataclasses.dataclass(frozen=True)
class Shard:
    """A process-local contiguous view of the global token stream."""

    tokens: np.ndarray  # 1-D uint16 view (memmap-backed)
    global_len: int
    offset: int  # start of this shard in the global stream


def load_shard(
    path: str,
    process_index: int = 0,
    process_count: int = 1,
    in_memory: bool = True,
) -> Shard:
    """Memmap ``path`` and take this process's contiguous 1/process_count
    slice (parity: train.py:132-136, split_array_by_idx train.py:122-124)."""
    data = np.memmap(path, dtype=np.uint16, mode="r")
    n = len(data)
    per = n // process_count
    lo, hi = process_index * per, (process_index + 1) * per
    shard = data[lo:hi]
    if in_memory:
        shard = np.asarray(shard)  # host-RAM copy (reference .copy())
    return Shard(tokens=shard, global_len=n, offset=lo)


def _rng(seed: int, step: int, process_index: int, stream: int) -> np.random.Generator:
    """Counter-based generator: unique, reproducible per (seed, step, proc)."""
    return np.random.Generator(
        np.random.Philox(key=seed, counter=[0, stream, step, process_index])
    )


def sample_batch(
    shard: Shard,
    block_size: int,
    batch_shape: tp.Tuple[int, ...],
    seed: int,
    step: int,
    process_index: int = 0,
    stream: int = 0,
) -> tp.Tuple[np.ndarray, np.ndarray]:
    """Random block_size windows, with replacement.

    Returns (x, y) int32 arrays shaped ``batch_shape + (block_size,)``;
    y is x shifted by one (parity: train.py:56-66, incl. the
    ``(g_accum, B, T)`` reshape for microbatching).
    """
    n_seqs = int(np.prod(batch_shape))
    rng = _rng(seed, step, process_index, stream)
    offsets = rng.integers(
        0, len(shard.tokens) - block_size - 1, size=(n_seqs,)
    )
    x, y = gather_windows(shard.tokens, offsets, block_size)
    return (
        x.reshape(*batch_shape, block_size),
        y.reshape(*batch_shape, block_size),
    )


@dataclasses.dataclass
class Loader:
    """Stateful wrapper holding the (tiny) loader state = current step.

    ``state_dict``/``load_state_dict`` round-trip through checkpoints;
    restoring the step reproduces the exact batch sequence.
    """

    shard: Shard
    block_size: int
    batch_shape: tp.Tuple[int, ...]  # e.g. (g_accum, local_batch)
    seed: int
    process_index: int = 0
    step: int = 0
    stream: int = 0

    def next(self) -> tp.Tuple[np.ndarray, np.ndarray]:
        x, y = sample_batch(
            self.shard,
            self.block_size,
            self.batch_shape,
            self.seed,
            self.step,
            self.process_index,
            self.stream,
        )
        self.step += 1
        return x, y

    def peek(self, step: int) -> tp.Tuple[np.ndarray, np.ndarray]:
        return sample_batch(
            self.shard,
            self.block_size,
            self.batch_shape,
            self.seed,
            step,
            self.process_index,
            self.stream,
        )

    def state_dict(self) -> tp.Dict[str, int]:
        return {"step": self.step, "seed": self.seed}

    def load_state_dict(self, state: tp.Mapping[str, int]) -> None:
        assert int(state["seed"]) == self.seed, (
            f"loader seed changed: ckpt {state['seed']} vs config {self.seed}"
        )
        self.step = int(state["step"])


class _PrefetchError:
    """Wraps an exception raised on the prefetch thread for re-raising on
    the consumer thread (a bare daemon-thread death would hang next())."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class _PlanExhausted:
    """Terminal sentinel enqueued when a finite window_plan runs out, so a
    next() past the plan raises instead of blocking forever on an empty
    queue (the worker has exited)."""


class PrefetchLoader:
    """Background-thread prefetch around a Loader: the next batch is
    gathered (and optionally pushed to device) while the current train step
    runs. The reference assembles every batch synchronously between steps
    (train.py:203-207); overlapping it removes that host time from the
    step critical path.

    ``transform`` (e.g. a make_global_array closure) runs on the prefetch
    thread — jax.device_put / make_array_from_process_local_data are
    thread-safe for this producer/consumer pattern.

    **Window mode** (``window > 1`` or an explicit ``window_plan``): each
    produced item stacks W consecutive batches along a new leading axis —
    ``[W, *batch_shape, T]`` — feeding the fused multi-step dispatch
    (train.make_train_window) one K-deep batch window per launch.
    ``window_plan`` is the trainer's finite per-item size schedule (a
    short first window re-aligns an off-grid resume; a short last window
    covers ``max_steps % K``); the worker stops when the plan runs out.
    Consumption is accounted in LOADER STEPS: ``state_dict`` counts only
    the batches of consumed windows, so stop/resume mid-window replays
    every unconsumed window batch exactly.

    Checkpointing goes through the wrapped loader's state_dict; the
    prefetch queue is drained on load so resumed batches are exact.
    """

    def __init__(
        self,
        loader: Loader,
        transform: tp.Optional[tp.Callable] = None,
        depth: int = 2,
        window: int = 1,
        window_plan: tp.Optional[tp.Sequence[int]] = None,
    ):
        assert window >= 1, window
        self.loader = loader
        self._transform = transform if transform is not None else lambda *b: b
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread: tp.Optional[threading.Thread] = None
        self._window = window
        self._plan = tuple(window_plan) if window_plan is not None else None
        self._windowed = window > 1 or self._plan is not None
        # consumption is tracked here, not via loader.step: the worker may
        # have drawn batches that no one has consumed yet. In window mode
        # _consumed counts loader STEPS (batches), _consumed_items counts
        # windows (the plan cursor for a restarted worker generation).
        self._start_step = loader.step
        self._consumed = 0
        self._consumed_items = 0

    def _item_sizes(self, from_item: int) -> tp.Iterator[int]:
        """Window sizes the worker should produce, starting at item index
        ``from_item``: the remaining plan suffix, or an unbounded stream
        of ``window``-sized items when no plan was given."""
        if self._plan is not None:
            yield from self._plan[from_item:]
            return
        while True:
            yield self._window

    def _worker(
        self, stop: threading.Event, q: "queue.Queue", begin_step: int,
        from_item: int,
    ) -> None:
        # draws via the PURE loader.peek with a generation-local counter —
        # the shared Loader is never mutated, so a join-timeout zombie
        # cannot corrupt another generation's (or a resume's) data order
        produced = 0
        for w in self._item_sizes(from_item):
            if stop.is_set():
                return
            try:
                if self._windowed:
                    draws = [
                        self.loader.peek(begin_step + produced + i)
                        for i in range(w)
                    ]
                    batch = self._transform(
                        *(np.stack(col) for col in zip(*draws))
                    )
                else:
                    batch = self._transform(
                        *self.loader.peek(begin_step + produced)
                    )
                produced += w
                item = (w, batch)
            except BaseException as exc:  # propagate to the consumer
                item = (w, _PrefetchError(exc))
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue
            if isinstance(item[1], _PrefetchError):
                return
        # finite plan exhausted (only a bounded _item_sizes ends the loop):
        # publish a terminal sentinel so a consumer calling next() past the
        # plan raises instead of blocking forever on an empty queue
        sentinel = (0, _PlanExhausted())
        while not stop.is_set():
            try:
                q.put(sentinel, timeout=0.1)
                break
            except queue.Full:
                continue

    def start(self) -> "PrefetchLoader":
        if self._thread is None:
            # each worker generation gets its own stop event + queue so a
            # join-timeout zombie from a previous generation can never feed
            # the current one
            self._thread = threading.Thread(
                target=self._worker,
                args=(
                    self._stop, self._queue,
                    self._start_step + self._consumed, self._consumed_items,
                ),
                daemon=True,
            )
            self._thread.start()
        return self

    def next(self):
        if self._thread is None:
            self.start()
        w, batch = self._queue.get()
        if isinstance(batch, _PrefetchError):
            self.stop()
            raise batch.exc
        if isinstance(batch, _PlanExhausted):
            self.stop()
            raise RuntimeError(
                f"PrefetchLoader window_plan exhausted after "
                f"{self._consumed_items} windows ({self._consumed} batches): "
                "next() was called more times than the plan has items"
            )
        self._consumed += w
        self._consumed_items += 1
        return batch

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            while True:  # unblock a producer stuck on a full queue
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break
            # A worker stuck >5s mid-transform stays alive, but it holds
            # THIS generation's stop event (already set) + queue and only
            # ever calls the pure loader.peek, so it can neither feed a
            # later generation nor corrupt shared state.
            self._thread.join(timeout=5)
            self._thread = None
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self._queue.maxsize)

    def state_dict(self) -> tp.Dict[str, int]:
        # batches sitting in the queue (or in-flight on the worker) were
        # drawn but never consumed — resume replays from the consumed count
        return {
            "step": self._start_step + self._consumed,
            "seed": self.loader.seed,
        }

    def load_state_dict(self, state: tp.Mapping[str, int]) -> None:
        # note for window mode: a restored step generally needs a NEW
        # window plan (the trainer recomputes it from the restored step and
        # constructs a fresh PrefetchLoader); loading here restarts any
        # existing plan from its first entry
        self.stop()
        self.loader.load_state_dict(state)
        self._start_step = self.loader.step
        self._consumed = 0
        self._consumed_items = 0


def write_tokens(path: str, tokens: np.ndarray) -> None:
    """Write a uint16 token stream the way the prep scripts do
    (parity: data/shakespeare_char/prepare.py:54-61 .tofile)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.asarray(tokens, dtype=np.uint16).tofile(path)
