"""Int8 quantized SERVING weight path: per-output-channel-scaled int8
parameter pytree with the dequantization fused into each matmul.

PERF.md's decode roofline accounting (r5) puts 124M B=8 serving at
0.905 ms/tok against a ~0.43 ms HBM floor, and the bf16 weight stream
(~0.31 ms/step of that floor) is the single largest term: every decode
step re-reads every parameter. Halving the weight bytes moves the floor
itself (~0.43 -> ~0.27 ms/step), which no dispatch/kernel optimization
can — so the quantized pytree is a SERVING artifact: converted from any
training checkpoint (``quantize_model`` / ``scripts/quantize_ckpt.py``),
never trained, and consumed by the same model code the bf16 engine runs.

Design rules (the Liger-Kernel fuse-small-ops discipline, PAPERS.md):

- **The int8 array is what streams from HBM.** A :class:`QuantLinear`
  leaf holds ``weight`` (int8, stored ``[..., in, out]`` like
  :class:`~midgpt_tpu.models.layers.Linear`) and ``scale`` (f32, one per
  OUTPUT channel). The forward is ``(x @ w_int8) * scale`` — the
  dequant lives in the matmul epilogue at ACTIVATION shape. Nothing may
  materialize a full-precision weight-matrix buffer (audited:
  ``no-dequant-materialization`` in midgpt_tpu.analysis, CI-gated).
- **Exactness-preserving scales by default** (``mode="po2"``): scales
  are powers of two, so ``q * scale`` is exact in f32 AND bf16 (|q| <=
  127 fits both mantissas; a po2 shift never rounds), and the epilogue
  form ``(x @ q) * scale`` is BITWISE equal to ``x @ (q * scale)`` —
  scaling every addend of a float sum by 2^k shifts exponents uniformly
  and changes no rounding decision. Consequence (tested, not assumed):
  the quantized engine is greedy token-identical to the bf16 engine
  running ``dequantize_model(qmodel)``, across the whole serving
  exactness matrix (prefix cache x chunked prefill x speculation x
  eviction). The identity-scale special case (``mode="identity"``,
  scale == 1 over already-integer weights) is the same contract with
  the shift k = 0. Po2 rounding costs at most one bit of SNR vs
  fractional absmax scales (``mode="absmax"``, no bitwise contract) —
  int8 per-channel has headroom for it, and a quantization whose
  correctness is bit-testable is worth a bit.
- **Per-channel, output axis.** Scales index the matmul's OUT dim
  (axis -1 of the stored weight), one scale vector per stacked layer
  (``[L, out]`` on scan-stacked block leaves, ``[out]`` unstacked), so
  the epilogue is a row-broadcast multiply the compiler folds into the
  matmul consumer.

What quantizes: every dense matmul on the serving hot path — attention
``wqkv``/``wo``, MLP ``w_up``/``w_gate``/``w_down``, and the LM head
(materialized as a quantized head even for tied/init-tied embeddings:
``GPT.project`` is the one head entry point and fuses the epilogue).
What stays full-precision: the token embedding (a gather, not a
matmul), the tiny QK-norm / RMSNorm scales, and MoE expert stacks
(``mlp="moe"`` is a training configuration; the serving configs are
dense — quantize_model asserts).

Tensor parallelism (the TP serving engine, ``ServingEngine(mesh=...)``):
a QuantLinear shards exactly like the Linear it replaced — the int8
weight takes the weight rule (column-parallel wqkv/w_up/gate/lm_head,
row-parallel wo/w_down) and the scale vector splits along the SAME out
dim (``GPT_PARAM_RULES`` has explicit ``.../scale`` entries), so the
epilogue multiply stays a local row-broadcast on every shard and the
per-chip int8 stream is 1/tp of the whole model. Exactness composes:
column-parallel epilogues are bitwise per output column, and the po2
contract is per-channel, so the quantized TP engine relates to the
dequantized TP engine exactly as in the single-chip case.
"""

from __future__ import annotations

import dataclasses
import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.models.gpt import GPT, Block
from midgpt_tpu.models.layers import Linear
from midgpt_tpu.pytree import module

Array = jax.Array

QUANT_MODES = ("po2", "absmax", "identity")


@module
class QuantLinear:
    """Bias-free linear over an int8 weight with per-output-channel f32
    scales; the dequant is fused into the matmul epilogue. Drop-in for
    :class:`~midgpt_tpu.models.layers.Linear` everywhere the model only
    CALLS the projection (all decode/prefill/verify paths); leaves are
    layer-stackable exactly like Linear's (``weight [L, in, out]``,
    ``scale [L, out]`` — a static layer slice ``tree.map(a[i])`` yields
    the per-layer ``[in, out]`` / ``[out]`` pair)."""

    weight: Array  # int8 [..., in, out] — the HBM-resident stream
    scale: Array  # f32 [..., out] — per-output-channel dequant scale

    def __call__(self, x: Array) -> Array:  # [..., in] -> [..., out]
        with jax.named_scope("quant_linear"):
            # the convert feeds the dot directly (no materialized
            # full-precision weight; audited) and the scale lands on the
            # ACTIVATION-shaped result — with po2 scales this is bitwise
            # x @ dequant(w)
            y = x @ self.weight.astype(x.dtype)
            return y * self.scale.astype(y.dtype)


def _po2_ceil(x: Array) -> Array:
    """Smallest power of two >= x (elementwise, x > 0)."""
    return po2_ceil_exact(jnp.asarray(x, jnp.float32))


def quantize_per_channel(
    w: Array, *, mode: str = "po2"
) -> tp.Tuple[Array, Array]:
    """Quantize ``w [..., in, out]`` to int8 with one scale per OUTPUT
    channel (reduced over the ``in`` axis only — stacked leading axes
    each get their own scale rows). Returns ``(q int8, scale f32)`` with
    ``dequantize(q, scale) ~= w``; the elementwise error is bounded by
    ``scale / 2``.

    Modes: ``"po2"`` (default) rounds the absmax/127 scale UP to a power
    of two — exact ``q * scale`` products and a bitwise epilogue
    contract (module docstring) for <= 1 bit of extra grid coarseness;
    ``"absmax"`` keeps the fractional scale (tightest grid, no bitwise
    contract); ``"identity"`` pins scale = 1 (weights must already be
    integer-valued in [-127, 127] to round-trip exactly). All-zero
    channels quantize to zeros with scale 1 (nothing to scale; avoids a
    0-divide), constant channels land on +-127 (po2: the nearest po2
    grid point) exactly."""
    assert mode in QUANT_MODES, f"mode {mode!r} not in {QUANT_MODES}"
    w32 = jnp.asarray(w, jnp.float32)
    assert w32.ndim >= 2, f"need [..., in, out], got {w32.shape}"
    if mode == "identity":
        scale = jnp.ones(w32.shape[:-2] + w32.shape[-1:], jnp.float32)
    else:
        absmax = jnp.max(jnp.abs(w32), axis=-2)  # [..., out]
        scale = jnp.where(absmax > 0.0, absmax / 127.0, 1.0)
        if mode == "po2":
            scale = jnp.where(absmax > 0.0, _po2_ceil(scale), 1.0)
    q = jnp.clip(
        jnp.round(w32 / scale[..., None, :]), -127.0, 127.0
    ).astype(jnp.int8)
    return q, scale


def dequantize(q: Array, scale: Array) -> Array:
    """``q int8 [..., in, out]`` x ``scale [..., out]`` -> f32 weights —
    the reference the quantized matmul is tested against. Exact for
    po2/identity scales (an int8 code times a power of two never
    rounds; this is what the bitwise epilogue contract rests on); with
    fractional ``absmax`` scales each product carries one ordinary f32
    rounding (up to ~31 significant bits into 24), so no bitwise
    contract holds there."""
    return q.astype(jnp.float32) * scale[..., None, :]


# ---------------------------------------------------------------------------
# Int8 KV-cache grid (the serving paged pool, midgpt_tpu.serving.paged)
#
# The KV analogue of the po2 weight contract above, with one extra
# obligation the weights never had: pool pages are quantized INCREMENTALLY
# (a page's rows arrive across decode windows / prefill chunks / verify
# dispatches), so the scale of a page must be a pure function of the token
# stream — never of window size, chunk size, or speculation — or the
# engine's greedy token-identity matrix breaks. The scheme: one f32 po2
# scale per (page, KV-head) plane, fixed at PAGE BIRTH from the page's
# first row (positions fill contiguously, so every writer sees the same
# birth row), and every in-dispatch reader sees rows ROUNDED through that
# grid — a value on the grid survives quantize -> dequantize bitwise
# (|q| <= 127 times a po2 scale is exact in f32 AND bf16), so the int8
# pool behaves exactly like a bf16 pool whose values happen to lie on the
# grid. Scale derivation is ROUNDING-STABLE (tested): deriving from a
# row already rounded to its own grid returns the identical scale, which
# is what lets the bulk page writes re-derive scales from the rounded
# rows they receive instead of threading scale state through every scan.
# ---------------------------------------------------------------------------

KV_QMAX = 127.0
# the BIRTH-ROW divisor: a page's scale targets its first row's absmax
# at code <= 63, leaving one power-of-two of headroom for the LATER
# rows that share the scale (codes clip at +-127, so a later row only
# clips past ~2-4x the birth absmax — rare for stationary activations;
# with divisor 127 any later row larger than the birth row clipped).
# 63 is also what keeps scale derivation ROUNDING-STABLE: a rounded
# birth row's absmax is q * s with q = round(absmax/s) in [32, 63], and
# q*s/63 lands in (0.5079*s, s] — strictly inside the po2-ceil bucket
# of s, so re-deriving from the rounded row returns s bit-for-bit.
# (Divisor 127 with headroom *2 would put the boundary at 64/127 =
# 0.5039 of TWICE the scale — the wrong side of a po2 boundary.)
KV_BIRTH_QMAX = 63.0
# Scale floor: the smallest NORMAL f32 power of two. A subnormal scale
# would be correct arithmetic on paper, but XLA CPU flushes subnormal
# operands/results to zero (FTZ), so ``q * scale`` and ``row / scale``
# stop being exact — and whether a backend flushes is implementation
# noise. Clamping here keeps every grid product (|q| <= 127 times a
# normal po2) normal in f32 AND bf16 on every backend; rows tiny enough
# to want a smaller scale (absmax < ~63 * 2^-126) round to codes near
# zero, which is the right answer for values of that size anyway.
KV_SCALE_MIN = 2.0**-126


def _pow2_f32(e: Array) -> Array:
    """Exact f32 ``2**e`` from an integer exponent, assembled from IEEE
    bit fields — NOT ``jnp.exp2``, whose polynomial approximation is off
    by ulps at integer arguments outside a narrow band (measured on XLA
    CPU: wrong at e = -13, 13, 15, ... and everything past ~[-14, 28],
    underflowing to 0.0 below ~-125). Normal range [-126, 127] sets the
    exponent field; [-149, -127] sets the matching subnormal mantissa
    bit; past either end the true f32 value of ``2**e`` is inf / 0.0."""
    e = jnp.asarray(e, jnp.int32)
    en = jnp.clip(e, -126, 128)  # 128 -> biased 255 -> inf
    normal = ((en + 127) << 23).astype(jnp.uint32)
    sub = jnp.left_shift(
        jnp.uint32(1), jnp.clip(e + 149, 0, 23).astype(jnp.uint32)
    )
    bits = jnp.where(
        e >= -126, normal, jnp.where(e >= -149, sub, jnp.uint32(0))
    )
    return jax.lax.bitcast_convert_type(bits, jnp.float32)


def po2_ceil_exact(y: Array) -> Array:
    """Smallest power of two >= y (y > 0), computed by EXPONENT
    EXTRACTION (frexp) + bit assembly rather than
    ``exp2(ceil(log2 y))`` — log2 AND exp2 are approximations (exp2's
    value at plain integer arguments is implementation noise, see
    :func:`_pow2_f32`), and the KV grid's rounding-stability proof needs
    the boundary case ``y == 2^k`` to land on ``2^k`` bit-for-bit on
    every backend. The decomposition reads the IEEE bit fields directly
    (bitcast) instead of calling frexp on y: jax's frexp misreads the
    zero exponent field of subnormals (returns e=-149 for all of them
    on this pin), and XLA CPU flushes subnormal arithmetic to zero, so
    no float-arithmetic normalization of a subnormal is trustworthy.
    Writing y = mant * 2^k with integer mant in [1, 2^24) (normals get
    the implicit leading bit ORed in, subnormals are already that form),
    mant converts to f32 EXACTLY and lands in frexp's well-behaved
    normal range."""
    y = jnp.asarray(y, jnp.float32)
    bits = jax.lax.bitcast_convert_type(y, jnp.uint32)  # y > 0: sign 0
    expf = (bits >> 23).astype(jnp.int32)
    mant = (bits & jnp.uint32(0x7FFFFF)).astype(jnp.int32)
    mant_full = jnp.where(expf > 0, mant | (1 << 23), mant)
    k = jnp.where(expf > 0, expf - 150, -149)  # y = mant_full * 2^k
    m, e = jnp.frexp(mant_full.astype(jnp.float32))
    e = e.astype(jnp.int32) + k
    return jnp.where(m == 0.5, _pow2_f32(e - 1), _pow2_f32(e))


def kv_scale_from_absmax(absmax: Array) -> Array:
    """Per-(page, head) po2 KV scale from a birth row's |absmax| over
    head_dim: smallest po2 >= absmax / 63 (the birth row's codes stay
    <= 63, leaving one bit of headroom for the later rows that share
    the page's scale — see KV_BIRTH_QMAX), floored at KV_SCALE_MIN (the
    smallest normal po2 — subnormal scales are FTZ territory), 1.0 for
    an EFFECTIVELY all-zero row: absmax <= KV_SCALE_MIN/2 rounds to
    code 0 even on the floored grid (banker's round of <= 0.5), so the
    rounded row is all zeros and re-deriving from it must return the
    same scale — those rows take the all-zero branch up front. f32 in,
    f32 out."""
    am = jnp.asarray(absmax, jnp.float32)
    sc = jnp.maximum(
        po2_ceil_exact(am / KV_BIRTH_QMAX), jnp.float32(KV_SCALE_MIN)
    )
    return jnp.where(am > jnp.float32(KV_SCALE_MIN / 2), sc, 1.0)


def quantize_kv_rows(rows: Array, scales: Array) -> Array:
    """``rows [..., C]`` x ``scales [...]`` -> int8 codes. Exact (no
    rounding at all) when the rows are already on the grid — the case
    the serving write paths are in, because every row was rounded
    in-dispatch before anyone read it."""
    q = jnp.clip(
        jnp.round(rows.astype(jnp.float32) / scales[..., None]),
        -KV_QMAX, KV_QMAX,
    )
    return q.astype(jnp.int8)


def round_kv_rows_to_grid(rows: Array, scales: Array) -> Array:
    """Round K/V rows through their page's int8 grid, returned in the
    rows' own dtype: ``round(row / s) * s`` with ``|code| <= 127`` and a
    po2 ``s`` is exactly representable in bf16 and f32, so the returned
    values are BITWISE what a later pool read will dequantize to — the
    statement that makes in-dispatch reads and post-flush reads of the
    same position indistinguishable."""
    q = jnp.clip(
        jnp.round(rows.astype(jnp.float32) / scales[..., None]),
        -KV_QMAX, KV_QMAX,
    )
    return (q * scales[..., None]).astype(rows.dtype)


def quantize_linear(lin: Linear, *, mode: str = "po2") -> QuantLinear:
    q, scale = quantize_per_channel(lin.weight, mode=mode)
    return QuantLinear(weight=q, scale=scale)


def dequantize_linear(qlin: QuantLinear) -> Linear:
    return Linear(weight=dequantize(qlin.weight, qlin.scale))


def is_quantized(model: GPT) -> bool:
    return isinstance(model.lm_head, QuantLinear)


def quantize_model(model: GPT, *, mode: str = "po2") -> GPT:
    """Convert a (trained) GPT into its int8 serving form: every dense
    matmul weight becomes a :class:`QuantLinear`; the LM head is always
    materialized quantized (from ``wte.weight.T`` when tied/init-tied —
    the embedding GATHER keeps the full-precision table, but the head
    MATMUL streams int8). The result is the same GPT pytree class with
    the same static config: every decode/prefill/verify program accepts
    either form through one code path (``GPT.project`` + the block
    methods calling the projections)."""
    assert not is_quantized(model), "model is already quantized"
    cfg = model.config
    assert cfg.mlp != "moe", (
        "int8 serving quantization covers the dense configs; the MoE "
        "expert stacks are raw arrays, not Linear leaves (ROADMAP serving "
        "configs are dense)"
    )
    blocks: Block = model.blocks
    qlin = lambda lin: quantize_linear(lin, mode=mode)  # noqa: E731
    attn = dataclasses.replace(
        blocks.attn, wqkv=qlin(blocks.attn.wqkv), wo=qlin(blocks.attn.wo)
    )
    mlp = dataclasses.replace(
        blocks.mlp,
        w_up=qlin(blocks.mlp.w_up),
        w_down=qlin(blocks.mlp.w_down),
        w_gate=(
            qlin(blocks.mlp.w_gate) if blocks.mlp.w_gate is not None else None
        ),
    )
    head = (
        model.lm_head
        if model.lm_head is not None
        else Linear(weight=model.wte.weight.T)
    )
    return dataclasses.replace(
        model,
        blocks=dataclasses.replace(blocks, attn=attn, mlp=mlp),
        lm_head=qlin(head),
    )


def dequantize_model(qmodel: GPT) -> GPT:
    """The full-precision model the quantized one encodes: every
    QuantLinear becomes a plain Linear holding ``dequantize(w, scale)``
    (exact in f32). With po2 scales the bf16/f32 engine running THIS
    model is greedy token-identical to the quantized engine running
    ``qmodel`` — the testable statement of the exactness contract."""
    assert is_quantized(qmodel), "model is not quantized"
    blocks: Block = qmodel.blocks
    dq = dequantize_linear
    attn = dataclasses.replace(
        blocks.attn, wqkv=dq(blocks.attn.wqkv), wo=dq(blocks.attn.wo)
    )
    mlp = dataclasses.replace(
        blocks.mlp,
        w_up=dq(blocks.mlp.w_up),
        w_down=dq(blocks.mlp.w_down),
        w_gate=(
            dq(blocks.mlp.w_gate) if blocks.mlp.w_gate is not None else None
        ),
    )
    return dataclasses.replace(
        qmodel,
        blocks=dataclasses.replace(blocks, attn=attn, mlp=mlp),
        lm_head=dq(qmodel.lm_head),
    )


def quant_weight_shapes(model: GPT) -> tp.FrozenSet[tp.Tuple[int, ...]]:
    """Every shape a dequantized weight-matrix buffer could take in a
    compiled program: the stacked ``[L, in, out]`` leaves AND their
    static per-layer ``[in, out]`` slices (the serving programs' layer
    loops slice statically). The ``no-dequant-materialization`` audit
    flags any full-precision buffer/multiply at one of these shapes.

    Sharding-aware: when the model's leaves carry a ``NamedSharding``
    (the TP serving path — GPT_PARAM_RULES splits each QuantLinear's
    weight over 'tensor' and its scale vector consistently along the
    same OUT dim), the shapes returned are the per-shard LOCAL shapes,
    because that is what the SPMD-partitioned HLO the audit parses
    actually contains. Unsharded models are unchanged (the local shape
    IS the global shape)."""
    shapes: tp.Set[tp.Tuple[int, ...]] = set()

    def _local_shape(arr) -> tp.Tuple[int, ...]:
        sharding = getattr(arr, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            return tuple(int(d) for d in sharding.shard_shape(arr.shape))
        return tuple(int(d) for d in arr.shape)

    def _collect(leaf):
        if isinstance(leaf, QuantLinear):
            s = _local_shape(leaf.weight)
            shapes.add(s)
            if len(s) > 2:
                shapes.add(s[1:])  # the static layer slice

    for lin in (
        model.blocks.attn.wqkv,
        model.blocks.attn.wo,
        model.blocks.mlp.w_up,
        model.blocks.mlp.w_down,
        model.blocks.mlp.w_gate,
        model.lm_head,
    ):
        if lin is not None:
            _collect(lin)
    return frozenset(shapes)


# ---------------------------------------------------------------------------
# Checkpoint conversion (scripts/quantize_ckpt.py is the CLI front end)
# ---------------------------------------------------------------------------

QUANT_ITEM = "params_q8"  # the checkpoint item name of a quantized pytree


def abstract_quantized(model_cfg) -> GPT:
    """Shape/dtype template of the quantized pytree for ``model_cfg`` —
    what :meth:`Checkpointer.restore` needs to land a ``params_q8`` item
    without materializing a full-precision model first."""
    return jax.eval_shape(
        lambda: quantize_model(GPT.init(jax.random.PRNGKey(0), model_cfg))
    )


def restore_quantized(ckpt, model_cfg, step: tp.Optional[int] = None) -> GPT:
    """Restore a pre-quantized ``params_q8`` item from a checkpoint
    written by ``scripts/quantize_ckpt.py`` (params-only, no optimizer
    state, int8 weights land directly — no f32 staging)."""
    items, _ = ckpt.restore(
        {QUANT_ITEM: abstract_quantized(model_cfg)}, step=step
    )
    return items[QUANT_ITEM]
