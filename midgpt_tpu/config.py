"""Experiment / model configuration.

Capability parity with the reference's config system
(/root/reference/src/train.py:26-44 ``ExperimentConfig``,
/root/reference/src/model.py:108-115 ``GPTConfig``,
/root/reference/launch.py:25-27 name-based resolution,
/root/reference/sample.py:49-65 JSON round-trip), redesigned:

- nested dataclasses with a generic JSON (de)serializer instead of the
  hand-rolled ``from_json``;
- a mesh spec (``MeshConfig``) making DP / FSDP / SP / TP axis sizes explicit
  instead of the hardcoded ``(n_devices // 8, 8)`` mesh (train.py:130);
- named registry populated by ``midgpt_tpu.configs``.
"""

from __future__ import annotations

import dataclasses
import json
import typing as tp


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture. Superset of the reference GPTConfig (model.py:108-115):
    adds GQA (n_kv_head), SwiGLU (mlp), and kernel/remat knobs for the
    Llama-style family required by BASELINE.json."""

    block_size: int  # max sequence length
    vocab_size: int
    n_layer: int
    n_head: int
    n_embd: int
    dropout: float = 0.0
    n_kv_head: tp.Optional[int] = None  # None => MHA (= n_head); < n_head => GQA
    mlp: str = "gelu"  # "gelu" (GPT-2, 4x) | "swiglu" (Llama) | "moe"
    # (Switch-style top-1 mixture of GELU experts; expert-parallel over
    # the 'tensor' mesh axis — see models/gpt.MoEMLP)
    moe_experts: int = 8  # experts per MoE layer (mlp="moe")
    moe_top_k: int = 1  # experts per token: 1 = Switch, 2 = GShard-style
    # (renormalized top-2 gates; aux loss tracks first choices)
    # per-row capacity factor: C = ceil(cf * top_k * T / E) — K claims per
    # token share the expert buffers, so capacity scales with top_k
    # (models/gpt.MoEMLP.__call__)
    moe_capacity: float = 1.25
    moe_aux_weight: float = 0.01  # load-balance aux loss weight (train)
    mlp_ratio: float = 4.0  # hidden = ratio * n_embd (swiglu: per-branch width)
    # exact hidden width; None = ratio * n_embd, with FRACTIONAL products
    # rounded up to a multiple of 256 (Llama's multiple_of rule; also the
    # MXU-friendly width — r3). Set explicitly to pin any width, e.g. to
    # restore a checkpoint trained before the rounding rule existed.
    mlp_hidden: tp.Optional[int] = None
    rope_base: float = 10000.0
    qk_norm: bool = True  # per-head QK-LayerNorm (model.py:52-53)
    tie_embeddings: bool = False  # True = one shared param (true tying);
    # False = reference semantics: shared init, independent params
    # (model.py:134-138, SURVEY.md 2.3)
    # "fused" = projection-natural QK-LN+RoPE+flash (ops/fused_attn);
    # "auto" prefers it on TPU when shapes allow
    # ring = streaming K/V ring over 'sequence'; ulysses = all-to-all
    # head<->sequence trade (parallel/ulysses.py — exact attention +
    # exact dropout, needs H % S == 0 and tensor == 1)
    attn_impl: str = "auto"  # auto | naive | flash | ring | ulysses | fused
    ring_schedule: str = "zigzag"  # zigzag (balanced) | standard; zigzag
    # auto-falls back to standard when T doesn't divide 2*sequence
    norm_impl: str = "auto"  # auto | jnp | fused (Pallas one-pass RMSNorm)
    # remat "auto": train() picks none/dots/full by an HBM-fit estimate at
    # startup and logs the choice (resolve_auto_knobs) — remat=none with a
    # fully-unrolled scan measured 1.5-2.6x faster than remat=full when it
    # fits (PERF.md); outside train() (sampling) "auto" behaves as none
    remat: str = "full"  # auto | full | dots | none  (model.py:149 uses full)
    scan_unroll: int = 1  # lax.scan unroll over layers; 0 = n_layer (full)

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head if self.n_kv_head is not None else self.n_head

    @property
    def head_dim(self) -> int:
        assert self.n_embd % self.n_head == 0
        return self.n_embd // self.n_head


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh axis sizes. -1 on at most one axis means "all remaining
    devices". Axis roles:
      replica  - pure DP, gradients all-reduced (DCN axis for multi-slice)
      fsdp     - DP + parameter/optimizer sharding (ZeRO-3)
      sequence - context parallelism (ring attention)
      tensor   - Megatron-style tensor parallelism
    """

    replica: int = 1
    fsdp: int = -1
    sequence: int = 1
    tensor: int = 1
    # GPipe pipeline stages (midgpt_tpu.parallel.pipeline); outermost axis
    pipeline: int = 1

    # number of slices for hybrid ICI/DCN meshes; 1 = single slice
    num_slices: int = 1

    # microbatches streamed through the pipeline per step (GPipe bubble =
    # (S-1)/(M+S-1)); 0 = auto (2 * pipeline stages)
    pp_microbatches: int = 0
    # dtype of activations crossing stage boundaries (inter-stage ppermute
    # + shard_map boundary). "float32" (default) works everywhere; a bf16
    # boundary halves ppermute bytes but crashes XLA CPU's
    # AllReducePromotion pass on the current pin (diagnosed r4: a bf16
    # manual-boundary all-reduce whose region root is a sharding
    # constraint cannot be cloned) — try "bfloat16" on real TPU hardware.
    pp_boundary_dtype: str = "float32"

    @property
    def axis_names(self) -> tp.Tuple[str, ...]:
        return ("pipeline", "replica", "fsdp", "sequence", "tensor")

    def sizes(self, n_devices: int) -> tp.Tuple[int, ...]:
        sizes = [self.pipeline, self.replica, self.fsdp, self.sequence, self.tensor]
        if -1 in sizes:
            known = 1
            for s in sizes:
                if s != -1:
                    known *= s
            assert n_devices % known == 0, (
                f"cannot infer -1 axis: {n_devices} devices, fixed product {known}"
            )
            sizes[sizes.index(-1)] = n_devices // known
        total = 1
        for s in sizes:
            total *= s
        assert total == n_devices, (
            f"mesh {sizes} does not cover {n_devices} devices"
        )
        return tuple(sizes)


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """Full experiment schema (parity: /root/reference/src/train.py:26-44)."""

    model: ModelConfig
    rundir: str = ""
    data_dir: str = ""
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    min_lr: float = 3e-5
    lr_decay_steps: int = 5000
    max_steps: int = 5000
    batch_size: int = 32  # GLOBAL batch size (train.py:31)
    g_accum_iters: int = 1
    # optimizer steps fused into ONE jitted lax.scan dispatch
    # (train.make_train_window): amortizes the fixed per-dispatch host/
    # runtime latency over K steps (PERF.md r5 measured +25-50 ms/step of
    # pure dispatch overhead on a bad relay day). 1 = today's one-dispatch-
    # per-step loop, bit-for-bit. K > 1 requires eval/ckpt intervals to be
    # multiples of K (resolve_dispatch_intervals — intervals get window
    # granularity) and holds a K-deep batch window in HBM.
    steps_per_dispatch: int = 1
    beta1: float = 0.9
    beta2: float = 0.95
    weight_decay: float = 1e-4
    grad_clip: float = 1.0
    independent_wd: bool = True  # add_decayed_weights(wd / lr) (train.py:156)
    eval_interval: int = 1000
    eval_batches: int = 200  # (train.py:110)
    # True: evaluate the SAME held-out batch sweep every interval (the
    # counter-based loader makes this free) — comparable, low-noise curves
    # for long runs. False (default) = reference parity: fresh random eval
    # batches each interval (train.py:110-116)
    eval_fixed: bool = False
    log_interval: int = 20  # wandb loss logging cadence (train.py:212)
    ckpt_interval: tp.Optional[int] = None  # None => eval_interval (train.py:143)
    ckpt_keep: int = 1  # max_to_keep (train.py:141)
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    seed: int = 0
    data_seed: int = 1234  # seeded loader (fixes train.py:60 nondeterminism)
    # T-chunk size for chunked cross-entropy (ops/loss.py): the [B,T,V] f32
    # logits never materialize. None = dense loss (reference parity path).
    # Works under a sharded sequence axis too: chunking runs shard-local
    # inside shard_map (train.py:129-140), so each rank chunks its own slice.
    loss_chunk: tp.Optional[int] = None
    # unroll the chunk scan: kills the while-loop overhead (carried [D,V]
    # dW re-read/written per backward iteration) while keeping per-chunk
    # logits checkpointed — measured win on the flagship shape (PERF.md r2)
    loss_chunk_unroll: bool = False
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    use_wandb: bool = False  # wandb.init on proc 0 (parity: launch.py:68)
    debug: bool = False
    # training-loop lifecycle tracing (midgpt_tpu.train_telemetry):
    # prefetch-wait / window launch+harvest / eval / checkpoint events +
    # Perfetto timeline + flight recorder, written into the rundir.
    # Tracing is loop-side only — the jitted window program is the
    # identical cached callable either way and the loss sequence is
    # bitwise unchanged (tests/test_train_telemetry.py). The anomaly
    # monitors run regardless of this flag (they only read scalars the
    # logging path already pulled to the host).
    train_telemetry: bool = False

    @property
    def microbatch_size(self) -> int:
        assert self.batch_size % self.g_accum_iters == 0
        return self.batch_size // self.g_accum_iters


# ---------------------------------------------------------------------------
# JSON round-trip (generic over the nested dataclasses above)
# ---------------------------------------------------------------------------


def to_dict(cfg: tp.Any) -> tp.Any:
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        return {f.name: to_dict(getattr(cfg, f.name)) for f in dataclasses.fields(cfg)}
    if isinstance(cfg, (list, tuple)):
        return [to_dict(v) for v in cfg]
    return cfg


def _from_dict(cls: tp.Any, data: tp.Any) -> tp.Any:
    if data is None:
        return None
    if dataclasses.is_dataclass(cls):
        kwargs = {}
        hints = tp.get_type_hints(cls)
        for f in dataclasses.fields(cls):
            if f.name not in data:
                continue
            ftype = hints[f.name]
            # unwrap Optional[X]
            origin = tp.get_origin(ftype)
            if origin is tp.Union:
                args = [a for a in tp.get_args(ftype) if a is not type(None)]
                ftype = args[0] if args else ftype
            if dataclasses.is_dataclass(ftype):
                kwargs[f.name] = _from_dict(ftype, data[f.name])
            else:
                kwargs[f.name] = data[f.name]
        return cls(**kwargs)
    return data


def to_json(cfg: ExperimentConfig) -> str:
    return json.dumps(to_dict(cfg), indent=2)


def from_json(s: str) -> ExperimentConfig:
    return _from_dict(ExperimentConfig, json.loads(s))


def from_dict(d: tp.Mapping[str, tp.Any]) -> ExperimentConfig:
    return _from_dict(ExperimentConfig, d)


# ---------------------------------------------------------------------------
# steps_per_dispatch interval resolution
# ---------------------------------------------------------------------------


def resolve_dispatch_intervals(cfg: ExperimentConfig) -> ExperimentConfig:
    """Validate/align the interval knobs against ``steps_per_dispatch``.

    With K steps fused into one dispatch, the host only sees the train
    state at window boundaries (multiples of K), so anything that needs
    the state *between* steps — eval sweeps, checkpoint saves — must land
    on the K grid. Misaligned explicit intervals FAIL FAST here with an
    actionable message instead of silently skewing the eval/ckpt cadence.

    ``log_interval`` needs no alignment: per-step (loss, grad-norm, lr)
    come back as stacked scan outputs of the fused window, so logging
    stays per-step exact at any cadence with at most one host sync per
    logging window. ``ckpt_interval=None`` resolves to ``eval_interval``
    (already validated). ``max_steps`` need not divide K — the final
    window is a shorter program (ceil(max_steps / K) dispatches total).

    K=1 returns ``cfg`` unchanged (the identical object): the trainer
    keeps today's one-dispatch-per-step loop and jitted step.
    """
    k = cfg.steps_per_dispatch
    if k == 1:
        return cfg
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")

    def _aligned(name: str, value: int) -> None:
        if value % k != 0:
            lo, hi = (value // k) * k, -(-value // k) * k
            suggestion = f"{hi}" if lo == 0 else f"{lo} or {hi}"
            raise ValueError(
                f"{name}={value} is not divisible by steps_per_dispatch={k}: "
                f"the fused window only exposes the train state every {k} "
                f"steps, so the {name.split('_')[0]} cadence would silently "
                f"skew to window boundaries. Set {name} to a multiple of {k} "
                f"(e.g. {suggestion}) or change steps_per_dispatch."
            )

    _aligned("eval_interval", cfg.eval_interval)
    if cfg.ckpt_interval is not None:
        _aligned("ckpt_interval", cfg.ckpt_interval)
    return cfg


# ---------------------------------------------------------------------------
# Named registry (parity: launch.py:25-27 dynamic import by name)
# ---------------------------------------------------------------------------

_REGISTRY: tp.Dict[str, tp.Callable[[], ExperimentConfig]] = {}


def register(name: str):
    def deco(fn: tp.Callable[[], ExperimentConfig]):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_config(name: str, **overrides) -> ExperimentConfig:
    # populate registry
    from midgpt_tpu import configs as _  # noqa: F401

    if name not in _REGISTRY:
        raise KeyError(f"unknown config {name!r}; have {sorted(_REGISTRY)}")
    cfg = _REGISTRY[name]()
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg


def list_configs() -> tp.List[str]:
    from midgpt_tpu import configs as _  # noqa: F401

    return sorted(_REGISTRY)
