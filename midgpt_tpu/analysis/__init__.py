"""Static HLO/sharding analysis: compiled-step collective audits, a
declarative sharding-invariant ruleset, comms cost reports, and an AST
lint for TPU footguns.

Layering:

- :mod:`~midgpt_tpu.analysis.hlo`, :mod:`~midgpt_tpu.analysis.rules`,
  :mod:`~midgpt_tpu.analysis.cost`, :mod:`~midgpt_tpu.analysis.pylint_pass`,
  :mod:`~midgpt_tpu.analysis.traffic`, :mod:`~midgpt_tpu.analysis.budgets`
  are jax-free (pure text/AST/arithmetic processing) — importable
  anywhere, unit-testable in milliseconds against canned fixtures.
- :mod:`~midgpt_tpu.analysis.harness` imports jax and compiles the real
  train step; :mod:`~midgpt_tpu.analysis.choreo` imports jax and traces
  the serving programs to jaxprs, and
  :mod:`~midgpt_tpu.analysis.fusion` /
  :mod:`~midgpt_tpu.analysis.dispatch` (the scan-equivalence prover and
  the launch auditor) build on its flattener. Their names are
  re-exported lazily so ``import midgpt_tpu.analysis`` stays light (the
  CLI must configure the platform *before* jax loads).

CLI: ``python -m midgpt_tpu.analysis --config <name> --mesh 8`` — see the
README's "Static sharding analysis" section.
"""

from midgpt_tpu.analysis.cost import cost_report
from midgpt_tpu.analysis.hlo import (
    AliasEntry,
    Collective,
    MeshInfo,
    count_entry_parameters,
    dtypes_used,
    parse_collectives,
    parse_input_output_alias,
    parse_replica_groups,
)
from midgpt_tpu.analysis.budgets import (
    budget_for,
    check_budget,
    check_dispatch_budget,
    dispatch_budget_for,
)
from midgpt_tpu.analysis.pylint_pass import Finding, lint_paths, lint_source
from midgpt_tpu.analysis.traffic import (
    TrafficReport,
    floor_decomposition,
    floor_table_markdown,
    traffic_report,
    weight_stream_bytes,
)
from midgpt_tpu.analysis.rules import (
    Report,
    Rule,
    RuleSet,
    StepAnalysis,
    Violation,
    rules_for_config,
)

_HARNESS_NAMES = (
    "analyze_train_step",
    "audit_config",
    "audit_serving_dispatch",
    "compile_eval_sweep",
    "compile_train_step",
    "override_logical_rules",
    "prove_scan_equivalence",
    "prove_serving_choreography",
    "serving_dispatch_reports",
    "shrink_for_audit",
    "train_step_comms_summary",
)

__all__ = [
    "AliasEntry",
    "Collective",
    "Finding",
    "MeshInfo",
    "Report",
    "Rule",
    "RuleSet",
    "StepAnalysis",
    "TrafficReport",
    "Violation",
    "budget_for",
    "check_budget",
    "check_dispatch_budget",
    "dispatch_budget_for",
    "cost_report",
    "floor_decomposition",
    "floor_table_markdown",
    "traffic_report",
    "weight_stream_bytes",
    "count_entry_parameters",
    "dtypes_used",
    "lint_paths",
    "lint_source",
    "parse_collectives",
    "parse_input_output_alias",
    "parse_replica_groups",
    "rules_for_config",
    *_HARNESS_NAMES,
]


def __getattr__(name: str):
    if name in _HARNESS_NAMES:
        from midgpt_tpu.analysis import harness

        return getattr(harness, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
