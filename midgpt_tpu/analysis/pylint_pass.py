"""AST-level lint for TPU footguns in jax training code.

Static checks (no jax import needed to *run* the walker; the mesh-axis
check lazily reads the canonical axis names from ``parallel.mesh``):

- ``host-sync-in-jit`` — ``.item()``, ``jax.device_get`` or
  ``np.asarray``/``np.array`` reached from code that is jit-compiled or
  traced (functions passed to/decorated with ``jax.jit``/``pjit``/
  ``filter_jit``, bodies handed to ``lax.scan``/``fori_loop``/
  ``while_loop``/``shard_map``/``remat``, and anything under
  ``grad``/``value_and_grad``). Each of these forces a device->host
  transfer (or a tracer error) on every step.
- ``unknown-mesh-axis`` — a string literal inside a
  ``PartitionSpec(...)``/``P(...)`` call that is not one of the mesh
  axis names declared in ``parallel.mesh.AXIS_NAMES``. A typo'd axis
  name silently shards nothing.
- ``missing-donate`` — a ``jax.jit`` call site (or decorator) on a
  state-threading function (first parameter named ``state`` /
  ``train_state``) without ``donate_argnums``/``donate_argnames``: the
  step would hold two copies of params + optimizer state in HBM.
- ``no-model-closure-jit`` — in ``midgpt_tpu/serving/`` modules only: a
  ``jax.jit``/``pjit``/``filter_jit`` whose traced function references
  ``model`` as a FREE variable (a closure or global capture) instead of
  taking it as a parameter. Closed over, jax bakes every weight into
  the executable as an HLO constant — and for a quantized model XLA
  constant-folds the dequant back into full f32 matrices, silently
  doubling the weight stream the int8 path halves (the PR 6 bug,
  caught here at the AST level before anything compiles; the
  ``no-dequant-materialization`` HLO rule and the traffic budget gate
  are the compile-time backstops).

Findings are waivable inline with ``# shardlint: disable=<rule>`` (or a
bare ``# shardlint: disable`` for all rules) on the offending line —
waivers are reported but don't fail the pass.

Detection is intentionally static and name-based: it follows references
within one module (a function *named* in a jit/scan call is treated as
traced, transitively through nested defs) but does not build a cross-
module call graph. That bounds false negatives at module boundaries and
keeps the pass milliseconds-fast for CI.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
import typing as tp
from pathlib import Path

RULES = {
    "host-sync-in-jit": "host-device sync inside jit/traced code",
    "unknown-mesh-axis": "PartitionSpec axis literal not a declared mesh axis",
    "missing-donate": "jax.jit on a state-threading function without donation",
    "no-model-closure-jit": (
        "serving jit captures the model instead of taking it as a "
        "parameter"
    ),
    "no-unrolled-layer-loop": (
        "serving jit unrolls a Python for-loop over model layers "
        "instead of using the lax.scan layer fold"
    ),
}

# call targets whose function arguments are traced/compiled
_TRACED_ENTRIES = {
    "jit", "pjit", "filter_jit",
    "scan", "fori_loop", "while_loop", "cond", "shard_map",
    "remat", "checkpoint", "grad", "value_and_grad", "vmap", "pmap",
}
# of those, the ones that compile a *top-level* step (donation applies)
_JIT_ENTRIES = {"jit", "pjit", "filter_jit"}

_PRAGMA_RE = re.compile(r"#\s*shardlint:\s*disable(?:=([\w,\-]+))?")

_STATE_PARAM_NAMES = {"state", "train_state"}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    lineno: int
    rule: str
    message: str
    waived: bool = False

    def __str__(self) -> str:
        tag = " (waived)" if self.waived else ""
        return f"{self.path}:{self.lineno}: [{self.rule}]{tag} {self.message}"


def _mesh_axis_names() -> tp.FrozenSet[str]:
    from midgpt_tpu.parallel.mesh import AXIS_NAMES

    return frozenset(AXIS_NAMES)


def _dotted(node: ast.AST) -> str:
    """'jax.lax.scan' for nested attributes, 'jit' for bare names."""
    parts: tp.List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _tail(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


def _pragma_waivers(source: str) -> tp.Dict[int, tp.FrozenSet[str]]:
    """line -> rules waived on that line ({'*'} = all)."""
    out: tp.Dict[int, tp.FrozenSet[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = (
                frozenset(x.strip() for x in m.group(1).split(","))
                if m.group(1)
                else frozenset({"*"})
            )
            out[tok.start[0]] = out.get(tok.start[0], frozenset()) | rules
    except tokenize.TokenizeError:  # pragma: no cover — ast.parse catches 1st
        pass
    return out


def _string_literals(node: ast.AST) -> tp.Iterator[tp.Tuple[str, int]]:
    """(string, lineno) for every str constant under ``node`` (through
    tuples/lists), e.g. the axes of ``P(None, ('replica', 'fsdp'), 'seq')``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value, sub.lineno


class _ModuleLint:
    def __init__(self, path: str, tree: ast.Module, axis_names: tp.FrozenSet[str]):
        self.path = path
        self.tree = tree
        self.axis_names = axis_names
        self.findings: tp.List[tp.Tuple[int, str, str]] = []
        # every def in the module, by name (last one wins — good enough
        # for the intra-module reference following we do)
        self.defs: tp.Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[node.name] = node

    def add(self, lineno: int, rule: str, message: str) -> None:
        self.findings.append((lineno, rule, message))

    # -- traced-region discovery -------------------------------------------

    def _traced_roots(self) -> tp.List[ast.AST]:
        roots: tp.List[ast.AST] = []
        seen: tp.Set[int] = set()
        names: tp.Set[str] = set()

        def mark(node: tp.Optional[ast.AST]) -> None:
            if node is None:
                return
            if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) not in seen:
                    seen.add(id(node))
                    roots.append(node)
            elif isinstance(node, ast.Name):
                names.add(node.id)

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                entry = _tail(_dotted(node.func))
                if entry in _TRACED_ENTRIES:
                    for arg in node.args:
                        mark(arg)
                elif entry == "partial" and node.args:
                    if _tail(_dotted(node.args[0])) in _TRACED_ENTRIES:
                        for arg in node.args[1:]:
                            mark(arg)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    d = deco.func if isinstance(deco, ast.Call) else deco
                    entry = _tail(_dotted(d))
                    if entry in _TRACED_ENTRIES:
                        mark(node)
                    elif entry == "partial" and isinstance(deco, ast.Call):
                        if deco.args and _tail(_dotted(deco.args[0])) in _TRACED_ENTRIES:
                            mark(node)
        # transitively include defs referenced by name from marked code:
        # jax.jit(wrapped) -> wrapped -> step_fn(...) called inside
        frontier = list(names)
        resolved: tp.Set[str] = set()
        while frontier:
            name = frontier.pop()
            if name in resolved:
                continue
            resolved.add(name)
            node = self.defs.get(name)
            if node is None or id(node) in seen:
                continue
            seen.add(id(node))
            roots.append(node)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id in self.defs:
                    frontier.append(sub.id)
        return roots

    # -- rules --------------------------------------------------------------

    def check_host_sync(self) -> None:
        reported: tp.Set[tp.Tuple[int, str]] = set()
        for root in self._traced_roots():
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                msg = None
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    msg = ".item() forces a device->host sync in traced code"
                else:
                    dotted = _dotted(node.func)
                    if _tail(dotted) == "device_get":
                        msg = f"{dotted}() forces a device->host sync in traced code"
                    elif dotted in (
                        "np.asarray", "np.array", "numpy.asarray",
                        "numpy.array", "onp.asarray", "onp.array",
                    ):
                        msg = (
                            f"{dotted}() on a traced value forces a host "
                            "round-trip (use jnp instead)"
                        )
                if msg and (node.lineno, msg) not in reported:
                    reported.add((node.lineno, msg))
                    self.add(node.lineno, "host-sync-in-jit", msg)

    def check_mesh_axes(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if _tail(_dotted(node.func)) not in ("P", "PartitionSpec"):
                continue
            for arg in node.args:
                for s, lineno in _string_literals(arg):
                    if s not in self.axis_names:
                        self.add(
                            lineno,
                            "unknown-mesh-axis",
                            f"PartitionSpec axis {s!r} is not a mesh axis "
                            f"(declared: {sorted(self.axis_names)})",
                        )

    def _first_param(self, fn: tp.Optional[ast.AST]) -> tp.Optional[str]:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return None
        args = fn.args.args
        return args[0].arg if args else None

    def check_missing_donate(self) -> None:
        def has_donate(call: ast.Call) -> bool:
            return any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in call.keywords
            )

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                if _tail(_dotted(node.func)) not in _JIT_ENTRIES:
                    continue
                target = node.args[0] if node.args else None
                fn = (
                    self.defs.get(target.id)
                    if isinstance(target, ast.Name)
                    else target
                )
                first = self._first_param(fn)
                if first in _STATE_PARAM_NAMES and not has_donate(node):
                    self.add(
                        node.lineno,
                        "missing-donate",
                        f"jax.jit on state-threading function "
                        f"(first param {first!r}) without donate_argnums — "
                        "the step holds two copies of the state in HBM",
                    )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                first = self._first_param(node)
                if first not in _STATE_PARAM_NAMES:
                    continue
                for deco in node.decorator_list:
                    d = deco.func if isinstance(deco, ast.Call) else deco
                    entry = _tail(_dotted(d))
                    donated = isinstance(deco, ast.Call) and (
                        has_donate(deco)
                        or any(  # @partial(jax.jit, donate_argnums=...)
                            kw.arg in ("donate_argnums", "donate_argnames")
                            for kw in deco.keywords
                        )
                    )
                    applies = entry in _JIT_ENTRIES or (
                        entry == "partial"
                        and isinstance(deco, ast.Call)
                        and deco.args
                        and _tail(_dotted(deco.args[0])) in _JIT_ENTRIES
                    )
                    if applies and not donated:
                        self.add(
                            deco.lineno,
                            "missing-donate",
                            f"jit-decorated state-threading function "
                            f"{node.name!r} without donate_argnums",
                        )

    def check_model_closure(self) -> None:
        """``no-model-closure-jit``: any jitted function in a serving
        module that references the model as a free variable. The PR 6
        bug class, caught before a single compile: jax bakes a captured
        model's weights into the executable as constants (and constant-
        folds a quantized model's dequant back to full f32 matrices)."""
        def flag_if_captured(fn: tp.Optional[ast.AST], lineno: int,
                             desc: str) -> None:
            if fn is None:
                return
            captured = _free_names(fn) & _MODEL_NAMES
            if captured:
                self.add(
                    lineno,
                    "no-model-closure-jit",
                    f"{desc} captures {sorted(captured)} from the "
                    "enclosing scope instead of taking it as a "
                    "parameter — jit bakes the weights into the "
                    "executable as constants (and constant-folds a "
                    "quantized model's dequant back to f32)",
                )

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                if _tail(_dotted(node.func)) not in _JIT_ENTRIES:
                    continue
                target = node.args[0] if node.args else None
                fn = (
                    self.defs.get(target.id)
                    if isinstance(target, ast.Name)
                    else target
                )
                name = (
                    target.id
                    if isinstance(target, ast.Name)
                    else "<lambda>"
                )
                flag_if_captured(
                    fn, node.lineno, f"jitted function {name!r}"
                )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    d = deco.func if isinstance(deco, ast.Call) else deco
                    entry = _tail(_dotted(d))
                    applies = entry in _JIT_ENTRIES or (
                        entry == "partial"
                        and isinstance(deco, ast.Call)
                        and deco.args
                        and _tail(_dotted(deco.args[0])) in _JIT_ENTRIES
                    )
                    if applies:
                        flag_if_captured(
                            node, deco.lineno,
                            f"jit-decorated function {node.name!r}",
                        )


    def check_unrolled_layer_loop(self) -> None:
        """``no-unrolled-layer-loop`` (serving modules only, waivable):
        a Python-level ``for`` over the model's layers inside jitted/
        traced serving code. The layer fold exists
        (models.gpt ``layer_scan="on"``, proven bitwise and gated by
        analysis.fusion/dispatch) — a new serving program body that
        unrolls ``for i in range(cfg.n_layer)`` re-introduces the L×
        per-layer launch structure the fold removed, silently (zero
        byte movement, so only the dispatch budget or this lint sees
        it). The models/ drivers keep their unrolled ``layer_scan=
        "off"`` branches on purpose (the off path is the fold's
        bitwise reference); this rule scopes to ``midgpt_tpu/serving/``
        where program BODIES live."""
        def mentions_layers(node: ast.AST) -> bool:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) and sub.attr == "n_layer":
                    return True
                if isinstance(sub, ast.Name) and sub.id == "n_layer":
                    return True
            return False

        reported: tp.Set[int] = set()
        for root in self._traced_roots():
            for node in ast.walk(root):
                if not isinstance(node, (ast.For, ast.AsyncFor)):
                    continue
                if node.lineno in reported:
                    continue
                if mentions_layers(node.iter):
                    reported.add(node.lineno)
                    self.add(
                        node.lineno,
                        "no-unrolled-layer-loop",
                        "Python for-loop over model layers in a traced "
                        "serving body — use the lax.scan layer fold "
                        "(models.gpt layer_scan) so decode dispatch "
                        "structure stays 1 inlined body per program "
                        "(gated by analysis.dispatch budgets)",
                    )


def _free_names(fn: ast.AST) -> tp.Set[str]:
    """Names a function LOADS but never binds — its closure/global
    captures, to the static approximation one module allows. Scope-
    aware: each nested def/lambda is resolved in ITS OWN scope first
    (its params and local Stores bind only there), and only its
    residual free names propagate out — so a nested helper's `model`
    parameter neither hides an enclosing capture nor fabricates one."""
    bound: tp.Set[str] = set()
    loaded: tp.Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        for arg in [
            *a.args, *a.kwonlyargs, *getattr(a, "posonlyargs", []),
        ]:
            bound.add(arg.arg)
        if a.vararg:
            bound.add(a.vararg.arg)
        if a.kwarg:
            bound.add(a.kwarg.arg)

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                bound.add(child.name)
                loaded.update(_free_names(child))
                # decorators and defaults evaluate in THIS scope
                for d in child.decorator_list:
                    visit(d)
                for d in [
                    *child.args.defaults,
                    *[x for x in child.args.kw_defaults if x],
                ]:
                    visit(d)
                continue
            if isinstance(child, ast.Lambda):
                loaded.update(_free_names(child))
                for d in [
                    *child.args.defaults,
                    *[x for x in child.args.kw_defaults if x],
                ]:
                    visit(d)
                continue
            if isinstance(child, ast.Name):
                if isinstance(child.ctx, (ast.Store, ast.Del)):
                    bound.add(child.id)
                else:
                    loaded.add(child.id)
            elif isinstance(child, (ast.Import, ast.ImportFrom)):
                for alias in child.names:
                    bound.add((alias.asname or alias.name).split(".")[0])
            visit(child)

    visit(fn)
    return loaded - bound


# the captured names the serving closure rule flags: the model pytree
# must always be an ENTRY PARAMETER of a jitted serving program
_MODEL_NAMES = {"model", "qmodel"}


def lint_source(source: str, path: str = "<string>") -> tp.List[Finding]:
    """Lint one module's source text."""
    tree = ast.parse(source, filename=path)
    lint = _ModuleLint(path, tree, _mesh_axis_names())
    lint.check_host_sync()
    lint.check_mesh_axes()
    lint.check_missing_donate()
    # the model-closure rule covers the serving package — where every
    # jitted program's model MUST be an entry parameter (engine.py's
    # program cache and the int8 path both depend on it) — plus the
    # train-side jit sites (train.py, bench.py): a train program that
    # closes over params would silently constant-fold the whole model
    # into the executable and break donation, exactly the PR 6 serving
    # bug class on the other side of the fence. Trainers legitimately
    # close over config-derived structures; only _MODEL_NAMES trip it.
    if (
        "serving" in Path(path).parts
        or Path(path).name in ("train.py", "bench.py")
    ):
        lint.check_model_closure()
    if "serving" in Path(path).parts:
        # the layer-loop rule stays serving-scoped: serving program
        # bodies must take the scan fold; the models/ drivers keep
        # their unrolled branch as the fold's bitwise reference, and
        # train.py's loop structure is gated semantically by the
        # train dispatch budget instead
        lint.check_unrolled_layer_loop()
    waivers = _pragma_waivers(source)
    findings = []
    for lineno, rule, message in sorted(lint.findings):
        waived_rules = waivers.get(lineno, frozenset())
        findings.append(Finding(
            path=path,
            lineno=lineno,
            rule=rule,
            message=message,
            waived="*" in waived_rules or rule in waived_rules,
        ))
    return findings


def lint_paths(paths: tp.Iterable[tp.Union[str, Path]]) -> tp.List[Finding]:
    """Lint every ``.py`` file under the given files/directories."""
    files: tp.List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        else:
            files.append(p)
    findings: tp.List[Finding] = []
    for f in files:
        findings.extend(lint_source(f.read_text(), str(f)))
    return findings


def unwaived(findings: tp.Iterable[Finding]) -> tp.List[Finding]:
    return [f for f in findings if not f.waived]
