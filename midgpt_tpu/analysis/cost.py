"""Per-step communication cost report from a parsed compiled step.

Attributes every collective's estimated wire traffic to the mesh axes it
crosses and splits the total into ICI (intra-slice) vs DCN (cross-slice)
bytes — the numbers a comms roofline needs, in the same
one-JSON-object-with-scalar-fields shape as the ``BENCH_*.json``
trajectory records so the two can ride the same tooling.

Caveat (stated in the report itself): counts are *static* — a collective
inside a non-unrolled ``while`` loop (grad-accumulation scan, chunked
loss) is counted once, not per trip. The shipped audit configs compile
with ``g_accum_iters=1`` and unrolled chunk loops so the static count is
the per-step count there.
"""

from __future__ import annotations

import typing as tp

from midgpt_tpu.analysis.rules import StepAnalysis

SCHEMA_VERSION = 1


def cost_report(a: StepAnalysis) -> tp.Dict[str, tp.Any]:
    """JSON-ready comms report for one compiled step."""
    by_kind: tp.Dict[str, tp.Dict[str, int]] = {}
    by_axis: tp.Dict[str, int] = {}
    collectives = []
    total_traffic = 0
    dcn_traffic = 0
    for c in a.collectives:
        axes = a.mesh.collective_axes(c)
        crosses_dcn = a.mesh.collective_crosses_slice(c)
        traffic = c.traffic_bytes
        total_traffic += traffic
        if crosses_dcn:
            dcn_traffic += traffic
        k = by_kind.setdefault(c.kind, {"count": 0, "traffic_bytes": 0})
        k["count"] += 1
        k["traffic_bytes"] += traffic
        axis_key = "+".join(axes) if axes else "none"
        by_axis[axis_key] = by_axis.get(axis_key, 0) + traffic
        collectives.append({
            "kind": c.kind,
            "result_shapes": [
                f"{d}[{','.join(map(str, s))}]" for d, s in c.result_shapes
            ],
            "bytes": c.result_bytes,
            "traffic_bytes": traffic,
            "group_size": c.group_size,
            "mesh_axes": list(axes),
            "medium": "dcn" if crosses_dcn else ("ici" if axes else "local"),
            "dims": list(c.dims),
            "op_name": c.op_name,
        })
    return {
        "schema_version": SCHEMA_VERSION,
        "metric": "comms_traffic_bytes_per_step",
        "value": total_traffic,
        "unit": "bytes",
        "ici_bytes": total_traffic - dcn_traffic,
        "dcn_bytes": dcn_traffic,
        "collective_count": len(a.collectives),
        "by_kind": by_kind,
        "by_axis": by_axis,
        "mesh": {
            "axis_names": list(a.mesh.axis_names),
            "axis_sizes": list(a.mesh.axis_sizes),
            "num_slices": a.mesh.num_slices,
        },
        "note": (
            "static counts: collectives inside while loops are counted "
            "once, not per trip"
        ),
        "collectives": collectives,
    }
