"""Scan-equivalence prover for the fused serving layer loop.

ROADMAP item 1 folds the per-layer loop of the three serving programs
(decode window / prefill chunk / speculative verify) into one
``lax.scan`` (``layer_scan="on"``, models.gpt). The fold is an
arithmetic-touching rewrite of the hottest path in the engine, and this
repo's hard-won rule (PRs 4/5/6/8) is that such rewrites only land
behind a machine-checked static gate. This module is that gate — the
SIXTH audit family, next to donation / host-sync / dequant /
choreography / traffic:

1. **Layer homogeneity** — the unrolled program's per-layer normalized
   op-and-dtype traces (choreo.py's extractor: float arithmetic only,
   shapes dropped, weight matmuls classified by entry-parameter origin)
   are IDENTICAL, layer for layer. That is the precondition that makes
   the fold legal at all: ``lax.scan`` runs ONE body L times, so a
   program whose layers differ (a per-layer dtype special case, a
   depth-dependent branch) cannot be folded without changing what some
   layer computes. Checked twice, at two granularities: the attention
   regions (the subgraph the choreography contracts live in) and the
   FULL per-layer trace segment (everything between consecutive layers'
   first weight projections — attention + MLP + the following norm).
2. **Fold structure** — the fused program's flat trace contains exactly
   ONE inlined layer body (the scan body, traced once), i.e. the loop
   really did fold; a re-unrolled "fused" program shows L bodies and
   fails here before any dispatch budget looks at it.
3. **Scan-body equivalence** — the fused program's single layer body is
   op-for-op equal to the unrolled program's per-layer trace (attention
   region, full segment, softmax signature, lm-head choreography), the
   same way choreo.py proves verify ≡ decode. A dtype drift that exists
   only on the scan path — the exact class of bug a fused rewrite can
   introduce while the unrolled path stays green — turns this red
   before anything compiles.

Everything operates on jaxprs through :mod:`~midgpt_tpu.analysis.choreo`'s
flattener (no compilation, no execution); a full three-program proof of
both layer_scan values runs in seconds on CPU. The runtime side of the
gate is the bitwise on-vs-off token-identity matrix in
``tests/test_serving.py`` / ``test_serving_sharded.py``; the launch-count
side is :mod:`~midgpt_tpu.analysis.dispatch`.
"""

from __future__ import annotations

import dataclasses
import typing as tp

from midgpt_tpu.analysis.choreo import (
    FlatGraph,
    SoftmaxSignature,
    TraceRec,
    _FLOAT_DTYPES,
    _dot_kind,
    _first_diff,
    attention_regions,
    flatten_jaxpr,
    kernel_choreography,
    normalized_trace,
    softmax_signature,
)

PROGRAMS = ("decode_window", "prefill_chunk", "verify")


def layer_segments(
    trace: tp.Sequence[TraceRec], n_layers: int
) -> tp.Optional[tp.List[tp.Tuple[TraceRec, ...]]]:
    """Split a full normalized trace into per-layer segments.

    Layer boundaries are the weight projections ('proj' records): every
    transformer layer contracts the same fixed set of weight matrices
    (wqkv, wo, w_up, w_down[, w_gate]) and the program ends with exactly
    one lm-head projection, so with P = (total_projs - 1) / n_layers
    projections per layer, layer i's segment spans from its FIRST proj
    to just before layer i+1's first proj (the last layer's segment ends
    at the lm-head proj). A segment therefore carries the layer's whole
    arithmetic — attention, MLP, and the RMSNorm records that precede
    the NEXT first-proj (which for the last layer is ``ln_f``, the same
    weightless-RMSNorm op sequence as a block's ``ln1``). Pre-layer
    records (rope-row casts, embedding) sit before the first proj and
    are excluded; post-head records (sampling, acceptance) come after
    the last boundary and are excluded.

    Returns ``None`` when the trace does not have the expected proj
    structure (not enough projections, or a count that does not divide
    into ``n_layers`` equal groups) — the caller reports that as a
    failed check, never as a vacuous pass."""
    projs = [i for i, rec in enumerate(trace) if rec[0] == "proj"]
    if n_layers < 1 or len(projs) < n_layers + 1:
        return None
    if (len(projs) - 1) % n_layers:
        return None
    per = (len(projs) - 1) // n_layers
    return [
        tuple(trace[projs[i * per] : projs[(i + 1) * per]])
        for i in range(n_layers)
    ]


def _program_softmax(
    name: str, graph: FlatGraph
) -> tp.Optional[SoftmaxSignature]:
    """The program's softmax-core signature — from the Pallas kernel
    body when the attention is kernelized, from the first float ``exp``
    otherwise. Unlike ``extract_choreography`` this does NOT assert
    cross-layer equality (homogeneity is this module's own soft check);
    returns ``None`` when no softmax is found (reported as a failure)."""
    kernels = [k for k in graph.kernels if k is not None]
    if kernels:
        return kernel_choreography(name, kernels[0])
    exps = [
        op for op in graph.ops
        if op.prim == "exp" and op.out_dtypes[0] in _FLOAT_DTYPES
    ]
    if not exps:
        return None
    return softmax_signature(graph, exps[0])


def _program_lm_head(
    graph: FlatGraph,
) -> tp.Tuple[tp.Optional[TraceRec], bool]:
    """The last weight projection in program order + whether the
    quantized dequant-epilogue multiply follows it (the same extraction
    ``extract_choreography`` performs)."""
    lm_op = None
    for op in graph.ops:
        if op.prim == "dot_general" and _dot_kind(op) == "proj":
            lm_op = op
    if lm_op is None:
        return None, False
    epilogue = any(
        c.prim == "mul" and "invar" in c.in_origins
        for c in graph.consumers.get(lm_op.out_ids[0], [])
    )
    return ("proj", lm_op.in_dtypes, lm_op.out_dtypes), epilogue


@dataclasses.dataclass(frozen=True)
class FusionCheck:
    name: str
    ok: bool
    detail: str = ""


@dataclasses.dataclass(frozen=True)
class FusionReport:
    """The scan-equivalence proof over the three serving programs."""

    checks: tp.Tuple[FusionCheck, ...]

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "ok": self.ok,
            "checks": [
                {"name": c.name, "ok": c.ok, "detail": c.detail}
                for c in self.checks
            ],
        }


def _segment_diff(
    a: tp.Optional[tp.Sequence], b: tp.Optional[tp.Sequence]
) -> str:
    if a is None or b is None:
        return "segmentation failed"
    return _first_diff(tuple(a), tuple(b)) or ""


def prove_program_fusion(
    name: str, unrolled_jaxpr, fused_jaxpr
) -> tp.List[FusionCheck]:
    """The per-program checks: homogeneity of the unrolled trace, fold
    structure of the fused trace, and scan-body ≡ per-layer equivalence
    between the two."""
    checks: tp.List[FusionCheck] = []
    un_graph = flatten_jaxpr(unrolled_jaxpr)
    fu_graph = flatten_jaxpr(fused_jaxpr)
    un_regions = attention_regions(un_graph)
    fu_regions = attention_regions(fu_graph)
    n_layers = len(un_regions)
    un_trace = normalized_trace(un_graph)
    fu_trace = normalized_trace(fu_graph)
    un_segs = layer_segments(un_trace, n_layers) if n_layers else None
    fu_segs = layer_segments(fu_trace, 1)

    # 1a. homogeneity at attention granularity
    hetero = ""
    for i, r in enumerate(un_regions[1:], start=2):
        if tuple(r) != tuple(un_regions[0]):
            hetero = (
                f"layer {i} vs layer 1: "
                f"{_first_diff(tuple(un_regions[0]), tuple(r))}"
            )
            break
    checks.append(FusionCheck(
        name=f"{name}: unrolled layers are homogeneous (attention)",
        ok=n_layers >= 2 and not hetero,
        detail=hetero or (f"only {n_layers} attention region(s) found"
                          if n_layers < 2 else ""),
    ))
    # 1b. homogeneity over the FULL per-layer segment
    seg_detail = ""
    seg_ok = un_segs is not None and len(un_segs) == n_layers
    if seg_ok:
        for i, s in enumerate(un_segs[1:], start=2):
            if s != un_segs[0]:
                seg_ok = False
                seg_detail = (
                    f"layer {i} vs layer 1: "
                    f"{_first_diff(un_segs[0], s)}"
                )
                break
    else:
        seg_detail = (
            "per-layer segmentation failed (projection structure does "
            f"not divide into {n_layers} equal layers)"
        )
    checks.append(FusionCheck(
        name=f"{name}: unrolled layers are homogeneous (full trace)",
        ok=seg_ok,
        detail=seg_detail,
    ))

    # 2. the fused program really folded the loop: ONE inlined body
    fold_ok = len(fu_regions) == 1 and fu_segs is not None
    checks.append(FusionCheck(
        name=f"{name}: fused program folds the layer loop into one body",
        ok=fold_ok,
        detail=(
            "" if fold_ok
            else (
                f"{len(fu_regions)} inlined layer bodies in the fused "
                "trace (1 = folded; the unrolled count means the scan "
                "did not fold)"
                if len(fu_regions) != 1
                else "segmentation failed"
            )
        ),
    ))

    # 3a. scan body ≡ per-layer trace, attention region
    att_diff = (
        _first_diff(tuple(un_regions[0]), tuple(fu_regions[0]))
        if un_regions and fu_regions
        else "missing attention region"
    )
    checks.append(FusionCheck(
        name=f"{name}: scan body equals the per-layer trace (attention)",
        ok=bool(un_regions and fu_regions) and not att_diff,
        detail=att_diff,
    ))
    # 3b. ... and over the full layer segment
    full_ok = (
        un_segs is not None and fu_segs is not None
        and fu_segs[0] == un_segs[0]
    )
    checks.append(FusionCheck(
        name=f"{name}: scan body equals the per-layer trace (full segment)",
        ok=full_ok,
        detail=(
            "" if full_ok
            else _segment_diff(
                un_segs[0] if un_segs else None,
                fu_segs[0] if fu_segs else None,
            )
        ),
    ))
    # 3c. softmax-core signature (the PR 4/5 bug-class granularity) +
    # extraction-degeneracy guard: an unreadable signature is a
    # violation, never a vacuous pass (the PR 9 lesson)
    un_sig = _program_softmax(f"{name}/unrolled", un_graph)
    fu_sig = _program_softmax(f"{name}/fused", fu_graph)
    degenerate = (
        un_sig is None or fu_sig is None
        or not un_sig.qk_contracts or not fu_sig.qk_contracts
        or not un_sig.pv_contracts or not fu_sig.pv_contracts
    )
    checks.append(FusionCheck(
        name=f"{name}: scan body softmax signature equals per-layer",
        ok=not degenerate and un_sig == fu_sig,
        detail=(
            "degenerate signature extraction (no score/PV contractions "
            "visible to the prover)" if degenerate
            else (
                "" if un_sig == fu_sig
                else f"{un_sig.describe()} != {fu_sig.describe()}"
            )
        ),
    ))
    # 3d. lm-head choreography unchanged by the fold
    un_lm = _program_lm_head(un_graph)
    fu_lm = _program_lm_head(fu_graph)
    checks.append(FusionCheck(
        name=f"{name}: lm-head choreography unchanged by the fold",
        ok=un_lm == fu_lm and un_lm[0] is not None,
        detail=f"unrolled {un_lm} != fused {fu_lm}" if un_lm != fu_lm
        else ("no lm-head projection found" if un_lm[0] is None else ""),
    ))
    return checks


def prove_scan_fusion(
    unrolled: tp.Mapping[str, tp.Any],
    fused: tp.Mapping[str, tp.Any],
) -> FusionReport:
    """Prove all three serving programs' scan-equivalence contracts.
    ``unrolled``/``fused`` map program name -> traced ClosedJaxpr
    (``serving.engine.trace_serving_programs`` with ``layer_scan`` off
    and on respectively — the very jitted callables the engine launches)."""
    checks: tp.List[FusionCheck] = []
    for prog in PROGRAMS:
        assert prog in unrolled and prog in fused, (
            f"missing program {prog!r} in the traced set"
        )
        checks.extend(
            prove_program_fusion(prog, unrolled[prog], fused[prog])
        )
    return FusionReport(checks=tuple(checks))
