"""Structured parser over post-optimization HLO text.

The input is ``step.lower(...).compile().as_text()`` — the partitioner's
actual output, after GSPMD has inserted every collective. This module
turns that text into typed records:

- :class:`Collective` — one per all-gather / all-reduce / reduce-scatter /
  collective-permute / all-to-all instruction: result + operand
  shapes/dtypes, byte counts, device groups (both ``{{0,1},{2,3}}`` and
  iota ``[G,S]<=[N...]T(...)`` forms), gather ``dimensions``, and the
  source op_name/line XLA recorded.
- :class:`AliasEntry` — the module header's ``input_output_alias`` map,
  i.e. which parameter buffers the executable actually reuses for
  outputs. This is the ground truth for "did ``donate_argnums`` stick".
- :class:`MeshInfo` — a jax-free description of the device mesh (axis
  names/sizes, HLO device id -> mesh coordinates, slice split) so rules
  and cost attribution can ask *which mesh axes a collective crosses*
  without importing jax. Built from a live ``jax.sharding.Mesh`` via
  :meth:`MeshInfo.from_mesh`, or directly from literals in tests.

Everything here is pure text/array processing — no jax import — so the
fixture-based unit tests run in milliseconds and the module is usable
from hosts without an accelerator runtime.
"""

from __future__ import annotations

import dataclasses
import re
import typing as tp

import numpy as np

# HLO primitive-type byte widths (shapes look like ``bf16[8,256,1024]``)
DTYPE_BYTES: tp.Mapping[str, int] = {
    "pred": 1,
    "s4": 1, "u4": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

COLLECTIVE_KINDS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_COLL_RE = re.compile(
    r"\b(all-gather|all-reduce|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start)?\("
)
# dtype[dims]  — dims empty for scalars
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{.*?\}\}|\[[^\]]*\]<=\[[^\]]*\](?:T\([^)]*\))?)"
)
_PAIRS_RE = re.compile(r"source_target_pairs=(\{\{.*?\}\})")
_DIMS_RE = re.compile(r"dimensions=\{([0-9,]+)\}")
_CHANNEL_RE = re.compile(r"channel_id=([0-9]+)")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_SRCLINE_RE = re.compile(r"source_line=([0-9]+)")
_ALIAS_ENTRY_RE = re.compile(
    r"\{([0-9,\s]*)\}:\s*\(([0-9]+),\s*\{[0-9,\s]*\},\s*(may-alias|must-alias)\)"
)


ShapeT = tp.Tuple[int, ...]


def shape_bytes(dtype: str, shape: ShapeT) -> int:
    """Byte size of one ``dtype[shape]`` buffer (unknown dtypes count 0 so
    token/opaque types never inflate a report)."""
    n = int(np.prod(shape)) if shape else 1
    return n * DTYPE_BYTES.get(dtype, 0)


def parse_replica_groups(spec: str) -> tp.List[tp.List[int]]:
    """``replica_groups``/``source_target_pairs`` -> list of device-id groups.

    Handles both the explicit ``{{0,1},{2,3}}`` form and the iota form
    ``[G,S]<=[N0,N1,...]`` with an optional ``T(perm)`` transpose suffix.
    """
    if spec.startswith("{{"):
        return [
            [int(x) for x in g.split(",") if x.strip() != ""]
            for g in re.findall(r"\{([0-9,\s]+)\}", spec)
        ]
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](T\(([0-9,]+)\))?", spec)
    if not m:
        raise ValueError(f"unparsed replica_groups {spec!r}")
    gshape = [int(x) for x in m.group(1).split(",")]
    rshape = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(int(np.prod(rshape))).reshape(rshape)
    if m.group(3):
        ids = np.transpose(ids, [int(x) for x in m.group(4).split(",")])
    ids = ids.reshape(gshape)
    return [list(map(int, row)) for row in ids]


@dataclasses.dataclass(frozen=True)
class Collective:
    """One collective instruction from the compiled module."""

    kind: str  # all-gather | all-reduce | ... (``-start`` normalized away)
    line: str  # the full instruction text, stripped
    lineno: int  # 1-based line in the HLO text
    result_shapes: tp.Tuple[tp.Tuple[str, ShapeT], ...]  # (dtype, dims)
    operand_shapes: tp.Tuple[tp.Tuple[str, ShapeT], ...]
    groups: tp.Tuple[tp.Tuple[int, ...], ...]  # device-id groups
    dims: tp.Tuple[int, ...]  # gather/scatter `dimensions={...}`
    channel_id: tp.Optional[int] = None
    op_name: str = ""  # jax op_name metadata (trace provenance)
    source_line: tp.Optional[int] = None

    @property
    def shapes(self) -> tp.Tuple[ShapeT, ...]:
        """Result dims only (dtype-less) — what shape-pattern rules match."""
        return tuple(s for _, s in self.result_shapes)

    @property
    def result_bytes(self) -> int:
        return sum(shape_bytes(d, s) for d, s in self.result_shapes)

    @property
    def operand_bytes(self) -> int:
        return sum(shape_bytes(d, s) for d, s in self.operand_shapes)

    @property
    def group_size(self) -> int:
        return max((len(g) for g in self.groups), default=1)

    @property
    def traffic_bytes(self) -> int:
        """Per-device wire-traffic estimate under the standard ring
        algorithms (the numbers comms-bound roofline models use):

        - all-gather: each device receives (G-1)/G of the result
        - all-reduce: reduce-scatter + all-gather = 2·(G-1)/G of the buffer
        - reduce-scatter: sends (G-1)/G of the *input* (≈ (G-1)× output)
        - collective-permute: the whole buffer moves one hop
        - all-to-all: (G-1)/G of the buffer is exchanged
        """
        g = self.group_size
        if g <= 1:
            return 0
        if self.kind == "all-gather":
            return self.result_bytes * (g - 1) // g
        if self.kind == "all-reduce":
            return 2 * self.result_bytes * (g - 1) // g
        if self.kind == "reduce-scatter":
            return self.operand_bytes * (g - 1) // g
        if self.kind == "collective-permute":
            return self.result_bytes
        if self.kind == "all-to-all":
            return self.result_bytes * (g - 1) // g
        return self.result_bytes


def _split_result_operand(line: str, op_start: int) -> tp.Tuple[str, str]:
    """Split an instruction line into its result-shape text (between '='
    and the op keyword) and the operand text (inside the op's parens)."""
    head = line[:op_start]
    if " = " in head:
        head = head.split(" = ", 1)[1]
    # operand list: from the '(' that opens the op call to its matching ')'
    lparen = line.index("(", op_start)
    depth, rparen = 0, len(line)
    for i in range(lparen, len(line)):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                rparen = i
                break
    return head, line[lparen + 1 : rparen]


def parse_collectives(hlo: str) -> tp.List[Collective]:
    """Every collective instruction in the module, in textual order."""
    out: tp.List[Collective] = []
    for lineno, raw in enumerate(hlo.splitlines(), start=1):
        m = _COLL_RE.search(raw)
        if m is None or "=" not in raw:
            continue
        line = raw.strip()
        m = _COLL_RE.search(line)
        assert m is not None
        kind = m.group(1)

        gm = _GROUPS_RE.search(line)
        pm = _PAIRS_RE.search(line)
        if gm:
            groups = parse_replica_groups(gm.group(1))
        elif pm:
            # each {src,dst} pair is a 2-device "group" for crossing checks
            groups = parse_replica_groups(pm.group(1))
        else:
            groups = []

        head, operands = _split_result_operand(line, m.start())
        result_shapes = tuple(
            (d, tuple(int(x) for x in dims.split(",") if x != ""))
            for d, dims in _SHAPE_RE.findall(head)
        )
        operand_shapes = tuple(
            (d, tuple(int(x) for x in dims.split(",") if x != ""))
            for d, dims in _SHAPE_RE.findall(operands)
        )

        dm = _DIMS_RE.search(line)
        cm = _CHANNEL_RE.search(line)
        om = _OPNAME_RE.search(line)
        sm = _SRCLINE_RE.search(line)
        out.append(
            Collective(
                kind=kind,
                line=line,
                lineno=lineno,
                result_shapes=result_shapes,
                operand_shapes=operand_shapes,
                groups=tuple(tuple(g) for g in groups),
                dims=tuple(int(x) for x in dm.group(1).split(",")) if dm else (),
                channel_id=int(cm.group(1)) if cm else None,
                op_name=om.group(1) if om else "",
                source_line=int(sm.group(1)) if sm else None,
            )
        )
    return out


# ---------------------------------------------------------------------------
# Buffer-donation audit
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AliasEntry:
    """One entry of the module's ``input_output_alias`` map."""

    output_index: tp.Tuple[int, ...]  # index into the (tuple) result
    param_number: int  # flat entry-parameter number
    kind: str  # may-alias | must-alias


def parse_input_output_alias(hlo: str) -> tp.List[AliasEntry]:
    """The executable's input->output buffer aliasing, from the module
    header. Empty when donation was dropped (or never requested)."""
    for line in hlo.splitlines():
        if "input_output_alias=" not in line:
            continue
        return [
            AliasEntry(
                output_index=tuple(
                    int(x) for x in e[0].split(",") if x.strip() != ""
                ),
                param_number=int(e[1]),
                kind=e[2],
            )
            for e in _ALIAS_ENTRY_RE.findall(line)
        ]
    return []


def parse_entry_parameters(
    hlo: str,
) -> tp.Tuple[tp.Tuple[str, ShapeT], ...]:
    """(dtype, shape) of every flat entry parameter, from the module's
    ``entry_computation_layout={(...)->...}`` header clause — what the
    program actually streams in from HBM each launch. The
    no-dequant-materialization rule checks quantized weights enter as
    s8 here (and that no full-precision copy does)."""
    m = re.search(r"entry_computation_layout=\{\((.*?)\)->", hlo)
    if not m:
        return ()
    return tuple(
        (d, tuple(int(x) for x in dims.split(",") if x != ""))
        for d, dims in _SHAPE_RE.findall(m.group(1))
    )


def count_entry_parameters(hlo: str) -> int:
    """Number of flat parameters of the entry computation, from the
    ``entry_computation_layout={(...)->...}`` header clause."""
    m = re.search(r"entry_computation_layout=\{\((.*?)\)->", hlo)
    if not m:
        return 0
    inner = m.group(1).strip()
    if not inner:
        return 0
    # parameters are comma-separated shapes; commas also appear inside
    # [dims] and {layout} brackets, so count only depth-0 commas
    depth = 0
    count = 1
    for ch in inner:
        if ch in "[{(":
            depth += 1
        elif ch in "]})":
            depth -= 1
        elif ch == "," and depth == 0:
            count += 1
    return count


def dtypes_used(hlo: str) -> tp.Set[str]:
    """Every HLO primitive dtype appearing in a shape anywhere in the
    module (the no-f64 rule scans this)."""
    return {d for d, _ in _SHAPE_RE.findall(hlo) if d in DTYPE_BYTES}


# ---------------------------------------------------------------------------
# Mesh description (jax-free)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MeshInfo:
    """Axis names/sizes + HLO-device-id -> mesh-coordinate mapping.

    HLO collectives name devices by *logical* id — the position in the
    mesh's device assignment, i.e. the flattened index into
    ``mesh.devices`` — so coordinates are ``unravel_index(id, shape)``.

    ``num_slices > 1`` marks the leading factor of the ``replica`` axis as
    the DCN (cross-slice) dimension, matching
    ``parallel.mesh.hybrid_device_layout``.
    """

    axis_names: tp.Tuple[str, ...]
    axis_sizes: tp.Tuple[int, ...]
    num_slices: int = 1

    @classmethod
    def from_mesh(cls, mesh, num_slices: int = 1) -> "MeshInfo":
        return cls(
            axis_names=tuple(mesh.axis_names),
            axis_sizes=tuple(mesh.devices.shape),
            num_slices=num_slices,
        )

    @property
    def shape(self) -> tp.Dict[str, int]:
        return dict(zip(self.axis_names, self.axis_sizes))

    @property
    def n_devices(self) -> int:
        return int(np.prod(self.axis_sizes))

    def coords(self, device_id: int) -> tp.Tuple[int, ...]:
        return tuple(
            int(c) for c in np.unravel_index(device_id, self.axis_sizes)
        )

    def crossed_axes(self, group: tp.Sequence[int]) -> tp.Tuple[str, ...]:
        """Mesh axes along which the group's devices differ — the axes
        this collective actually moves data across."""
        if len(group) < 2:
            return ()
        coords = np.asarray([self.coords(d) for d in group])
        return tuple(
            name
            for i, name in enumerate(self.axis_names)
            if len(set(coords[:, i].tolist())) > 1
        )

    def collective_axes(self, coll: Collective) -> tp.Tuple[str, ...]:
        axes: tp.List[str] = []
        for g in coll.groups:
            for a in self.crossed_axes(g):
                if a not in axes:
                    axes.append(a)
        return tuple(sorted(axes, key=self.axis_names.index))

    def slice_of(self, device_id: int) -> int:
        """Slice (DCN domain) of a device: the leading ``num_slices``
        factor of its 'replica' coordinate."""
        if self.num_slices <= 1:
            return 0
        rep_axis = self.axis_names.index("replica")
        rep = self.coords(device_id)[rep_axis]
        per_slice = self.axis_sizes[rep_axis] // self.num_slices
        return rep // per_slice

    def crosses_slice(self, group: tp.Sequence[int]) -> bool:
        return len({self.slice_of(d) for d in group}) > 1

    def collective_crosses_slice(self, coll: Collective) -> bool:
        return any(self.crosses_slice(g) for g in coll.groups if g)
