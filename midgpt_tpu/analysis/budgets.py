"""Checked-in HBM byte budgets for the serving programs.

The numbers a serving program is ALLOWED to stream, per dispatch, at
the CI audit geometry — the generalization of the shape-matching
``no-dequant-materialization`` / ``no-batch-allgather-in-page-gather``
rules into plain accounting: any regression that re-materializes,
re-gathers, or constant-folds a large buffer moves bytes, and a moved
byte count trips the gate regardless of what the HLO happens to look
like. Concretely:

- a model CLOSED OVER by a program (the PR 6 bug) removes the weight
  stream from the entry interface (below the weights band) and dumps it
  into ``constants`` (above the constants cap) — two trips, with the
  quantized variant additionally 4x over on the folded f32 copies;
- a KV-head-sharded pool regathered through the page gathers (the PR 7
  bug class) multiplies the sharded geometry's ``comms`` bytes past its
  cap;
- an accidental full-precision weight copy smuggled in as a second
  input lands in ``unclassified`` (its own violation).

Budgets are exact measured values with a relative tolerance band, keyed
by ``(program, precision, geometry)`` at the ONE audit geometry CI
compiles (:data:`AUDIT_GEOMETRY`): openwebtext shrunk to 2 layers /
block 256 / vocab 1024, slots=4, window=4, page_size=16, spec_len=4.
Regenerate after an intentional geometry or model change with::

    python -m midgpt_tpu.analysis --config openwebtext --serving \
        --traffic --print-budgets

and paste the emitted dict here — the diff IS the review artifact.

jax-free (pure numbers), like rules.py.
"""

from __future__ import annotations

import typing as tp

# the geometry every budget below was measured at; the CLI refuses to
# gate traffic on a non-matching geometry rather than mis-fail it
AUDIT_GEOMETRY: tp.Dict[str, tp.Any] = {
    "config": "openwebtext",
    "n_layer": 2,
    "block_size": 256,
    "vocab_size": 1024,
    "slots": 4,
    "window": 4,
    "page_size": 16,
    "spec_len": 4,
}

# streams are bytes at the compiled program's entry interface
# (traffic.traffic_report); comms is the per-dispatch collective wire
# estimate (cost.py's ring-algorithm arithmetic) on sharded geometries
BUDGETS: tp.Dict[tp.Tuple[str, str, str], tp.Dict[str, int]] = {
    # --- single chip, bf16 ---
    ("decode_window", "bf16", "single"): {
        "weights": 31457792, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    ("prefill_chunk", "bf16", "single"): {
        "weights": 31457792, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    ("verify_program", "bf16", "single"): {
        "weights": 31457792, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    # --- single chip, int8 (s8 matrices + f32 per-channel scales) ---
    ("decode_window", "int8", "single"): {
        "weights": 16574976, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    ("prefill_chunk", "int8", "single"): {
        "weights": 16574976, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    ("verify_program", "int8", "single"): {
        "weights": 16574976, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    # --- tp=2,replica=2 (per-shard local streams: weights and the
    # whole-KV-head pool halve; replica rides replicated) ---
    ("decode_window", "bf16", "replica2,tensor2"): {
        "weights": 15729152, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 165936,
    },
    ("prefill_chunk", "bf16", "replica2,tensor2"): {
        "weights": 15729152, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 2654208,
    },
    ("verify_program", "bf16", "replica2,tensor2"): {
        "weights": 15729152, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 829728,
    },
    ("decode_window", "int8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 165936,
    },
    ("prefill_chunk", "int8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 2654208,
    },
    ("verify_program", "int8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 829728,
    },
    # --- int8-quantized KV pool (serving.paged kv_quant="int8"):
    # payload halves (s8 pages) + 12,288 B of f32 per-(page, KV-head)
    # scale planes join the KV stream — 3,158,016 = 6,291,456 / 2 +
    # 12,288, i.e. the pool bytes serving decode streams per step drop
    # to ~50.2% of the bf16 cells (asserted by tests/test_traffic.py).
    # Regenerated with --kv-quant on; weight streams are untouched. ---
    ("decode_window", "bf16-kv8", "single"): {
        "weights": 31457792, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("prefill_chunk", "bf16-kv8", "single"): {
        "weights": 31457792, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("verify_program", "bf16-kv8", "single"): {
        "weights": 31457792, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("decode_window", "int8-kv8", "single"): {
        "weights": 16574976, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("prefill_chunk", "int8-kv8", "single"): {
        "weights": 16574976, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("verify_program", "int8-kv8", "single"): {
        "weights": 16574976, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    # --- tp=2,replica=2 x int8 KV: per-shard pool payload halves again
    # (whole-KV-head sharding), scale planes shard with their heads ---
    ("decode_window", "bf16-kv8", "replica2,tensor2"): {
        "weights": 15729152, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 165936,
    },
    ("prefill_chunk", "bf16-kv8", "replica2,tensor2"): {
        "weights": 15729152, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 2654208,
    },
    ("verify_program", "bf16-kv8", "replica2,tensor2"): {
        "weights": 15729152, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 829728,
    },
    ("decode_window", "int8-kv8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 165936,
    },
    ("prefill_chunk", "int8-kv8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 2654208,
    },
    ("verify_program", "int8-kv8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 829728,
    },
    # --- sequence-parallel prefill chunk (ServingEngine prefill_sp,
    # --prefill-sp on): the SP program streams BYTE-IDENTICAL
    # weights/kv/logits to the plain chunk cells above — SP moves no
    # resident bytes; only the wire changes. Measured comms is the plain
    # chunk's TP collectives (1,769,472 B) + the SP row gathers of the
    # [1, 64, 768] chunk activations (983,040 B = the "SP combine");
    # comms_max caps at 1.5x measured, so a program that regathers
    # anything beyond the SP combine (e.g. a reduce-scatter+all-gather
    # pair replacing a psum, the bitwise hazard) trips the guard.
    # Regenerated with --prefill-sp on --mesh-shape tp=2,replica=2. ---
    ("prefill_chunk_sp", "bf16", "replica2,tensor2"): {
        "weights": 15729152, "kv": 3145728, "logits": 8192,
        "constants_max": 245760, "comms_max": 4128768,
    },
    ("prefill_chunk_sp", "int8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 3145728, "logits": 8192,
        "constants_max": 245760, "comms_max": 4128768,
    },
    ("prefill_chunk_sp", "bf16-kv8", "replica2,tensor2"): {
        "weights": 15729152, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 4128768,
    },
    ("prefill_chunk_sp", "int8-kv8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 4128768,
    },
}

# band half-width for the exact streams: wide enough for layout/padding
# noise across jax/XLA versions, narrow enough that the cheapest real
# regression (one duplicated weight matrix: the [256, 1024] head, +5%
# of the weight stream at this geometry) cannot hide inside it
TOLERANCE = 0.04

# ---------------------------------------------------------------------------
# dispatch/launch budgets (analysis.dispatch) — the launch-side twin of
# the byte budgets above. Keyed (program, layer_scan) at AUDIT_GEOMETRY
# (n_layer=2 after the audit shrink; the layer-scan trip count IS that
# depth). Every entry gates EXACTLY (no band — launch structure is
# integral): the fused cells demand launches_per_window == 1 with the
# layer loop inside a scan of trip n_layer and ONE inlined layer body;
# the unrolled cells pin the legacy shape so a half-fused hybrid can't
# pass either budget. Re-unrolling a fused program moves zero bytes —
# the byte budgets stay green — but flips inlined_layer_bodies to
# n_layer and layer_scan_length to 0, tripping the "on" cells.
# Host transfers are pinned at 0 everywhere (the jaxpr-level twin of
# the compiled no-host-sync rule).
# ---------------------------------------------------------------------------

DISPATCH_BUDGETS: tp.Dict[tp.Tuple[str, str], tp.Dict[str, int]] = {
    ("decode_window", "on"): {
        "launches_per_window": 1, "inlined_layer_bodies": 1,
        "layer_scan_length": 2, "host_transfers": 0,
    },
    ("decode_window", "off"): {
        "launches_per_window": 1, "inlined_layer_bodies": 2,
        "layer_scan_length": 0, "host_transfers": 0,
    },
    ("prefill_chunk", "on"): {
        "launches_per_window": 1, "inlined_layer_bodies": 1,
        "layer_scan_length": 2, "host_transfers": 0,
    },
    ("prefill_chunk", "off"): {
        "launches_per_window": 1, "inlined_layer_bodies": 2,
        "layer_scan_length": 0, "host_transfers": 0,
    },
    ("verify_program", "on"): {
        "launches_per_window": 1, "inlined_layer_bodies": 1,
        "layer_scan_length": 2, "host_transfers": 0,
    },
    ("verify_program", "off"): {
        "launches_per_window": 1, "inlined_layer_bodies": 2,
        "layer_scan_length": 0, "host_transfers": 0,
    },
    # the sequence-parallel chunk: resharding constraints change ZERO
    # launch structure — the cells are the plain chunk's verbatim, and
    # that equality is itself the gate (an SP variant that split the
    # chunk into per-shard dispatches would trip launches_per_window)
    ("prefill_chunk_sp", "on"): {
        "launches_per_window": 1, "inlined_layer_bodies": 1,
        "layer_scan_length": 2, "host_transfers": 0,
    },
    ("prefill_chunk_sp", "off"): {
        "launches_per_window": 1, "inlined_layer_bodies": 2,
        "layer_scan_length": 0, "host_transfers": 0,
    },
}


def dispatch_budget_for(
    program: str, layer_scan: str
) -> tp.Optional[tp.Dict[str, int]]:
    return DISPATCH_BUDGETS.get((program, layer_scan))


def check_dispatch_budget(
    report,  # dispatch.DispatchReport
    budget: tp.Mapping[str, int],
) -> tp.List[str]:
    """Evaluate one program's measured launch structure against its
    dispatch budget; returns violation strings (empty = pass). Exact
    equality, no band: a launch count is an integer, and both
    directions are regressions (an extra inlined body is re-unrolling;
    a missing one means the audit traced the wrong program)."""
    out: tp.List[str] = []
    got = report.to_dict()
    for key in (
        "launches_per_window", "inlined_layer_bodies",
        "layer_scan_length", "host_transfers",
    ):
        expect = budget.get(key)
        if expect is None:
            continue
        if got[key] != expect:
            hint = ""
            if key == "inlined_layer_bodies" and got[key] > expect:
                hint = (
                    " — the layer loop re-unrolled (every decode "
                    "dispatch pays per-layer launch overhead again)"
                )
            elif key == "layer_scan_length" and got[key] == 0:
                hint = " — no folded layer scan found in the program"
            elif key == "host_transfers":
                hint = " — a host callback joined the hot path"
            out.append(
                f"{report.program}: {key} {got[key]} != budget "
                f"{expect}{hint}"
            )
    return out


def precision_key(precision: str, kv_quant: bool = False) -> str:
    """Budget-cell precision tag: the weight precision, suffixed
    ``-kv8`` when the paged KV pool is int8-quantized (serving.paged) —
    the pool payload halves and f32 per-(page, KV-head) scale planes
    join the KV stream, so kv-quant cells are distinct budget rows."""
    return f"{precision}-kv8" if kv_quant else precision


def geometry_key(
    mesh_shape: tp.Optional[tp.Mapping[str, int]]
) -> str:
    """``None`` -> 'single'; ``{"tensor": 2, "replica": 2}`` ->
    'replica2,tensor2' (sorted, size-1 axes dropped)."""
    if not mesh_shape:
        return "single"
    parts = [
        f"{name}{size}"
        for name, size in sorted(mesh_shape.items())
        if size > 1
    ]
    return ",".join(parts) if parts else "single"


def budget_for(
    program: str, precision: str, geometry: str
) -> tp.Optional[tp.Dict[str, int]]:
    return BUDGETS.get((program, precision, geometry))


def check_budget(
    report,  # traffic.TrafficReport
    budget: tp.Mapping[str, int],
    *,
    tolerance: float = TOLERANCE,
) -> tp.List[str]:
    """Evaluate one program's measured streams against its budget;
    returns violation strings (empty = pass). The exact streams are a
    BAND, not a cap — bytes leaving a stream are as much a regression
    as bytes joining one (a weight stream at 0 means the weights moved
    into the executable, not that serving got free)."""
    out: tp.List[str] = []
    for stream in ("weights", "kv", "logits"):
        expect = budget.get(stream)
        if expect is None:
            continue
        got = report.streams.get(stream, 0)
        lo = int(expect * (1 - tolerance))
        hi = int(expect * (1 + tolerance))
        if not (lo <= got <= hi):
            out.append(
                f"{report.program}: {stream} stream {got:,} B outside "
                f"budget [{lo:,}, {hi:,}] (expected ~{expect:,})"
            )
    cmax = budget.get("constants_max")
    if cmax is not None and report.streams.get("constants", 0) > cmax:
        out.append(
            f"{report.program}: {report.streams['constants']:,} B of "
            f"large constants baked into the executable (cap {cmax:,}) "
            "— model state is being constant-folded instead of streamed "
            "as entry parameters (the PR 6 closed-over-model bug class)"
        )
    comms_max = budget.get("comms_max")
    if comms_max is not None and report.comms_bytes > comms_max:
        out.append(
            f"{report.program}: {report.comms_bytes:,} B of collective "
            f"wire traffic per dispatch (cap {comms_max:,}) — a sharded "
            "buffer is being regathered (the page-gather all-gather "
            "bug class)"
        )
    if report.unclassified:
        shapes = ", ".join(
            f"{d}[{','.join(map(str, s))}]"
            for d, s in report.unclassified
        )
        out.append(
            f"{report.program}: unclassified large float entry "
            f"parameter(s): {shapes} — an unexplained stream joined "
            "the program interface"
        )
    return out


# ---------------------------------------------------------------------------
# TRAIN-side budgets: per-(mesh geometry, window K) wire-byte cells for
# the compiled fused train window, plus the window dispatch budget.
# Same philosophy as the serving cells above — exact measured values
# with a band — but the classified quantity is COLLECTIVE WIRE BYTES
# split by interconnect tier (cost.py's ring arithmetic): ICI bytes stay
# inside a slice; DCN bytes cross slices. The bug classes each cell
# catches:
#
# - a param spec widened across the slice axis (cross-slice FSDP
#   re-gather) moves the whole per-step gather/reduce-scatter volume
#   from ICI onto DCN — the dcn2 cell's ``dcn_bytes`` band trips AND the
#   single-slice cells' expected-zero DCN trips on any bytes at all;
# - an f32 operand reaching a collective that should carry bf16 doubles
#   that axis's bytes past the 4% band (this is how the psum-dtype
#   clause of the precision contract is gated — the jaxpr-level prover
#   cannot see collectives, see train_choreo's scope note);
# - a resharded activation or an extra all-gather shows up as an
#   unexpected ``by_axis`` key (its own violation, like ``unclassified``
#   in the serving cells).
#
# K=1 and K=4 cells are IDENTICAL by construction — cost.py counts a
# scan-body collective once per dispatch, and the fused window executes
# the same per-step collective set K times inside one scan. Checking
# both K values pins exactly that: a window whose bytes GREW with K has
# lost the scan (re-unrolled window) even before the dispatch gate runs.
# ---------------------------------------------------------------------------

# the geometry every train cell below was measured at (shrunk
# openwebtext; batch 16 so the microbatch divides every batch-sharding
# in TRAIN_AUDIT_GEOMETRIES)
TRAIN_AUDIT_GEOMETRY: tp.Dict[str, tp.Any] = {
    "config": "openwebtext",
    "n_layer": 2,
    "block_size": 256,
    "vocab_size": 1024,
    "batch_size": 16,
    "g_accum_iters": 2,
}

# the three mesh geometries the CI train-audit matrix compiles (8 host
# devices via --xla_force_host_platform_device_count): pure FSDP, a
# tensor*fsdp hybrid, and a 2-slice DCN mesh with FSDP inside each slice
TRAIN_AUDIT_GEOMETRIES: tp.Dict[str, tp.Dict[str, int]] = {
    "fsdp": dict(replica=1, fsdp=8, sequence=1, tensor=1),
    "tp_fsdp": dict(replica=1, fsdp=4, sequence=1, tensor=2),
    "dcn2": dict(replica=2, fsdp=4, sequence=1, tensor=1, num_slices=2),
}

# measured cells, keyed (geometry, window_steps). ``by_axis`` is the
# full per-mesh-axis split ("+"-joined for multi-axis collectives); any
# axis key not present here is an unexpected collective. Regenerate
# after an intentional change with::
#
#     python -m midgpt_tpu.analysis --config openwebtext --train-audit \
#         --train-geometry <g> --print-budgets
TRAIN_BUDGETS: tp.Dict[
    tp.Tuple[str, int], tp.Dict[str, tp.Any]
] = {
    ("fsdp", 1): {
        "ici_bytes": 108739547, "dcn_bytes": 0,
        "by_axis": {"fsdp": 108739547},
    },
    ("fsdp", 4): {
        "ici_bytes": 108739547, "dcn_bytes": 0,
        "by_axis": {"fsdp": 108739547},
    },
    ("tp_fsdp", 1): {
        "ici_bytes": 71978366, "dcn_bytes": 0,
        "by_axis": {"fsdp": 50725710, "tensor": 21252656},
    },
    ("tp_fsdp", 4): {
        "ici_bytes": 71978366, "dcn_bytes": 0,
        "by_axis": {"fsdp": 50725710, "tensor": 21252656},
    },
    # dcn2: the per-slice FSDP gathers stay on ICI; the cross-slice
    # grad reduction (replica axis + the replica+fsdp mixed reduce)
    # is the ONLY traffic allowed on DCN
    ("dcn2", 1): {
        "ici_bytes": 92605512, "dcn_bytes": 14156679,
        "by_axis": {
            "fsdp": 92605512, "replica+fsdp": 5505927,
            "replica": 8650752,
        },
    },
    ("dcn2", 4): {
        "ici_bytes": 92605512, "dcn_bytes": 14156679,
        "by_axis": {
            "fsdp": 92605512, "replica+fsdp": 5505927,
            "replica": 8650752,
        },
    },
}

# launch-side window budget (same on every geometry — the dispatch
# structure is mesh-independent): ONE launch per K-step window, the
# grad-accum loop folded as a scan of trip G, zero host transfers, and
# 100% of the donated train state aliased in the compiled executable
# (27 leaves at the audit geometry: 8 params + step + 8 mu + 8 nu +
# 2 optax counts)
TRAIN_DISPATCH_BUDGETS: tp.Dict[str, int] = {
    "launches_per_window": 1,
    "accum_scan_length": TRAIN_AUDIT_GEOMETRY["g_accum_iters"],
    "host_transfers": 0,
    "donated_leaves": 27,
}


def train_budget_for(
    geometry: str, window_steps: int
) -> tp.Optional[tp.Dict[str, tp.Any]]:
    return TRAIN_BUDGETS.get((geometry, window_steps))


def check_train_budget(
    report: tp.Mapping[str, tp.Any],  # harness.train_traffic_cell dict
    budget: tp.Mapping[str, tp.Any],
    *,
    geometry: str = "",
    tolerance: float = TOLERANCE,
) -> tp.List[str]:
    """Evaluate one compiled window's measured wire bytes against its
    cell; returns violation strings (empty = pass). Bands work both
    ways (a collective that vanished means the compiler stopped
    sharding something, not that training got free) — except
    expected-zero tiers, which trip on ANY bytes: a single DCN byte on
    a single-slice mesh means a spec leaked across the slice axis."""
    out: tp.List[str] = []
    tag = f"train_window[{geometry}]" if geometry else "train_window"
    for tier in ("ici_bytes", "dcn_bytes"):
        expect = budget.get(tier)
        if expect is None:
            continue
        got = int(report.get(tier, 0))
        if expect == 0:
            if got:
                out.append(
                    f"{tag}: {got:,} B of {tier.split('_')[0].upper()} "
                    f"traffic where the budget expects NONE — a sharding "
                    "spec crossed the slice boundary (the cross-slice "
                    "re-gather bug class)"
                )
            continue
        lo = int(expect * (1 - tolerance))
        hi = int(expect * (1 + tolerance))
        if not (lo <= got <= hi):
            hint = ""
            if got > hi:
                hint = (
                    " — extra collective volume joined the step (an f32 "
                    "operand on a bf16 collective, or a re-gathered "
                    "buffer)"
                )
            out.append(
                f"{tag}: {tier} {got:,} B outside budget "
                f"[{lo:,}, {hi:,}] (expected ~{expect:,}){hint}"
            )
    expect_axes = budget.get("by_axis")
    if expect_axes is not None:
        got_axes = dict(report.get("by_axis", {}))
        for axis, b in got_axes.items():
            if axis not in expect_axes and b:
                out.append(
                    f"{tag}: unexpected collective axis '{axis}' "
                    f"carrying {b:,} B — a collective the budget has "
                    "never seen joined the window"
                )
        for axis, expect in expect_axes.items():
            got = int(got_axes.get(axis, 0))
            lo = int(expect * (1 - tolerance))
            hi = int(expect * (1 + tolerance))
            if not (lo <= got <= hi):
                out.append(
                    f"{tag}: axis '{axis}' {got:,} B outside budget "
                    f"[{lo:,}, {hi:,}] (expected ~{expect:,})"
                )
    return out


def check_train_dispatch_budget(
    report,  # dispatch.TrainDispatchReport
    budget: tp.Mapping[str, int] = TRAIN_DISPATCH_BUDGETS,
    *,
    aliased_leaves: tp.Optional[int] = None,
) -> tp.List[str]:
    """Evaluate the traced window's launch structure (plus, when
    ``aliased_leaves`` is given, the compiled donation accounting)
    against the train dispatch budget. Exact equality, like the
    serving dispatch cells — launch structure is integral."""
    out: tp.List[str] = []
    got = report.to_dict()
    for key in ("launches_per_window", "accum_scan_length",
                "host_transfers"):
        expect = budget.get(key)
        if expect is None:
            continue
        if got[key] != expect:
            hint = ""
            if key == "launches_per_window":
                hint = (
                    " — the K-step window scan is gone; every step pays "
                    "dispatch latency again"
                )
            elif key == "accum_scan_length" and got[key] == 0:
                hint = (
                    " — the grad-accum loop re-unrolled (G inlined "
                    "copies of the step body, zero bytes moved)"
                )
            elif key == "host_transfers":
                hint = " — a host callback joined the fused window"
            out.append(
                f"{report.program}: {key} {got[key]} != budget "
                f"{expect}{hint}"
            )
    expect_donated = budget.get("donated_leaves")
    if aliased_leaves is not None and expect_donated is not None:
        if aliased_leaves != expect_donated:
            out.append(
                f"{report.program}: {aliased_leaves} donated state "
                f"leaves aliased in the executable != budget "
                f"{expect_donated} — un-aliased donation doubles the "
                "train state's HBM residency"
            )
    return out


def train_geometry_key(mesh_shape: tp.Mapping[str, int]) -> tp.Optional[str]:
    """Reverse lookup: the TRAIN_AUDIT_GEOMETRIES name whose axis sizes
    match ``mesh_shape`` (num_slices included), or None."""
    probe = {k: v for k, v in mesh_shape.items() if v != 1}
    for name, axes in TRAIN_AUDIT_GEOMETRIES.items():
        ref = {k: v for k, v in axes.items() if v != 1}
        if probe == ref:
            return name
    return None
