"""Checked-in HBM byte budgets for the serving programs.

The numbers a serving program is ALLOWED to stream, per dispatch, at
the CI audit geometry — the generalization of the shape-matching
``no-dequant-materialization`` / ``no-batch-allgather-in-page-gather``
rules into plain accounting: any regression that re-materializes,
re-gathers, or constant-folds a large buffer moves bytes, and a moved
byte count trips the gate regardless of what the HLO happens to look
like. Concretely:

- a model CLOSED OVER by a program (the PR 6 bug) removes the weight
  stream from the entry interface (below the weights band) and dumps it
  into ``constants`` (above the constants cap) — two trips, with the
  quantized variant additionally 4x over on the folded f32 copies;
- a KV-head-sharded pool regathered through the page gathers (the PR 7
  bug class) multiplies the sharded geometry's ``comms`` bytes past its
  cap;
- an accidental full-precision weight copy smuggled in as a second
  input lands in ``unclassified`` (its own violation).

Budgets are exact measured values with a relative tolerance band, keyed
by ``(program, precision, geometry)`` at the ONE audit geometry CI
compiles (:data:`AUDIT_GEOMETRY`): openwebtext shrunk to 2 layers /
block 256 / vocab 1024, slots=4, window=4, page_size=16, spec_len=4.
Regenerate after an intentional geometry or model change with::

    python -m midgpt_tpu.analysis --config openwebtext --serving \
        --traffic --print-budgets

and paste the emitted dict here — the diff IS the review artifact.

jax-free (pure numbers), like rules.py.
"""

from __future__ import annotations

import typing as tp

# the geometry every budget below was measured at; the CLI refuses to
# gate traffic on a non-matching geometry rather than mis-fail it
AUDIT_GEOMETRY: tp.Dict[str, tp.Any] = {
    "config": "openwebtext",
    "n_layer": 2,
    "block_size": 256,
    "vocab_size": 1024,
    "slots": 4,
    "window": 4,
    "page_size": 16,
    "spec_len": 4,
}

# streams are bytes at the compiled program's entry interface
# (traffic.traffic_report); comms is the per-dispatch collective wire
# estimate (cost.py's ring-algorithm arithmetic) on sharded geometries
BUDGETS: tp.Dict[tp.Tuple[str, str, str], tp.Dict[str, int]] = {
    # --- single chip, bf16 ---
    ("decode_window", "bf16", "single"): {
        "weights": 31457792, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    ("prefill_chunk", "bf16", "single"): {
        "weights": 31457792, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    ("verify_program", "bf16", "single"): {
        "weights": 31457792, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    # --- single chip, int8 (s8 matrices + f32 per-channel scales) ---
    ("decode_window", "int8", "single"): {
        "weights": 16574976, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    ("prefill_chunk", "int8", "single"): {
        "weights": 16574976, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    ("verify_program", "int8", "single"): {
        "weights": 16574976, "kv": 6291456, "logits": 16384,
        "constants_max": 262144,
    },
    # --- tp=2,replica=2 (per-shard local streams: weights and the
    # whole-KV-head pool halve; replica rides replicated) ---
    ("decode_window", "bf16", "replica2,tensor2"): {
        "weights": 15729152, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 165936,
    },
    ("prefill_chunk", "bf16", "replica2,tensor2"): {
        "weights": 15729152, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 2654208,
    },
    ("verify_program", "bf16", "replica2,tensor2"): {
        "weights": 15729152, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 829728,
    },
    ("decode_window", "int8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 165936,
    },
    ("prefill_chunk", "int8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 2654208,
    },
    ("verify_program", "int8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 3145728, "logits": 8192,
        "constants_max": 262144, "comms_max": 829728,
    },
    # --- int8-quantized KV pool (serving.paged kv_quant="int8"):
    # payload halves (s8 pages) + 12,288 B of f32 per-(page, KV-head)
    # scale planes join the KV stream — 3,158,016 = 6,291,456 / 2 +
    # 12,288, i.e. the pool bytes serving decode streams per step drop
    # to ~50.2% of the bf16 cells (asserted by tests/test_traffic.py).
    # Regenerated with --kv-quant on; weight streams are untouched. ---
    ("decode_window", "bf16-kv8", "single"): {
        "weights": 31457792, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("prefill_chunk", "bf16-kv8", "single"): {
        "weights": 31457792, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("verify_program", "bf16-kv8", "single"): {
        "weights": 31457792, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("decode_window", "int8-kv8", "single"): {
        "weights": 16574976, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("prefill_chunk", "int8-kv8", "single"): {
        "weights": 16574976, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    ("verify_program", "int8-kv8", "single"): {
        "weights": 16574976, "kv": 3158016, "logits": 16384,
        "constants_max": 245760,
    },
    # --- tp=2,replica=2 x int8 KV: per-shard pool payload halves again
    # (whole-KV-head sharding), scale planes shard with their heads ---
    ("decode_window", "bf16-kv8", "replica2,tensor2"): {
        "weights": 15729152, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 165936,
    },
    ("prefill_chunk", "bf16-kv8", "replica2,tensor2"): {
        "weights": 15729152, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 2654208,
    },
    ("verify_program", "bf16-kv8", "replica2,tensor2"): {
        "weights": 15729152, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 829728,
    },
    ("decode_window", "int8-kv8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 165936,
    },
    ("prefill_chunk", "int8-kv8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 2654208,
    },
    ("verify_program", "int8-kv8", "replica2,tensor2"): {
        "weights": 8293888, "kv": 1579008, "logits": 8192,
        "constants_max": 245760, "comms_max": 829728,
    },
}

# band half-width for the exact streams: wide enough for layout/padding
# noise across jax/XLA versions, narrow enough that the cheapest real
# regression (one duplicated weight matrix: the [256, 1024] head, +5%
# of the weight stream at this geometry) cannot hide inside it
TOLERANCE = 0.04

# ---------------------------------------------------------------------------
# dispatch/launch budgets (analysis.dispatch) — the launch-side twin of
# the byte budgets above. Keyed (program, layer_scan) at AUDIT_GEOMETRY
# (n_layer=2 after the audit shrink; the layer-scan trip count IS that
# depth). Every entry gates EXACTLY (no band — launch structure is
# integral): the fused cells demand launches_per_window == 1 with the
# layer loop inside a scan of trip n_layer and ONE inlined layer body;
# the unrolled cells pin the legacy shape so a half-fused hybrid can't
# pass either budget. Re-unrolling a fused program moves zero bytes —
# the byte budgets stay green — but flips inlined_layer_bodies to
# n_layer and layer_scan_length to 0, tripping the "on" cells.
# Host transfers are pinned at 0 everywhere (the jaxpr-level twin of
# the compiled no-host-sync rule).
# ---------------------------------------------------------------------------

DISPATCH_BUDGETS: tp.Dict[tp.Tuple[str, str], tp.Dict[str, int]] = {
    ("decode_window", "on"): {
        "launches_per_window": 1, "inlined_layer_bodies": 1,
        "layer_scan_length": 2, "host_transfers": 0,
    },
    ("decode_window", "off"): {
        "launches_per_window": 1, "inlined_layer_bodies": 2,
        "layer_scan_length": 0, "host_transfers": 0,
    },
    ("prefill_chunk", "on"): {
        "launches_per_window": 1, "inlined_layer_bodies": 1,
        "layer_scan_length": 2, "host_transfers": 0,
    },
    ("prefill_chunk", "off"): {
        "launches_per_window": 1, "inlined_layer_bodies": 2,
        "layer_scan_length": 0, "host_transfers": 0,
    },
    ("verify_program", "on"): {
        "launches_per_window": 1, "inlined_layer_bodies": 1,
        "layer_scan_length": 2, "host_transfers": 0,
    },
    ("verify_program", "off"): {
        "launches_per_window": 1, "inlined_layer_bodies": 2,
        "layer_scan_length": 0, "host_transfers": 0,
    },
}


def dispatch_budget_for(
    program: str, layer_scan: str
) -> tp.Optional[tp.Dict[str, int]]:
    return DISPATCH_BUDGETS.get((program, layer_scan))


def check_dispatch_budget(
    report,  # dispatch.DispatchReport
    budget: tp.Mapping[str, int],
) -> tp.List[str]:
    """Evaluate one program's measured launch structure against its
    dispatch budget; returns violation strings (empty = pass). Exact
    equality, no band: a launch count is an integer, and both
    directions are regressions (an extra inlined body is re-unrolling;
    a missing one means the audit traced the wrong program)."""
    out: tp.List[str] = []
    got = report.to_dict()
    for key in (
        "launches_per_window", "inlined_layer_bodies",
        "layer_scan_length", "host_transfers",
    ):
        expect = budget.get(key)
        if expect is None:
            continue
        if got[key] != expect:
            hint = ""
            if key == "inlined_layer_bodies" and got[key] > expect:
                hint = (
                    " — the layer loop re-unrolled (every decode "
                    "dispatch pays per-layer launch overhead again)"
                )
            elif key == "layer_scan_length" and got[key] == 0:
                hint = " — no folded layer scan found in the program"
            elif key == "host_transfers":
                hint = " — a host callback joined the hot path"
            out.append(
                f"{report.program}: {key} {got[key]} != budget "
                f"{expect}{hint}"
            )
    return out


def precision_key(precision: str, kv_quant: bool = False) -> str:
    """Budget-cell precision tag: the weight precision, suffixed
    ``-kv8`` when the paged KV pool is int8-quantized (serving.paged) —
    the pool payload halves and f32 per-(page, KV-head) scale planes
    join the KV stream, so kv-quant cells are distinct budget rows."""
    return f"{precision}-kv8" if kv_quant else precision


def geometry_key(
    mesh_shape: tp.Optional[tp.Mapping[str, int]]
) -> str:
    """``None`` -> 'single'; ``{"tensor": 2, "replica": 2}`` ->
    'replica2,tensor2' (sorted, size-1 axes dropped)."""
    if not mesh_shape:
        return "single"
    parts = [
        f"{name}{size}"
        for name, size in sorted(mesh_shape.items())
        if size > 1
    ]
    return ",".join(parts) if parts else "single"


def budget_for(
    program: str, precision: str, geometry: str
) -> tp.Optional[tp.Dict[str, int]]:
    return BUDGETS.get((program, precision, geometry))


def check_budget(
    report,  # traffic.TrafficReport
    budget: tp.Mapping[str, int],
    *,
    tolerance: float = TOLERANCE,
) -> tp.List[str]:
    """Evaluate one program's measured streams against its budget;
    returns violation strings (empty = pass). The exact streams are a
    BAND, not a cap — bytes leaving a stream are as much a regression
    as bytes joining one (a weight stream at 0 means the weights moved
    into the executable, not that serving got free)."""
    out: tp.List[str] = []
    for stream in ("weights", "kv", "logits"):
        expect = budget.get(stream)
        if expect is None:
            continue
        got = report.streams.get(stream, 0)
        lo = int(expect * (1 - tolerance))
        hi = int(expect * (1 + tolerance))
        if not (lo <= got <= hi):
            out.append(
                f"{report.program}: {stream} stream {got:,} B outside "
                f"budget [{lo:,}, {hi:,}] (expected ~{expect:,})"
            )
    cmax = budget.get("constants_max")
    if cmax is not None and report.streams.get("constants", 0) > cmax:
        out.append(
            f"{report.program}: {report.streams['constants']:,} B of "
            f"large constants baked into the executable (cap {cmax:,}) "
            "— model state is being constant-folded instead of streamed "
            "as entry parameters (the PR 6 closed-over-model bug class)"
        )
    comms_max = budget.get("comms_max")
    if comms_max is not None and report.comms_bytes > comms_max:
        out.append(
            f"{report.program}: {report.comms_bytes:,} B of collective "
            f"wire traffic per dispatch (cap {comms_max:,}) — a sharded "
            "buffer is being regathered (the page-gather all-gather "
            "bug class)"
        )
    if report.unclassified:
        shapes = ", ".join(
            f"{d}[{','.join(map(str, s))}]"
            for d, s in report.unclassified
        )
        out.append(
            f"{report.program}: unclassified large float entry "
            f"parameter(s): {shapes} — an unexplained stream joined "
            "the program interface"
        )
    return out
