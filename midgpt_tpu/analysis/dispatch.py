"""Static dispatch/launch auditor for the serving programs.

The residual between r5's measured 0.905 ms/tok and the 0.278 ms HBM
floor is LAUNCH structure, not bytes (PERF.md): the whole-model decode
step unrolls its layer loop, so every window dispatch carries L inlined
copies of the per-layer kernel set — L times the launch overhead, L
times the executable size, and [B, 1, D] matmul shapes that cannot
amortize any of it. The byte budgets (analysis.traffic/budgets) cannot
see this class of regression: re-unrolling a folded loop moves ZERO
bytes at the entry interface. This module is the launch-side
counterpart — count the dispatch structure statically from the traced
program and gate it against checked-in budgets, exactly like the HBM
byte budgets:

- **launches per window** — XLA dispatches the engine must issue per
  scheduler window for this program. The decode window's K-step scan
  must cover all ``window_steps`` model steps, or the remainder would
  need extra launches (the PR 2/PR 3 fused-dispatch contract, now
  machine-checked).
- **scan trip structure** — every attention-carrying ``lax.scan`` in
  the traced program, with trip count and nesting depth; the fused
  program must show the layer loop as a scan of trip ``n_layer``
  (``layer_scan_length``) nested inside the window scan, and a
  re-unrolled program shows ``layer_scan_length == 0`` and fails the
  "on" budget.
- **inlined layer bodies** — how many copies of the per-layer attention
  arithmetic the flat trace carries (choreo.py's region extractor):
  1 when folded, ``n_layer`` when unrolled.
- **host transfers** — callback/infeed/outfeed primitives anywhere in
  the program (each is a device->host sync per dispatch; the budget
  pins 0, the jaxpr-level twin of the compiled no-host-sync rule).

Operates on jaxprs (no compilation); budgets live in
:data:`midgpt_tpu.analysis.budgets.DISPATCH_BUDGETS`, keyed by
``(program, layer_scan)`` at the audit geometry, and are gated by
:func:`midgpt_tpu.analysis.budgets.check_dispatch_budget`.
"""

from __future__ import annotations

import dataclasses
import typing as tp

from midgpt_tpu.analysis.choreo import attention_regions, flatten_jaxpr

# primitives that force a device->host transfer inside the program
_HOST_TRANSFER_PRIMS = frozenset({
    "io_callback", "pure_callback", "python_callback", "callback",
    "outside_call", "host_callback_call", "debug_callback", "infeed",
    "outfeed",
})


@dataclasses.dataclass(frozen=True)
class ScanInfo:
    """One ``lax.scan`` in the traced program."""

    length: int  # trip count
    depth: int  # scan-nesting depth (0 = top level)
    attention_regions: int  # inlined layer bodies in its FLAT body
    has_nested_attention_scan: bool  # an attention scan nests inside

    @property
    def is_layer_scan(self) -> bool:
        """The layer fold: an attention-carrying scan whose body holds
        exactly ONE inlined layer and no deeper attention scan — its
        trip count is the layer count. (The decode window's K-step scan
        has a NESTED layer scan when fused, or multiple inlined bodies
        when unrolled, so it never matches.)"""
        return (
            self.attention_regions == 1
            and not self.has_nested_attention_scan
        )

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "length": self.length,
            "depth": self.depth,
            "attention_regions": self.attention_regions,
            "is_layer_scan": self.is_layer_scan,
        }


@dataclasses.dataclass(frozen=True)
class DispatchReport:
    """Static launch structure of one traced serving program."""

    program: str
    window_steps: int  # model steps one scheduler window must cover
    scans: tp.Tuple[ScanInfo, ...]  # attention-carrying scans only
    inlined_layer_bodies: int  # attention regions in the flat trace
    host_transfers: int

    @property
    def layer_scan_length(self) -> int:
        """Trip count of the folded layer loop; 0 = unrolled."""
        for s in self.scans:
            if s.is_layer_scan:
                return s.length
        return 0

    @property
    def launches_per_window(self) -> int:
        """XLA dispatches per scheduler window: the outermost NON-layer
        attention scan must cover all ``window_steps`` model steps in
        one launch (ceil of the shortfall otherwise). Programs that run
        one model step per window (prefill chunk, verify) are one
        launch by construction."""
        steps_per_launch = max(
            (s.length for s in self.scans if not s.is_layer_scan),
            default=1,
        )
        return -(-self.window_steps // steps_per_launch)

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "program": self.program,
            "window_steps": self.window_steps,
            "scans": [s.to_dict() for s in self.scans],
            "layer_scan_length": self.layer_scan_length,
            "inlined_layer_bodies": self.inlined_layer_bodies,
            "launches_per_window": self.launches_per_window,
            "host_transfers": self.host_transfers,
        }


def _param_jaxprs(params: tp.Mapping[str, tp.Any]) -> tp.Iterator[tp.Any]:
    """Every jaxpr-like value in an eqn's params — including ones nested
    inside tuple/list params (``lax.cond``'s ``branches`` is a plain
    tuple of ClosedJaxprs; a bare hasattr test over params.values()
    would skip it and let a callback hidden in a cond branch pass the
    host-transfer gate vacuously)."""
    for p in params.values():
        candidates = p if isinstance(p, (tuple, list)) else (p,)
        for c in candidates:
            if hasattr(c, "eqns") or hasattr(c, "jaxpr"):
                yield c


def _walk(jpr, depth: int, scans: tp.List[ScanInfo],
          host: tp.List[int]) -> bool:
    """Recursive eqn walk; returns True when this jaxpr (transitively)
    contains attention arithmetic inside a scan at any depth."""
    found_attn_scan = False
    for eqn in jpr.eqns:
        name = eqn.primitive.name
        if name in _HOST_TRANSFER_PRIMS:
            host[0] += 1
        if name == "scan":
            body = eqn.params.get("jaxpr")
            inner = getattr(body, "jaxpr", body)
            nested_attn = _walk(inner, depth + 1, scans, host)
            regions = len(attention_regions(flatten_jaxpr(body)))
            if regions:
                scans.append(ScanInfo(
                    length=int(eqn.params.get("length", 0)),
                    depth=depth,
                    attention_regions=regions,
                    has_nested_attention_scan=nested_attn,
                ))
                found_attn_scan = True
            found_attn_scan = found_attn_scan or nested_attn
            continue
        for p in _param_jaxprs(eqn.params):
            sub = getattr(p, "jaxpr", p)
            found_attn_scan = (
                _walk(sub, depth, scans, host) or found_attn_scan
            )
    return found_attn_scan


@dataclasses.dataclass(frozen=True)
class TrainDispatchReport:
    """Static launch structure of the traced K-step TRAIN window.

    The training-side dispatch contract (train.make_train_window):

    - the whole window is ONE XLA dispatch — a depth-0 scan of trip
      count K carrying the optimizer state (``window_scan_length``);
      K separate launches would re-pay the relay/dispatch latency the
      fused window exists to amortize (PERF.md r5);
    - the grad-accum loop inside each step is a ``lax.scan`` of trip
      count G (``accum_scan_length``) — re-unrolling it (the PR 11
      serving bug class, training-side) moves zero wire bytes but
      multiplies the compiled body by G;
    - no host transfers anywhere in the window (a mid-window callback
      serializes the whole fused dispatch).

    Donation accounting (100% of the donated state aliased) needs the
    compiled HLO, so it rides the traffic cell
    (:func:`midgpt_tpu.analysis.harness.train_traffic_cell`), not this
    trace-level report."""

    program: str
    window_steps: int  # expected K
    g_accum_iters: int  # expected G
    window_scan_length: int  # traced window-scan trip count (0 = absent)
    accum_scan_length: int  # traced accum-scan trip count (0 = absent)
    accum_carry_leaves: int  # float leaves carried by the accum scan
    host_transfers: int

    @property
    def launches_per_window(self) -> int:
        """1 when the K-step window scan is intact; K when the window
        structure is gone (each step body would need its own launch to
        preserve the step boundary the trainer observes)."""
        return (
            1
            if self.window_scan_length == self.window_steps
            else self.window_steps
        )

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "program": self.program,
            "window_steps": self.window_steps,
            "g_accum_iters": self.g_accum_iters,
            "window_scan_length": self.window_scan_length,
            "accum_scan_length": self.accum_scan_length,
            "accum_carry_leaves": self.accum_carry_leaves,
            "launches_per_window": self.launches_per_window,
            "host_transfers": self.host_transfers,
        }


def train_dispatch_report(
    closed_jaxpr, *, window_steps: int, g_accum_iters: int,
    program: str = "train_window",
) -> TrainDispatchReport:
    """Build the :class:`TrainDispatchReport` from a traced window
    jaxpr (``jax.make_jaxpr`` over ``train.get_train_window``'s
    program — no compilation). Scan identification is structural:
    the window scan is the depth-0 scan carrying an int32 scalar
    (``state.step`` + optax counts); the accum scan nests directly
    inside it and carries the whole grad tree plus the f32 loss
    accumulator (>= 3 float leaves — the layer scans carry one)."""
    from midgpt_tpu.analysis.train_choreo import (
        find_accum_scan,
        find_window_scan,
        window_scans,
    )

    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    host = [0]
    _count_host_transfers(jaxpr, host)
    scans = window_scans(closed_jaxpr)
    wscan = find_window_scan(scans, window_steps)
    ascan = find_accum_scan(scans, wscan is not None)
    return TrainDispatchReport(
        program=program,
        window_steps=window_steps,
        g_accum_iters=g_accum_iters,
        window_scan_length=wscan.length if wscan is not None else 0,
        accum_scan_length=ascan.length if ascan is not None else 0,
        accum_carry_leaves=ascan.float_carries if ascan is not None else 0,
        host_transfers=host[0],
    )


def _count_host_transfers(jpr, host: tp.List[int]) -> None:
    for eqn in jpr.eqns:
        if eqn.primitive.name in _HOST_TRANSFER_PRIMS:
            host[0] += 1
        for p in _param_jaxprs(eqn.params):
            _count_host_transfers(getattr(p, "jaxpr", p), host)


def dispatch_report(
    closed_jaxpr, *, program: str, window_steps: int = 1
) -> DispatchReport:
    """Build the :class:`DispatchReport` for one traced program.
    ``window_steps`` is the number of model steps one scheduler window
    must cover with this program (the decode window's K; 1 for the
    prefill chunk and the verify program).

    Note the ``n_layer >= 2`` requirement of the audit geometry: at a
    single layer an unrolled window body is indistinguishable from a
    folded one (one inlined body either way)."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    scans: tp.List[ScanInfo] = []
    host = [0]
    _walk(jaxpr, 0, scans, host)
    flat = flatten_jaxpr(closed_jaxpr)
    return DispatchReport(
        program=program,
        window_steps=window_steps,
        scans=tuple(sorted(scans, key=lambda s: (s.depth, -s.length))),
        inlined_layer_bodies=len(attention_regions(flat)),
        host_transfers=host[0],
    )
