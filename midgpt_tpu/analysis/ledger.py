"""Perf-trajectory ledger: the regression gate over BENCH_*.json rows
and bench record files.

The repo accumulates one machine-written performance record per
hardware round (``BENCH_r*.json``) plus per-run bench records
(``bench.py`` / ``scripts/bench_serving.py`` JSON rows), but until this
module nothing *related* successive records: a silent MFU cliff, a
byte-stream regression that moved the static floor, or a tier-1 suite
quietly doubling its wall time would ride into the trajectory unread —
the exact blindness that let the r4/r5 wedged rounds sit undiagnosed.
The ledger ingests the trajectory, diffs a current record against it
with per-key tolerance bands, renders a markdown trend report, and
exits nonzero on regression (``python -m midgpt_tpu.analysis
--ledger``; the ``perf-ledger`` CI job drives it over a CPU bench run).

Gating policy (the heart of the module):

- **Static keys** — bytes/token, the HBM/compute floors, the dispatch
  launch structure, flops-per-token — are *compiled-in arithmetic*:
  they may not drift between records of the same geometry at all, on
  any backend. Violations are HARD (exit nonzero) everywhere.
- **Wall-clock keys** — MFU, tok/s, goodput, latency percentiles — are
  measurements: gated HARD on hardware rows (``device`` names a TPU),
  but only *informational* on CPU rows, where the numbers are
  noise-dominated by design (the CI job runs on shared runners).
- **Row status** is respected: ``watchdog`` / ``error`` / ``partial``
  rows (the r4/r5 wedges) are excluded from the reference trajectory
  and never gated as regressions — a hardware wedge is a wedge, not a
  perf cliff, which is the whole reason bench rows carry ``status``.
- **Key inventory**: a serving record silently *losing* keys its
  predecessor carried is itself a hard finding (the record-schema twin
  of the pinned ``ENGINE_STATS_KEYS`` contract); train records only
  warn, because a failed auxiliary rung legitimately drops its family
  (and says so via the ``*_error`` key).
- Comparisons only happen between *comparable* rows: serving records
  must share ``serve_shape``, train headline keys must share
  ``metric`` (the rung ladder changes shape between rounds), prefixed
  families (``gpt2s_``, ``llama_``, ...) match on their own ``*_metric``
  keys. The reference for each key is the most recent comparable OK row
  that carries it.

jax-free by construction (it runs in CI next to records, never on a
device).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
import typing as tp

__all__ = [
    "BANDS",
    "Band",
    "Finding",
    "Row",
    "diff_record",
    "load_record",
    "load_suite_timing",
    "load_trajectory",
    "markdown_report",
    "parse_multichip_record",
    "row_hardware",
    "row_kind",
    "row_ok",
]


# ---------------------------------------------------------------------------
# Rows
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Row:
    """One trajectory entry: ``record`` is the parsed bench row,
    ``source`` where it came from, ``index`` its ordering key (the
    BENCH round number, then file order for ingested record dirs)."""

    source: str
    index: int
    record: tp.Mapping[str, tp.Any]


def row_kind(rec: tp.Mapping[str, tp.Any]) -> str:
    if rec.get("kind") == "multichip":
        return "multichip"
    if "serve_shape" in rec:
        return "serving"
    if rec.get("kind") == "suite" or "suite_total_call_s" in rec:
        return "suite"
    return "train"


def row_ok(rec: tp.Mapping[str, tp.Any]) -> bool:
    """Gateable rows only: watchdog/error/partial rows (hardware
    wedges, the r4/r5 class) are neither references nor regressions."""
    return (
        rec.get("status", "ok") == "ok"
        and rec.get("metric") != "bench_error"
        and not rec.get("partial")
    )


def row_hardware(rec: tp.Mapping[str, tp.Any]) -> bool:
    return "tpu" in str(rec.get("device", "")).lower()


def load_record(path: str) -> tp.Dict[str, tp.Any]:
    """One bench record: a raw bench/bench_serving JSON row, or a
    BENCH_r*.json driver wrapper (whose row sits under ``parsed``)."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "parsed" in data and isinstance(
        data["parsed"], dict
    ):
        return data["parsed"]
    return data


_BENCH_RE = re.compile(r"BENCH_r(\d+)\.json$")
_MULTICHIP_RE = re.compile(r"MULTICHIP_r(\d+)\.json$")

#: tail-line prefixes of the multichip dryrun driver, mapped to the
#: ledger key tag each loss lands under. Order matters: more specific
#: prefixes first ("dryrun GPT pipeline" before "dryrun pipeline").
_MULTICHIP_LINE_TAGS: tp.Tuple[tp.Tuple[str, str], ...] = (
    ("dryrun_multichip", "mesh"),
    ("dryrun fused attention", "fused_attention"),
    ("dryrun MoE expert parallelism", "moe"),
    ("dryrun ring attention", "ring_attention"),
    ("dryrun ulysses", "ulysses"),
    ("dryrun multi-slice", "multi_slice"),
    ("dryrun GPT pipeline", "gpt_pipeline"),
    ("dryrun pipeline", "pipeline"),
)

_MULTICHIP_LOSS_RE = re.compile(r"loss=([0-9][0-9.eE+-]*)")


def parse_multichip_record(
    raw: tp.Mapping[str, tp.Any],
) -> tp.Dict[str, tp.Any]:
    """A ``MULTICHIP_r*.json`` driver wrapper as a ledger row: the
    per-parallelism dryrun losses from the ``tail`` text become
    ``multichip_<tag>_loss`` keys (STATIC-banded — a loss that drifts
    between rounds on a fixed seed/geometry means a parallelism path
    changed numerics), ``n_devices`` is the population key, and a
    non-ok/skipped wrapper becomes a wedge row (``status='error'``,
    excluded from the reference like the r4/r5 BENCH wedges)."""
    ok = (
        bool(raw.get("ok"))
        and raw.get("rc", 1) == 0
        and not raw.get("skipped")
    )
    rec: tp.Dict[str, tp.Any] = {
        "kind": "multichip",
        "status": "ok" if ok else "error",
        "n_devices": raw.get("n_devices"),
    }
    for line in str(raw.get("tail", "")).splitlines():
        line = line.strip()
        if not line.endswith("OK"):
            continue
        m = _MULTICHIP_LOSS_RE.search(line)
        if not m:
            continue
        for prefix, tag in _MULTICHIP_LINE_TAGS:
            if line.startswith(prefix):
                rec[f"multichip_{tag}_loss"] = float(m.group(1))
                break
    return rec


def load_trajectory(
    root: str, record_dirs: tp.Sequence[str] = (),
) -> tp.List[Row]:
    """The reference trajectory: every ``BENCH_r*.json`` under ``root``
    (ordered by round number), then every ``MULTICHIP_r*.json`` (round
    order, indices continuing past the BENCH rounds), then every
    ``*.json`` bench record in ``record_dirs`` (file order) — the r6
    queue's per-rung records and CI-archived rows ingest this way."""
    rows: tp.List[Row] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = _BENCH_RE.search(path)
        if not m:
            continue
        try:
            rows.append(Row(path, int(m.group(1)), load_record(path)))
        except (json.JSONDecodeError, OSError):
            continue
    rows.sort(key=lambda r: r.index)
    nxt = (rows[-1].index + 1) if rows else 0
    for path in sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json"))):
        if not _MULTICHIP_RE.search(path):
            continue
        try:
            with open(path) as f:
                raw = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        if not isinstance(raw, dict):
            continue
        rows.append(Row(path, nxt, parse_multichip_record(raw)))
        nxt += 1
    for d in record_dirs:
        for path in sorted(glob.glob(os.path.join(d, "*.json"))):
            try:
                rec = load_record(path)
            except (json.JSONDecodeError, OSError):
                continue
            if not isinstance(rec, dict):
                continue
            rows.append(Row(path, nxt, rec))
            nxt += 1
    return rows


def load_suite_timing(path: str) -> tp.Dict[str, tp.Any]:
    """The conftest slowest-phase artifact (SUITE_TIMING_OUT), as a
    ledger row: tier-1 suite wall time tracked like any other metric."""
    with open(path) as f:
        rec = json.load(f)
    rec.setdefault("kind", "suite")
    return rec


# ---------------------------------------------------------------------------
# Bands
# ---------------------------------------------------------------------------

STATIC, HIGHER, LOWER = "static", "higher", "lower"


@dataclasses.dataclass(frozen=True)
class Band:
    """One key's gating policy. ``direction``: ``static`` (may not
    drift at all — hard everywhere), ``higher`` (higher is better;
    a drop beyond ``tol`` regresses), ``lower`` (vice versa).
    Wall-clock bands gate hard only on hardware rows."""

    direction: str
    tol: float


#: The per-key tolerance bands. Static keys are compiled-in arithmetic
#: (exact up to float rounding); throughput keys get 10%, latency
#: percentiles 25% (tail-noisy even on hardware).
BANDS: tp.Dict[str, Band] = {
    # --- static: serving byte/floor decomposition + launch structure --
    "serve_bytes_per_token_static": Band(STATIC, 1e-6),
    "serve_bytes_per_step_static": Band(STATIC, 1e-6),
    "serve_weights_bytes_per_step_static": Band(STATIC, 1e-6),
    "serve_kv_bytes_per_step_static": Band(STATIC, 1e-6),
    "serve_hbm_floor_ms_static": Band(STATIC, 1e-3),
    "serve_floor_ms_per_tok_static": Band(STATIC, 1e-3),
    "serve_static_launches_per_window": Band(STATIC, 0.0),
    "serve_static_inlined_layer_bodies": Band(STATIC, 0.0),
    "serve_static_layer_scan_length": Band(STATIC, 0.0),
    "serve_static_host_transfers": Band(STATIC, 0.0),
    "serve_comms_bytes_per_dispatch": Band(STATIC, 1e-6),
    # --- static: training floors / FLOP accounting --------------------
    "model_flops_per_token": Band(STATIC, 1e-6),
    "train_hbm_floor_ms": Band(STATIC, 1e-3),
    "train_compute_floor_ms": Band(STATIC, 1e-3),
    # --- wall-clock: training throughput -------------------------------
    "value": Band(HIGHER, 0.10),
    "tokens_per_sec_per_chip": Band(HIGHER, 0.10),
    "train_attainment_frac": Band(HIGHER, 0.10),
    "gpt2s_mfu": Band(HIGHER, 0.10),
    "gpt2s_tokens_per_sec_per_chip": Band(HIGHER, 0.10),
    "llama_mfu": Band(HIGHER, 0.10),
    "llama_tokens_per_sec_per_chip": Band(HIGHER, 0.10),
    "long_ctx_mfu": Band(HIGHER, 0.10),
    "long_ctx8k_mfu": Band(HIGHER, 0.10),
    "decode_tok_s": Band(HIGHER, 0.15),
    "decode_prefill_tok_s": Band(HIGHER, 0.15),
    "decode_ms_per_tok": Band(LOWER, 0.15),
    "decode_attainment_frac": Band(HIGHER, 0.15),
    # --- wall-clock: serving throughput / latency ----------------------
    "serve_tok_s": Band(HIGHER, 0.10),
    "serve_goodput_tok_s": Band(HIGHER, 0.10),
    "serve_goodput_slo_tok_s": Band(HIGHER, 0.10),
    "serve_ms_per_tok": Band(LOWER, 0.10),
    "serve_attainment_frac": Band(HIGHER, 0.10),
    "serve_mfu": Band(HIGHER, 0.10),
    "serve_ttft_p99_ms": Band(LOWER, 0.25),
    "serve_tbt_p99_ms": Band(LOWER, 0.25),
    "serve_queue_delay_p99_ms": Band(LOWER, 0.25),
    # --- suite time (always informational: CI boxes vary) --------------
    "suite_total_call_s": Band(LOWER, 0.25),
    # --- static: multichip dryrun losses (fixed seed + geometry — a
    # drifting loss means a parallelism path changed numerics; the 5%
    # band absorbs cross-version RNG/layout noise, which measured at
    # most 0.64% across the shipped rounds) -----------------------------
    "multichip_mesh_loss": Band(STATIC, 0.05),
    "multichip_fused_attention_loss": Band(STATIC, 0.05),
    "multichip_moe_loss": Band(STATIC, 0.05),
    "multichip_ring_attention_loss": Band(STATIC, 0.05),
    "multichip_ulysses_loss": Band(STATIC, 0.05),
    "multichip_multi_slice_loss": Band(STATIC, 0.05),
    "multichip_gpt_pipeline_loss": Band(STATIC, 0.05),
    "multichip_pipeline_loss": Band(STATIC, 0.05),
}

#: Train headline keys that only compare between rows with the same
#: ``metric`` (the rung ladder legitimately changes shape per round).
_HEADLINE_KEYS = frozenset((
    "value", "tokens_per_sec_per_chip", "step_ms", "batch_per_chip",
    "model_flops_per_token", "train_hbm_floor_ms",
    "train_compute_floor_ms", "train_attainment_frac",
))

#: Prefixed train families match on their own ``<prefix>metric`` /
#: ``<prefix>shape`` key when both rows carry it.
_FAMILY_TAGS = (
    ("gpt2s_", "gpt2s_metric"),
    ("llama_", "llama_metric"),
    ("long_ctx_", "long_ctx_metric"),
    ("decode_", "decode_shape"),
)


#: Kinds whose key inventory is gated HARD, restricted to their own
#: prefix (losing a ``serve_``/``multichip_`` key is a schema break;
#: other keys on those rows are wrapper metadata).
_INVENTORY_PREFIXES = {"serving": "serve_", "multichip": "multichip_"}


def _same_population(
    kind: str,
    cur: tp.Mapping[str, tp.Any],
    ref: tp.Mapping[str, tp.Any],
) -> bool:
    """Row-level comparability: serving rows must share the geometry
    AND the offered load (serve_shape omits rate/request-count, and two
    rungs at different arrival rates legitimately differ several-fold
    on every wall-clock key); train rows must share the device + chip
    count (the static floors embed peak FLOPs and n_devices — a CPU
    smoke row must never hard-gate a TPU round's floors, or vice
    versa)."""
    if kind == "serving":
        return (
            cur.get("serve_shape") == ref.get("serve_shape")
            and cur.get("serve_rate_req_s") == ref.get("serve_rate_req_s")
            and cur.get("serve_requests") == ref.get("serve_requests")
        )
    if kind == "train":
        return (
            cur.get("device") == ref.get("device")
            and cur.get("n_devices") == ref.get("n_devices")
        )
    if kind == "multichip":
        # the dryrun losses depend on the virtual device pool (mesh
        # factorizations change with it) but not on the host device
        return cur.get("n_devices") == ref.get("n_devices")
    return True


def _comparable(
    kind: str,
    cur: tp.Mapping[str, tp.Any],
    ref: tp.Mapping[str, tp.Any],
    key: str,
) -> bool:
    if not _same_population(kind, cur, ref):
        return False
    if kind == "train":
        if key in _HEADLINE_KEYS:
            return cur.get("metric") == ref.get("metric")
        for prefix, tag in _FAMILY_TAGS:
            if key.startswith(prefix):
                a, b = cur.get(tag), ref.get(tag)
                return a is None or b is None or a == b
    return True


# ---------------------------------------------------------------------------
# Diffing
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    """One ledger observation. ``severity``: ``hard`` fails the gate;
    ``info`` rides the report only (CPU wall-clock drift, inventory
    warnings on train rows)."""

    severity: str
    key: str
    note: str
    current: tp.Optional[float] = None
    reference: tp.Optional[float] = None
    ref_source: tp.Optional[str] = None

    def __str__(self) -> str:
        vals = (
            f" (current {self.current!r} vs {self.reference!r}"
            f" from {self.ref_source})"
            if self.reference is not None else ""
        )
        return f"[{self.severity}] {self.key}: {self.note}{vals}"


def _find_ref(
    rows: tp.Sequence[Row],
    kind: str,
    cur: tp.Mapping[str, tp.Any],
    key: str,
) -> tp.Optional[Row]:
    for row in reversed(rows):
        rec = row.record
        if not row_ok(rec) or row_kind(rec) != kind:
            continue
        if rec.get(key) is None:
            continue
        if not _comparable(kind, cur, rec, key):
            continue
        return row
    return None


def diff_record(
    cur: tp.Mapping[str, tp.Any],
    rows: tp.Sequence[Row],
    *,
    hardware: tp.Optional[bool] = None,
) -> tp.List[Finding]:
    """Diff one record against the trajectory. ``hardware`` overrides
    the row's own device detection (the CI job pins CPU)."""
    if not row_ok(cur):
        return [Finding(
            "info", "status",
            f"non-ok row (status={cur.get('status', 'ok')!r}): a wedge "
            "is a wedge, not a regression — not gated",
        )]
    kind = row_kind(cur)
    hw = row_hardware(cur) if hardware is None else hardware
    findings: tp.List[Finding] = []

    for key, band in BANDS.items():
        cv = cur.get(key)
        if not isinstance(cv, (int, float)) or isinstance(cv, bool):
            continue
        ref = _find_ref(rows, kind, cur, key)
        if ref is None:
            continue
        rv = float(ref.record[key])
        cv = float(cv)
        scale = max(abs(rv), 1e-9)
        if band.direction == STATIC:
            if abs(cv - rv) > band.tol * scale + 1e-12:
                findings.append(Finding(
                    "hard", key,
                    "STATIC key drifted — compiled-in arithmetic "
                    "changed without a geometry change",
                    cv, rv, ref.source,
                ))
            continue
        frac = (rv - cv) / scale if band.direction == HIGHER else (
            (cv - rv) / scale
        )
        if frac > band.tol:
            sev = "hard" if hw else "info"
            findings.append(Finding(
                sev, key,
                f"regressed {frac:.1%} past the {band.tol:.0%} band"
                + ("" if hw else " (CPU row: informational)"),
                cv, rv, ref.source,
            ))

    # key-inventory gate: the record-schema twin of the pinned
    # ENGINE_STATS_KEYS contract
    prev = None
    for row in reversed(rows):
        if row_ok(row.record) and row_kind(row.record) == kind and (
            _same_population(kind, cur, row.record)
        ):
            prev = row
            break
    if prev is not None:
        # prefixed-inventory kinds gate hard on their own key family
        # (the record-schema contract); train/suite rows only warn — a
        # failed auxiliary rung legitimately drops its family
        prefix = _INVENTORY_PREFIXES.get(kind)
        lost = [
            k for k in prev.record
            if k not in cur and (prefix is None or k.startswith(prefix))
        ]
        for k in sorted(lost):
            findings.append(Finding(
                "hard" if prefix is not None else "info", k,
                f"key present in {prev.source} is missing from the "
                "current record (inventory shrank)",
            ))
    return findings


# ---------------------------------------------------------------------------
# Trend report
# ---------------------------------------------------------------------------

_TREND_COLUMNS = {
    "train": (
        "metric", "value", "gpt2s_mfu", "llama_mfu", "long_ctx_mfu",
        "decode_tok_s", "train_attainment_frac", "status",
    ),
    "serving": (
        "serve_tok_s", "serve_goodput_slo_tok_s", "serve_ms_per_tok",
        "serve_attainment_frac", "serve_mfu", "serve_hbm_floor_ms_static",
        "serve_bytes_per_token_static", "status",
    ),
    "suite": ("suite_total_call_s", "suite_n_calls", "status"),
    "multichip": (
        "n_devices", "multichip_mesh_loss", "multichip_multi_slice_loss",
        "multichip_gpt_pipeline_loss", "multichip_ring_attention_loss",
        "multichip_moe_loss", "status",
    ),
}


def _cell(v: tp.Any) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)[:40]


def markdown_report(
    rows: tp.Sequence[Row],
    current: tp.Sequence[tp.Tuple[str, tp.Mapping[str, tp.Any]]] = (),
    findings: tp.Sequence[Finding] = (),
) -> str:
    """The trend report the ``perf-ledger`` CI job uploads: one table
    per row kind over the trajectory (+ the current records, marked),
    then the findings, hard first."""
    out = ["# Perf-trajectory ledger", ""]
    by_kind: tp.Dict[str, tp.List[tp.Tuple[str, tp.Mapping]]] = {}
    for row in rows:
        by_kind.setdefault(row_kind(row.record), []).append(
            (os.path.basename(row.source), row.record)
        )
    for name, rec in current:
        by_kind.setdefault(row_kind(rec), []).append(
            (f"**{os.path.basename(name)}** (current)", rec)
        )
    for kind in ("train", "serving", "suite", "multichip"):
        entries = by_kind.get(kind)
        if not entries:
            continue
        cols = _TREND_COLUMNS[kind]
        out.append(f"## {kind} trajectory")
        out.append("")
        out.append("| source | " + " | ".join(cols) + " |")
        out.append("|---" * (len(cols) + 1) + "|")
        for src, rec in entries:
            status = (
                "ok" if row_ok(rec) else rec.get("status", "error")
            )
            vals = [
                _cell(status if c == "status" else rec.get(c))
                for c in cols
            ]
            out.append(f"| {src} | " + " | ".join(vals) + " |")
        out.append("")
    out.append("## Findings")
    out.append("")
    ordered = sorted(findings, key=lambda f: f.severity != "hard")
    if not ordered:
        out.append("No findings — trajectory clean.")
    for f in ordered:
        out.append(f"- {f}")
    out.append("")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# CLI driver (python -m midgpt_tpu.analysis --ledger)
# ---------------------------------------------------------------------------


def run_ledger(
    *,
    trajectory_root: str,
    records: tp.Sequence[str] = (),
    record_dirs: tp.Sequence[str] = (),
    suite_timing: tp.Optional[str] = None,
    report_path: tp.Optional[str] = None,
    hardware: tp.Optional[bool] = None,
) -> int:
    """The --ledger entry point. With ``records``: diff each against
    the trajectory (+ ingested record dirs). Without: self-check the
    trajectory — its most recent OK row is diffed against the rows
    before it (how CI keeps the shipped BENCH_r*.json green). Returns
    the exit code (1 on any hard finding)."""
    rows = load_trajectory(trajectory_root, record_dirs)
    if suite_timing:
        rows.append(Row(
            suite_timing,
            (rows[-1].index + 1) if rows else 0,
            load_suite_timing(suite_timing),
        ))

    current: tp.List[tp.Tuple[str, tp.Mapping[str, tp.Any]]] = []
    findings: tp.List[Finding] = []
    if records:
        for path in records:
            rec = load_record(path)
            current.append((path, rec))
            findings.extend(
                diff_record(rec, rows, hardware=hardware)
            )
    else:
        # self-check mode: the newest OK row OF EACH KIND vs everything
        # before it — the trajectory now ships several families (train
        # BENCH rounds, MULTICHIP rounds, ingested serving/suite rows),
        # and a single global "latest" would leave every other family's
        # shipped rows unchecked
        for kind in ("train", "serving", "suite", "multichip"):
            ok_rows = [
                r for r in rows
                if row_ok(r.record) and row_kind(r.record) == kind
            ]
            if not ok_rows:
                continue
            last = ok_rows[-1]
            before = [r for r in rows if r.index < last.index]
            current.append((f"{last.source} (self-check)", last.record))
            findings.extend(
                diff_record(last.record, before, hardware=hardware)
            )

    text = markdown_report(rows, current, findings)
    if report_path:
        with open(report_path, "w") as f:
            f.write(text + "\n")
    hard = [f for f in findings if f.severity == "hard"]
    summary = {
        "mode": "ledger",
        "trajectory_rows": len(rows),
        "records": [name for name, _ in current],
        "findings": len(findings),
        "hard": len(hard),
        "ok": not hard,
        "report": report_path,
    }
    print(json.dumps(summary, indent=2))
    import sys

    for f in findings:
        print(f"LEDGER {f}", file=sys.stderr)
    return 1 if hard else 0
