"""Declarative ruleset engine over a parsed compiled step.

A :class:`StepAnalysis` bundles everything a rule may inspect (HLO text,
parsed collectives, mesh description, batch geometry, donation audit);
each :class:`Rule` returns :class:`Violation` records. Rules are pure
functions of the analysis — no jax, no compilation — so they run against
canned fixtures in unit tests exactly as they run against a freshly
compiled train step.

The built-in rules encode the repo's sharding invariants (previously
300 lines of ad-hoc regex inside tests/test_hlo_collectives.py):

- ``no-batch-allgather`` — the classic GSPMD trap: an opaque boundary
  makes the partitioner gather the full batch onto every device.
- ``dcn-allreduce-only`` + ``cross-slice-grad-allreduce`` — the
  multi-slice DCN contract (SURVEY.md 2.6: DP-only across slices).
- ``seq-permute-not-gather`` — ring attention must move K/V by
  collective-permute hops, never by reconstituting the full sequence.
- ``expect-collective`` — a required collective kind exists (e.g. the
  MoE expert-combine psum).
- ``no-f64`` — nothing in the module computes in double precision.
- ``donation-intact`` — ``donate_argnums`` actually produced
  input/output buffer aliases (donation silently drops when shapes,
  layouts, or shardings stop matching).
- ``no-host-sync`` — nothing in the compiled program round-trips through
  the host (infeed/outfeed, host-transfer send/recv, python-callback
  custom-calls). Matters most for the fused K-step dispatch
  (``steps_per_dispatch``): a stray ``debug.print``/``pure_callback``
  inside the window would stall the whole K-step launch on the host,
  resurrecting exactly the per-dispatch latency the fusion amortizes.

New parallel configs pick their rules via :func:`rules_for_config`
(or build a custom list) instead of copy-pasting regexes.
"""

from __future__ import annotations

import dataclasses
import re
import typing as tp

from midgpt_tpu.analysis import hlo as hlo_mod
from midgpt_tpu.analysis.hlo import AliasEntry, Collective, MeshInfo

# mesh axes a global batch is sharded over / the sequence axis — kept in
# sync with parallel.mesh (imported lazily there to stay jax-free here)
BATCH_AXES = ("replica", "fsdp")
SEQUENCE_AXIS = "sequence"


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    message: str
    line: str = ""  # offending HLO line, when there is one

    def __str__(self) -> str:
        s = f"[{self.rule}] {self.message}"
        if self.line:
            s += f"\n    {self.line}"
        return s


@dataclasses.dataclass(frozen=True)
class StepAnalysis:
    """Everything the rules (and cost report) inspect about one compiled
    step. Build from HLO text + mesh description; the compile harness
    (:mod:`midgpt_tpu.analysis.harness`) fills this from a live config."""

    hlo: str
    mesh: MeshInfo
    collectives: tp.Tuple[Collective, ...]
    global_batch: tp.Optional[int] = None  # per-microstep sequences (B)
    block: tp.Optional[int] = None  # sequence length (T)
    aliases: tp.Tuple[AliasEntry, ...] = ()
    donated_leaves: tp.Optional[int] = None  # expected aliased buffers

    @classmethod
    def from_text(
        cls,
        hlo: str,
        mesh: MeshInfo,
        global_batch: tp.Optional[int] = None,
        block: tp.Optional[int] = None,
        donated_leaves: tp.Optional[int] = None,
    ) -> "StepAnalysis":
        return cls(
            hlo=hlo,
            mesh=mesh,
            collectives=tuple(hlo_mod.parse_collectives(hlo)),
            global_batch=global_batch,
            block=block,
            aliases=tuple(hlo_mod.parse_input_output_alias(hlo)),
            donated_leaves=donated_leaves,
        )

    @property
    def local_batch(self) -> tp.Optional[int]:
        """Per-device batch: B over the data-parallel axes."""
        if self.global_batch is None:
            return None
        shape = self.mesh.shape
        div = 1
        for a in BATCH_AXES:
            div *= shape.get(a, 1)
        return max(1, self.global_batch // div)

    @property
    def local_t(self) -> tp.Optional[int]:
        if self.block is None:
            return None
        return self.block // self.mesh.shape.get(SEQUENCE_AXIS, 1)


class Rule:
    """Base rule: subclasses set ``name``/``description`` and implement
    :meth:`check` returning a list of violations (empty = pass)."""

    name: str = "rule"
    description: str = ""

    def check(self, a: StepAnalysis) -> tp.List[Violation]:  # pragma: no cover
        raise NotImplementedError

    def violation(self, message: str, line: str = "") -> Violation:
        return Violation(rule=self.name, message=message, line=line)


_FLOAT_DTYPES = frozenset(
    {"f8e4m3fn", "f8e5m2", "f8e4m3b11fnuz", "f16", "bf16", "f32", "f64"}
)


class NoBatchAllGather(Rule):
    """No all-gather over dim 0 of a ``[B_local, T_local, ...]``
    floating-point activation. Rank-2 gathers are FSDP param shards
    (legitimate); feature-dim gathers are TP traffic (legitimate);
    integer gathers are index plumbing — e.g. the ``[B, T, 1]`` s32
    token-id gather an embed-dim-sharded embedding take needs — tiny
    and intended, not the trap."""

    name = "no-batch-allgather"
    description = "no batch-dim all-gather of activations"

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        assert a.global_batch is not None and a.block is not None, (
            f"{self.name} needs batch/block geometry on the StepAnalysis"
        )
        b_local, t_local = a.local_batch, a.local_t
        out = []
        for c in a.collectives:
            if c.kind != "all-gather":
                continue
            for dtype, shape in c.result_shapes:
                if dtype not in _FLOAT_DTYPES:
                    continue
                # activations are rank>=3 [B, T, ...]; the sequence dim
                # carries T_local on sequence-sharded meshes
                if (
                    len(shape) >= 3
                    and 0 in c.dims
                    and shape[1] in (t_local, a.block)
                    and shape[0] >= b_local
                ):
                    out.append(self.violation(
                        "batch-dim all-gather of an activation "
                        f"{shape} (op {c.op_name or '?'})",
                        c.line,
                    ))
        return out


class NoFullSequenceGather(Rule):
    """No rank>=3 activation all-gather that reconstitutes the full
    sequence length T on any dim >= 1 — the anti-pattern ring attention
    exists to avoid (K/V sit at [B,H,T,C] with T at dim 2)."""

    name = "seq-permute-not-gather"
    description = "sequence moves by permute hops, not full-T gathers"

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        assert a.block is not None, f"{self.name} needs block geometry"
        out = []
        for c in a.collectives:
            if c.kind != "all-gather":
                continue
            for shape in c.shapes:
                if len(shape) >= 3 and any(
                    d >= 1 and d < len(shape) and shape[d] == a.block
                    for d in c.dims
                ):
                    out.append(self.violation(
                        f"full-sequence all-gather of an activation {shape}",
                        c.line,
                    ))
        return out


class ExpectCollective(Rule):
    """A collective of ``kind`` must EXIST (e.g. the ring's permute hops,
    the MoE expert-combine psum) — its absence means the schedule the
    config paid for is not in the compiled step."""

    name = "expect-collective"
    description = "a required collective kind is present"

    def __init__(self, kind: str, why: str = ""):
        self.kind = kind
        self.why = why
        self.name = f"expect-{kind}"

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        if any(c.kind == self.kind for c in a.collectives):
            return []
        msg = f"no {self.kind} found in the compiled step"
        if self.why:
            msg += f" — {self.why}"
        return [self.violation(msg)]


class DcnAllReduceOnly(Rule):
    """Multislice DCN contract: every collective whose device group
    crosses the slice boundary must be an all-reduce (gradient/loss sums)
    with no activation-shaped operand — FSDP/TP gathers and permutes must
    stay inside a slice (SURVEY.md 2.6)."""

    name = "dcn-allreduce-only"
    description = "cross-slice traffic is all-reduce-only, no activations"

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        assert a.mesh.num_slices > 1, f"{self.name} needs a multislice mesh"
        b_local = a.local_batch
        out = []
        for c in a.collectives:
            if not a.mesh.collective_crosses_slice(c):
                continue
            if c.kind != "all-reduce":
                out.append(self.violation(
                    f"{c.kind} crosses the slice boundary (DCN)", c.line
                ))
                continue
            if b_local is not None and a.block is not None:
                for shape in c.shapes:
                    if len(shape) >= 2 and shape[:2] == (b_local, a.block):
                        out.append(self.violation(
                            "activation-shaped all-reduce crosses slices",
                            c.line,
                        ))
        return out


class CrossSliceGradAllReduce(Rule):
    """The cross-slice gradient all-reduce must EXIST: a step with no
    replica sync at all would silently train divergent replicas."""

    name = "cross-slice-grad-allreduce"
    description = "a param-shaped all-reduce crosses the slice boundary"

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        assert a.mesh.num_slices > 1, f"{self.name} needs a multislice mesh"
        for c in a.collectives:
            if c.kind != "all-reduce":
                continue
            if not a.mesh.collective_crosses_slice(c):
                continue
            if any(len(s) >= 2 for s in c.shapes):  # param-shaped sync
                return []
        return [self.violation(
            "no cross-slice gradient all-reduce found — replicas would "
            "train divergently (DP sync missing from the compiled step)"
        )]


class NoF64(Rule):
    """No f64/c128 anywhere in the module: TPUs emulate double precision
    at a catastrophic slowdown, so any f64 means an accidental promotion
    (a Python float, np default dtype, ...) leaked into the step."""

    name = "no-f64"
    description = "no double-precision buffers in the compiled step"

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        bad = hlo_mod.dtypes_used(a.hlo) & {"f64", "c128"}
        if not bad:
            return []
        return [self.violation(
            f"double-precision dtypes in the compiled step: {sorted(bad)}"
        )]


class NoHostSync(Rule):
    """No host round-trips inside the compiled step: infeed/outfeed ops,
    send/recv with ``is_host_transfer=true``, or python-callback
    custom-calls (``pure_callback``/``io_callback``/``debug.print`` lower
    to ``custom_call_target="xla_*_callback"``). Any of these serializes
    the program against the host — and inside a fused K-step window
    (steps_per_dispatch) it stalls all K steps per launch, undoing the
    dispatch-latency amortization the fusion exists for."""

    name = "no-host-sync"
    description = "no host callbacks / infeed / outfeed in the step"

    # the op kind sits between the result shape (possibly a nested tuple)
    # and its operand list: preceded by whitespace/'='/')', never by the
    # '%' of an instruction-name reference
    _OP = re.compile(
        r"[=\s)](infeed|outfeed|send|recv|custom-call)"
        r"(?:-(?:done|start))?\("
    )
    _CALLBACK = re.compile(
        r'custom_call_target="[^"]*callback[^"]*"', re.I
    )

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        out = []
        for line in a.hlo.splitlines():
            m = self._OP.search(line)
            if not m:
                continue
            kind = m.group(1)
            if kind in ("infeed", "outfeed"):
                out.append(self.violation(
                    f"{kind} in the compiled step (host transfer)",
                    line.strip(),
                ))
            elif kind in ("send", "recv"):
                if "is_host_transfer=true" in line:
                    out.append(self.violation(
                        f"host-transfer {kind} in the compiled step",
                        line.strip(),
                    ))
            elif self._CALLBACK.search(line):
                out.append(self.violation(
                    "python-callback custom-call in the compiled step "
                    "(pure_callback / io_callback / debug.print)",
                    line.strip(),
                ))
        return out


class NoDequantMaterialization(Rule):
    """Quantized serving programs (midgpt_tpu.quant) must stream their
    weights as int8 and keep the dequantization fused into each matmul's
    epilogue — the whole point of the int8 path is halving the per-token
    weight HBM stream, and one stray ``dequantize_model`` (or a scale
    applied to the WEIGHT instead of the matmul result) silently restores
    the full-precision stream while the engine still reports quant=on.

    Checked against the compiled HLO, parameterized by the quantized
    weight-matrix shapes (stacked ``[L, in, out]`` leaves and their
    static per-layer slices, ``midgpt_tpu.quant.quant_weight_shapes``):

    - at least one s8 weight-shaped ENTRY PARAMETER exists (the int8
      array is what crosses the HBM->program boundary);
    - no f32/bf16/f16 entry parameter or constant has a weight-matrix
      shape (nobody smuggled a dequantized copy in);
    - no ``multiply`` instruction produces an f32/bf16/f16 result of a
      weight-matrix shape — the scale must land on the ACTIVATION-shaped
      matmul result (the epilogue), never on the weights (which would
      materialize the dequantized matrix per use).

    A transient weight-shaped ``convert`` is deliberately NOT flagged:
    inside a fusion it is exactly the fused dequant this rule demands
    (TPU fuses the s8->bf16 read into the dot; the CPU test backend
    materializes it in a loop fusion as an artifact of its Eigen dot
    lowering — a backend decision the program can't control)."""

    name = "no-dequant-materialization"
    description = "int8 weights stream quantized; dequant stays fused"

    _MAT = re.compile(
        r"=\s*(f32|bf16|f16)\[([0-9,]*)\](?:\{[^}]*\})?\s+"
        r"(multiply|constant)\("
    )

    def __init__(self, weight_shapes: tp.Iterable[tp.Tuple[int, ...]]):
        self.weight_shapes = frozenset(
            tuple(int(d) for d in s) for s in weight_shapes
        )
        assert self.weight_shapes, "need the quantized weight shapes"

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        out = []
        params = hlo_mod.parse_entry_parameters(a.hlo)
        if not any(
            d == "s8" and s in self.weight_shapes for d, s in params
        ):
            out.append(self.violation(
                "no s8 weight-shaped entry parameter — the compiled "
                "program is not consuming the quantized pytree (weights "
                "dequantized before compilation?)"
            ))
        for d, s in params:
            if d in ("f32", "bf16", "f16") and s in self.weight_shapes:
                out.append(self.violation(
                    f"full-precision weight-matrix entry parameter "
                    f"{d}{list(s)} — a dequantized copy streams from HBM"
                ))
        for line in a.hlo.splitlines():
            m = self._MAT.search(line)
            if not m:
                continue
            shape = tuple(
                int(x) for x in m.group(2).split(",") if x != ""
            )
            if shape not in self.weight_shapes:
                continue
            kind = m.group(3)
            msg = (
                "scale applied at weight shape (dequantized weight "
                "materialized) — the epilogue multiply must be "
                "activation-shaped"
                if kind == "multiply"
                else "full-precision weight-matrix constant baked into "
                "the program"
            )
            out.append(self.violation(
                f"{msg}: {m.group(1)}{list(shape)}", line.strip()
            ))
        return out


class NoPageGatherAllGather(Rule):
    """Sharded paged serving (``ServingEngine(mesh=...)``): the KV pool
    shards by WHOLE KV HEADS, precisely so the block-table page gathers
    (an index into the replicated page dim) stay shard-local — the one
    mesh decision that keeps serving dispatch collective costs at two
    activation-row psums per layer. The footgun this rule gates
    (ROADMAP item 1 named it when the work was scoped): one missing or
    wrong sharding constraint around the gather and the partitioner
    "helps" by all-gathering the pool payload (every page of every head
    onto every chip — the KV stream times tp) or the whole per-slot
    batch through the gather, silently erasing the memory/bandwidth
    split the mesh exists for while the engine still reports tp > 1.

    Checked against the compiled (SPMD-partitioned, local-shape) HLO,
    parameterized with the FULL (unsharded) pool/page-gather payload
    shapes for the audited geometry (``serving_payload_shapes``) and the
    slot count:

    - no floating-point all-gather result takes a full payload shape
      (a per-shard payload regathered to all heads);
    - no rank>=3 floating-point all-gather gathers dim 0 of a
      slot-batched activation (slot/batch dim >= slots) — slot state is
      replicated by design (DP is shared-nothing replicas, not a
      sharded slot axis), so any batch-dim gather means an activation
      was left unconstrained through the page gather.

    Integer gathers (block tables, index plumbing) are never flagged —
    they are the replicated index arrays the design feeds every shard."""

    name = "no-batch-allgather-in-page-gather"
    description = "page gathers stay shard-local: no pool/batch all-gather"

    def __init__(
        self,
        payload_shapes: tp.Iterable[tp.Tuple[int, ...]],
        slots: int,
    ):
        self.payload_shapes = frozenset(
            tuple(int(d) for d in s) for s in payload_shapes
        )
        assert self.payload_shapes, "need the pool payload shapes"
        assert slots >= 1, slots
        self.slots = slots

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        out = []
        for c in a.collectives:
            if c.kind != "all-gather":
                continue
            for dtype, shape in c.result_shapes:
                if dtype not in _FLOAT_DTYPES:
                    continue
                if shape in self.payload_shapes:
                    out.append(self.violation(
                        "pool-payload all-gather: a KV-head-sharded "
                        f"page buffer regathered to full shape {shape} "
                        f"(op {c.op_name or '?'}) — the block-table "
                        "gather must stay shard-local",
                        c.line,
                    ))
                elif (
                    len(shape) >= 3
                    and 0 in c.dims
                    and shape[0] >= self.slots
                ):
                    out.append(self.violation(
                        "slot/batch-dim all-gather of an activation "
                        f"{shape} (op {c.op_name or '?'}) in a sharded "
                        "serving program — slot state is replicated by "
                        "design, nothing may gather it",
                        c.line,
                    ))
        return out


class DonationIntact(Rule):
    """``donate_argnums`` actually stuck: the executable aliases at least
    ``donated_leaves`` parameter buffers to outputs. XLA silently drops
    donation when an output's shape/layout/sharding stops matching its
    donated input — at 1.5B params that silently doubles state HBM."""

    name = "donation-intact"
    description = "donated state buffers are aliased input->output"

    def check(self, a: StepAnalysis) -> tp.List[Violation]:
        expected = a.donated_leaves
        assert expected is not None and expected > 0, (
            f"{self.name} needs donated_leaves on the StepAnalysis"
        )
        aliased = {e.param_number for e in a.aliases}
        if len(aliased) >= expected:
            return []
        return [self.violation(
            f"only {len(aliased)} of {expected} donated state buffers are "
            "aliased input->output — donation was (partially) dropped and "
            "the step holds two copies of the un-aliased state"
        )]


@dataclasses.dataclass(frozen=True)
class RuleResult:
    rule: str
    description: str
    violations: tp.Tuple[Violation, ...]

    @property
    def ok(self) -> bool:
        return not self.violations


@dataclasses.dataclass(frozen=True)
class Report:
    results: tp.Tuple[RuleResult, ...]

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    @property
    def violations(self) -> tp.Tuple[Violation, ...]:
        return tuple(v for r in self.results for v in r.violations)

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "ok": self.ok,
            "rules": [
                {
                    "rule": r.rule,
                    "ok": r.ok,
                    "description": r.description,
                    "violations": [
                        {"message": v.message, "line": v.line}
                        for v in r.violations
                    ],
                }
                for r in self.results
            ],
        }


class RuleSet:
    def __init__(self, rules: tp.Iterable[Rule]):
        self.rules = list(rules)

    def evaluate(self, analysis: StepAnalysis) -> Report:
        return Report(results=tuple(
            RuleResult(
                rule=r.name,
                description=r.description,
                violations=tuple(r.check(analysis)),
            )
            for r in self.rules
        ))


def rules_for_config(cfg, mesh: MeshInfo) -> RuleSet:
    """The invariants a shipped config must satisfy, derived from its
    declared parallelism. New parallel configs extend this mapping (or
    pass a hand-built RuleSet to the CLI/tests) instead of writing HLO
    regexes.

    ``cfg`` is an :class:`midgpt_tpu.config.ExperimentConfig`; only its
    declarative fields are read, so this stays jax-free.
    """
    rules: tp.List[Rule] = [
        NoF64(),
        NoBatchAllGather(),
        DonationIntact(),
        NoHostSync(),
    ]
    shape = mesh.shape
    if cfg.model.attn_impl == "ring" and shape.get(SEQUENCE_AXIS, 1) > 1:
        rules.append(NoFullSequenceGather())
        rules.append(ExpectCollective(
            "collective-permute",
            "the ring schedule is not in the compiled step",
        ))
    if cfg.model.mlp == "moe" and shape.get("tensor", 1) > 1:
        rules.append(ExpectCollective(
            "all-reduce", "the expert-combine psum is missing"
        ))
    if mesh.num_slices > 1:
        rules.append(DcnAllReduceOnly())
        rules.append(CrossSliceGradAllReduce())
    return RuleSet(rules)
