"""Static HBM traffic auditor for the serving programs.

Serving decode is HBM-bound at every practical batch (PERF.md r5), so
its performance floor is a BYTES budget: the weight stream + the live
KV stream, per decode step, against the chip's HBM bandwidth. Two
shipped bug classes silently changed those bytes without changing any
output: PR 6's closed-over-model constant folding (weights baked into
the executable — and, quantized, folded back to full f32, doubling the
exact stream the int8 path halves) and the PR 7 class of partitioner
"help" (a sharded buffer regathered through a page gather, multiplying
the per-chip stream by tp). Each was caught by a hand-written rule that
happened to match its HLO shape; this module generalizes both into a
BYTE budget: compute the streams from the compiled program's entry
interface, and gate them against checked-in expectations
(:mod:`midgpt_tpu.analysis.budgets`) — any regression that
re-materializes or re-gathers a large buffer moves bytes and trips the
gate, regardless of what the HLO looks like.

Two layers, both jax-free:

- **HLO streams** (:func:`traffic_report`): classify every entry
  parameter of the compiled program into weight / KV-pool / logits /
  control streams by (dtype, shape) against the live trees' keys
  (:func:`stream_keys` — the harness builds these from the very model/
  pool/logits it compiled), and count large CONSTANTS separately — a
  weight that stops being an entry parameter did not stop streaming,
  it moved into the executable, which is exactly the PR 6 bug.
- **Roofline floor** (:func:`floor_decomposition`): the analytic
  bytes-per-step decomposition (weights + live KV + logits) and its
  ms floor at a given HBM bandwidth — the same arithmetic
  ``scripts/bench_decode.py`` records as ``decode_hbm_floor_ms``, so
  PERF.md's floor table is generated, not hand-computed.

Accounting note (found by writing this auditor): PERF.md's r5 prose
stated the 124M B=8 KV stream as ~0.12 ms, which counts the K and V
planes as ONE stream; both are read every step (K for scores, V for
the value sum — exactly as scripts/bench_decode.py's recorded floor
computes), so the decomposition below reports ~0.24 ms at the same
geometry and the regenerated PERF table carries the corrected total.
"""

from __future__ import annotations

import dataclasses
import re
import typing as tp

from midgpt_tpu.analysis import hlo as hlo_mod

ShapeT = tp.Tuple[int, ...]
KeyT = tp.Tuple[str, ShapeT]  # (hlo dtype, shape)

STREAMS = ("weights", "kv", "logits", "control", "constants")

# jax dtype name -> HLO primitive type (entry-parameter classification
# compares live pytree leaves against parsed HLO shapes)
_JAX_TO_HLO_DTYPE = {
    "bfloat16": "bf16", "float16": "f16", "float32": "f32",
    "float64": "f64", "int8": "s8", "uint8": "u8", "int16": "s16",
    "int32": "s32", "int64": "s64", "uint32": "u32", "uint64": "u64",
    "bool": "pred",
}

_CONST_RE = re.compile(
    r"=\s*([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?\s+constant\("
)


def hlo_dtype(jax_dtype) -> str:
    """'bfloat16' (or a numpy dtype) -> 'bf16'."""
    name = str(jax_dtype)
    return _JAX_TO_HLO_DTYPE.get(name, name)


def parse_large_constants(
    hlo: str, *, min_bytes: int = 4096
) -> tp.List[KeyT]:
    """Every ``constant(...)`` instruction in the module whose buffer is
    at least ``min_bytes`` — below that sit iota tables, norm epsilons
    and mask literals (legitimate); above it sits baked-in model state
    (the PR 6 closed-over-model bug class)."""
    out: tp.List[KeyT] = []
    for line in hlo.splitlines():
        m = _CONST_RE.search(line)
        if not m:
            continue
        dtype = m.group(1)
        shape = tuple(int(x) for x in m.group(2).split(",") if x != "")
        if hlo_mod.shape_bytes(dtype, shape) >= min_bytes:
            out.append((dtype, shape))
    return out


@dataclasses.dataclass(frozen=True)
class TrafficReport:
    """Per-dispatch HBM stream decomposition of one compiled program."""

    program: str
    streams: tp.Mapping[str, int]  # bytes per stream (entry interface)
    window_steps: int  # model steps per dispatch (the K-step scan)
    comms_bytes: int  # collective wire bytes per dispatch (sharded)
    unclassified: tp.Tuple[KeyT, ...]  # float params matching no key set

    @property
    def weights_bytes_per_dispatch(self) -> int:
        """The weight stream is re-read by every step of the fused
        window scan — per dispatch it pays ``window_steps`` times."""
        return self.streams["weights"] * self.window_steps

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "program": self.program,
            "streams": dict(self.streams),
            "window_steps": self.window_steps,
            "weights_bytes_per_dispatch": self.weights_bytes_per_dispatch,
            "comms_bytes": self.comms_bytes,
            "unclassified": [
                f"{d}[{','.join(map(str, s))}]" for d, s in self.unclassified
            ],
        }


def traffic_report(
    hlo: str,
    *,
    program: str,
    stream_keys: tp.Mapping[str, tp.Collection[KeyT]],
    window_steps: int = 1,
    comms_bytes: int = 0,
    min_const_bytes: int = 4096,
) -> TrafficReport:
    """Classify the compiled program's entry parameters into streams.

    ``stream_keys`` maps ``weights`` / ``kv`` / ``logits`` to the
    (dtype, shape) keys of the live trees the program was compiled
    against (shard-LOCAL shapes under a mesh — the partitioned HLO
    contains those). Integer/bool parameters are ``control`` (block
    tables, masks, lengths); float parameters matching no key set are
    reported as ``unclassified`` rather than silently binned — an
    unexplained large float input is itself a finding."""
    params = hlo_mod.parse_entry_parameters(hlo)
    weight_keys = frozenset(stream_keys.get("weights", ()))
    kv_keys = frozenset(stream_keys.get("kv", ()))
    logit_keys = frozenset(stream_keys.get("logits", ()))
    streams = {s: 0 for s in STREAMS}
    unclassified: tp.List[KeyT] = []
    for dtype, shape in params:
        nbytes = hlo_mod.shape_bytes(dtype, shape)
        key = (dtype, shape)
        if key in weight_keys:
            streams["weights"] += nbytes
        elif key in kv_keys:
            streams["kv"] += nbytes
        elif key in logit_keys:
            streams["logits"] += nbytes
        elif dtype in ("s8", "bf16", "f16", "f32", "f64"):
            # s8 counts as a potential weight dtype: an s8 param that
            # matches no expected shape is just as suspicious
            if nbytes >= min_const_bytes:
                unclassified.append(key)
            else:
                streams["control"] += nbytes
        else:
            streams["control"] += nbytes
    for dtype, shape in parse_large_constants(
        hlo, min_bytes=min_const_bytes
    ):
        streams["constants"] += hlo_mod.shape_bytes(dtype, shape)
    return TrafficReport(
        program=program,
        streams=streams,
        window_steps=window_steps,
        comms_bytes=comms_bytes,
        unclassified=tuple(unclassified),
    )


# ---------------------------------------------------------------------------
# analytic roofline floor (config arithmetic, no HLO needed)
# ---------------------------------------------------------------------------


def _mlp_hidden(cfg) -> int:
    # mirrors models.gpt.mlp_hidden_dim without importing jax: pinned
    # width, else ratio*D rounded UP to a multiple of 256 when fractional
    if cfg.mlp_hidden is not None:
        return cfg.mlp_hidden
    f = cfg.mlp_ratio * cfg.n_embd
    if f == int(f):
        return int(f)
    return 256 * -(-int(f) // 256)


def weight_stream_bytes(cfg, *, quant: bool = False) -> int:
    """Bytes of model weights ONE decode step streams from HBM.

    Counts every matrix a decode forward contracts against: the block
    projections and the lm head ([D, V] — counted once; the embedding
    side of a tied/init-tied pair is a B-row GATHER, not a stream),
    plus the small norm vectors. Matches ``count_params(model) * 2``
    (scripts/bench_decode.py's floor numerator) to within the norm
    vectors at bf16, and prices the int8 path as s8 matrices + f32
    per-output-channel scales (midgpt_tpu.quant)."""
    assert cfg.mlp in ("gelu", "swiglu"), (
        f"analytic weight stream covers dense MLPs, got {cfg.mlp!r}"
    )
    d, c = cfg.n_embd, cfg.head_dim
    h, hkv = cfg.n_head, cfg.kv_heads
    f = _mlp_hidden(cfg)
    qkv_out = (h + 2 * hkv) * c
    gate = 1 if cfg.mlp == "swiglu" else 0
    # per-layer matmul element counts and their per-matrix OUT dims
    mats = [
        (d * qkv_out, qkv_out),  # wqkv
        (h * c * d, d),  # wo
        (d * f, f),  # w_up
        (f * d, d),  # w_down
    ] + [(d * f, f)] * gate
    head = (d * cfg.vocab_size, cfg.vocab_size)
    norm_bytes = 0
    if cfg.qk_norm:
        # q/k LayerNorms: one [C] scale each per layer, model dtype
        norm_bytes += cfg.n_layer * 2 * c * 2
    if quant:
        per_layer = sum(n for n, _ in mats) * 1  # s8
        per_layer += sum(out for _, out in mats) * 4  # f32 scales
        head_bytes = head[0] * 1 + head[1] * 4
    else:
        per_layer = sum(n for n, _ in mats) * 2  # bf16
        head_bytes = head[0] * 2
    return cfg.n_layer * per_layer + head_bytes + norm_bytes


def kv_stream_bytes(
    cfg, *, slots: int, live_tokens: float, cache_bytes: int = 2
) -> int:
    """Bytes of KV cache ONE decode step streams: every slot's live
    context, K for the scores and V for the value sum, all layers —
    the same arithmetic as scripts/bench_decode.py's recorded floor."""
    return int(
        cfg.n_layer * slots * cfg.kv_heads * live_tokens * cfg.head_dim
        * cache_bytes * 2  # K and V are both read
    )


def floor_decomposition(
    cfg,
    *,
    slots: int,
    live_tokens: tp.Optional[float] = None,
    quant: bool = False,
    kv_quant: bool = False,
    cache_bytes: int = 2,
    page_size: int = 16,
    hbm_gbps: float = 800.0,
    tp_degree: int = 1,
) -> tp.Dict[str, tp.Any]:
    """The static bytes-per-step roofline for one serving geometry:
    weight + KV + logits streams, bytes per token, and the ms/step HBM
    floor at ``hbm_gbps``. ``live_tokens`` defaults to ``block_size``
    (the fully-grown worst case); pass a trace mean for a workload
    floor. Under TP the weight and KV streams are per-CHIP (1/tp each
    — column/row-parallel weights, whole-KV-head pool sharding); the
    cross-chip wire bytes are cost_report territory, not HBM.
    ``kv_quant`` prices the int8 paged pool: 1-byte K/V elements plus
    the f32 per-(page, KV-head) scale planes of the live pages (one
    f32 per plane per K and V — ``page_size`` sets how many positions
    share a scale)."""
    live = float(
        cfg.block_size if live_tokens is None else live_tokens
    )
    w = weight_stream_bytes(cfg, quant=quant) // tp_degree
    kv_bytes = 1 if kv_quant else cache_bytes
    kv = kv_stream_bytes(
        cfg, slots=slots, live_tokens=live, cache_bytes=kv_bytes
    ) // tp_degree
    if kv_quant:
        # per-page dequant scales: live pages x KV heads x f32, K and V
        live_pages = -(-int(live) // page_size)
        kv += (
            cfg.n_layer * slots * live_pages * cfg.kv_heads * 4 * 2
        ) // tp_degree
    # the carried [S, V] f32 logits are read (sampling) and written
    # (carry) once per step; vocab-sharded under TP
    logits = 2 * slots * cfg.vocab_size * 4 // tp_degree
    total = w + kv + logits
    to_ms = 1e3 / (hbm_gbps * 1e9)
    return {
        "slots": slots,
        "live_tokens": live,
        "quant": quant,
        "kv_quant": kv_quant,
        "tp": tp_degree,
        "hbm_gbps": hbm_gbps,
        "weights_bytes_per_step": w,
        "kv_bytes_per_step": kv,
        "logits_bytes_per_step": logits,
        "bytes_per_step": total,
        "bytes_per_token": total // slots,
        "weights_floor_ms": round(w * to_ms, 4),
        "kv_floor_ms": round(kv * to_ms, 4),
        "floor_ms_per_step": round(total * to_ms, 4),
        # per emitted token (a full-occupancy decode step emits one
        # token per slot): the numerator of the serving attainment
        # fraction — attainment = floor_ms_per_token / measured ms/tok.
        # Significant digits, not decimals: tiny CPU test geometries
        # sit at ~1e-5 ms and must not round to a hard zero.
        "floor_ms_per_token": float(f"{total * to_ms / slots:.4g}"),
    }


def train_param_count(cfg) -> int:
    """Analytic parameter count of a dense GPT config (jax-free mirror
    of ``models.gpt.count_params`` PLUS the embedding table — the
    optimizer state streams the embedding too, so the training-step
    byte floor counts it even though the FLOP accounting doesn't)."""
    assert cfg.mlp in ("gelu", "swiglu"), (
        f"analytic train floor covers dense MLPs, got {cfg.mlp!r}"
    )
    d, c = cfg.n_embd, cfg.head_dim
    f = _mlp_hidden(cfg)
    qkv_out = (cfg.n_head + 2 * cfg.kv_heads) * c
    per_layer = (
        d * qkv_out + cfg.n_head * c * d
        + (3 if cfg.mlp == "swiglu" else 2) * d * f
    )
    return cfg.n_layer * per_layer + 2 * cfg.vocab_size * d


#: Bytes of HBM traffic one optimizer step moves per parameter under
#: the donated f32-Adam step: f32 params read+written (8) + Adam m,v
#: read+written (16) + the f32 grad written then read by the update (8)
#: + the bf16 compute-cast copy written then re-read by the backward
#: (4). Deliberately coarse (activations excluded — they are the
#: compute side's concern) but stated, so the floor is reproducible
#: arithmetic rather than folklore.
TRAIN_STATE_BYTES_PER_PARAM = 36


def train_floor_decomposition(
    cfg,
    *,
    batch_size: int,
    n_devices: int = 1,
    flops_per_token: float,
    peak_flops_per_device: float,
    hbm_gbps: float = 800.0,
    state_shards: tp.Optional[int] = None,
) -> tp.Dict[str, tp.Any]:
    """The static per-step roofline for one TRAINING geometry: the
    compute floor (model FLOPs at the chip's peak — what MFU is
    measured against) and the optimizer-state HBM floor
    (:data:`TRAIN_STATE_BYTES_PER_PARAM` per parameter, sharded over
    ``state_shards`` — defaults to ``n_devices``, the FSDP default),
    combined as ``floor_ms_per_step = max(compute, hbm)``. The
    attainment fraction a measured step carries is
    ``floor_ms_per_step / measured_step_ms`` — 1.0 means the hardware
    ceiling, and for the compute-bound training regime it tracks MFU by
    construction. ``flops_per_token``/``peak_flops_per_device`` are
    passed in so this stays jax-free (utils.metrics wires the
    device-dependent values)."""
    n_params = train_param_count(cfg)
    shards = max(1, n_devices if state_shards is None else state_shards)
    hbm_bytes = n_params * TRAIN_STATE_BYTES_PER_PARAM // shards
    tokens_per_step = batch_size * cfg.block_size
    compute_ms = (
        tokens_per_step * flops_per_token
        / (peak_flops_per_device * max(1, n_devices)) * 1e3
    )
    hbm_ms = hbm_bytes / (hbm_gbps * 1e9) * 1e3
    return {
        "n_params": n_params,
        "tokens_per_step": tokens_per_step,
        "hbm_gbps": hbm_gbps,
        "train_state_bytes_per_step": hbm_bytes,
        "train_compute_floor_ms": round(compute_ms, 4),
        "train_hbm_floor_ms": round(hbm_ms, 4),
        "train_floor_ms_per_step": round(max(compute_ms, hbm_ms), 4),
        "train_floor_bound": (
            "compute" if compute_ms >= hbm_ms else "hbm"
        ),
    }


def floor_table_markdown(rows: tp.Sequence[tp.Dict[str, tp.Any]]) -> str:
    """Render floor decompositions as the PERF.md markdown table. The
    CI serving-audit job regenerates this; PERF.md carries the output
    verbatim, so the published floor numbers can never drift from the
    auditor's arithmetic."""
    lines = [
        "| geometry | weights MB | KV MB | bytes/token | weights ms "
        "| KV ms | floor ms/step |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        geom = (
            f"B={r['slots']} live={int(r['live_tokens'])}"
            f"{' int8' if r['quant'] else ' bf16'}"
            + (" kv8" if r.get("kv_quant") else "")
            + (f" tp={r['tp']}" if r.get("tp", 1) > 1 else "")
        )
        lines.append(
            f"| {geom} "
            f"| {r['weights_bytes_per_step'] / 1e6:.1f} "
            f"| {r['kv_bytes_per_step'] / 1e6:.1f} "
            f"| {r['bytes_per_token']:,} "
            f"| {r['weights_floor_ms']:.3f} "
            f"| {r['kv_floor_ms']:.3f} "
            f"| {r['floor_ms_per_step']:.3f} |"
        )
    return "\n".join(lines)


def train_budget_table_markdown(
    budgets: tp.Mapping[tp.Tuple[str, int], tp.Mapping[str, tp.Any]],
) -> str:
    """Render the checked-in train traffic cells
    (:data:`midgpt_tpu.analysis.budgets.TRAIN_BUDGETS`) as the PERF.md
    markdown table — one row per (mesh geometry, window K) cell, with
    the ICI/DCN tier split and the per-axis decomposition. Generated
    from the budget dict itself, so the published numbers can never
    drift from what CI gates. jax-free."""
    lines = [
        "| geometry | K | ICI MB/step | DCN MB/step | by axis |",
        "|---|---|---|---|---|",
    ]
    for (geom, k), cell in sorted(budgets.items()):
        axes = ", ".join(
            f"{a}: {b / 1e6:.1f}"
            for a, b in sorted(cell.get("by_axis", {}).items())
        )
        lines.append(
            f"| {geom} | {k} "
            f"| {cell['ici_bytes'] / 1e6:.1f} "
            f"| {cell['dcn_bytes'] / 1e6:.1f} "
            f"| {axes} |"
        )
    return "\n".join(lines)
