"""Mixed-precision choreography prover for the fused K-step TRAIN window.

train.py states its precision contract in prose: f32 master params and
f32 Adam moments, bf16 matmul operands (``cast_floating(state.params,
compute_dtype)``), f32 loss/softmax accumulation, a grad-accum scan
whose carried grads stay in compute dtype with one f32 loss scalar, and
a remat policy whose checkpointed segments recompute the forward
op-for-op. Every one of those clauses has a serving-side twin that
shipped as a real bug before the choreography prover existed (PR 8's
bf16 drift class), and the training side has none of that machine
checking — a ``mu_dtype=bfloat16`` slipped into the optimizer chain, an
accidental f32 upcast before the projections, or a remat policy that
recomputes something *else* would all train, converge slightly worse,
and burn a hardware round to notice.

This module proves the contract on the traced jaxpr of the REAL fused
window program (``train.get_train_window`` — the same cache the trainer
launches from), using :mod:`midgpt_tpu.analysis.choreo`'s flattened-
trace machinery. All checks are dtype/structure assertions on the
trace + the ``jax.eval_shape`` output tree; nothing executes.

Scope note — collective operand dtypes: the jaxpr of a pjit program
contains no collectives (GSPMD materializes them at compile time), so
psum/all-reduce wire dtypes are NOT provable here. They are gated
byte-wise by the train traffic budgets
(:data:`midgpt_tpu.analysis.budgets.TRAIN_BUDGETS` — an f32 gather of
a bf16 shard doubles its wire bytes and trips the band), which is the
stronger check anyway.

Deferral semantics — the grad-accum carry check: when the trace has no
grad-accum scan at all (``g_accum_iters == 1``, or the re-unrolled-loop
fault class), there is no carry whose dtype could be wrong, so the
check reports ok with an explicit "no grad-accum scan in trace" detail
— the *structure* (trip count == G) is the dispatch budget's gate
(:func:`midgpt_tpu.analysis.dispatch.train_dispatch_report`), and the
green-path tests assert the "found" detail so the check can never pass
vacuously on the shipped configs.
"""

from __future__ import annotations

import dataclasses
import typing as tp
from collections import Counter

from midgpt_tpu.analysis.choreo import (
    ChoreoCheck,
    FlatGraph,
    TraceRec,
    _FLOAT_DTYPES,
    attention_regions,
    flatten_jaxpr,
    normalized_trace,
)

__all__ = [
    "ScanRec",
    "TrainChoreoReport",
    "collapse_dot_kinds",
    "find_accum_scan",
    "find_window_scan",
    "prove_window_choreography",
    "window_scans",
]


# ---------------------------------------------------------------------------
# Scan discovery (jaxpr walk)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ScanRec:
    """One ``lax.scan`` in the traced window, with its carry avals.

    ``depth`` counts enclosing scans only (call-like primitives — pjit,
    remat, custom_vjp — are transparent): the window scan sits at depth
    0, the grad-accum scan at depth 1, the layer scans at depth 2."""

    depth: int
    length: int
    carry_dtypes: tp.Tuple[str, ...]
    carry_shapes: tp.Tuple[tp.Tuple[int, ...], ...]

    @property
    def int32_scalar_carries(self) -> int:
        return sum(
            1
            for d, s in zip(self.carry_dtypes, self.carry_shapes)
            if d == "int32" and s == ()
        )

    @property
    def float_carries(self) -> int:
        return sum(1 for d in self.carry_dtypes if d in _FLOAT_DTYPES)


def _sub_jaxprs(params: tp.Mapping[str, tp.Any]):
    for p in params.values():
        cands = p if isinstance(p, (tuple, list)) else (p,)
        for c in cands:
            if hasattr(c, "eqns"):
                yield c
            elif hasattr(c, "jaxpr"):
                yield c.jaxpr


def window_scans(closed) -> tp.List[ScanRec]:
    """Every scan in the closed jaxpr, depth-annotated (scans nest,
    call-like wrappers are transparent), in traversal order."""
    out: tp.List[ScanRec] = []

    def walk(jpr, depth: int) -> None:
        for eqn in jpr.eqns:
            if eqn.primitive.name == "scan":
                nc = int(eqn.params.get("num_consts", 0))
                ncarry = int(eqn.params.get("num_carry", 0))
                carry = eqn.invars[nc : nc + ncarry]
                out.append(ScanRec(
                    depth=depth,
                    length=int(eqn.params.get("length", 0)),
                    carry_dtypes=tuple(
                        str(v.aval.dtype) for v in carry
                    ),
                    carry_shapes=tuple(
                        tuple(v.aval.shape) for v in carry
                    ),
                ))
                body = eqn.params.get("jaxpr")
                if body is not None:
                    walk(getattr(body, "jaxpr", body), depth + 1)
            else:
                for sub in _sub_jaxprs(eqn.params):
                    walk(sub, depth)

    walk(closed.jaxpr, 0)
    return out


def find_window_scan(
    scans: tp.Sequence[ScanRec], window_steps: int
) -> tp.Optional[ScanRec]:
    """The K-step window scan: a depth-0 scan of length K carrying the
    optimizer state — identified by the int32 scalar(s) in its carry
    (``state.step`` + the optax count leaves), which no data-plane scan
    carries."""
    for s in scans:
        if (
            s.depth == 0
            and s.length == window_steps
            and s.int32_scalar_carries >= 1
        ):
            return s
    return None


def find_accum_scan(
    scans: tp.Sequence[ScanRec], has_window_scan: bool
) -> tp.Optional[ScanRec]:
    """The grad-accum scan: nested directly inside the window scan body
    (depth 1 — or 0 when the window scan itself is absent), carrying the
    whole grad tree plus the f32 loss accumulator. The layer scans nest
    deeper and carry a single activation leaf, so ``float_carries >= 3``
    separates them even when ``n_layer == g_accum_iters``."""
    depth = 1 if has_window_scan else 0
    for s in scans:
        if s.depth == depth and s.float_carries >= 3:
            return s
    return None


# ---------------------------------------------------------------------------
# Trace helpers
# ---------------------------------------------------------------------------


def collapse_dot_kinds(rec: TraceRec) -> TraceRec:
    """Fold the dot sub-kinds (proj/rope/dot) into one. Inside a remat
    recompute the rope tables arrive as scan-body vars instead of
    consts, so the recomputed rotation dots classify as 'dot' where the
    forward's classified 'rope' — the op-for-op comparison must not
    care."""
    kind, ins, outs = rec
    if kind in ("proj", "rope", "dot"):
        return ("dot", ins, outs)
    return rec


def _float_leaves(tree) -> tp.List[str]:
    import jax

    return [
        str(leaf.dtype)
        for leaf in jax.tree.leaves(tree)
        if str(leaf.dtype) in _FLOAT_DTYPES
    ]


# ---------------------------------------------------------------------------
# The report
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TrainChoreoReport:
    """The train-window choreography proof: one ChoreoCheck per contract
    clause, plus the traced program names for the report."""

    checks: tp.Tuple[ChoreoCheck, ...]
    programs: tp.Tuple[str, ...]

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "ok": self.ok,
            "programs": list(self.programs),
            "checks": [
                {"name": c.name, "ok": c.ok, "detail": c.detail}
                for c in self.checks
            ],
        }


def prove_window_choreography(
    closed,
    out_tree,
    *,
    window_steps: int,
    g_accum_iters: int,
    compute_dtype: str = "bfloat16",
    param_dtype: str = "float32",
    n_param_leaves: tp.Optional[int] = None,
    remat_closed=None,
    program: str = "train_window",
) -> TrainChoreoReport:
    """Prove the train-window precision contract on a traced jaxpr.

    ``closed`` is ``jax.make_jaxpr(window)(state, xs, ys, key)`` for the
    remat="none" leg; ``out_tree`` the matching ``jax.eval_shape``
    result ``(new_state, aux)``. ``remat_closed``, when given, is the
    same trace with ``remat="full"`` and enables the remat-structure
    check (checkpointed segments recompute the forward op-for-op)."""
    checks: tp.List[ChoreoCheck] = []
    programs = [program]
    graph = flatten_jaxpr(closed)
    trace = normalized_trace(graph)

    # -- 1. matmul compute dtype: every weight-bearing dot runs on ----
    #       compute-dtype operands (the bf16 matmul clause)
    projs = [r for r in trace if r[0] == "proj"]
    bad = [
        r for r in projs
        if any(d in _FLOAT_DTYPES and d != compute_dtype for d in r[1])
    ]
    if not projs:
        checks.append(ChoreoCheck(
            "matmul-compute-dtype", False,
            "degenerate trace: no weight-bearing dots found",
        ))
    else:
        checks.append(ChoreoCheck(
            "matmul-compute-dtype", not bad,
            (
                f"{len(projs)} weight dots, all operands {compute_dtype}"
                if not bad
                else f"{len(bad)}/{len(projs)} weight dots carry non-"
                f"{compute_dtype} float operands, first: {bad[0]!r}"
            ),
        ))

    # -- 2. master params stay param_dtype, cast at the step boundary --
    new_state = out_tree[0]
    pdtypes = Counter(_float_leaves(new_state.params))
    params_ok = set(pdtypes) == {param_dtype}
    casts = [
        op for op in graph.ops
        if op.prim == "convert_element_type"
        and op.in_dtypes == (param_dtype,)
        and op.out_dtypes == (compute_dtype,)
        and "invar" in op.in_origins
    ]
    n_leaves = len(_float_leaves(new_state.params))
    want_casts = n_param_leaves if n_param_leaves is not None else n_leaves
    casts_ok = len(casts) >= want_casts
    checks.append(ChoreoCheck(
        "master-params-dtype", params_ok and casts_ok,
        (
            f"{n_leaves} param leaves {param_dtype}; "
            f"{len(casts)} {param_dtype}->{compute_dtype} input-origin "
            f"casts (cast_floating boundary, want >= {want_casts})"
            if params_ok and casts_ok
            else f"param leaf dtypes {dict(pdtypes)}, "
            f"{len(casts)} boundary casts (want >= {want_casts})"
        ),
    ))

    # -- 3. Adam moments stay param_dtype -----------------------------
    odtypes = Counter(_float_leaves(new_state.opt_state))
    moments_ok = set(odtypes) <= {param_dtype}
    checks.append(ChoreoCheck(
        "adam-moments-dtype", moments_ok,
        (
            f"{sum(odtypes.values())} optimizer float leaves, "
            f"all {param_dtype}"
            if moments_ok
            else f"optimizer float leaf dtypes {dict(odtypes)} — a "
            f"low-precision moment quietly degrades Adam's second-"
            f"moment tracking (the mu_dtype bug class)"
        ),
    ))

    # -- 4. softmax/loss accumulate in f32 -----------------------------
    exps = [r for r in trace if r[0] == "exp"]
    bad_exp = [
        r for r in exps
        if any(d != "float32" for d in r[1] + r[2])
    ]
    aux = out_tree[1]
    loss_dtype = str(aux["loss"].dtype) if "loss" in aux else "missing"
    softmax_ok = bool(exps) and not bad_exp and loss_dtype == "float32"
    checks.append(ChoreoCheck(
        "softmax-loss-f32", softmax_ok,
        (
            f"{len(exps)} exp ops all f32, loss output {loss_dtype}"
            if softmax_ok
            else f"exps={len(exps)} (bad: {bad_exp[:1]!r}), "
            f"loss output {loss_dtype}"
        ),
    ))

    # -- 5. grad-accum scan carry dtypes (deferral semantics) ----------
    scans = window_scans(closed)
    wscan = find_window_scan(scans, window_steps)
    ascan = find_accum_scan(scans, wscan is not None)
    if ascan is None:
        checks.append(ChoreoCheck(
            "grad-accum-carry", True,
            "no grad-accum scan in trace (structure gated by the "
            "dispatch budget)",
        ))
    else:
        bad_carry = [
            (d, s)
            for d, s in zip(ascan.carry_dtypes, ascan.carry_shapes)
            if d in _FLOAT_DTYPES and s != () and d != compute_dtype
        ]
        f32_scalars = sum(
            1
            for d, s in zip(ascan.carry_dtypes, ascan.carry_shapes)
            if d == "float32" and s == ()
        )
        ok = not bad_carry and f32_scalars >= 1
        checks.append(ChoreoCheck(
            "grad-accum-carry", ok,
            (
                f"found: length={ascan.length}, "
                f"{ascan.float_carries - f32_scalars} grad leaves "
                f"{compute_dtype}, {f32_scalars} f32 scalar accumulator"
                if ok
                else f"found: length={ascan.length}, non-{compute_dtype} "
                f"grad carries {bad_carry[:2]!r}, f32 scalars "
                f"{f32_scalars}"
            ),
        ))

    # -- 6. the window scan itself (carries the int32 step) ------------
    checks.append(ChoreoCheck(
        "window-scan-carry", wscan is not None,
        (
            f"window scan length={wscan.length}, "
            f"{wscan.int32_scalar_carries} int32 scalar carries "
            f"(state.step + optax counts)"
            if wscan is not None
            else f"no depth-0 scan of length {window_steps} with an "
            "int32 scalar carry — the fused window structure is gone "
            "(see the dispatch budget for the launch accounting)"
        ),
    ))

    # -- 7. remat: checkpointed segments recompute the forward ---------
    if remat_closed is not None:
        programs.append(program + "+remat")
        base_regions = attention_regions(graph)
        remat_regions = attention_regions(flatten_jaxpr(remat_closed))
        preserved = all(r in remat_regions for r in base_regions)
        extra = [r for r in remat_regions if r not in base_regions]
        fwd = Counter(
            collapse_dot_kinds(r) for r in (base_regions[0] if base_regions else ())
        )
        recompute_ok = any(
            not (fwd - Counter(collapse_dot_kinds(r) for r in e))
            for e in extra
        )
        ok = bool(base_regions) and preserved and bool(extra) and recompute_ok
        checks.append(ChoreoCheck(
            "remat-recompute", ok,
            (
                f"{len(base_regions)} forward/backward regions preserved "
                f"verbatim; {len(extra)} checkpointed segment(s), one "
                "contains the forward region op-for-op"
                if ok
                else f"base regions={len(base_regions)} "
                f"(preserved={preserved}), extra segments={len(extra)} "
                f"(forward-containing={recompute_ok}) — the remat "
                "policy recomputes something other than the forward"
            ),
        ))

    return TrainChoreoReport(
        checks=tuple(checks), programs=tuple(programs)
    )
