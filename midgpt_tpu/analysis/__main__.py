"""CLI: compile a named config's real train step and audit it.

    python -m midgpt_tpu.analysis --config openwebtext_xl --mesh 8
    python -m midgpt_tpu.analysis --lint [paths...]

The audit mode compiles the config's donated train step on a CPU virtual
mesh (``--mesh N`` devices; no TPU needed), evaluates the config's
sharding-invariant ruleset, and prints one JSON report (rules + comms
cost). Exit status: 0 = all rules pass, 1 = violations (or unwaived lint
findings), 2 = usage error.

``--override-logical-rule name=axes`` rewrites one entry of the
activation logical-rule table before compiling — ``batch=`` (empty =
unsharded) reproduces the opaque-boundary batch-gather trap, which is
how the test suite proves the audit fails loudly.

``--steps-per-dispatch K`` audits the fused K-step window program
(train.make_train_window) instead of the per-step jit — the CI gate that
catches donation-across-the-window (or host-callback) regressions on a
CPU mesh instead of a TPU run.

Platform note: env setup must precede the first jax import, which is why
this module parses args and sets ``JAX_PLATFORMS``/``XLA_FLAGS`` before
touching the harness; on hosts whose site config pins a platform the
in-process ``jax.config.update`` fallback (utils.platform_pin) applies.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import typing as tp


def _parse_override(spec: str) -> tp.Tuple[str, tp.Any]:
    if "=" not in spec:
        raise argparse.ArgumentTypeError(
            f"expected name=axes (axes may be empty or '+'-joined): {spec!r}"
        )
    name, axes = spec.split("=", 1)
    if not axes:
        return name, None
    parts = axes.split("+")
    return name, parts[0] if len(parts) == 1 else tuple(parts)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m midgpt_tpu.analysis",
        description="static HLO/sharding audit of a config's train step",
    )
    p.add_argument("--config", help="named config (midgpt_tpu.get_config)")
    p.add_argument(
        "--mesh", type=int, default=8, metavar="N",
        help="CPU virtual device count to compile on (default 8)",
    )
    p.add_argument(
        "--platform", default="cpu", choices=("cpu", "tpu"),
        help="backend to compile on (default cpu: no hardware needed)",
    )
    p.add_argument(
        "--no-shrink", action="store_true",
        help="compile the config at full size instead of audit size",
    )
    p.add_argument(
        "--steps-per-dispatch", type=int, default=None, metavar="K",
        help="audit the fused K-step window program (train.make_train_window)"
        " instead of the per-step jit; default: the config's own value",
    )
    p.add_argument(
        "--override-logical-rule", action="append", default=[],
        type=_parse_override, metavar="NAME=AXES",
        help="rewrite an activation logical-rule entry before compiling "
        "(e.g. batch= to inject the batch-gather trap)",
    )
    p.add_argument(
        "--json", metavar="PATH",
        help="also write the JSON report to PATH",
    )
    p.add_argument(
        "--full", action="store_true",
        help="include the per-collective listing in the report",
    )
    p.add_argument(
        "--lint", nargs="*", metavar="PATH",
        help="run the AST TPU-footgun lint instead of the HLO audit "
        "(default path: the midgpt_tpu package)",
    )
    p.add_argument(
        "--serving", action="store_true",
        help="audit the serving engine's three hot-path programs "
        "(midgpt_tpu.serving) instead of the train step: the fused "
        "K-step DECODE window, the suffix-prefill CHUNK, and the "
        "speculative VERIFY program — donation must stay intact (KV "
        "pool + logits alias input->output) and no host sync may hide "
        "inside any of them; each program is then compiled AGAIN on "
        "the int8 quantized weight path (midgpt_tpu.quant) and must "
        "additionally pass no-dequant-materialization (int8 streams "
        "as s8 entry params, dequant fused into each matmul); "
        "--steps-per-dispatch sets K (default 4)",
    )
    p.add_argument(
        "--serving-slots", type=int, default=4, metavar="S",
        help="decode slots for the serving audit (default 4)",
    )
    p.add_argument(
        "--serving-page-size", type=int, default=16, metavar="P",
        help="KV page size for the serving audit (default 16)",
    )
    p.add_argument(
        "--serving-spec-len", type=int, default=4, metavar="N",
        help="draft length for the speculative verify-program audit "
        "(default 4)",
    )
    p.add_argument(
        "--serving-spec-sampled", action="store_true",
        help="with --serving: audit the speculative verify program a "
        "SECOND time at temperature 0.8 / top_k 20 — the rejection-"
        "sampling acceptance path (engine.py). The donation and "
        "no-host-sync rules apply unchanged, and with --traffic the "
        "sampled program gates against the SAME verify_program budget "
        "cells: the sampled wrapper appends only the per-slot seeds "
        "and the PRNG key (control scalars) to the entry interface, "
        "so any dense draft-probability stream joining the dispatch "
        "trips the unclassified-float rule.",
    )
    p.add_argument(
        "--choreo", action="store_true",
        help="with --serving: run the arithmetic-choreography prover "
        "(analysis.choreo) over the three serving programs, bf16 AND "
        "int8 — trace each program to a jaxpr, normalize the attention "
        "/lm-head subgraphs into op-and-dtype traces, and prove verify "
        "mirrors decode op-for-op, the prefill chunk mirrors "
        "naive_attention's softmax core, and the shared arithmetic "
        "(f32 softmax/accumulation, mask-before-scale, one lm-head "
        "choreography) holds everywhere. Each cell is then proven "
        "AGAIN at temperature 0.8 / top_k 20 ('<cell>/sampled'): the "
        "verify program's row-0 sampler must mirror the decode "
        "window's categorical op-for-op, and the rejection-sampling "
        "acceptance compares / residual renormalization / target "
        "softmax must all run in f32. The machine check for the "
        "PR 4/PR 5 bf16 argmax-flip bug class, extended to the "
        "sampled acceptance rule.",
    )
    p.add_argument(
        "--traffic", action="store_true",
        help="with --serving: compute each compiled program's static "
        "HBM streams (weight/KV/logits/control entry parameters + "
        "baked-in constants + collective wire bytes, analysis.traffic) "
        "and gate them against the checked-in byte budgets "
        "(analysis.budgets) when the audit geometry matches. The "
        "accounting generalization of no-dequant-materialization: any "
        "regression that re-materializes, re-gathers or constant-folds "
        "a large buffer moves bytes and trips the gate.",
    )
    p.add_argument(
        "--print-budgets", action="store_true",
        help="with --serving --traffic: print the measured streams as "
        "a ready-to-paste analysis/budgets.py BUDGETS fragment "
        "(regeneration path after an intentional geometry change)",
    )
    p.add_argument(
        "--precision", choices=("bf16", "int8", "both"), default="both",
        help="which weight paths the serving audits compile (default "
        "both; the CI matrix runs one per job so a quant failure "
        "cannot mask a bf16 one)",
    )
    p.add_argument(
        "--kv-quant", choices=("off", "on", "both"), default="off",
        help="which KV-pool precisions the serving audits compile: "
        "'on' stores the paged pool int8 with per-(page, KV-head) po2 "
        "scales (serving.paged) — the budget cells gain a '-kv8' "
        "precision suffix and the KV stream must land at ~half its "
        "bf16 bytes; 'both' compiles each selected weight precision "
        "with the float AND the int8 pool (default off)",
    )
    p.add_argument(
        "--layer-scan", choices=("off", "on", "both"), default="off",
        help="which layer-loop modes the serving audits compile: 'on' "
        "builds the programs with the per-layer loop folded into one "
        "lax.scan (ServingEngine layer_scan knob, ROADMAP item 1); "
        "'both' compiles and audits each selected precision/kv cell "
        "both ways (the fused program streams the same bytes, so the "
        "same budget cells gate it)",
    )
    p.add_argument(
        "--prefill-sp", choices=("off", "on", "both"), default="off",
        help="which prefill-chunk sharding modes the serving audits "
        "compile: 'on' additionally audits the SEQUENCE-PARALLEL chunk "
        "program (ServingEngine prefill_sp knob — the chunk's "
        "replicated row segments shard over the 'tensor' axis) as its "
        "own 'prefill_chunk_sp' budget cells; needs --mesh-shape with "
        "tensor > 1. With --choreo the SP leg is proven per precision "
        "cell: the SP trace must equal the plain chunk trace op for op "
        "(resharding only, zero arithmetic change — the bitwise-"
        "identity gate). With --fusion the SP program's launch "
        "structure gates against its own DISPATCH_BUDGETS cells. "
        "'both' = audit off and on (default off)",
    )
    p.add_argument(
        "--fusion", action="store_true",
        help="run the SCAN-EQUIVALENCE prover (analysis.fusion) + the "
        "static dispatch/launch budgets (analysis.dispatch, "
        "budgets.DISPATCH_BUDGETS): trace the three serving programs "
        "with the layer loop unrolled AND folded, prove the unrolled "
        "layers homogeneous (the fold's legality precondition) and the "
        "fused scan body op-for-op equal to the per-layer trace, then "
        "gate launches-per-window / scan trip structure / inlined "
        "layer bodies / host transfers for BOTH layer_scan values. "
        "Tracing only — no compilation; the sixth audit family. Runs "
        "standalone (like --choreo) or inside --serving.",
    )
    p.add_argument(
        "--telemetry", choices=("off", "on"), default="off",
        help="with --serving (or standalone): run the telemetry-"
        "inertness proof (analysis.harness.prove_telemetry_inert) — "
        "two engines differing only in telemetry= must resolve to the "
        "IDENTICAL cached jitted callables (telemetry is not a program-"
        "factory parameter, so donation/no-host-sync/traffic/dispatch "
        "results proven for the untraced programs apply verbatim with "
        "tracing on) and produce bitwise-equal greedy streams, with "
        "events actually recorded. Runs on a fixed tiny model in "
        "seconds, decode-window and verify paths both.",
    )
    p.add_argument(
        "--ledger", action="store_true",
        help="run the perf-trajectory ledger (analysis.ledger) instead "
        "of an HLO audit: ingest the BENCH_r*.json trajectory (+ any "
        "--records-dir bench rows and the --suite-timing artifact), "
        "diff the --record file(s) — or, with none, the newest OK "
        "trajectory row — against it with per-key tolerance bands "
        "(static byte/floor/dispatch keys gated hard everywhere; "
        "wall-clock keys hard on hardware rows, informational on CPU), "
        "render the --report markdown trend table, and exit 1 on any "
        "hard regression. jax-free.",
    )
    p.add_argument(
        "--record", action="append", default=[], metavar="PATH",
        help="with --ledger: current bench record(s) to gate against "
        "the trajectory (bench.py / bench_serving.py JSON rows, or a "
        "BENCH_r*.json driver wrapper)",
    )
    p.add_argument(
        "--records-dir", action="append", default=[], metavar="DIR",
        help="with --ledger: directory of *.json bench records to "
        "ingest into the reference trajectory (file order, after the "
        "BENCH rounds)",
    )
    p.add_argument(
        "--trajectory", default=None, metavar="DIR",
        help="with --ledger: directory holding BENCH_r*.json "
        "(default: the repo root)",
    )
    p.add_argument(
        "--suite-timing", default=None, metavar="PATH",
        help="with --ledger: the conftest suite-timing JSON artifact "
        "(SUITE_TIMING_OUT) — tier-1 wall time joins the trend table",
    )
    p.add_argument(
        "--report", default=None, metavar="PATH",
        help="with --ledger: write the markdown trend report here",
    )
    p.add_argument(
        "--hardware", choices=("auto", "on", "off"), default="auto",
        help="with --ledger: gate wall-clock keys hard (on), "
        "informationally (off), or by the record's own device field "
        "(auto, the default)",
    )
    p.add_argument(
        "--train-audit", action="store_true",
        help="run the TRAIN-side verification suite (the seventh audit "
        "family) instead of the per-step HLO audit: trace the fused "
        "K-step train window through the trainer's own "
        "get_train_window cache and PROVE the mixed-precision "
        "choreography (bf16 matmul operands, f32 master params + Adam "
        "moments, f32 loss/softmax, compute-dtype grad-accum carry, "
        "remat recompute structure — analysis.train_choreo); compile "
        "the window and gate its ICI/DCN collective wire bytes against "
        "the checked-in per-geometry cells (budgets.TRAIN_BUDGETS); "
        "and gate the launch structure (one launch per window, "
        "grad-accum scan of trip G, zero host transfers, 100%% donation "
        "aliasing — budgets.TRAIN_DISPATCH_BUDGETS). Runs K=1 AND K=4 "
        "by default (--train-window-steps); the CI train-audit job "
        "fans the three --train-geometry values out as a matrix.",
    )
    p.add_argument(
        "--train-geometry", default="fsdp", metavar="G",
        choices=("fsdp", "tp_fsdp", "dcn2"),
        help="with --train-audit: the mesh geometry cell to audit "
        "(budgets.TRAIN_AUDIT_GEOMETRIES; all need --mesh 8): 'fsdp' = "
        "8-way FSDP, 'tp_fsdp' = tensor=2 x fsdp=4, 'dcn2' = 2 slices "
        "over DCN with fsdp=4 inside each (default fsdp)",
    )
    p.add_argument(
        "--train-window-steps", default="1,4", metavar="K[,K...]",
        help="with --train-audit: comma-separated fused-window lengths "
        "to audit (default '1,4' — the budget cells pin the two equal, "
        "which is itself the window-scan invariant)",
    )
    p.add_argument(
        "--mesh-shape", default=None, metavar="SPEC",
        help="serving-audit mesh, e.g. 'tp=2' or 'tp=2,replica=2' "
        "(keys: tp/tensor, dp/replica, fsdp): compile/audit the three "
        "serving programs TP-SHARDED — KV-head-sharded pool, "
        "column/row-parallel weights, vocab-sharded logits — adding "
        "the no-batch-allgather-in-page-gather rule; needs --mesh >= "
        "the axis product. --serving only.",
    )
    return p


def _run_lint(paths: tp.List[str]) -> int:
    from midgpt_tpu.analysis.pylint_pass import lint_paths, unwaived

    if not paths:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    findings = lint_paths(paths)
    for f in findings:
        print(f)
    bad = unwaived(findings)
    n_waived = len(findings) - len(bad)
    print(
        f"shardlint: {len(bad)} finding(s), {n_waived} waived",
        file=sys.stderr,
    )
    return 1 if bad else 0


def _ensure_devices(platform: str, n: int) -> None:
    """Pin the backend + device count; must run before jax backend init.

    When jax is already initialized in-process (tests), just verify the
    existing device pool is big enough for the requested mesh.
    """
    already = "jax" in sys.modules
    if platform == "cpu" and not already:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    from midgpt_tpu.utils.platform_pin import apply_platform

    apply_platform(platform)
    import jax

    jax.config.update("jax_threefry_partitionable", True)  # train.py parity
    have = jax.device_count()
    if have < n:
        raise SystemExit(
            f"requested --mesh {n} but only {have} device(s) are visible "
            "(is jax already initialized with a smaller pool?)"
        )


def _precisions(args) -> tp.Tuple[str, ...]:
    return {
        "bf16": ("bf16",), "int8": ("int8",), "both": ("bf16", "int8"),
    }[args.precision]


def _kv_modes(args) -> tp.Tuple[bool, ...]:
    return {
        "off": (False,), "on": (True,), "both": (False, True),
    }[args.kv_quant]


def _layer_scan_modes(args) -> tp.Tuple[str, ...]:
    return {
        "off": ("off",), "on": ("on",), "both": ("off", "on"),
    }[args.layer_scan]


def _sp_on(args) -> bool:
    return getattr(args, "prefill_sp", "off") in ("on", "both")


def _run_fusion(args, cfg):
    """The scan-equivalence prover + dispatch budgets (the sixth audit
    family): prove every selected precision x kv x backend cell, then
    gate the static launch structure for BOTH layer_scan values.
    Returns ``(section_dict, ok, violation_strings)``."""
    from midgpt_tpu.analysis.budgets import precision_key
    from midgpt_tpu.analysis.harness import (
        audit_serving_dispatch,
        prove_scan_equivalence,
    )

    out: tp.Dict[str, tp.Any] = {"equivalence": {}, "dispatch": {}}
    ok = True
    violations: tp.List[str] = []
    for precision in _precisions(args):
        for kvq in _kv_modes(args):
            for backend in ("xla", "pallas"):
                rep = prove_scan_equivalence(
                    cfg, quant=(precision == "int8"), kv_quant=kvq,
                    paged_kernel=backend,
                )
                tag = f"{precision_key(precision, kvq)}/{backend}"
                out["equivalence"][tag] = rep.to_dict()
                ok = ok and rep.ok
                violations.extend(
                    f"[fusion/{tag}] {c.name}: {c.detail}"
                    for c in rep.checks
                    if not c.ok
                )
    # launch budgets: structure is precision/backend-invariant (dtypes
    # change, scan nesting does not) — one trace per layer_scan value;
    # with --prefill-sp the sequence-parallel chunk rides along as its
    # own prefill_chunk_sp cells (resharding must not change launches)
    for ls in ("off", "on"):
        reports, bad = audit_serving_dispatch(
            cfg, layer_scan=ls,
            prefill_sp="on" if _sp_on(args) else "off",
        )
        out["dispatch"][ls] = {
            name: rep.to_dict() for name, rep in reports.items()
        }
        ok = ok and not bad
        violations.extend(f"[dispatch/ls={ls}] {v}" for v in bad)
    return out, ok, violations


def _run_fusion_only(args, cfg) -> int:
    section, ok, violations = _run_fusion(args, cfg)
    out: tp.Dict[str, tp.Any] = {
        "config": args.config, "mode": "scan-equivalence",
        **section, "ok": ok,
    }
    return _emit_report(out, ok, violations, args)


def _run_choreo(args, cfg):
    """Run the choreography prover for the selected precisions; returns
    ``(per_precision_dicts, ok, violation_strings)`` — shared by the
    standalone ``--choreo`` mode and the ``--serving --choreo`` path."""
    from midgpt_tpu.analysis.harness import prove_serving_choreography

    from midgpt_tpu.analysis.budgets import precision_key

    out: tp.Dict[str, tp.Any] = {}
    ok = True
    violations: tp.List[str] = []
    for precision in _precisions(args):
        for kvq in _kv_modes(args):
            # both paged-attention backends are proven per cell: the
            # prover only TRACES (no compilation), so the Pallas kernel
            # contract rides along at ~zero cost — the kernel body's
            # softmax signature must equal the decode window's
            for backend in ("xla", "pallas"):
                cell = f"{precision_key(precision, kvq)}/{backend}"
                # each cell is proven twice: greedy (the PR 4/PR 5
                # argmax choreography) and sampled (temperature > 0:
                # the verify row-0 sampler must mirror the decode
                # window's categorical, and the rejection-sampling
                # acceptance/residual/target-softmax arithmetic must
                # run in f32 — choreo.prove_sampled_choreography)
                for tag, kw in (
                    (cell, {}),
                    (f"{cell}/sampled",
                     dict(temperature=0.8, top_k=20)),
                ):
                    rep = prove_serving_choreography(
                        cfg, quant=(precision == "int8"), kv_quant=kvq,
                        paged_kernel=backend, **kw
                    )
                    out[tag] = rep.to_dict()
                    ok = ok and rep.ok
                    violations.extend(
                        f"[choreo/{tag}] {c.name}: {c.detail}"
                        for c in rep.checks
                        if not c.ok
                    )
            if _sp_on(args):
                # the sequence-parallel prefill leg: the SP chunk trace
                # must equal the plain chunk trace op for op (resharding
                # only — harness.prove_sp_prefill_choreography). Traced
                # on its own tensor=2 mesh; backend-independent (the SP
                # reshard wraps the whole block, not the kernel)
                from midgpt_tpu.analysis.harness import (
                    prove_sp_prefill_choreography,
                )

                tag = f"{precision_key(precision, kvq)}/sp"
                rep = prove_sp_prefill_choreography(
                    cfg, quant=(precision == "int8"), kv_quant=kvq,
                )
                out[tag] = rep.to_dict()
                ok = ok and rep.ok
                violations.extend(
                    f"[choreo/{tag}] {c.name}: {c.detail}"
                    for c in rep.checks
                    if not c.ok
                )
    return out, ok, violations


def _emit_report(
    out: tp.Dict[str, tp.Any], ok: bool, violations: tp.List[str], args
) -> int:
    """Shared report epilogue for the tracing-only prover modes: print
    the JSON report (+ --json file), the VIOLATION lines, and map ok to
    the exit code."""
    text = json.dumps(out, indent=2)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    for v in violations:
        print(f"VIOLATION {v}", file=sys.stderr)
    return 0 if ok else 1


def _run_choreo_only(args, cfg) -> int:
    sections, ok, violations = _run_choreo(args, cfg)
    out: tp.Dict[str, tp.Any] = {
        "config": args.config, "mode": "serving-choreography",
        **sections, "ok": ok,
    }
    return _emit_report(out, ok, violations, args)


def _run_telemetry() -> tp.Tuple[tp.Dict[str, tp.Any], bool, tp.List[str]]:
    """The telemetry-inertness proof (--telemetry on): both dispatch
    shapes — the fused decode window (with chunked prefill, so the
    prefill-bucket programs are covered too) and the speculative verify
    program. Tiny fixed model, seconds, no compilation of the named
    config (the proof is an engine-logic property — see
    harness.prove_telemetry_inert)."""
    from midgpt_tpu.analysis.harness import prove_telemetry_inert

    sections: tp.Dict[str, tp.Any] = {}
    violations: tp.List[str] = []
    for name, kw in (
        ("decode_window_chunked", dict(prefill_chunk=4, speculate=0)),
        ("verify_spec4", dict(prefill_chunk=None, speculate=4)),
    ):
        try:
            sections[name] = prove_telemetry_inert(**kw)
        except AssertionError as e:
            sections[name] = {"ok": False, "error": str(e)}
            violations.append(f"telemetry-inert/{name}: {e}")
    return sections, not violations, violations


def _run_serving(args, cfg, mesh_shape) -> int:
    """The --serving audits: compile the engine's three hot-path
    programs (decode window / prefill chunk / speculative verify) on
    one shared geometry per selected precision, evaluate the serving
    ruleset on each, and optionally (a) gate the static HBM streams
    against the checked-in byte budgets (--traffic) and (b) run the
    arithmetic-choreography prover (--choreo)."""
    from midgpt_tpu.analysis.harness import (
        audit_decode_window,
        audit_prefill_chunk,
        audit_verify_program,
    )

    k = args.steps_per_dispatch or 4
    precisions = _precisions(args)
    # the chunked-prefill steady state interleaves a prefill chunk
    # between decode windows, and with speculation on every decode
    # dispatch IS a verify dispatch: all three programs are audited on
    # one shared geometry (_serving_audit_setup) per precision — the
    # int8 leg additionally gates no-dequant-materialization (s8 entry
    # params, dequant fused into each matmul)
    program_specs = (
        ("decode_window", audit_decode_window, dict(
            slots=args.serving_slots, window=k,
            page_size=args.serving_page_size,
        ), k),
        ("prefill_chunk", audit_prefill_chunk, dict(
            page_size=args.serving_page_size,
        ), 1),
        ("verify_program", audit_verify_program, dict(
            slots=args.serving_slots, spec_len=args.serving_spec_len,
            page_size=args.serving_page_size,
        ), 1),
    )
    if _sp_on(args):
        # the sequence-parallel prefill leg: its own budget cells (the
        # SP combine is real wire traffic — comms_max pins it) next to
        # the plain chunk's, same donation/no-host-sync/no-f64 rules
        if not (mesh_shape and mesh_shape.get("tensor", 1) > 1):
            print(
                "error: --prefill-sp needs --mesh-shape with tensor > 1 "
                "(single-chip SP is a no-op)",
                file=sys.stderr,
            )
            return 2
        program_specs = program_specs + (
            ("prefill_chunk_sp", audit_prefill_chunk, dict(
                page_size=args.serving_page_size, prefill_sp="on",
            ), 1),
        )
    if args.serving_spec_sampled:
        # the rejection-sampling verify leg: same program geometry at
        # temperature > 0. It gates against the SAME verify_program
        # budget cells — the sampled wrapper appends only the per-slot
        # seeds and the PRNG key (control scalars), so the weight/KV/
        # logits streams must land byte-identical to the greedy audit
        # and any dense draft-probability tensor joining the entry
        # interface trips the unclassified-float rule
        program_specs = program_specs + (
            ("verify_program_sampled", audit_verify_program, dict(
                slots=args.serving_slots, spec_len=args.serving_spec_len,
                page_size=args.serving_page_size,
                temperature=0.8, top_k=20,
            ), 1),
        )

    # --traffic budget gating applies only at the geometry the budgets
    # were measured at (analysis/budgets.AUDIT_GEOMETRY)
    budget_geom = None
    if args.traffic:
        from midgpt_tpu.analysis.budgets import (
            AUDIT_GEOMETRY,
            geometry_key,
        )

        matches = (
            args.config == AUDIT_GEOMETRY["config"]
            and not args.no_shrink
            and args.serving_slots == AUDIT_GEOMETRY["slots"]
            and k == AUDIT_GEOMETRY["window"]
            and args.serving_page_size == AUDIT_GEOMETRY["page_size"]
            and args.serving_spec_len == AUDIT_GEOMETRY["spec_len"]
        )
        budget_geom = geometry_key(mesh_shape) if matches else None

    ok = True
    violations: tp.List[str] = []
    sections: tp.Dict[str, tp.Any] = {}
    budget_fragment: tp.Dict[tp.Tuple[str, str], tp.Any] = {}
    from midgpt_tpu.analysis.budgets import precision_key

    cells = [
        (precision, kvq, ls)
        for precision in precisions
        for kvq in _kv_modes(args)
        for ls in _layer_scan_modes(args)
    ]
    for precision, kvq, ls in cells:
        pkey = precision_key(precision, kvq)
        for name, fn, kw, steps in program_specs:
            res = fn(
                cfg, shrink=not args.no_shrink,
                quant=(precision == "int8"), kv_quant=kvq,
                layer_scan=ls, mesh_shape=mesh_shape,
                traffic=args.traffic, **kw
            )
            analysis, report = res[0], res[1]
            ok = ok and report.ok
            violations.extend(str(v) for v in report.violations)
            section = {
                "donated_leaves": analysis.donated_leaves,
                "aliased_buffers": len(
                    {e.param_number for e in analysis.aliases}
                ),
                "rules": report.to_dict()["rules"],
            }
            if args.traffic:
                from midgpt_tpu.analysis.budgets import (
                    budget_for,
                    check_budget,
                )

                traf = res[2]
                section["traffic"] = traf.to_dict()
                # --print-budgets regeneration fragment: record the
                # FIRST layer_scan leg only (the unrolled one under
                # 'both' — the convention the checked-in cells were
                # measured with); letting the fused leg overwrite it
                # would regenerate cells from fused numbers exactly
                # when the two legs diverge. The sampled verify leg is
                # excluded: it gates against (and must match) the
                # greedy verify_program cells, it does not get its own
                if (
                    ls == _layer_scan_modes(args)[0]
                    and name != "verify_program_sampled"
                ):
                    budget_fragment[(name, pkey)] = traf
                budget_name = (
                    "verify_program"
                    if name == "verify_program_sampled"
                    else name
                )
                budget = (
                    budget_for(budget_name, pkey, budget_geom)
                    if budget_geom
                    else None
                )
                if budget is not None:
                    bad = check_budget(traf, budget)
                    section["budget"] = {
                        "geometry": budget_geom,
                        "ok": not bad,
                        "violations": bad,
                    }
                    ok = ok and not bad
                    violations.extend(bad)
                else:
                    section["budget"] = {
                        "geometry": budget_geom,
                        "ok": None,
                        "violations": [],
                    }
            # the fused program streams the same bytes through the same
            # entry interface, so both layer_scan legs gate against the
            # same budget cells; the section key records which leg
            sections[f"{name}/{pkey}" + ("/scan" if ls == "on" else "")] = (
                section
            )

    choreo_out = None
    if args.choreo:
        choreo_out, choreo_ok, choreo_violations = _run_choreo(args, cfg)
        ok = ok and choreo_ok
        violations.extend(choreo_violations)
    fusion_out = None
    if args.fusion:
        fusion_out, fusion_ok, fusion_violations = _run_fusion(args, cfg)
        ok = ok and fusion_ok
        violations.extend(fusion_violations)
    telemetry_out = None
    if args.telemetry == "on":
        telemetry_out, tele_ok, tele_violations = _run_telemetry()
        ok = ok and tele_ok
        violations.extend(tele_violations)

    out = {
        "config": args.config,
        "mode": "serving-audit",
        "precisions": list(precisions),
        "kv_quant": args.kv_quant,
        "layer_scan": args.layer_scan,
        "ok": ok,
        "geometry": {
            "slots": args.serving_slots,
            "steps_per_dispatch": k,
            "page_size": args.serving_page_size,
            "spec_len": args.serving_spec_len,
            "spec_sampled": bool(args.serving_spec_sampled),
            "mesh_shape": mesh_shape,
        },
        "programs": sections,
    }
    if choreo_out is not None:
        out["choreography"] = choreo_out
    if fusion_out is not None:
        out["fusion"] = fusion_out
    if telemetry_out is not None:
        out["telemetry"] = telemetry_out
    text = json.dumps(out, indent=2)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    if args.print_budgets and args.traffic:
        # the fragment must carry EVERY gate key: a pasted budget
        # missing constants_max/comms_max would silently disable the
        # constant-folding and regather trips check_budget keys on
        geom = budget_geom
        if geom is None:
            from midgpt_tpu.analysis.budgets import geometry_key

            geom = geometry_key(mesh_shape)
            print(
                "# WARNING: non-default audit geometry — update "
                "AUDIT_GEOMETRY alongside the budgets",
                file=sys.stderr,
            )
        print("# analysis/budgets.py fragment (measured):", file=sys.stderr)
        for (name, precision), traf in budget_fragment.items():
            entry = {
                "weights": traf.streams["weights"],
                "kv": traf.streams["kv"],
                "logits": traf.streams["logits"],
                # headroom over the measured baseline: constants are
                # geometry-constant rope tables (any baked weight jumps
                # past 3x), comms scales with the audited payloads
                "constants_max": 3 * max(
                    traf.streams["constants"], 4096
                ),
            }
            if traf.comms_bytes:
                entry["comms_max"] = traf.comms_bytes * 3 // 2
            print(
                f"    ({name!r}, {precision!r}, {geom!r}): "
                + json.dumps(entry),
                file=sys.stderr,
            )
    if not ok:
        for v in violations:
            print(f"VIOLATION {v}", file=sys.stderr)
        return 1
    return 0


def _run_train_audit(args, cfg) -> int:
    """The --train-audit mode: prover + traffic cells + dispatch gate
    for one mesh geometry of the fused train window (see the flag help
    for the contract). Budget gating only applies when the audited
    config/window match what the cells were measured at
    (budgets.TRAIN_AUDIT_GEOMETRY) — like the serving budget_geom
    guard, a non-matching invocation still runs the prover but reports
    the missing cells as violations."""
    from midgpt_tpu.analysis.budgets import TRAIN_AUDIT_GEOMETRY
    from midgpt_tpu.analysis.harness import audit_train

    try:
        window_steps = tuple(
            int(s) for s in args.train_window_steps.split(",") if s.strip()
        )
    except ValueError:
        print(
            f"error: bad --train-window-steps {args.train_window_steps!r} "
            "(want comma-separated ints)",
            file=sys.stderr,
        )
        return 2
    if not window_steps or any(k < 1 for k in window_steps):
        print(
            "error: --train-window-steps needs at least one K >= 1",
            file=sys.stderr,
        )
        return 2
    if args.config != TRAIN_AUDIT_GEOMETRY["config"]:
        print(
            f"# note: train budget cells were measured on "
            f"{TRAIN_AUDIT_GEOMETRY['config']!r}; auditing "
            f"{args.config!r} will report missing cells",
            file=sys.stderr,
        )
    report = audit_train(cfg, args.train_geometry, window_steps)
    out = {
        "config": args.config,
        "mode": "train-audit",
        "geometry": args.train_geometry,
        "window_steps": list(window_steps),
        **{k: v for k, v in report.items() if k != "geometry"},
    }
    if args.print_budgets:
        print(
            "# analysis/budgets.py TRAIN_BUDGETS fragment (measured):",
            file=sys.stderr,
        )
        for cell in report["cells"]:
            traf = cell["traffic"]
            entry = {
                "ici_bytes": traf["ici_bytes"],
                "dcn_bytes": traf["dcn_bytes"],
                "by_axis": traf["by_axis"],
            }
            print(
                f"    ({args.train_geometry!r}, "
                f"{cell['window_steps']}): " + json.dumps(entry),
                file=sys.stderr,
            )
    return _emit_report(out, report["ok"], report["violations"], args)


def main(argv: tp.Optional[tp.Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.lint is not None:
        return _run_lint(list(args.lint))
    if args.ledger:
        # jax-free: the ledger reads JSON records only — no devices, no
        # config compile (it must run on any CI box in seconds)
        from midgpt_tpu.analysis.ledger import run_ledger

        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        return run_ledger(
            trajectory_root=args.trajectory or repo_root,
            records=args.record,
            record_dirs=args.records_dir,
            suite_timing=args.suite_timing,
            report_path=args.report,
            hardware={"auto": None, "on": True, "off": False}[
                args.hardware
            ],
        )
    if not args.config:
        build_parser().print_usage(sys.stderr)
        print("error: --config (or --lint) is required", file=sys.stderr)
        return 2

    _ensure_devices(args.platform, args.mesh)

    from midgpt_tpu.analysis.harness import audit_config
    from midgpt_tpu.config import get_config

    try:
        cfg = get_config(args.config)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.steps_per_dispatch is not None:
        if args.steps_per_dispatch < 1:
            print(
                f"error: --steps-per-dispatch must be >= 1, got "
                f"{args.steps_per_dispatch}",
                file=sys.stderr,
            )
            return 2
        import dataclasses

        cfg = dataclasses.replace(
            cfg, steps_per_dispatch=args.steps_per_dispatch
        )

    mesh_shape = None
    if args.mesh_shape:
        from midgpt_tpu.analysis.harness import parse_mesh_shape

        if not args.serving:
            print(
                "error: --mesh-shape applies to the --serving audits",
                file=sys.stderr,
            )
            return 2
        try:
            mesh_shape = parse_mesh_shape(args.mesh_shape)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if args.train_audit:
        return _run_train_audit(args, cfg)
    if args.serving:
        return _run_serving(args, cfg, mesh_shape)
    if args.choreo and args.fusion:
        # both tracing-only provers in one invocation, one combined
        # report (running only one of them here would silently drop the
        # other's gate)
        c_sections, c_ok, c_viol = _run_choreo(args, cfg)
        f_sections, f_ok, f_viol = _run_fusion(args, cfg)
        ok = c_ok and f_ok
        out = {
            "config": args.config,
            "mode": "serving-choreography+scan-equivalence",
            "choreography": c_sections,
            "fusion": f_sections,
            "ok": ok,
        }
        return _emit_report(out, ok, c_viol + f_viol, args)
    if args.choreo:
        # standalone prover: no compilation, jaxpr tracing only — the
        # fast CI gate (--serving --choreo runs it next to the audits)
        return _run_choreo_only(args, cfg)
    if args.fusion:
        # standalone scan-equivalence prover + dispatch budgets: also
        # tracing only — the serving-choreo CI job's sixth-family gate
        return _run_fusion_only(args, cfg)
    if args.telemetry == "on":
        # standalone telemetry-inertness proof (tiny fixed model — the
        # named config only labels the report)
        sections, ok, viol = _run_telemetry()
        out = {
            "config": args.config, "mode": "telemetry-inertness",
            "telemetry": sections, "ok": ok,
        }
        return _emit_report(out, ok, viol, args)

    overrides = dict(args.override_logical_rule) or None
    if overrides:
        # validate before compiling so a typo'd axis name is a usage
        # error (exit 2), not a traceback misread as a rule violation
        from midgpt_tpu.parallel.sharding import DEFAULT_LOGICAL_RULES

        unknown = set(overrides) - set(DEFAULT_LOGICAL_RULES)
        if unknown:
            print(
                f"error: unknown logical axes {sorted(unknown)} "
                f"(known: {sorted(DEFAULT_LOGICAL_RULES)})",
                file=sys.stderr,
            )
            return 2
    analysis, report, cost = audit_config(
        cfg, shrink=not args.no_shrink, logical_overrides=overrides
    )
    if not args.full:
        cost = {k: v for k, v in cost.items() if k != "collectives"}
    out = {
        "config": args.config,
        "ok": report.ok,
        "mesh": {
            "axis_names": list(analysis.mesh.axis_names),
            "axis_sizes": list(analysis.mesh.axis_sizes),
            "num_slices": analysis.mesh.num_slices,
        },
        "geometry": {
            "global_batch": analysis.global_batch,
            "block": analysis.block,
            "steps_per_dispatch": cfg.steps_per_dispatch,
            "donated_leaves": analysis.donated_leaves,
            "aliased_buffers": len({e.param_number for e in analysis.aliases}),
        },
        "rules": report.to_dict()["rules"],
        "cost": cost,
    }
    text = json.dumps(out, indent=2)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    if not report.ok:
        for v in report.violations:
            print(f"VIOLATION {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
