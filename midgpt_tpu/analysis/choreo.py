"""Arithmetic-choreography prover for the serving programs.

The two most expensive serving bugs this repo has shipped were DTYPE
CHOREOGRAPHY drift between attention paths that must agree at greedy-
argmax granularity:

- PR 4: a chunk-prefill variant that upcast the pool K/V to f32 before
  the score einsum (and kept f32 probs through the PV contraction)
  drifted ~2 bf16 ulps from the fixed-batch sampler and flipped
  near-tied greedy argmaxes on a real checkpoint;
- PR 5: the first cut of the speculative VERIFY program reused the
  prefill choreography instead of the decode window's, flipping
  near-tied acceptance argmaxes the same way.

Both were only caught by the ``sample.py --serve`` hardware drive. The
contracts live as prose in ``models/gpt.py`` docstrings ("the dtype
choreography deliberately MIRRORS decode_paged_at op for op"); this
module turns them into a machine check: trace each serving program to a
jaxpr, slice out the per-layer attention subgraph and the lm-head
projection, normalize them into an op-and-dtype trace (primitive,
operand dtypes, cast positions, accumulation dtype, softmax arithmetic
order — shapes deliberately dropped, the programs differ in T), and
assert:

1. ``decode == verify`` — the decode window and the verify program
   produce IDENTICAL normalized attention traces, op for op (the PR 5
   contract: acceptance must reproduce the decode path's argmaxes, so
   it must share the decode path's arithmetic).
2. ``prefill == naive`` — the prefill chunk's softmax-core signature
   (operand dtypes at the score contract, mask-add position, scale op,
   softmax dtype, the probs dtype entering the PV contraction) equals
   ``ops.attention.naive_attention``'s (the PR 4 contract: with an
   empty pool part the chunk must be bitwise what the monolithic
   ``model.hidden`` prefill computes).
3. shared arithmetic — all three programs agree on the invariants they
   DO share: scores accumulate in f32, the additive mask lands before
   the softmax scale, softmax runs in f32 with one joint exp per layer,
   and the lm-head projection choreography (operand dtypes + quant
   epilogue) is identical everywhere.

The deliberate asymmetry between (1) and (2) is the point: decode and
prefill legitimately differ (f32 probs through PV vs probs rounded to
the value dtype; ``/ sqrt(c)`` vs ``* (1/sqrt(c))``), which is exactly
why a verify program that drifts toward the prefill flavor is a bug the
full-sequence check catches.

At ``temperature > 0`` the same discipline extends past the attention
stack into the SAMPLER (:func:`extract_sampler_choreography` /
:func:`prove_sampled_choreography`): the verify program's row-0
categorical (softmax -> temperature -> key-derived gumbel argmax) must
mirror the decode window's op for op, the rejection-sampling acceptance
compare ``u * q(t) <= p(t)`` must run in f32 (a bf16 compare flips
near-tie accept/reject decisions the same way the PR 5 bf16 argmax
flipped near-tie acceptance), and the residual renormalization
``max(p - q, 0)`` with its target softmax must run in f32.

Everything here operates on jaxprs (no compilation, no execution) — a
full three-program proof runs in seconds on CPU. jax is imported at
module level; the CLI imports this module only after platform setup
(same discipline as :mod:`~midgpt_tpu.analysis.harness`).
"""

from __future__ import annotations

import dataclasses
import typing as tp

import jax

# ---------------------------------------------------------------------------
# jaxpr flattening with origin tracking
# ---------------------------------------------------------------------------

# ops that forward their (first) operand's ORIGIN unchanged: moving or
# re-viewing a buffer does not change what the value fundamentally is,
# so a model weight sliced out of the stacked [L, ...] leaf and cast to
# the compute dtype still traces back to its entry parameter
_PASSTHRU = frozenset({
    "slice", "squeeze", "reshape", "transpose", "broadcast_in_dim",
    "device_put", "copy", "convert_element_type", "expand_dims",
    "sharding_constraint",
})

# sub-jaxpr-carrying primitives the flattener recurses into; params are
# scanned generically for ClosedJaxpr/Jaxpr values so new call prims
# (or renamed ones across jax versions) degrade to unaligned recursion
# instead of silently dropping a body
_ALIGNED_CALLS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "custom_jvp_call",
    "custom_vjp_call", "remat", "checkpoint", "scan", "while",
})

_FLOAT_DTYPES = frozenset({"bfloat16", "float16", "float32", "float64"})

# the arithmetic alphabet a normalized trace keeps; everything else
# (layout ops, comparisons, integer plumbing, scatters/gathers) is
# movement, not arithmetic, and differs legitimately between programs
_ARITH = frozenset({
    "dot_general", "convert_element_type", "add", "sub", "mul", "div",
    "exp", "exp2", "log", "reduce_max", "reduce_sum", "max", "min",
    "neg", "rsqrt", "sqrt", "square", "integer_pow", "tanh", "erf",
    "logistic", "pow",
})


@dataclasses.dataclass(frozen=True)
class Op:
    """One flattened jaxpr equation, with global value ids and origins."""

    idx: int
    prim: str
    in_dtypes: tp.Tuple[str, ...]
    out_dtypes: tp.Tuple[str, ...]
    in_ids: tp.Tuple[int, ...]  # -1 for literals
    out_ids: tp.Tuple[int, ...]
    # per-input provenance: 'invar' (traces to a program entry through
    # pass-through ops only), 'const', 'lit', or 'var' (computed)
    in_origins: tp.Tuple[str, ...]
    # static shape metadata for the few prims where a positional fact
    # IS the contract: a ``slice``'s start_indices (so the banded-
    # accumulation-order extractor can read which probability columns a
    # PV partial consumed). None for everything else.
    meta: tp.Optional[tp.Tuple[int, ...]] = None


class FlatGraph:
    """Flattened jaxpr: linear op list + producer/consumer maps.
    ``kernels`` collects the body jaxprs of any Pallas kernel calls the
    walk encountered (each appears in ``ops`` as one ``paged_kernel``
    CONTRACT NODE rather than as inlined internals — the kernel body is
    proven separately, see :func:`extract_choreography`)."""

    def __init__(self, ops: tp.List[Op],
                 kernels: tp.Optional[tp.List[tp.Any]] = None):
        self.ops = ops
        self.kernels = kernels or []
        self.producer: tp.Dict[int, Op] = {}
        self.consumers: tp.Dict[int, tp.List[Op]] = {}
        for op in ops:
            for vid in op.out_ids:
                self.producer[vid] = op
            for vid in op.in_ids:
                if vid >= 0:
                    self.consumers.setdefault(vid, []).append(op)


def flatten_jaxpr(closed) -> FlatGraph:
    """Flatten a (Closed)Jaxpr into a single linear op list, recursing
    into pjit/scan/while/custom_jvp bodies (each body once — choreography
    is per-iteration-identical by construction of a scan). Value ids are
    global; sub-jaxpr invars inherit the caller operands' ids/origins, so
    an entry parameter keeps its 'invar' origin through any call depth."""
    jaxpr = getattr(closed, "jaxpr", closed)
    ops: tp.List[Op] = []
    next_id = [0]
    # var -> (vid, origin)
    env: tp.Dict[tp.Any, tp.Tuple[int, str]] = {}

    def fresh(origin: str) -> tp.Tuple[int, str]:
        vid = next_id[0]
        next_id[0] += 1
        return (vid, origin)

    for i, v in enumerate(jaxpr.invars):
        env[v] = fresh("invar")
    for v in jaxpr.constvars:
        env[v] = fresh("const")

    def read(env_, atom) -> tp.Tuple[int, str]:
        if hasattr(atom, "val"):  # Literal
            return (-1, "lit")
        if atom not in env_:
            env_[atom] = fresh("var")
        return env_[atom]

    kernels: tp.List[tp.Any] = []

    def walk(jpr, env_) -> None:
        for eqn in jpr.eqns:
            if eqn.primitive.name == "pallas_call":
                # a Pallas kernel is ONE contract node in the outer
                # trace: (operand dtypes, output dtypes). Its body is
                # collected for the separate kernel-choreography proof
                # rather than inlined — the internals are a different
                # alphabet (refs, DMAs) and the contract the outer
                # comparison needs is "same operands in, same dtype
                # arithmetic inside, same dtype out".
                kernels.append(eqn.params.get("jaxpr"))
                ins = [read(env_, a) for a in eqn.invars]
                in_d = tuple(
                    str(getattr(a.aval, "dtype", "?")) for a in eqn.invars
                )
                out_d = tuple(
                    str(getattr(v.aval, "dtype", "?"))
                    for v in eqn.outvars
                )
                rec_outs = []
                for ov in eqn.outvars:
                    vid, _ = fresh("var")
                    env_[ov] = (vid, "var")
                    rec_outs.append(vid)
                ops.append(Op(
                    idx=len(ops),
                    prim="paged_kernel",
                    in_dtypes=in_d,
                    out_dtypes=out_d,
                    in_ids=tuple(vid for vid, _ in ins),
                    out_ids=tuple(rec_outs),
                    in_origins=tuple(origin for _, origin in ins),
                ))
                continue
            # include jaxprs nested inside tuple/list params too:
            # lax.cond's 'branches' is a plain TUPLE of ClosedJaxprs,
            # which a bare hasattr over params.values() would skip —
            # arithmetic inside a cond branch would then vanish from
            # the normalized trace (a vacuous pass, the same blind spot
            # class the extraction-degeneracy guard exists for). Tuple
            # params recurse UNALIGNED (fresh origins), the safe
            # degradation the comment above describes.
            subs = [
                c
                for p in eqn.params.values()
                for c in (p if isinstance(p, (tuple, list)) else (p,))
                if hasattr(c, "eqns") or hasattr(c, "jaxpr")
            ]
            nested = [getattr(s, "jaxpr", s) for s in subs]
            if nested:
                aligned = (
                    eqn.primitive.name in _ALIGNED_CALLS
                    and len(nested) == 1
                    and len(nested[0].invars) == len(eqn.invars)
                )
                for sub in nested:
                    senv: tp.Dict[tp.Any, tp.Tuple[int, str]] = {}
                    if aligned:
                        for iv, oa in zip(sub.invars, eqn.invars):
                            senv[iv] = read(env_, oa)
                    else:
                        for iv in sub.invars:
                            senv[iv] = fresh("var")
                    for cv in sub.constvars:
                        senv[cv] = fresh("const")
                    walk(sub, senv)
                    if aligned:
                        for ov, io in zip(eqn.outvars, sub.outvars):
                            env_[ov] = (
                                senv[io]
                                if io in senv
                                else fresh("var")
                            )
                if not aligned:
                    for ov in eqn.outvars:
                        env_[ov] = fresh("var")
                continue
            ins = [read(env_, a) for a in eqn.invars]
            in_d = tuple(
                str(getattr(a.aval, "dtype", "?")) for a in eqn.invars
            )
            out_d = tuple(
                str(getattr(v.aval, "dtype", "?")) for v in eqn.outvars
            )
            nm = eqn.primitive.name
            # every op gets fresh OUT ids (so it appears in the graph),
            # but pass-through ops forward their first operand's ORIGIN
            # — the invariant _dot_kind's 'proj' classification rests on
            out_origin = (
                ins[0][1] if nm in _PASSTHRU and ins else "var"
            )
            rec_outs = []
            for ov in eqn.outvars:
                vid, _ = fresh(out_origin)
                env_[ov] = (vid, out_origin)
                rec_outs.append(vid)
            meta = None
            if nm == "slice":
                si = eqn.params.get("start_indices")
                if si is not None:
                    meta = tuple(int(x) for x in si)
            ops.append(Op(
                idx=len(ops),
                prim=nm,
                in_dtypes=in_d,
                out_dtypes=out_d,
                in_ids=tuple(vid for vid, _ in ins),
                out_ids=tuple(rec_outs),
                in_origins=tuple(origin for _, origin in ins),
                meta=meta,
            ))

    walk(jaxpr, env)
    return FlatGraph(ops, kernels)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

# one record of a normalized trace: (kind, in_dtypes, out_dtypes)
TraceRec = tp.Tuple[str, tp.Tuple[str, ...], tp.Tuple[str, ...]]


def _is_float_op(op: Op) -> bool:
    return bool(
        (set(op.in_dtypes) | set(op.out_dtypes)) & _FLOAT_DTYPES
    )


def _dot_kind(op: Op) -> str:
    """'proj' for a weight matmul (an operand traces to a program entry
    parameter through pass-through ops only — the model pytree is an
    ENTRY PARAMETER of every serving program, PR 6), 'rope' for the
    const rotation-matrix contraction of apply_rotary, 'dot' for a
    data-data contraction (QK scores / PV)."""
    if "invar" in op.in_origins:
        return "proj"
    if "const" in op.in_origins:
        return "rope"
    return "dot"


def _trace_pairs(
    graph: FlatGraph,
) -> tp.List[tp.Tuple[TraceRec, Op]]:
    """(record, op) pairs of the program's float arithmetic in program
    order — the op reference lets region extraction ask structural
    questions (is this exp an attention softmax?) that the record's
    dtypes alone cannot answer."""
    out: tp.List[tp.Tuple[TraceRec, Op]] = []
    for op in graph.ops:
        if op.prim == "paged_kernel":
            # the kernel contract node: float/int8 operand dtypes as a
            # sorted multiset (decode and verify pass the same PAYLOADS
            # — queries, row buffers, pool planes, scales — in different
            # positional orders and with different integer plumbing;
            # the contract is what dtypes cross the kernel boundary)
            kept = tuple(sorted(
                d for d in op.in_dtypes
                if d in _FLOAT_DTYPES or d == "int8"
            ))
            out.append((("paged_kernel", kept, op.out_dtypes), op))
            continue
        if op.prim not in _ARITH or not _is_float_op(op):
            continue
        kind = _dot_kind(op) if op.prim == "dot_general" else op.prim
        out.append(((kind, op.in_dtypes, op.out_dtypes), op))
    return out


def normalized_trace(graph: FlatGraph) -> tp.List[TraceRec]:
    """The program's float arithmetic as (kind, in_dtypes, out_dtypes)
    records in program order — the 'op-and-dtype trace'. Shapes are
    deliberately absent (decode is T=1, verify T=spec+1, a chunk T=N;
    the choreography contract is about dtypes and order, not widths)."""
    return [rec for rec, _ in _trace_pairs(graph)]


def attention_regions(graph: FlatGraph) -> tp.List[tp.List[TraceRec]]:
    """Per-layer normalized ATTENTION traces: the arithmetic between the
    QKV projection and the output projection of each layer, located as
    the inter-'proj' region containing that layer's joint softmax (its
    ``exp``). One region per transformer layer; programs traced at the
    same depth must produce the same number of regions."""
    regions: tp.List[tp.List[TraceRec]] = []
    current: tp.List[TraceRec] = []
    has_exp = False
    for rec, op in _trace_pairs(graph):
        if rec[0] == "proj":
            if has_exp:
                regions.append(current)
            current = []
            has_exp = False
            continue
        current.append(rec)
        if rec[0] == "paged_kernel":
            # a paged_kernel node IS the layer's joint softmax (the exp
            # lives in the kernel body, proven separately)
            has_exp = True
        elif rec[0] == "exp" and _is_attention_exp(graph, op):
            # ONLY an attention softmax flags a region: the sampled
            # verify program's target softmax (sampling.target_probs
            # over the lm-head logits, temperature > 0) also trails an
            # exp, but that is sampler arithmetic with its own prover
            # (prove_sampled_choreography), not an attention layer
            has_exp = True
    if has_exp:  # trailing region (the bare naive_attention reference)
        regions.append(current)
    return regions


@dataclasses.dataclass(frozen=True)
class SoftmaxSignature:
    """The softmax-core dtype choreography of one attention subgraph —
    the facts the PR 4/PR 5 bugs flipped, independent of how many score
    blocks feed the joint softmax (decode: pool+recent; verify:
    pool+self; prefill: pool+self; naive: one)."""

    # canonical score contractions feeding the softmax: each is
    # ('dot' | 'mulsum', multiply operand dtypes, accumulation dtype)
    qk_contracts: tp.FrozenSet[tp.Tuple[str, tp.Tuple[str, ...], str]]
    mask_add_dtypes: tp.FrozenSet[tp.Tuple[str, ...]]  # additive-mask adds
    scale_op: str  # 'div' | 'mul' — the 1/sqrt(C) application
    scale_before_mask: bool  # True = scale applied before the mask add
    softmax_dtype: str  # exp operand/result dtype
    # probability operand dtype entering the PV contraction(s), and the
    # canonical PV contractions themselves
    probs_dtype: tp.FrozenSet[str]
    pv_contracts: tp.FrozenSet[tp.Tuple[str, tp.Tuple[str, ...], str]]

    def describe(self) -> str:
        return (
            f"qk={sorted(self.qk_contracts)} "
            f"mask_adds={sorted(self.mask_add_dtypes)} "
            f"scale={self.scale_op}"
            f"{' (before mask)' if self.scale_before_mask else ''} "
            f"softmax={self.softmax_dtype} "
            f"probs->pv={sorted(self.probs_dtype)} "
            f"pv={sorted(self.pv_contracts)}"
        )


def _canonical_contract(
    graph: FlatGraph, op: Op
) -> tp.Tuple[str, tp.Tuple[str, ...], str]:
    """Canonicalize a contraction: a ``dot_general`` keeps its operand
    dtypes with the dot's own output as the accumulation dtype; a
    ``reduce_sum``-over-``mul`` (the decode path's VPU broadcast-multiply
    form) reports the MULTIPLY's operand dtypes with the reduce's output
    as the accumulation dtype. Numerically these are the same object —
    'what dtypes are the products formed at, what dtype do they sum in'."""
    if op.prim == "dot_general":
        return ("dot", op.in_dtypes, op.out_dtypes[0])
    assert op.prim == "reduce_sum", op.prim
    src = graph.producer.get(op.in_ids[0])
    if src is not None and src.prim == "mul":
        return ("mulsum", src.in_dtypes, op.out_dtypes[0])
    return ("sum", op.in_dtypes, op.out_dtypes[0])


def _backward_ops(
    graph: FlatGraph, start_ids: tp.Iterable[int], *, limit: int = 200
) -> tp.List[Op]:
    """Producer-closure walk from ``start_ids``, stopping at contraction
    boundaries (dot_general / reduce_sum) — those are collected but not
    walked past, so the slice stays inside one softmax's score path."""
    seen: tp.Set[int] = set()
    out: tp.List[Op] = []
    stack = list(start_ids)
    while stack and len(out) < limit:
        vid = stack.pop()
        op = graph.producer.get(vid)
        if op is None or op.idx in seen:
            continue
        seen.add(op.idx)
        out.append(op)
        if op.prim in ("dot_general", "reduce_sum"):
            continue  # boundary: a contraction starts a new segment
        stack.extend(i for i in op.in_ids if i >= 0)
    return out


def _leads_to_contract(
    graph: FlatGraph, vid: int, *, limit: int = 60
) -> bool:
    """Does ``vid``'s producer subtree contain a data-data contraction
    (a QK score block)? Distinguishes the score-carrying operand of a
    scale/mask op from the scalar/mask operand."""
    seen: tp.Set[int] = set()
    stack = [vid]
    while stack and len(seen) < limit:
        v = stack.pop()
        op = graph.producer.get(v)
        if op is None or op.idx in seen:
            continue
        seen.add(op.idx)
        if op.prim == "dot_general" and _dot_kind(op) == "dot":
            return True
        if op.prim == "reduce_sum":
            src = graph.producer.get(op.in_ids[0])
            if src is not None and src.prim == "mul":
                return True
            continue
        stack.extend(i for i in op.in_ids if i >= 0)
    return False


def _is_attention_exp(graph: FlatGraph, exp_op: Op) -> bool:
    """Is this ``exp`` an attention softmax? Its backward slice (stopping
    at contraction boundaries) then contains the QK score contraction —
    a data-data ``dot`` or a ``reduce_sum``-over-``mul``. A SAMPLER
    softmax (``sampling.target_probs`` over the lm-head logits in the
    temperature>0 verify program) stops at the lm-head weight projection
    instead and has neither."""
    for op in _backward_ops(graph, [i for i in exp_op.in_ids if i >= 0]):
        if op.prim == "dot_general" and _dot_kind(op) == "dot":
            return True
        if op.prim == "reduce_sum":
            src = graph.producer.get(op.in_ids[0])
            if src is not None and src.prim == "mul":
                return True
    return False


def softmax_signature(
    graph: FlatGraph, exp_op: Op
) -> SoftmaxSignature:
    """Extract the :class:`SoftmaxSignature` around one ``exp``."""
    # --- the score chain: walk BACKWARD from the softmax argument
    # through the score-carrying operand of each div/mul/add, recording
    # the order the scale and the additive mask were applied in (the
    # walk sees last-applied first)
    sub = graph.producer.get(exp_op.in_ids[0])
    chain: tp.List[str] = []  # 'div' | 'mul' | 'mask', last-applied first
    mask_adds: tp.Set[tp.Tuple[str, ...]] = set()
    vid = sub.in_ids[0] if sub is not None else exp_op.in_ids[0]
    for _ in range(32):
        op = graph.producer.get(vid)
        if op is None:
            break
        if op.prim in _PASSTHRU or op.prim == "concatenate":
            # a concatenated joint softmax: every branch shares the
            # suffix arithmetic by construction; follow branch 0
            vid = op.in_ids[0]
            continue
        if op.prim in ("div", "mul", "add"):
            score_side = [
                i for i in op.in_ids
                if i >= 0 and _leads_to_contract(graph, i)
            ]
            if not score_side:
                break
            if op.prim == "add":
                chain.append("mask")
                mask_adds.add(op.in_dtypes)
            else:
                chain.append(op.prim)
            vid = score_side[0]
            continue
        break  # the QK contraction (or something unexpected): done
    scale_op = next((c for c in chain if c != "mask"), "?")
    # the walk sees last-applied first: scale BEFORE mask means the
    # scale shows up AFTER a mask entry in the chain
    scale_before_mask = (
        "mask" in chain
        and scale_op in chain
        and chain.index(scale_op) > chain.index("mask")
    )

    # --- score contractions: the contraction boundaries of the
    # backward slice (qk-norm/rope arithmetic sits behind them and is
    # never reached; proj/rope dots are classified out)
    back = _backward_ops(graph, [i for i in exp_op.in_ids if i >= 0])
    qk: tp.Set[tp.Tuple[str, tp.Tuple[str, ...], str]] = set()
    for op in back:
        if op.prim == "dot_general" and _dot_kind(op) == "dot":
            qk.add(_canonical_contract(graph, op))
        elif op.prim == "reduce_sum":
            rec = _canonical_contract(graph, op)
            if rec[0] == "mulsum":
                qk.add(rec)
    # --- forward: exp -> reduce_sum -> div (normalize) -> [convert] -> PV
    denom_div = None
    for c in graph.consumers.get(exp_op.out_ids[0], []):
        if c.prim == "div":
            denom_div = c
            break
        if c.prim == "reduce_sum":
            for c2 in graph.consumers.get(c.out_ids[0], []):
                if c2.prim == "div":
                    denom_div = c2
                    break
    probs_dtype: tp.Set[str] = set()
    pv: tp.Set[tp.Tuple[str, tp.Tuple[str, ...], str]] = set()
    if denom_div is not None:
        frontier = [denom_div.out_ids[0]]
        hops = 0
        # the banded kernels (PR 20) slice the probability row once per
        # page band — up to MAX_BANDS slices plus their view chains —
        # so the walk needs far more than the pre-banding ~4 hops
        while frontier and hops < 256:
            hops += 1
            vid = frontier.pop()
            for c in graph.consumers.get(vid, []):
                if c.prim == "dot_general":
                    pv.add(_canonical_contract(graph, c))
                    probs_dtype.add(c.in_dtypes[0])
                elif c.prim == "mul":
                    # decode's VPU form: probs * values, then reduce_sum
                    reduced = False
                    for c2 in graph.consumers.get(c.out_ids[0], []):
                        if c2.prim == "reduce_sum":
                            pv.add(_canonical_contract(graph, c2))
                            probs_dtype.add(c.in_dtypes[0])
                            reduced = True
                    if not reduced:
                        frontier.append(c.out_ids[0])
                elif c.prim in _PASSTHRU or c.prim in (
                    "concatenate", "dynamic_slice", "gather",
                ):
                    frontier.extend(c.out_ids)
    return SoftmaxSignature(
        qk_contracts=frozenset(qk),
        mask_add_dtypes=frozenset(mask_adds),
        scale_op=scale_op,
        scale_before_mask=scale_before_mask,
        softmax_dtype=exp_op.out_dtypes[0],
        probs_dtype=frozenset(probs_dtype),
        pv_contracts=frozenset(pv),
    )


def band_accumulation_order(
    graph: FlatGraph, exp_op: Op
) -> tp.Optional[tp.Tuple[int, ...]]:
    """The PV accumulation ORDER around one attention softmax: the
    tuple of last-dim probability-row offsets of the fold's add-tree
    leaves, in the order the fold sums them.

    The banded paged kernels (PR 20, ops.paged_attn) split the PV
    contraction into per-page-band partials — each one a slice of the
    normalized probability row times its band's values — and fold them
    with ``banded_fold`` in pinned ascending-band order; the XLA
    reference runs the identical chunked reduction. f32 addition is
    not associative, so the fold's LEAF ORDER is a bitwise contract
    the dtype-level softmax signature cannot see. This extractor reads
    it straight off the jaxpr: walk forward from the normalized probs
    (the softmax's denominator ``div``) carrying the cumulative
    last-dim slice offset, mark every ``mul`` -> ``reduce_sum``
    consumer as one PV partial at its offset, then linearize the add
    tree that folds the partials — the left-to-right leaf sequence IS
    the summation order. The recent/self partial appears as the final
    leaf at offset W (its probability slice starts past the pool
    columns), so a correct fold reads strictly ascending.

    Returns None when the softmax's PV is not a probs-slice fold — the
    prefill chunk and the naive reference contract their probs with an
    einsum (``dot_general``), which has no fold and no order to pin —
    or when fewer than two partials exist. The prover's banded-order
    clause applies only to decode and verify, where None is itself a
    violation (their PV has had the mul/reduce_sum shape since PR 6)."""
    denom = None
    for c in graph.consumers.get(exp_op.out_ids[0], []):
        if c.prim == "div":
            denom = c
            break
        if c.prim == "reduce_sum":
            for c2 in graph.consumers.get(c.out_ids[0], []):
                if c2.prim == "div":
                    denom = c2
                    break
    if denom is None:
        return None
    # forward walk from the normalized probs, carrying the cumulative
    # last-dim offset; a mul -> reduce_sum consumer is one PV partial
    partials: tp.Dict[int, int] = {}
    frontier: tp.List[tp.Tuple[int, int]] = [(denom.out_ids[0], 0)]
    hops = 0
    while frontier and hops < 1024:
        hops += 1
        vid, off = frontier.pop()
        for c in graph.consumers.get(vid, []):
            if c.prim == "slice":
                noff = off + (c.meta[-1] if c.meta else 0)
                frontier.extend((o, noff) for o in c.out_ids)
            elif c.prim in _PASSTHRU:
                frontier.extend((o, off) for o in c.out_ids)
            elif c.prim == "mul":
                for c2 in graph.consumers.get(c.out_ids[0], []):
                    if c2.prim == "reduce_sum":
                        partials[c2.out_ids[0]] = off
    if len(partials) < 2:
        return None
    # find the fold's root by climbing add-consumers from one partial
    # (the fold is a left spine: each add's output feeds the next)
    cur = next(iter(partials))
    climbed = False
    for _ in range(len(partials) + 8):
        nxt = next(
            (c for c in graph.consumers.get(cur, []) if c.prim == "add"),
            None,
        )
        if nxt is None:
            break
        climbed = True
        cur = nxt.out_ids[0]
    if not climbed:
        return None

    def leaves(vid: int, depth: int = 0) -> tp.List[int]:
        op = graph.producer.get(vid)
        if op is not None and op.prim == "add" and depth < 200:
            return (
                leaves(op.in_ids[0], depth + 1)
                + leaves(op.in_ids[1], depth + 1)
            )
        return [vid]

    lv = leaves(cur)
    if any(v not in partials for v in lv):
        return None
    return tuple(partials[v] for v in lv)


# ---------------------------------------------------------------------------
# per-program choreography
# ---------------------------------------------------------------------------


def _has_kv_dequant(graph: FlatGraph) -> bool:
    """Does the graph multiply an int8-converted value — the
    ``f32(codes) * scale`` dequant of an int8 KV pool? Distinguished
    from the int8 WEIGHT path's epilogue by position: a weight's
    ``convert(s8)`` feeds its dot_general and the epilogue multiplies
    the DOT OUTPUT, while the KV dequant multiplies the converted codes
    themselves (before any contraction)."""
    for op in graph.ops:
        if op.prim != "mul":
            continue
        for vid in op.in_ids:
            v = vid
            for _ in range(8):  # chase pass-through views
                src = graph.producer.get(v)
                if src is None:
                    break
                if src.prim == "convert_element_type":
                    if src.in_dtypes[0] == "int8" and (
                        src.out_dtypes[0] in _FLOAT_DTYPES
                    ):
                        return True
                    break
                if src.prim in _PASSTHRU or src.prim in (
                    "gather", "dynamic_slice", "concatenate",
                ):
                    if not src.in_ids or src.in_ids[0] < 0:
                        break
                    v = src.in_ids[0]
                    continue
                break
    return False


def kernel_choreography(name: str, kernel_jaxpr) -> SoftmaxSignature:
    """The softmax-core signature of a Pallas kernel BODY: the body is
    ordinary jnp arithmetic over refs, so the very same extractor that
    reads the XLA programs reads it — which is the point: the kernel's
    contract (f32 score accumulation, mask before scale, f32 softmax,
    f32 probs through PV) is proven by the same machinery that proved
    the program it replaces, not by a parallel hand-written checklist."""
    graph = flatten_jaxpr(kernel_jaxpr)
    exps = [
        op for op in graph.ops
        if op.prim == "exp" and op.out_dtypes[0] in _FLOAT_DTYPES
    ]
    assert exps, f"{name}: kernel body contains no softmax exp"
    sig = softmax_signature(graph, exps[0])
    for e in exps[1:]:
        s2 = softmax_signature(graph, e)
        assert s2 == sig, (
            f"{name}: kernel body softmax signatures differ:\n"
            f"  {sig.describe()}\n  {s2.describe()}"
        )
    return sig


@dataclasses.dataclass(frozen=True)
class ProgramChoreography:
    """Everything the prover compares about one traced program."""

    name: str
    # the representative per-layer attention trace (all layers asserted
    # identical) and the number of layers seen
    attention: tp.Tuple[TraceRec, ...]
    n_layers: int
    softmax: SoftmaxSignature
    # the lm-head projection: operand dtypes + whether the quantized
    # dequant-epilogue multiply follows it
    lm_head: tp.Optional[TraceRec]
    lm_head_epilogue: bool
    # True when the attention runs inside a Pallas kernel (the softmax
    # signature above was extracted from the KERNEL BODY)
    kernelized: bool = False
    # the f32(s8-codes) * scale multiply of an int8 KV pool is present
    # (in the kernel body or the gathered view)
    kv_dequant: bool = False
    # the PV fold's summation order as probability-row offsets (see
    # band_accumulation_order); None for einsum-PV programs (prefill/
    # naive) where no fold exists
    band_order: tp.Optional[tp.Tuple[int, ...]] = None


def extract_choreography(name: str, closed_jaxpr) -> ProgramChoreography:
    """Normalize one traced program into its comparable choreography.

    Programs whose attention runs in the Pallas paged kernel
    (ops.paged_attn) carry the kernel call as ONE contract node in the
    attention trace; the softmax signature is then extracted from the
    KERNEL BODY (every per-layer body asserted identical), so the
    decode-choreography contract is proven about the arithmetic the
    kernel actually performs — a bf16-accumulating kernel variant turns
    the same checks red that a bf16-accumulating XLA edit would."""
    graph = flatten_jaxpr(closed_jaxpr)
    regions = attention_regions(graph)
    assert regions, f"{name}: no attention softmax found in the trace"
    rep = tuple(regions[0])
    for i, r in enumerate(regions[1:], start=2):
        assert tuple(r) == rep, (
            f"{name}: layer {i}'s attention trace differs from layer 1 "
            f"— the stacked layers do not share one choreography"
        )
    kernels = [k for k in graph.kernels if k is not None]
    kv_deq = _has_kv_dequant(graph)
    if kernels:
        sigs = {kernel_choreography(name, k) for k in kernels}
        assert len(sigs) == 1, (
            f"{name}: per-layer kernel bodies disagree:\n" + "\n".join(
                s.describe() for s in sigs
            )
        )
        sig = next(iter(sigs))
        kv_deq = kv_deq or any(
            _has_kv_dequant(flatten_jaxpr(k)) for k in kernels
        )
        # the banded-accumulation order is a property of the KERNEL
        # BODY's PV fold (the outer trace sees only the contract node)
        kgraph = flatten_jaxpr(kernels[0])
        kexps = [
            op for op in kgraph.ops
            if op.prim == "exp" and op.out_dtypes[0] in _FLOAT_DTYPES
        ]
        band_order = (
            band_accumulation_order(kgraph, kexps[0]) if kexps else None
        )
    else:
        exps = [
            op for op in graph.ops
            if op.prim == "exp" and op.out_dtypes[0] in _FLOAT_DTYPES
            and _is_attention_exp(graph, op)
        ]
        sig = softmax_signature(graph, exps[0])
        for e in exps[1:]:
            s2 = softmax_signature(graph, e)
            assert s2 == sig, (
                f"{name}: softmax signatures differ between layers:\n"
                f"  {sig.describe()}\n  {s2.describe()}"
            )
        band_order = band_accumulation_order(graph, exps[0])
    # lm head: the LAST weight projection in program order, plus its
    # epilogue (a following multiply whose other operand is an entry
    # parameter — the QuantLinear per-channel scale)
    lm = None
    lm_op = None
    for op in graph.ops:
        if op.prim == "dot_general" and _dot_kind(op) == "proj":
            lm_op = op
    if lm_op is not None:
        lm = ("proj", lm_op.in_dtypes, lm_op.out_dtypes)
    epilogue = False
    if lm_op is not None:
        for c in graph.consumers.get(lm_op.out_ids[0], []):
            if c.prim == "mul" and "invar" in c.in_origins:
                epilogue = True
    return ProgramChoreography(
        name=name,
        attention=rep,
        n_layers=len(regions),
        softmax=sig,
        lm_head=lm,
        lm_head_epilogue=epilogue,
        kernelized=bool(kernels),
        kv_dequant=kv_deq,
        band_order=band_order,
    )


# ---------------------------------------------------------------------------
# the prover
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChoreoCheck:
    name: str
    ok: bool
    detail: str = ""


@dataclasses.dataclass(frozen=True)
class ChoreoReport:
    checks: tp.Tuple[ChoreoCheck, ...]
    programs: tp.Tuple[ProgramChoreography, ...]

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "ok": self.ok,
            "checks": [
                {"name": c.name, "ok": c.ok, "detail": c.detail}
                for c in self.checks
            ],
            "programs": {
                p.name: {
                    "n_layers": p.n_layers,
                    "attention_ops": len(p.attention),
                    "attention": [
                        [k, list(i), list(o)] for k, i, o in p.attention
                    ],
                    "softmax": p.softmax.describe(),
                    "lm_head": list(p.lm_head) if p.lm_head else None,
                    "lm_head_epilogue": p.lm_head_epilogue,
                    "kernelized": p.kernelized,
                    "kv_dequant": p.kv_dequant,
                    "band_order": (
                        list(p.band_order)
                        if p.band_order is not None
                        else None
                    ),
                }
                for p in self.programs
            },
        }


def _first_diff(a: tp.Sequence[TraceRec], b: tp.Sequence[TraceRec]) -> str:
    for i, (ra, rb) in enumerate(zip(a, b)):
        if ra != rb:
            return f"op {i}: {ra} != {rb}"
    if len(a) != len(b):
        return f"length {len(a)} != {len(b)}"
    return ""


def prove_choreography(
    decode: ProgramChoreography,
    prefill: ProgramChoreography,
    verify: ProgramChoreography,
    naive: ProgramChoreography,
    *,
    expect_kv_dequant: bool = False,
) -> ChoreoReport:
    """Evaluate the three serving-choreography contracts (module
    docstring). ``naive`` is the reference trace of
    ``ops.attention.naive_attention`` — what the monolithic prefill (and
    the training forward) computes. ``expect_kv_dequant`` (the int8 KV
    pool): all three programs must carry the ``f32(codes) * scale``
    dequant multiply of the quantized pool — a program reading raw codes
    without its scale would be arithmetically wrong in a way no dtype
    check sees, so presence of the scale-multiply is itself a proven
    contract (and conversely, a float pool must NOT carry one)."""
    checks: tp.List[ChoreoCheck] = []

    # 1. verify mirrors decode OP FOR OP (the PR 5 contract)
    diff = _first_diff(decode.attention, verify.attention)
    sig_ok = decode.softmax == verify.softmax
    checks.append(ChoreoCheck(
        name="verify-mirrors-decode",
        ok=not diff and sig_ok,
        detail=diff or (
            ""
            if sig_ok
            else f"softmax: {decode.softmax.describe()} != "
            f"{verify.softmax.describe()}"
        ),
    ))

    # 2. the prefill chunk's softmax core mirrors naive_attention (the
    # PR 4 contract); full-sequence equality is not expected (the chunk
    # has a pool score block and rope/qk-norm the bare reference lacks)
    checks.append(ChoreoCheck(
        name="prefill-mirrors-naive",
        ok=prefill.softmax == naive.softmax,
        detail=(
            ""
            if prefill.softmax == naive.softmax
            else f"{prefill.softmax.describe()} != "
            f"{naive.softmax.describe()}"
        ),
    ))

    # 3. shared arithmetic across all three serving programs
    progs = (decode, prefill, verify)
    shared: tp.List[tp.Tuple[str, bool, str]] = []
    sm = {p.softmax.softmax_dtype for p in progs}
    shared.append((
        "softmax runs in f32 everywhere",
        sm == {"float32"},
        f"softmax dtypes {sorted(sm)}",
    ))
    # extraction-degeneracy guard: a signature with NO score
    # contractions or an unrecognized scale op means the program's
    # softmax no longer has the shape the extractor (and the contract)
    # expects — that is a violation, not a vacuous pass. Found by fault
    # injection: a bf16-accumulating kernel variant used to slip through
    # because jnp's silent re-promotion broke the score-chain walk and
    # left every dtype set empty.
    degenerate = {
        p.name: (not p.softmax.qk_contracts, p.softmax.scale_op)
        for p in progs
    }
    shared.append((
        "every program exposes its score contractions to the prover",
        all(
            p.softmax.qk_contracts and p.softmax.scale_op in ("div", "mul")
            and p.softmax.pv_contracts
            for p in progs
        ),
        f"degenerate signatures: {degenerate}",
    ))
    # PV accumulation is contract-specific (decode keeps f32 probs and
    # sums, the prefill chunk mirrors naive_attention's value-dtype
    # einsum) and is pinned per program by checks 1 and 2 — the SHARED
    # invariant is the score accumulation
    accs = {
        acc for p in progs for (_, _, acc) in p.softmax.qk_contracts
    }
    shared.append((
        "scores accumulate in f32 everywhere",
        accs == {"float32"},
        f"score accumulation dtypes {sorted(accs)}",
    ))
    sbm = {p.softmax.scale_before_mask for p in progs}
    shared.append((
        "mask is added before the softmax scale everywhere",
        sbm == {False},
        f"scale_before_mask {sorted(sbm)}",
    ))
    heads = {(p.lm_head, p.lm_head_epilogue) for p in progs}
    shared.append((
        "lm-head projection choreography is identical everywhere",
        len(heads) == 1,
        "; ".join(
            f"{p.name}: {p.lm_head} epilogue={p.lm_head_epilogue}"
            for p in progs
        ),
    ))
    layer_depths = {p.n_layers for p in progs}
    shared.append((
        "all programs traced at one depth",
        len(layer_depths) == 1,
        f"layer counts {sorted(layer_depths)}",
    ))
    deq = {p.name: p.kv_dequant for p in progs}
    if expect_kv_dequant:
        shared.append((
            "int8 KV: every program dequantizes the pool "
            "(codes * per-page scale)",
            all(deq.values()),
            f"kv_dequant {deq}",
        ))
    else:
        shared.append((
            "float KV: no stray int8 pool dequant anywhere",
            not any(deq.values()),
            f"kv_dequant {deq}",
        ))
    # banded PV accumulation order (PR 20): the decode and verify PV
    # folds — kernel body and banded XLA reference alike — must sum
    # their page-band partials in pinned ASCENDING-band order, with the
    # recent/self partial last (its probability slice starts past the
    # pool columns, so a correct fold reads strictly ascending), and
    # the two programs must agree exactly. f32 addition is not
    # associative: a reordered fold is a bitwise drift no dtype check
    # sees (the fault injection in tests/test_choreo.py reverses
    # ops.paged_attn._BAND_FOLD_ORDER and must fail exactly this
    # clause). The prefill chunk and naive reference contract their
    # probs with an einsum — no fold exists, band_accumulation_order
    # returns None for them, and they are exempt by construction; for
    # decode/verify a None is itself a violation (their PV lost the
    # shape the extractor pins).
    def _ascending(t: tp.Optional[tp.Tuple[int, ...]]) -> bool:
        return t is not None and all(a < b for a, b in zip(t, t[1:]))

    shared.append((
        "banded PV accumulation runs in pinned ascending-band order",
        _ascending(decode.band_order) and _ascending(verify.band_order)
        and decode.band_order == verify.band_order,
        f"band_order decode={decode.band_order} "
        f"verify={verify.band_order}",
    ))
    # decode and verify must agree on WHERE the attention runs (both in
    # the kernel or both in XLA) — a half-kernelized pair could pass the
    # per-program checks while running two different arithmetic stacks
    shared.append((
        "decode and verify share one attention backend",
        decode.kernelized == verify.kernelized,
        f"kernelized decode={decode.kernelized} "
        f"verify={verify.kernelized}",
    ))
    for name, ok, detail in shared:
        checks.append(ChoreoCheck(
            name=f"shared: {name}", ok=ok, detail="" if ok else detail
        ))

    return ChoreoReport(
        checks=tuple(checks),
        programs=(decode, prefill, verify, naive),
    )


def prove_sp_choreography(
    off: ProgramChoreography,
    sp: ProgramChoreography,
) -> ChoreoReport:
    """The sequence-parallel prefill contract: the SP chunk program
    (``ServingEngine(prefill_sp="on")``) must be the plain chunk program
    PLUS DATA MOVEMENT AND NOTHING ELSE. Row-sharding the chunk's
    replicated segments over the 'tensor' axis inserts only
    ``sharding_constraint`` ops — pass-through, outside the arithmetic
    alphabet — so the two programs' normalized traces must be IDENTICAL
    op for op: one differing record means SP changed arithmetic, which
    is exactly the bitwise-identity hazard (a reduce-scatter substituted
    for an all-reduce reassociates the psum and flips near-tied greedy
    argmaxes the same way the PR 4/PR 5 drifts did). Both traces must
    come from the same mesh so the comparison isolates the prefill_sp
    knob."""
    checks: tp.List[ChoreoCheck] = []
    diff = _first_diff(off.attention, sp.attention)
    checks.append(ChoreoCheck(
        name="sp-prefill-mirrors-off",
        ok=not diff,
        detail=diff,
    ))
    sig_ok = off.softmax == sp.softmax
    checks.append(ChoreoCheck(
        name="sp-prefill-softmax-identical",
        ok=sig_ok,
        detail=(
            ""
            if sig_ok
            else f"{off.softmax.describe()} != {sp.softmax.describe()}"
        ),
    ))
    head_ok = (
        off.lm_head == sp.lm_head
        and off.lm_head_epilogue == sp.lm_head_epilogue
    )
    checks.append(ChoreoCheck(
        name="sp-prefill-lm-head-identical",
        ok=head_ok,
        detail=(
            ""
            if head_ok
            else f"{off.lm_head} ep={off.lm_head_epilogue} != "
            f"{sp.lm_head} ep={sp.lm_head_epilogue}"
        ),
    ))
    struct_ok = (
        off.n_layers == sp.n_layers
        and off.kernelized == sp.kernelized
        and off.kv_dequant == sp.kv_dequant
    )
    checks.append(ChoreoCheck(
        name="sp-prefill-structure-identical",
        ok=struct_ok,
        detail=(
            ""
            if struct_ok
            else f"layers {off.n_layers}/{sp.n_layers} kernelized "
            f"{off.kernelized}/{sp.kernelized} kv_dequant "
            f"{off.kv_dequant}/{sp.kv_dequant}"
        ),
    ))
    return ChoreoReport(checks=tuple(checks), programs=(off, sp))


# ---------------------------------------------------------------------------
# the sampled-verify prover (temperature > 0)
# ---------------------------------------------------------------------------

# comparison primitives — deliberately OUTSIDE _ARITH (a compare is a
# decision, not arithmetic, so normalized traces drop it), collected
# explicitly here because the sampled acceptance test IS a float compare
# whose dtype decides near-tie accept/reject flips
_COMPARES = frozenset({"lt", "le", "gt", "ge"})


def _rng_downstream_ids(graph: FlatGraph) -> tp.Set[int]:
    """Value ids computed downstream of any PRNG draw (``random_bits``
    outputs, forward consumer closure). In a sampled program this is
    everything the drawn randomness can influence — the gumbel
    arithmetic, the categorical argmax, and (in the verify program) the
    acceptance compare and anything fed by an accepted token."""
    seen: tp.Set[int] = set()
    stack = [
        oid for op in graph.ops if op.prim == "random_bits"
        for oid in op.out_ids
    ]
    seen.update(stack)
    while stack:
        vid = stack.pop()
        for op in graph.consumers.get(vid, []):
            for oid in op.out_ids:
                if oid not in seen:
                    seen.add(oid)
                    stack.append(oid)
    return seen


def _slice_records(ops: tp.Iterable[Op]) -> tp.Tuple[TraceRec, ...]:
    """Sorted float-arithmetic records of an op slice — a multiset
    fingerprint (program order varies legitimately between the decode
    window's in-scan sampler and the verify program's row-0 sampler;
    what must agree is which float ops run at which dtypes)."""
    return tuple(sorted(
        (
            _dot_kind(op) if op.prim == "dot_general" else op.prim,
            op.in_dtypes,
            op.out_dtypes,
        )
        for op in ops
        if op.prim in _ARITH and _is_float_op(op)
    ))


@dataclasses.dataclass(frozen=True)
class SamplerChoreography:
    """The sampled-path dtype choreography of one traced program: what
    the temperature>0 prover compares between the decode window's
    sampler and the verify program's rejection-sampling acceptance."""

    name: str
    # sorted float-arith records of the backward slice of each
    # categorical argmax (jax lowers ``random.categorical`` to
    # argmax(logits/T + gumbel), so this slice IS the sampler: the
    # temperature division, the top-k mask arithmetic, the gumbel
    # -log(-log u) chain) — all categoricals asserted identical
    categorical: tp.Tuple[TraceRec, ...]
    n_categoricals: int
    # (prim, operand dtypes) of every float comparison downstream of the
    # PRNG — in the verify program the rejection-sampling acceptance
    # test ``u * q(t) <= p(t)`` lives here (the decode window has none:
    # its sampler decides by argmax, not threshold)
    rng_float_compares: tp.Tuple[tp.Tuple[str, tp.Tuple[str, ...]], ...]
    # {sub, max, div, log} records of the residual-resample slice — the
    # backward slice of the residual ``log`` (the one float log NOT in
    # any categorical's gumbel chain): ``max(p - q, 0)`` and its
    # renormalization (verify only; empty for the decode window)
    residual: tp.Tuple[TraceRec, ...]
    # the target-softmax ``exp`` inside the residual slice (the
    # ``target_probs`` softmax the acceptance threshold and residual are
    # computed from), None when absent
    residual_exp: tp.Optional[TraceRec]


def extract_sampler_choreography(
    name: str, closed_jaxpr
) -> SamplerChoreography:
    """Normalize one SAMPLED (temperature > 0) traced program into its
    comparable sampler choreography. Purely structural — no execution;
    degenerate extractions (no categorical, no residual log) are
    reported as empty fields and turned into failing checks by
    :func:`prove_sampled_choreography`, never silently passed."""
    graph = flatten_jaxpr(closed_jaxpr)
    rng_ids = _rng_downstream_ids(graph)
    argmaxes = [
        op for op in graph.ops
        if op.prim == "argmax"
        and op.in_dtypes and op.in_dtypes[0] in _FLOAT_DTYPES
        # the CATEGORICAL argmax consumes logits + gumbel noise; a
        # greedy/verification argmax reads deterministic logits only
        and any(i in rng_ids for i in op.in_ids if i >= 0)
    ]
    cat_op_idxs: tp.Set[int] = set()
    cat_sigs: tp.List[tp.Tuple[TraceRec, ...]] = []
    for am in argmaxes:
        ops = _backward_ops(
            graph, [i for i in am.in_ids if i >= 0]
        )
        cat_op_idxs.update(op.idx for op in ops)
        cat_sigs.append(_slice_records(ops))
    categorical: tp.Tuple[TraceRec, ...] = ()
    if cat_sigs:
        categorical = cat_sigs[0]
        assert all(s == categorical for s in cat_sigs[1:]), (
            f"{name}: categorical sampler slices disagree within one "
            f"program"
        )
    compares = tuple(
        (op.prim, op.in_dtypes)
        for op in graph.ops
        if op.prim in _COMPARES
        and op.in_dtypes and op.in_dtypes[0] in _FLOAT_DTYPES
        and any(i in rng_ids for i in op.in_ids if i >= 0)
    )
    # the residual-resample slice: every float log that is NOT gumbel
    # arithmetic (gumbel logs live in a categorical's backward slice)
    # roots the residual renormalization log(normalize(max(p - q, 0)))
    resid_logs = [
        op for op in graph.ops
        if op.prim == "log" and op.out_dtypes[0] in _FLOAT_DTYPES
        and op.idx not in cat_op_idxs
    ]
    resid_ops: tp.Dict[int, Op] = {}
    for lg in resid_logs:
        for op in _backward_ops(
            graph, [i for i in lg.in_ids if i >= 0]
        ):
            resid_ops[op.idx] = op
        resid_ops[lg.idx] = lg
    residual = tuple(
        rec for rec in _slice_records(resid_ops.values())
        if rec[0] in ("sub", "max", "div", "log")
    )
    exps = [
        op for op in resid_ops.values()
        if op.prim == "exp" and op.out_dtypes[0] in _FLOAT_DTYPES
    ]
    residual_exp = (
        ("exp", exps[0].in_dtypes, exps[0].out_dtypes) if exps else None
    )
    return SamplerChoreography(
        name=name,
        categorical=categorical,
        n_categoricals=len(argmaxes),
        rng_float_compares=compares,
        residual=residual,
        residual_exp=residual_exp,
    )


def prove_sampled_choreography(
    decode: SamplerChoreography,
    verify: SamplerChoreography,
) -> tp.Tuple[ChoreoCheck, ...]:
    """The four sampled-verify contracts, as checks to append to a
    temperature>0 :class:`ChoreoReport`:

    1. the verify program's row-0 categorical is the decode window's
       sampler op for op (same tempered/top-k/gumbel dtype records) —
       the sampled analogue of verify-mirrors-decode;
    2. every float comparison the drawn randomness feeds — the
       rejection-sampling acceptance test among them — runs in f32 (a
       bf16 acceptance compare flips near-tie accept/reject decisions
       exactly the way the PR 5 bf16 argmax flipped near-tie
       acceptance);
    3. the residual renormalization ``max(p - q, 0) / mass`` and its
       log-encoding run in f32;
    4. the target softmax feeding the acceptance threshold and the
       residual runs in f32.

    Degeneracy is failure: a sampled program in which the extractor
    finds no categorical, no acceptance compare, or no residual slice
    no longer has the shape the contract is about."""
    checks: tp.List[ChoreoCheck] = []

    ok1 = (
        decode.n_categoricals >= 1
        and verify.n_categoricals >= 1
        and decode.categorical == verify.categorical
    )
    checks.append(ChoreoCheck(
        name="sampled: verify row-0 sampler mirrors the decode window's "
        "categorical",
        ok=ok1,
        detail="" if ok1 else (
            f"decode categoricals={decode.n_categoricals} "
            f"{decode.categorical} != verify "
            f"categoricals={verify.n_categoricals} {verify.categorical}"
        ),
    ))

    bad = [
        (p, d) for (p, d) in verify.rng_float_compares
        if set(d) != {"float32"}
    ]
    ok2 = bool(verify.rng_float_compares) and not bad
    checks.append(ChoreoCheck(
        name="sampled: acceptance compares run in f32",
        ok=ok2,
        detail="" if ok2 else (
            f"non-f32 float compares downstream of the PRNG: {bad}"
            if bad else "no float compare downstream of the PRNG — the "
            "acceptance test is missing from the verify program"
        ),
    ))

    bad_r = [r for r in verify.residual if set(r[1]) | set(r[2]) != {"float32"}]
    ok3 = bool(verify.residual) and not bad_r
    checks.append(ChoreoCheck(
        name="sampled: residual renormalization runs in f32",
        ok=ok3,
        detail="" if ok3 else (
            f"non-f32 residual records: {bad_r}" if bad_r
            else "no residual-resample slice found in the verify program"
        ),
    ))

    ok4 = (
        verify.residual_exp is not None
        and set(verify.residual_exp[1]) | set(verify.residual_exp[2])
        == {"float32"}
    )
    checks.append(ChoreoCheck(
        name="sampled: target softmax runs in f32 in the verify sampler",
        ok=ok4,
        detail="" if ok4 else (
            f"target softmax exp: {verify.residual_exp}"
        ),
    ))
    return tuple(checks)
