"""Compile-the-real-train-step glue for the analyzers.

This is the only module in ``midgpt_tpu.analysis`` that imports jax: it
builds the mesh/optimizer/state for a named config, compiles the actual
``make_train_step`` (optionally shrunk to audit size), and hands the
post-optimization HLO to the jax-free parser/rules/cost layers.

Shrinking (``shrink_for_audit``) keeps the mesh axes, sharding rules and
code paths of the full config but cuts layers/vocab/sequence so the audit
compiles in seconds on the 8-device CPU virtual mesh — the partitioner
decisions the rules check are per-layer-shape, not per-depth.
"""

from __future__ import annotations

import contextlib
import dataclasses
import typing as tp

import numpy as np

from midgpt_tpu.analysis import hlo as hlo_mod
from midgpt_tpu.analysis.rules import Report, StepAnalysis, rules_for_config
from midgpt_tpu.config import ExperimentConfig, get_config

# the input-batch layout every entry point feeds the step with
# (train.py batch_spec); logical, resolved against the mesh axis names
BATCH_SPEC_AXES = (None, ("replica", "fsdp"), "sequence")


def shrink_for_audit(
    cfg: ExperimentConfig,
    *,
    n_layer: int = 2,
    block: int = 256,
    vocab: int = 1024,
    batch: int = 8,
) -> ExperimentConfig:
    """Audit-sized variant of ``cfg``: same mesh axes, sharding rules and
    code paths (incl. the chunked-loss path via ``loss_chunk=block//2``),
    shrunk to compile fast on the CPU virtual mesh."""
    model = dataclasses.replace(
        cfg.model,
        n_layer=n_layer,
        block_size=block,
        vocab_size=vocab,
        remat="none",
        scan_unroll=1,
    )
    return dataclasses.replace(
        cfg,
        model=model,
        batch_size=batch,
        g_accum_iters=1,
        loss_chunk=block // 2,  # 2 chunks: keeps the chunked-loss path
    )


@contextlib.contextmanager
def override_logical_rules(overrides: tp.Optional[tp.Mapping[str, tp.Any]]):
    """Temporarily rewrite entries of the activation logical-rule table
    (``parallel.sharding.DEFAULT_LOGICAL_RULES``).

    This is the fault-injection hook: mapping ``batch`` to ``None``
    reproduces the classic opaque-boundary trap (the partitioner gathers
    the full batch onto every device), which the ``no-batch-allgather``
    rule must catch. Also usable for what-if cost reports.
    """
    if not overrides:
        yield
        return
    from midgpt_tpu.parallel import sharding

    old = sharding.DEFAULT_LOGICAL_RULES
    unknown = set(overrides) - set(old)
    assert not unknown, f"unknown logical axes {sorted(unknown)}"
    patched = dict(old)
    patched.update(overrides)
    sharding.DEFAULT_LOGICAL_RULES = patched  # type: ignore[assignment]
    try:
        yield
    finally:
        sharding.DEFAULT_LOGICAL_RULES = old  # type: ignore[assignment]


def compile_train_step(
    cfg: ExperimentConfig,
    logical_overrides: tp.Optional[tp.Mapping[str, tp.Any]] = None,
):
    """Compile the real donated train program for ``cfg`` on the current
    backend's devices — the per-step jit when ``steps_per_dispatch == 1``,
    the fused K-step ``make_train_window`` scan otherwise (so the audit
    sees exactly the program the trainer launches, incl. donation across
    the whole window). Returns ``(hlo_text, mesh, donated_leaves)``."""
    import jax
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import (
        init_state,
        make_optimizer,
        make_train_step,
        make_train_window,
    )

    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    k = cfg.steps_per_dispatch
    with override_logical_rules(logical_overrides):
        # abstract: sharded ShapeDtypeStructs, not device buffers — the
        # audit lowers/compiles but never executes, so full-size configs
        # (bench.py's comms rung) don't pay params + Adam moments in HBM
        state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0), abstract=True)
        b = cfg.microbatch_size
        t = cfg.model.block_size
        if k > 1:
            step = make_train_window(cfg, tx, mesh, k)
            x = np.zeros((k, cfg.g_accum_iters, b, t), np.int32)
            spec = P(None, *BATCH_SPEC_AXES)
        else:
            step = make_train_step(cfg, tx, mesh)
            x = np.zeros((cfg.g_accum_iters, b, t), np.int32)
            spec = P(*BATCH_SPEC_AXES)
        xg = make_global_array(x, mesh, spec)
        hlo = step.lower(
            state, xg, xg, jax.random.PRNGKey(1)
        ).compile().as_text()
    donated_leaves = len(jax.tree.leaves(state))
    return hlo, mesh, donated_leaves


def compile_eval_sweep(cfg: ExperimentConfig, n_eval: int = 3):
    """Compile the stacked-batch eval sweep (``make_eval_step``) for
    ``cfg``. Returns ``(hlo_text, mesh)``."""
    import jax
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_eval_step, make_optimizer

    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0), abstract=True)
    sweep = make_eval_step(cfg, mesh)
    b = cfg.microbatch_size
    t = cfg.model.block_size
    x = np.zeros((n_eval, b, t), np.int32)
    xg = make_global_array(x, mesh, P(*BATCH_SPEC_AXES))
    hlo = sweep.lower(state.params, xg, xg).compile().as_text()
    return hlo, mesh


def analyze_train_step(
    cfg: ExperimentConfig,
    *,
    shrink: bool = True,
    logical_overrides: tp.Optional[tp.Mapping[str, tp.Any]] = None,
) -> StepAnalysis:
    """Compile ``cfg``'s train step and wrap it in a :class:`StepAnalysis`
    ready for rules/cost evaluation."""
    audit_cfg = shrink_for_audit(cfg) if shrink else cfg
    hlo, mesh, donated = compile_train_step(audit_cfg, logical_overrides)
    return StepAnalysis.from_text(
        hlo,
        hlo_mod.MeshInfo.from_mesh(mesh, num_slices=audit_cfg.mesh.num_slices),
        global_batch=audit_cfg.microbatch_size,
        block=audit_cfg.model.block_size,
        donated_leaves=donated,
    )


def audit_config(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    *,
    shrink: bool = True,
    logical_overrides: tp.Optional[tp.Mapping[str, tp.Any]] = None,
) -> tp.Tuple[StepAnalysis, Report, tp.Dict[str, tp.Any]]:
    """One-call audit: compile, evaluate the config's ruleset, build the
    cost report. Returns ``(analysis, rule_report, cost_report)``."""
    from midgpt_tpu.analysis.cost import cost_report

    cfg = (
        get_config(name_or_cfg)
        if isinstance(name_or_cfg, str)
        else name_or_cfg
    )
    analysis = analyze_train_step(
        cfg, shrink=shrink, logical_overrides=logical_overrides
    )
    report = rules_for_config(cfg, analysis.mesh).evaluate(analysis)
    return analysis, report, cost_report(analysis)


def parse_mesh_shape(spec: tp.Optional[str]) -> tp.Optional[tp.Dict[str, int]]:
    """``"tp=2,replica=2"`` -> ``{"tensor": 2, "replica": 2}`` (the
    --mesh-shape CLI flag for the sharded serving audits). Accepted keys:
    ``tp``/``tensor``, ``dp``/``replica``, ``fsdp``. jax-free."""
    if not spec:
        return None
    alias = {"tp": "tensor", "tensor": "tensor", "dp": "replica",
             "replica": "replica", "fsdp": "fsdp"}
    out: tp.Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, val = part.partition("=")
        key = alias.get(name.strip())
        try:
            size = int(val.strip())
        except ValueError:
            size = 0
        if key is None or size < 1:
            raise ValueError(
                f"bad --mesh-shape entry {part!r} (want tp=N / replica=N "
                "/ fsdp=N with N >= 1)"
            )
        out[key] = size
    return out or None


def serving_payload_shapes(
    model_cfg,
    *,
    slots: int,
    page_size: int,
    num_pages: int,
    rows: tp.Iterable[int],
) -> tp.FrozenSet[tp.Tuple[int, ...]]:
    """Every FULL (unsharded) shape a serving program's pool/page-gather
    payload can take at one audited geometry — what the
    ``no-batch-allgather-in-page-gather`` rule is parameterized with. An
    all-gather producing one of these in the SPMD-partitioned HLO means
    a KV-head-sharded buffer was regathered to all heads. ``rows`` lists
    the per-dispatch row counts the program writes (decode window K,
    prefill chunk length, verify spec_len + 1)."""
    from midgpt_tpu.serving.paged import pages_needed

    l = model_cfg.n_layer
    hkv = model_cfg.kv_heads
    c = model_cfg.head_dim
    ps = page_size
    pmax = pages_needed(model_cfg.block_size, page_size)
    shapes: tp.Set[tp.Tuple[int, ...]] = {
        (l, num_pages, hkv, c, ps),  # the pool itself
        (num_pages, hkv, c, ps),  # one layer's pool
        (slots, pmax, hkv, c, ps),  # block-table-gathered pages
        (slots, hkv, c, pmax * ps),  # the reshaped logical KV view
    }
    for r in rows:
        shapes.add((l, slots, hkv, r, c))  # stacked recent/row buffers
        shapes.add((slots, hkv, r, c))  # one layer's rows
    return frozenset(shapes)


def _serving_audit_setup(cfg: ExperimentConfig, *, slots: int,
                         page_size: int, shrink: bool,
                         quant: bool = False,
                         kv_quant: bool = False,
                         mesh_shape: tp.Optional[tp.Mapping[str, int]] = None):
    """Shared geometry for the three serving audits (decode window +
    prefill chunk + speculative verify): audit-shrunk model config,
    bf16-cast model, page pool and slot logits. ONE definition so the
    compiled programs can never silently audit different geometries.
    ``quant=True`` converts the model to the int8 quantized serving
    pytree (midgpt_tpu.quant) and additionally returns its weight-matrix
    shapes — what the no-dequant-materialization rule is parameterized
    with (empty when quant is off).

    ``mesh_shape`` (e.g. ``{"tensor": 2}``, the --mesh-shape CLI flag)
    compiles the SHARDED programs instead: a multi-device mesh over the
    first prod(axes) devices, model committed per GPT_PARAM_RULES (incl.
    the QuantLinear scale rules), pool KV-head-sharded, logits
    vocab-sharded — exactly how ``ServingEngine(mesh=...)`` places them,
    so the audit sees the partitioned HLO the sharded engine launches.
    The returned ``prog_mesh`` is the mesh to hand the program factories
    (None for the classic single-chip audit); with quant the returned
    weight shapes are the per-SHARD local shapes (what the partitioned
    module actually contains)."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.pytree import cast_floating
    from midgpt_tpu.serving.paged import PagedKVPool, pages_needed

    model_cfg = cfg.model
    if shrink:
        model_cfg = _dc.replace(
            model_cfg, n_layer=2, block_size=256, vocab_size=1024,
            remat="none", scan_unroll=1,
        )
    axes = {"replica": 1, "fsdp": 1, "sequence": 1, "tensor": 1}
    if mesh_shape:
        unknown = set(mesh_shape) - set(axes)
        assert not unknown, f"unknown serving mesh axes {sorted(unknown)}"
        axes.update(mesh_shape)
    n_dev = 1
    for v in axes.values():
        n_dev *= v
    assert n_dev <= len(jax.devices()), (
        f"mesh shape {axes} needs {n_dev} devices, have "
        f"{len(jax.devices())}"
    )
    mesh = create_mesh(MeshConfig(**axes), devices=jax.devices()[:n_dev])
    model = cast_floating(GPT.init(jax.random.PRNGKey(0), model_cfg), jnp.bfloat16)
    if quant:
        from midgpt_tpu.quant import quantize_model

        model = quantize_model(model)
    prog_mesh = None
    pmax = pages_needed(model_cfg.block_size, page_size)
    if mesh_shape:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from midgpt_tpu.models.gpt import GPT_PARAM_RULES
        from midgpt_tpu.parallel.sharding import param_shardings

        tp_sz = axes["tensor"]
        assert model_cfg.kv_heads % tp_sz == 0, (
            f"tensor={tp_sz} must divide kv_heads {model_cfg.kv_heads}"
        )
        assert model_cfg.vocab_size % tp_sz == 0, (
            f"tensor={tp_sz} must divide vocab {model_cfg.vocab_size}"
        )
        model = jax.device_put(
            model, param_shardings(mesh, model, GPT_PARAM_RULES)
        )
        pool = PagedKVPool.init(
            model_cfg, slots * pmax, page_size, mesh=mesh,
            kv_quant="int8" if kv_quant else None,
        )
        logits = jax.device_put(
            jnp.zeros((slots, model_cfg.vocab_size), jnp.float32),
            NamedSharding(mesh, P(None, "tensor")),
        )
        prog_mesh = mesh
    else:
        pool = PagedKVPool.init(
            model_cfg, slots * pmax, page_size,
            kv_quant="int8" if kv_quant else None,
        )
        logits = jnp.zeros((slots, model_cfg.vocab_size), jnp.float32)
    wshapes: tp.FrozenSet[tp.Tuple[int, ...]] = frozenset()
    if quant:
        from midgpt_tpu.quant import quant_weight_shapes

        # after device_put: sharded leaves yield per-shard local shapes
        wshapes = quant_weight_shapes(model)
    return model_cfg, mesh, model, pmax, pool, logits, wshapes, prog_mesh


def serving_stream_keys(model, pool, logits) -> tp.Dict[str, tp.FrozenSet]:
    """(dtype, shape) classification keys for the HBM traffic auditor
    (:func:`midgpt_tpu.analysis.traffic.traffic_report`), built from the
    live trees a serving program was compiled against — so the auditor
    classifies exactly the buffers the program streams, not a guess at
    them. Shard-LOCAL shapes under a mesh (what the partitioned HLO's
    entry interface contains)."""
    import jax

    from midgpt_tpu.analysis.traffic import hlo_dtype

    def local_key(arr) -> tp.Tuple[str, tp.Tuple[int, ...]]:
        sharding = getattr(arr, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = tuple(int(d) for d in sharding.shard_shape(arr.shape))
        else:
            shape = tuple(int(d) for d in arr.shape)
        return (hlo_dtype(arr.dtype), shape)

    return {
        "weights": frozenset(
            local_key(x) for x in jax.tree.leaves(model)
        ),
        "kv": frozenset(local_key(x) for x in jax.tree.leaves(pool)),
        "logits": frozenset([local_key(logits)]),
    }


def _serving_rules(
    wshapes,
    payload_shapes: tp.Optional[tp.FrozenSet] = None,
    slots: tp.Optional[int] = None,
) -> "RuleSet":
    """The serving-invariant ruleset all three program audits share:
    donation-intact + no-host-sync + no-f64, plus
    no-dequant-materialization when the program was compiled against the
    quantized pytree (``wshapes`` non-empty), plus
    no-batch-allgather-in-page-gather when it was compiled on a sharded
    mesh (``payload_shapes`` given — see serving_payload_shapes)."""
    from midgpt_tpu.analysis.rules import (
        DonationIntact,
        NoDequantMaterialization,
        NoF64,
        NoHostSync,
        NoPageGatherAllGather,
        RuleSet,
    )

    rules = [NoF64(), DonationIntact(), NoHostSync()]
    if wshapes:
        rules.append(NoDequantMaterialization(wshapes))
    if payload_shapes:
        rules.append(NoPageGatherAllGather(payload_shapes, slots or 1))
    return RuleSet(rules)


def _serving_traffic(
    program: str,
    analysis: StepAnalysis,
    stream_keys: tp.Mapping[str, tp.FrozenSet],
    *,
    window_steps: int,
):
    """Build the HBM :class:`~midgpt_tpu.analysis.traffic.TrafficReport`
    for one compiled serving program: entry-interface streams classified
    against the live trees' keys, plus the per-dispatch collective wire
    bytes (sharded geometries) so a pool-payload regather moves a budget
    number, not just an HLO shape."""
    from midgpt_tpu.analysis.traffic import traffic_report

    comms = sum(c.traffic_bytes for c in analysis.collectives)
    return traffic_report(
        analysis.hlo,
        program=program,
        stream_keys=stream_keys,
        window_steps=window_steps,
        comms_bytes=comms,
    )


def compile_decode_window(
    cfg: ExperimentConfig,
    *,
    slots: int = 4,
    window: int = 4,
    page_size: int = 16,
    shrink: bool = True,
    quant: bool = False,
    kv_quant: bool = False,
    layer_scan: str = "off",
    mesh_shape: tp.Optional[tp.Mapping[str, int]] = None,
):
    """Compile the serving engine's fused K-step decode window
    (``midgpt_tpu.serving.make_decode_window``) for ``cfg``'s model —
    the program the engine launches once per K generated tokens. Returns
    ``(hlo_text, mesh, donated_leaves, audited_block_size,
    quant_weight_shapes)`` — the block size is the AUDITED model's
    (shrunk when ``shrink``), which is the geometry the HLO was actually
    compiled at; the weight shapes are empty unless ``quant``.

    Audited for the same two regressions the K-step train window is:
    donation staying intact across the window (pool + logits buffers must
    alias input->output, or every window holds two copies of the KV pool
    in HBM) and no host sync hiding inside it (one stray callback stalls
    all K decode steps per launch). ``quant=True`` compiles the int8
    quantized weight path instead (midgpt_tpu.quant) for the
    no-dequant-materialization rule. ``mesh_shape`` (e.g.
    ``{"tensor": 2}``) compiles the TP-SHARDED program the mesh-aware
    engine launches — head-sharded pool, vocab-sharded logits — and
    additionally returns the full pool payload shapes the
    no-batch-allgather-in-page-gather rule needs."""
    import jax
    import numpy as np_

    from midgpt_tpu.serving.engine import make_decode_window

    model_cfg, mesh, model, pmax, pool, logits, wshapes, prog_mesh = (
        _serving_audit_setup(
            cfg, slots=slots, page_size=page_size, shrink=shrink,
            quant=quant, kv_quant=kv_quant, mesh_shape=mesh_shape,
        )
    )
    window_fn = make_decode_window(
        model, slots=slots, window=window, pmax=pmax,
        rope_len=model_cfg.block_size, mesh=prog_mesh,
        layer_scan=layer_scan,
    )
    i32 = lambda *shape: np_.zeros(shape, np_.int32)  # noqa: E731
    hlo = window_fn.lower(
        model, pool, logits, i32(slots, pmax), i32(slots),
        np_.zeros((slots,), bool), i32(slots), i32(slots), i32(slots),
        i32(slots), jax.random.PRNGKey(1),
    ).compile().as_text()
    donated_leaves = len(jax.tree.leaves((pool, logits)))
    payload = (
        serving_payload_shapes(
            model_cfg, slots=slots, page_size=page_size,
            num_pages=pool.num_pages, rows=(window,),
        )
        if prog_mesh is not None
        else None
    )
    # return the AUDITED model's block size: with shrink it differs from
    # cfg's, and geometry-dependent rules must see the compiled program's
    keys = serving_stream_keys(model, pool, logits)
    return (
        hlo, mesh, donated_leaves, model_cfg.block_size, wshapes, payload,
        keys,
    )


def audit_decode_window(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    *,
    slots: int = 4,
    window: int = 4,
    page_size: int = 16,
    shrink: bool = True,
    quant: bool = False,
    kv_quant: bool = False,
    layer_scan: str = "off",
    mesh_shape: tp.Optional[tp.Mapping[str, int]] = None,
    traffic: bool = False,
):
    """One-call serving audit: compile the fused decode window and check
    the serving invariants (donation-intact, no-host-sync, no-f64 —
    plus no-dequant-materialization when ``quant``, plus
    no-batch-allgather-in-page-gather when ``mesh_shape`` compiles the
    sharded program)."""
    cfg = (
        get_config(name_or_cfg)
        if isinstance(name_or_cfg, str)
        else name_or_cfg
    )
    hlo, mesh, donated, block, wshapes, payload, keys = (
        compile_decode_window(
            cfg, slots=slots, window=window, page_size=page_size,
            shrink=shrink, quant=quant, kv_quant=kv_quant,
            layer_scan=layer_scan, mesh_shape=mesh_shape,
        )
    )
    analysis = StepAnalysis.from_text(
        hlo,
        hlo_mod.MeshInfo.from_mesh(mesh, num_slices=1),
        global_batch=slots,
        block=block,
        donated_leaves=donated,
    )
    report = _serving_rules(wshapes, payload, slots).evaluate(analysis)
    if traffic:
        return analysis, report, _serving_traffic(
            "decode_window", analysis, keys, window_steps=window
        )
    return analysis, report


def compile_prefill_chunk(
    cfg: ExperimentConfig,
    *,
    chunk_len: int = 64,
    page_size: int = 16,
    shrink: bool = True,
    quant: bool = False,
    kv_quant: bool = False,
    layer_scan: str = "off",
    prefill_sp: str = "off",
    mesh_shape: tp.Optional[tp.Mapping[str, int]] = None,
):
    """Compile the serving engine's prefill-chunk program
    (``midgpt_tpu.serving.make_prefill_chunk_program``) — the suffix-only
    prefill the prefix cache and chunked-prefill scheduler dispatch
    between decode windows. Returns ``(hlo_text, mesh, donated_leaves,
    audited_block_size)``.

    Audited for the same serving invariants as the decode window: pool +
    logits donation intact (under chunked prefill a chunk runs between
    every pair of decode windows — an un-aliased pool would double KV
    HBM on the hot path) and no host sync inside the compiled chunk. The
    block table the chunk reads through may alias pages shared with
    other live slots (copy-on-write guarantees they are read-only); the
    compiled program is identical either way, which is exactly why the
    audit covers the sharing case.

    ``prefill_sp="on"`` compiles the SEQUENCE-PARALLEL chunk program
    (``ServingEngine(prefill_sp=...)``): the chunk's replicated row
    segments shard over the 'tensor' axis, so with --traffic the SP
    combine collectives land in ``comms`` — the budget cell for the
    ``prefill_chunk_sp`` program pins that wire traffic (and nothing
    else) via its ``comms_max``. Requires a sharded ``mesh_shape`` with
    tensor > 1 (single-chip SP would be a no-op audit)."""
    import jax
    import numpy as np_

    from midgpt_tpu.serving.engine import make_prefill_chunk_program

    assert prefill_sp in ("off", "on"), prefill_sp
    assert prefill_sp == "off" or (
        mesh_shape and mesh_shape.get("tensor", 1) > 1
    ), "prefill_sp='on' audits need a --mesh-shape with tensor > 1"
    model_cfg, mesh, model, pmax, pool, logits, wshapes, prog_mesh = (
        _serving_audit_setup(
            cfg, slots=4, page_size=page_size, shrink=shrink, quant=quant,
            kv_quant=kv_quant, mesh_shape=mesh_shape,
        )
    )
    assert chunk_len <= model_cfg.block_size, (chunk_len, model_cfg.block_size)
    chunk_fn = make_prefill_chunk_program(
        model, chunk_len=chunk_len, pmax=pmax,
        rope_len=model_cfg.block_size, mesh=prog_mesh,
        layer_scan=layer_scan, prefill_sp=prefill_sp,
    )
    i32 = lambda *shape: np_.zeros(shape, np_.int32)  # noqa: E731
    hlo = chunk_fn.lower(
        model, pool, logits, i32(), i32(1, chunk_len), i32(), i32(),
        i32(pmax),
    ).compile().as_text()
    donated_leaves = len(jax.tree.leaves((pool, logits)))
    payload = (
        serving_payload_shapes(
            model_cfg, slots=1, page_size=page_size,
            num_pages=pool.num_pages, rows=(chunk_len,),
        )
        if prog_mesh is not None
        else None
    )
    keys = serving_stream_keys(model, pool, logits)
    return (
        hlo, mesh, donated_leaves, model_cfg.block_size, wshapes, payload,
        keys,
    )


def audit_prefill_chunk(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    *,
    chunk_len: int = 64,
    page_size: int = 16,
    shrink: bool = True,
    quant: bool = False,
    kv_quant: bool = False,
    layer_scan: str = "off",
    prefill_sp: str = "off",
    mesh_shape: tp.Optional[tp.Mapping[str, int]] = None,
    traffic: bool = False,
):
    """One-call audit of the prefill-chunk program: donation-intact,
    no-host-sync, no-f64 (+ no-dequant-materialization when ``quant``)
    — the CI serving-audit job runs this next to
    :func:`audit_decode_window` so a window containing a mid-window
    prefill chunk (the chunked-prefill steady state) is covered end to
    end."""
    cfg = (
        get_config(name_or_cfg)
        if isinstance(name_or_cfg, str)
        else name_or_cfg
    )
    hlo, mesh, donated, block, wshapes, payload, keys = (
        compile_prefill_chunk(
            cfg, chunk_len=chunk_len, page_size=page_size, shrink=shrink,
            quant=quant, kv_quant=kv_quant, layer_scan=layer_scan,
            prefill_sp=prefill_sp, mesh_shape=mesh_shape,
        )
    )
    analysis = StepAnalysis.from_text(
        hlo,
        hlo_mod.MeshInfo.from_mesh(mesh, num_slices=1),
        global_batch=1,
        block=block,
        donated_leaves=donated,
    )
    report = _serving_rules(wshapes, payload, 1).evaluate(analysis)
    program = (
        "prefill_chunk_sp" if prefill_sp == "on" else "prefill_chunk"
    )
    if traffic:
        return analysis, report, _serving_traffic(
            program, analysis, keys, window_steps=1
        )
    return analysis, report


def compile_verify_program(
    cfg: ExperimentConfig,
    *,
    slots: int = 4,
    spec_len: int = 4,
    page_size: int = 16,
    shrink: bool = True,
    quant: bool = False,
    kv_quant: bool = False,
    layer_scan: str = "off",
    mesh_shape: tp.Optional[tp.Mapping[str, int]] = None,
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
):
    """Compile the serving engine's speculative VERIFY program
    (``midgpt_tpu.serving.make_verify_program``) — the single dispatch
    that scores all slots' ``spec_len + 1`` candidate rows against the
    resident pages, decides acceptance (greedy argmax at temperature 0,
    rejection sampling above it — the sampled signature appends only the
    per-slot request seeds and the base PRNG key, so the audited entry
    traffic is the greedy program's plus two control-stream scalars
    per slot), and folds only accepted rows' K/V into the pool. Returns
    ``(hlo_text, mesh, donated_leaves, audited_block_size)``.

    Audited for the same serving invariants as the decode window and the
    prefill chunk: pool + logits donation intact (with speculation on,
    EVERY decode dispatch is a verify dispatch — an un-aliased pool
    would double KV HBM on the hottest path in the engine) and no host
    sync inside the compiled program (drafting is host-side but arrives
    as ordinary inputs; acceptance, watermark, rollback and the page
    write are all in-program — one stray callback would stall every
    speculated token)."""
    import jax
    import numpy as np_

    from midgpt_tpu.serving.engine import make_verify_program

    model_cfg, mesh, model, pmax, pool, logits, wshapes, prog_mesh = (
        _serving_audit_setup(
            cfg, slots=slots, page_size=page_size, shrink=shrink,
            quant=quant, kv_quant=kv_quant, mesh_shape=mesh_shape,
        )
    )
    verify_fn = make_verify_program(
        model, slots=slots, spec_len=spec_len, pmax=pmax,
        rope_len=model_cfg.block_size, temperature=temperature,
        top_k=top_k, mesh=prog_mesh, layer_scan=layer_scan,
    )
    i32 = lambda *shape: np_.zeros(shape, np_.int32)  # noqa: E731
    lower_args = [
        model, pool, logits, i32(slots, pmax), i32(slots),
        np_.zeros((slots,), bool), i32(slots), i32(slots), i32(slots),
        i32(slots, spec_len), i32(slots),
    ]
    if temperature > 0.0:
        lower_args += [
            i32(slots), np_.zeros((2,), np_.uint32),
        ]
    hlo = verify_fn.lower(*lower_args).compile().as_text()
    donated_leaves = len(jax.tree.leaves((pool, logits)))
    payload = (
        serving_payload_shapes(
            model_cfg, slots=slots, page_size=page_size,
            num_pages=pool.num_pages, rows=(spec_len + 1,),
        )
        if prog_mesh is not None
        else None
    )
    keys = serving_stream_keys(model, pool, logits)
    return (
        hlo, mesh, donated_leaves, model_cfg.block_size, wshapes, payload,
        keys,
    )


def audit_verify_program(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    *,
    slots: int = 4,
    spec_len: int = 4,
    page_size: int = 16,
    shrink: bool = True,
    quant: bool = False,
    kv_quant: bool = False,
    layer_scan: str = "off",
    mesh_shape: tp.Optional[tp.Mapping[str, int]] = None,
    traffic: bool = False,
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
):
    """One-call audit of the speculative verify program: donation-intact,
    no-host-sync, no-f64 (+ no-dequant-materialization when ``quant``)
    — the CI serving-audit job runs this next to
    :func:`audit_decode_window` and :func:`audit_prefill_chunk` so all
    three serving hot-path programs are gated on one geometry.
    ``temperature > 0`` audits the rejection-sampling verify program
    against the SAME budgets: sampled acceptance must not cost a launch,
    a host sync, or a traffic band."""
    cfg = (
        get_config(name_or_cfg)
        if isinstance(name_or_cfg, str)
        else name_or_cfg
    )
    hlo, mesh, donated, block, wshapes, payload, keys = (
        compile_verify_program(
            cfg, slots=slots, spec_len=spec_len, page_size=page_size,
            shrink=shrink, quant=quant, kv_quant=kv_quant,
            layer_scan=layer_scan, mesh_shape=mesh_shape,
            temperature=temperature, top_k=top_k,
        )
    )
    analysis = StepAnalysis.from_text(
        hlo,
        hlo_mod.MeshInfo.from_mesh(mesh, num_slices=1),
        global_batch=slots,
        block=block,
        donated_leaves=donated,
    )
    report = _serving_rules(wshapes, payload, slots).evaluate(analysis)
    if traffic:
        return analysis, report, _serving_traffic(
            "verify_program", analysis, keys, window_steps=1
        )
    return analysis, report


def prove_serving_choreography(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    *,
    slots: int = 4,
    window: int = 2,
    spec_len: int = 2,
    chunk_len: int = 16,
    page_size: int = 16,
    quant: bool = False,
    kv_quant: bool = False,
    paged_kernel: str = "xla",
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
):
    """Run the arithmetic-choreography prover
    (:mod:`midgpt_tpu.analysis.choreo`) over the three serving programs
    of ``cfg``'s model family: trace each program to a jaxpr (through
    the very jitted callables the engine launches), slice out the
    attention and lm-head subgraphs, normalize them into op-and-dtype
    traces, and prove the three contracts — verify mirrors decode op
    for op (PR 5), the prefill chunk's softmax core mirrors
    ``naive_attention`` (PR 4), and the shared arithmetic (f32 softmax
    and accumulation, mask-before-scale, one lm-head choreography)
    holds everywhere. Returns a :class:`~midgpt_tpu.analysis.choreo.\
ChoreoReport`.

    Traced at choreography size (2 layers, block 64, vocab 128): the
    contract is per-layer-identical by construction (asserted by the
    extractor), so depth and width add nothing but trace time. No
    compilation happens — a full proof is seconds on CPU. ``quant``
    proves the int8 WEIGHT path instead (same contracts; the lm-head
    check additionally pins the dequant epilogue everywhere).
    ``kv_quant`` traces the programs against an int8 KV pool and
    additionally proves every program carries the pool's
    codes-times-scale dequant. ``paged_kernel="pallas"`` traces the
    Pallas ragged-walk programs: the kernel appears as one contract
    node in the attention traces and its BODY's softmax signature is
    what the decode/verify checks then compare — a bf16-accumulating
    kernel variant fails exactly like a bf16-accumulating XLA edit.
    ``temperature > 0`` traces the SAMPLED programs instead and appends
    the four sampled-verify checks
    (:func:`~midgpt_tpu.analysis.choreo.prove_sampled_choreography`):
    the verify row-0 categorical mirrors the decode window's sampler op
    for op, the rejection-sampling acceptance compare runs in f32, and
    the residual renormalization + target softmax run in f32."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from midgpt_tpu.analysis.choreo import (
        ChoreoReport,
        extract_choreography,
        extract_sampler_choreography,
        prove_choreography,
        prove_sampled_choreography,
    )
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.ops.attention import naive_attention
    from midgpt_tpu.pytree import cast_floating
    from midgpt_tpu.serving.engine import trace_serving_programs

    cfg = (
        get_config(name_or_cfg)
        if isinstance(name_or_cfg, str)
        else name_or_cfg
    )
    model_cfg = _dc.replace(
        cfg.model, n_layer=2, block_size=64, vocab_size=128,
        remat="none", scan_unroll=1,
    )
    model = cast_floating(
        GPT.init(jax.random.PRNGKey(0), model_cfg), jnp.bfloat16
    )
    if quant:
        from midgpt_tpu.quant import quantize_model

        model = quantize_model(model)
    jaxprs = trace_serving_programs(
        model, slots=slots, window=window, spec_len=spec_len,
        chunk_len=chunk_len, page_size=page_size,
        kv_quant="int8" if kv_quant else None, paged_kernel=paged_kernel,
        temperature=temperature, top_k=top_k,
    )

    # the naive reference: what the monolithic prefill / training
    # forward computes (ops.attention docstring: the correctness
    # oracle). q/k/v are derived from the traced input by an identity
    # multiply so the score contraction's operands are computed values,
    # not entry parameters (the prover classifies parameter-operand
    # contractions as weight projections).
    h, hkv, c = model_cfg.n_head, model_cfg.kv_heads, model_cfg.head_dim
    t = 8

    def naive_ref(x):
        one = jnp.asarray(1.0, x.dtype)
        q = x[:, :h] * one
        k = x[:, h : h + hkv] * one
        v = x[:, h + hkv :] * one
        return naive_attention(q, k, v, causal=True)

    naive_jaxpr = jax.make_jaxpr(naive_ref)(
        jax.ShapeDtypeStruct((1, h + 2 * hkv, t, c), jnp.bfloat16)
    )
    report = prove_choreography(
        decode=extract_choreography("decode_window", jaxprs["decode_window"]),
        prefill=extract_choreography("prefill_chunk", jaxprs["prefill_chunk"]),
        verify=extract_choreography("verify", jaxprs["verify"]),
        naive=extract_choreography("naive_reference", naive_jaxpr),
        expect_kv_dequant=kv_quant,
    )
    if temperature > 0.0:
        sampled = prove_sampled_choreography(
            extract_sampler_choreography(
                "decode_window", jaxprs["decode_window"]
            ),
            extract_sampler_choreography("verify", jaxprs["verify"]),
        )
        report = ChoreoReport(
            checks=report.checks + sampled, programs=report.programs
        )
    return report


def prove_sp_prefill_choreography(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    *,
    quant: bool = False,
    kv_quant: bool = False,
    layer_scan: str = "off",
    tp_size: int = 2,
    chunk_len: int = 16,
    page_size: int = 16,
):
    """The sequence-parallel prefill leg of the choreography suite:
    trace the prefill-chunk program TWICE on one ``tensor=tp_size`` mesh
    — ``prefill_sp`` off and on, through the very jitted factory the
    engine launches — and prove the two normalized traces identical op
    for op (:func:`~midgpt_tpu.analysis.choreo.prove_sp_choreography`).
    SP row-shards the chunk's replicated segments over 'tensor' with
    ``sharding_constraint`` ops only; any arithmetic difference between
    the traces is a bitwise-identity hazard (the landing gate for
    ``ServingEngine(prefill_sp=...)``). Tracing only — no compilation;
    needs ``tp_size`` visible devices for the mesh the constraints
    name."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from midgpt_tpu.analysis.choreo import (
        extract_choreography,
        prove_sp_choreography,
    )
    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.pytree import cast_floating
    from midgpt_tpu.serving.engine import trace_serving_programs

    cfg = (
        get_config(name_or_cfg)
        if isinstance(name_or_cfg, str)
        else name_or_cfg
    )
    model_cfg = _dc.replace(
        cfg.model, n_layer=2, block_size=64, vocab_size=128,
        remat="none", scan_unroll=1,
    )
    assert model_cfg.kv_heads % tp_size == 0, (
        f"tensor={tp_size} must divide kv_heads {model_cfg.kv_heads}"
    )
    model = cast_floating(
        GPT.init(jax.random.PRNGKey(0), model_cfg), jnp.bfloat16
    )
    if quant:
        from midgpt_tpu.quant import quantize_model

        model = quantize_model(model)
    mesh = create_mesh(
        MeshConfig(replica=1, fsdp=1, sequence=1, tensor=tp_size),
        devices=jax.devices()[:tp_size],
    )
    kw = dict(
        slots=4, window=2, spec_len=2, chunk_len=chunk_len,
        page_size=page_size, kv_quant="int8" if kv_quant else None,
        layer_scan=layer_scan, mesh=mesh,
    )
    off = trace_serving_programs(model, prefill_sp="off", **kw)
    on = trace_serving_programs(model, prefill_sp="on", **kw)
    return prove_sp_choreography(
        extract_choreography("prefill_chunk", off["prefill_chunk"]),
        extract_choreography("prefill_chunk_sp", on["prefill_chunk"]),
    )


def prove_scan_equivalence(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    *,
    quant: bool = False,
    kv_quant: bool = False,
    paged_kernel: str = "xla",
    n_layer: int = 3,
):
    """Run the scan-equivalence prover (:mod:`midgpt_tpu.analysis.fusion`)
    over the three serving programs of ``cfg``'s model family: trace
    each program BOTH ways (``layer_scan`` off and on, through the very
    jitted factories the engine launches), prove the unrolled traces
    layer-homogeneous (the fold's legality precondition), and prove the
    fused scan BODY's normalized trace op-for-op equal to the unrolled
    per-layer trace — attention region, full layer segment, softmax
    signature, lm-head choreography. Returns a
    :class:`~midgpt_tpu.analysis.fusion.FusionReport`.

    Traced at depth 3 (not the choreography size's 2): homogeneity
    needs a TRUE MIDDLE layer — at depth 2 every layer is first or
    last, and a first/last-layer special case would have nothing
    identical to be compared against. No compilation; a full proof of
    all six traces is seconds on CPU."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from midgpt_tpu.analysis.fusion import prove_scan_fusion
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.pytree import cast_floating
    from midgpt_tpu.serving.engine import trace_serving_programs

    cfg = (
        get_config(name_or_cfg)
        if isinstance(name_or_cfg, str)
        else name_or_cfg
    )
    model_cfg = _dc.replace(
        cfg.model, n_layer=n_layer, block_size=64, vocab_size=128,
        remat="none", scan_unroll=1,
    )
    model = cast_floating(
        GPT.init(jax.random.PRNGKey(0), model_cfg), jnp.bfloat16
    )
    if quant:
        from midgpt_tpu.quant import quantize_model

        model = quantize_model(model)
    kw = dict(
        slots=4, window=2, spec_len=2, chunk_len=16, page_size=16,
        kv_quant="int8" if kv_quant else None, paged_kernel=paged_kernel,
    )
    off = trace_serving_programs(model, layer_scan="off", **kw)
    on = trace_serving_programs(model, layer_scan="on", **kw)
    return prove_scan_fusion(off, on)


def serving_dispatch_reports(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    *,
    layer_scan: str = "off",
    prefill_sp: str = "off",
    quant: bool = False,
    kv_quant: bool = False,
    paged_kernel: str = "xla",
    slots: int = 4,
    window: int = 4,
    spec_len: int = 4,
    chunk_len: int = 64,
    page_size: int = 16,
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
) -> tp.Dict[str, tp.Any]:
    """Trace the three serving programs at the audit geometry (the same
    n_layer=2 shrink the byte budgets were measured at) and build their
    static :class:`~midgpt_tpu.analysis.dispatch.DispatchReport`\\ s,
    keyed by the budget program names (``decode_window`` /
    ``prefill_chunk`` / ``verify_program``). Launch structure is
    precision-independent (quant/kv-quant change dtypes, not the scan
    nesting) — the flags exist so fault-injection tests can audit any
    cell they traced. ``temperature > 0`` audits the SAMPLED programs
    against the same cells: rejection-sampling acceptance is in-program
    arithmetic and must not change the launch structure.
    ``prefill_sp="on"`` additionally traces the sequence-parallel chunk
    program on a tensor=2 mesh and reports it as ``prefill_chunk_sp``:
    SP is resharding only, so its launch structure must equal the plain
    chunk's (its own DISPATCH_BUDGETS cells pin exactly that)."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from midgpt_tpu.analysis.dispatch import dispatch_report
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.pytree import cast_floating
    from midgpt_tpu.serving.engine import trace_serving_programs

    cfg = (
        get_config(name_or_cfg)
        if isinstance(name_or_cfg, str)
        else name_or_cfg
    )
    model_cfg = _dc.replace(
        cfg.model, n_layer=2, block_size=256, vocab_size=1024,
        remat="none", scan_unroll=1,
    )
    model = cast_floating(
        GPT.init(jax.random.PRNGKey(0), model_cfg), jnp.bfloat16
    )
    if quant:
        from midgpt_tpu.quant import quantize_model

        model = quantize_model(model)
    jaxprs = trace_serving_programs(
        model, slots=slots, window=window, spec_len=spec_len,
        chunk_len=chunk_len, page_size=page_size,
        kv_quant="int8" if kv_quant else None,
        paged_kernel=paged_kernel, layer_scan=layer_scan,
        temperature=temperature, top_k=top_k,
    )
    out = {
        "decode_window": dispatch_report(
            jaxprs["decode_window"], program="decode_window",
            window_steps=window,
        ),
        "prefill_chunk": dispatch_report(
            jaxprs["prefill_chunk"], program="prefill_chunk",
        ),
        "verify_program": dispatch_report(
            jaxprs["verify"], program="verify_program",
        ),
    }
    if prefill_sp == "on":
        from midgpt_tpu.config import MeshConfig
        from midgpt_tpu.parallel.mesh import create_mesh

        assert model_cfg.kv_heads % 2 == 0, model_cfg.kv_heads
        mesh = create_mesh(
            MeshConfig(replica=1, fsdp=1, sequence=1, tensor=2),
            devices=jax.devices()[:2],
        )
        sp_jaxprs = trace_serving_programs(
            model, slots=slots, window=window, spec_len=spec_len,
            chunk_len=chunk_len, page_size=page_size,
            kv_quant="int8" if kv_quant else None,
            paged_kernel=paged_kernel, layer_scan=layer_scan,
            prefill_sp="on", mesh=mesh,
            temperature=temperature, top_k=top_k,
        )
        out["prefill_chunk_sp"] = dispatch_report(
            sp_jaxprs["prefill_chunk"], program="prefill_chunk_sp",
        )
    return out


def audit_serving_dispatch(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    *,
    layer_scan: str = "off",
    **kw,
) -> tp.Tuple[tp.Dict[str, tp.Any], tp.List[str]]:
    """One-call dispatch audit: trace the three programs with the given
    ``layer_scan`` and gate their launch structure against the
    checked-in :data:`~midgpt_tpu.analysis.budgets.DISPATCH_BUDGETS`
    cells for that value. Returns ``(reports, violations)`` — the CI
    serving-choreo job runs this for BOTH values, so a re-unrolled
    fused program (zero byte movement, L× launch structure) fails the
    "on" cells before any hardware sees it."""
    from midgpt_tpu.analysis.budgets import (
        check_dispatch_budget,
        dispatch_budget_for,
    )

    reports = serving_dispatch_reports(
        name_or_cfg, layer_scan=layer_scan, **kw
    )
    violations: tp.List[str] = []
    for name, rep in reports.items():
        budget = dispatch_budget_for(name, layer_scan)
        if budget is not None:
            violations.extend(check_dispatch_budget(rep, budget))
    return reports, violations


def train_step_comms_summary(
    cfg: ExperimentConfig, *, window_steps: tp.Optional[int] = None
) -> tp.Dict[str, tp.Any]:
    """Flat scalar comms summary for an already-benchmarked config —
    bench.py attaches this to its one-JSON-line record. Compiles the
    program the bench actually dispatched: the fused K-step window when
    ``window_steps > 1`` (bench's scan dispatch mode), the single step
    otherwise (the executable cache makes either a cache hit right
    after the bench rung compiled the same program). Per-axis byte
    splits are flattened into ``comms_axis_<axis>_bytes_per_step``
    scalars ('+' -> '_') so the one-line JSON record stays flat."""
    from midgpt_tpu.analysis.cost import cost_report

    k = window_steps if window_steps is not None else 1
    if k > 1:
        hlo, mesh, donated, _ = compile_train_window(cfg, k)
        analysis = StepAnalysis.from_text(
            hlo,
            hlo_mod.MeshInfo.from_mesh(
                mesh, num_slices=cfg.mesh.num_slices
            ),
            global_batch=cfg.batch_size,
            block=cfg.model.block_size,
            donated_leaves=donated,
        )
    else:
        analysis = analyze_train_step(cfg, shrink=False)
    rep = cost_report(analysis)
    out: tp.Dict[str, tp.Any] = {
        "comms_traffic_bytes_per_step": rep["value"],
        "comms_dcn_bytes_per_step": rep["dcn_bytes"],
        "comms_ici_bytes_per_step": rep["ici_bytes"],
        "comms_collective_count": rep["collective_count"],
        "comms_window_steps": k,
    }
    for axis, b in sorted(dict(rep["by_axis"]).items()):
        out[f"comms_axis_{axis.replace('+', '_')}_bytes_per_step"] = b
    return out


# ---------------------------------------------------------------------------
# TRAIN-side verification suite (analysis --train-audit): precision
# choreography prover + traffic cells + window dispatch gate for the
# fused K-step train window, at the checked-in audit geometry matrix.
# ---------------------------------------------------------------------------


def shrink_for_train_audit(
    cfg: ExperimentConfig,
    geometry: str,
    *,
    remat: str = "none",
) -> ExperimentConfig:
    """Audit-sized variant of ``cfg`` pinned to the train budget cell
    geometry (:data:`~midgpt_tpu.analysis.budgets.TRAIN_AUDIT_GEOMETRY`
    × :data:`~midgpt_tpu.analysis.budgets.TRAIN_AUDIT_GEOMETRIES`):
    the real trainer's code paths (grad accumulation G=2, fused window,
    layer scan) shrunk so every mesh geometry in the matrix compiles in
    seconds on the 8-device CPU virtual mesh. ``batch_size`` 16 keeps
    the microbatch divisible by every batch sharding in the matrix
    (8-way fsdp, 2×4 replica×fsdp, 4-way fsdp under tensor=2)."""
    from midgpt_tpu.analysis.budgets import (
        TRAIN_AUDIT_GEOMETRIES,
        TRAIN_AUDIT_GEOMETRY,
    )
    from midgpt_tpu.config import MeshConfig

    g = TRAIN_AUDIT_GEOMETRY
    model = dataclasses.replace(
        cfg.model,
        n_layer=g["n_layer"],
        block_size=g["block_size"],
        vocab_size=g["vocab_size"],
        remat=remat,
        scan_unroll=1,
    )
    return dataclasses.replace(
        cfg,
        model=model,
        batch_size=g["batch_size"],
        g_accum_iters=g["g_accum_iters"],
        loss_chunk=None,
        mesh=MeshConfig(**TRAIN_AUDIT_GEOMETRIES[geometry]),
    )


def compile_train_window(
    cfg: ExperimentConfig,
    window_steps: int,
    *,
    tx=None,
    param_rules=None,
    logical_overrides: tp.Optional[tp.Mapping[str, tp.Any]] = None,
):
    """Compile the fused K-step window UNCONDITIONALLY — unlike
    :func:`compile_train_step`, which picks the per-step jit at
    ``steps_per_dispatch == 1``. The train budget cells gate the window
    program at both K=1 and K=4, and the byte identity between them is
    itself a checked invariant (a window whose bytes grow with K has
    lost the scan). ``tx`` / ``param_rules`` / ``logical_overrides``
    are fault-injection seams (a mis-dtyped optimizer chain, a widened
    sharding spec); production callers leave them None.

    Returns ``(hlo_text, mesh, donated_leaves, aliased_leaves)`` —
    the last is the count of distinct entry parameters the compiled
    executable input/output-aliases (the donation accounting)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_window

    mesh = create_mesh(cfg.mesh)
    if tx is None:
        tx, _ = make_optimizer(cfg)
    rules_kw = {} if param_rules is None else {"param_rules": param_rules}
    with override_logical_rules(logical_overrides):
        state = init_state(
            cfg, mesh, tx, jax.random.PRNGKey(0), abstract=True, **rules_kw
        )
        step = make_train_window(cfg, tx, mesh, window_steps, **rules_kw)
        x = np.zeros(
            (window_steps, cfg.g_accum_iters, cfg.microbatch_size,
             cfg.model.block_size),
            np.int32,
        )
        xg = make_global_array(x, mesh, P(None, *BATCH_SPEC_AXES))
        hlo = step.lower(
            state, xg, xg, jax.random.PRNGKey(1)
        ).compile().as_text()
    donated = len(jax.tree.leaves(state))
    aliased = len({
        e.param_number for e in hlo_mod.parse_input_output_alias(hlo)
    })
    return hlo, mesh, donated, aliased


def trace_train_window(
    cfg: ExperimentConfig,
    window_steps: int,
    *,
    mesh=None,
    tx=None,
    use_cache: bool = True,
):
    """Trace (``jax.make_jaxpr``) + ``jax.eval_shape`` the fused window
    program. ``use_cache=True`` resolves it through
    ``train.get_train_window`` — the very cache the trainer launches
    from, so the proof covers the shipped lookup path, not a
    reconstruction. Fault-injection callers pass ``use_cache=False``
    (plus ``tx``) to build a poisoned window via ``make_train_window``
    without polluting the shared cache. Returns
    ``(closed_jaxpr, (new_state, aux) shape tree)``."""
    import jax
    import jax.numpy as jnp

    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.train import (
        get_train_window,
        init_state,
        make_optimizer,
        make_train_window,
    )

    if mesh is None:
        mesh = create_mesh(cfg.mesh)
    if tx is None:
        tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0), abstract=True)
    xs = jax.ShapeDtypeStruct(
        (window_steps, cfg.g_accum_iters, cfg.microbatch_size,
         cfg.model.block_size),
        jnp.int32,
    )
    if use_cache:
        prog = get_train_window(cfg, mesh, window_steps)
    else:
        prog = make_train_window(cfg, tx, mesh, window_steps)
    key = jax.random.PRNGKey(1)
    closed = jax.make_jaxpr(prog)(state, xs, xs, key)
    out_tree = jax.eval_shape(prog, state, xs, xs, key)
    return closed, out_tree


def prove_train_window_choreography(
    cfg: ExperimentConfig,
    geometry: str,
    window_steps: int,
):
    """Run the mixed-precision choreography prover on the REAL cached
    window program at the audit geometry: traces the ``remat="none"``
    leg through ``train.get_train_window`` plus a ``remat="full"`` leg
    for the recompute-structure check. Returns the
    :class:`~midgpt_tpu.analysis.train_choreo.TrainChoreoReport`."""
    from midgpt_tpu.analysis.train_choreo import prove_window_choreography

    base = shrink_for_train_audit(cfg, geometry, remat="none")
    closed, out_tree = trace_train_window(base, window_steps)
    remat_cfg = shrink_for_train_audit(cfg, geometry, remat="full")
    remat_closed, _ = trace_train_window(remat_cfg, window_steps)
    return prove_window_choreography(
        closed,
        out_tree,
        window_steps=window_steps,
        g_accum_iters=base.g_accum_iters,
        remat_closed=remat_closed,
    )


def train_traffic_cell(
    cfg: ExperimentConfig, geometry: str, window_steps: int
) -> tp.Dict[str, tp.Any]:
    """Compile the window at the audit geometry and measure its budget
    cell: ICI/DCN collective wire bytes + the per-mesh-axis split
    (cost.py's ring arithmetic on the compiled HLO), plus the donation
    accounting off the same executable. Keys line up with
    :data:`~midgpt_tpu.analysis.budgets.TRAIN_BUDGETS`."""
    from midgpt_tpu.analysis.cost import cost_report

    audit = shrink_for_train_audit(cfg, geometry)
    hlo, mesh, donated, aliased = compile_train_window(audit, window_steps)
    analysis = StepAnalysis.from_text(
        hlo,
        hlo_mod.MeshInfo.from_mesh(mesh, num_slices=audit.mesh.num_slices),
        global_batch=audit.batch_size,
        block=audit.model.block_size,
        donated_leaves=donated,
    )
    rep = cost_report(analysis)
    return {
        "geometry": geometry,
        "window_steps": window_steps,
        "ici_bytes": rep["ici_bytes"],
        "dcn_bytes": rep["dcn_bytes"],
        "collective_count": rep["collective_count"],
        "by_axis": dict(rep["by_axis"]),
        "donated_leaves": donated,
        "aliased_leaves": aliased,
    }


def train_dispatch_cell(
    cfg: ExperimentConfig, geometry: str, window_steps: int
):
    """Trace-level window dispatch report at the audit geometry (the
    launch-structure half of the train gate; the donation half rides
    the compiled :func:`train_traffic_cell`)."""
    from midgpt_tpu.analysis.dispatch import train_dispatch_report

    audit = shrink_for_train_audit(cfg, geometry)
    closed, _ = trace_train_window(audit, window_steps)
    return train_dispatch_report(
        closed,
        window_steps=window_steps,
        g_accum_iters=audit.g_accum_iters,
    )


def audit_train(
    name_or_cfg: tp.Union[str, ExperimentConfig],
    geometry: str,
    window_steps: tp.Sequence[int] = (1, 4),
) -> tp.Dict[str, tp.Any]:
    """One-call train audit for one mesh geometry: for each K, prove
    the precision choreography on the cached window trace, gate the
    compiled wire bytes against
    :data:`~midgpt_tpu.analysis.budgets.TRAIN_BUDGETS`, and gate the
    launch structure + donation against
    :data:`~midgpt_tpu.analysis.budgets.TRAIN_DISPATCH_BUDGETS`.
    Returns a JSON-able report with a flat ``violations`` list
    (empty = green)."""
    from midgpt_tpu.analysis.budgets import (
        check_train_budget,
        check_train_dispatch_budget,
        train_budget_for,
    )

    cfg = (
        get_config(name_or_cfg)
        if isinstance(name_or_cfg, str)
        else name_or_cfg
    )
    cells: tp.List[tp.Dict[str, tp.Any]] = []
    violations: tp.List[str] = []
    for k in window_steps:
        prover = prove_train_window_choreography(cfg, geometry, k)
        for c in prover.checks:
            if not c.ok:
                violations.append(
                    f"train_window[{geometry}] k={k}: prover check "
                    f"'{c.name}' failed — {c.detail}"
                )
        traffic = train_traffic_cell(cfg, geometry, k)
        budget = train_budget_for(geometry, k)
        if budget is None:
            violations.append(
                f"train_window[{geometry}] k={k}: no checked-in budget "
                "cell — regenerate with --print-budgets"
            )
        else:
            violations.extend(
                f"k={k}: {v}"
                for v in check_train_budget(traffic, budget,
                                            geometry=geometry)
            )
        disp = train_dispatch_cell(cfg, geometry, k)
        violations.extend(
            f"k={k}: {v}"
            for v in check_train_dispatch_budget(
                disp, aliased_leaves=traffic["aliased_leaves"]
            )
        )
        cells.append({
            "window_steps": k,
            "choreography": prover.to_dict(),
            "traffic": traffic,
            "dispatch": disp.to_dict(),
        })
    return {
        "geometry": geometry,
        "ok": not violations,
        "violations": violations,
        "cells": cells,
    }


def prove_telemetry_inert(
    *,
    slots: int = 2,
    window: int = 4,
    page_size: int = 8,
    prefill_chunk: tp.Optional[int] = 4,
    speculate: int = 0,
    max_new: int = 8,
) -> tp.Dict[str, tp.Any]:
    """Prove the serving telemetry layer cannot perturb the dispatch
    pipeline (the ``--telemetry on`` audit leg).

    Telemetry is deliberately NOT a parameter of any serving program
    factory, so the proof is two identities on a pair of engines that
    differ only in ``telemetry=``:

    1. **Program identity** — both engines must resolve to the *same*
       cached jitted callables (``is``, not ``==``). Every audit result
       established for the untraced programs — donation 3/3,
       no-host-sync, traffic + dispatch budgets — then applies verbatim
       to the traced engine, because it launches the very same
       executables.
    2. **Stream identity** — greedy token streams bitwise equal with
       tracing on vs off, and the traced run actually recorded events
       (a vacuously-inert telemetry that never fired would pass 1 for
       the wrong reason).

    The identities are engine-logic properties, independent of model
    size, so the proof runs on a fixed tiny model in seconds — like the
    choreography prover, no compilation of the named config is needed.
    Raises ``AssertionError`` on violation; returns a report dict.
    """
    import jax
    import jax.numpy as jnp

    from midgpt_tpu.config import ModelConfig
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.serving import ServingEngine

    cfg = ModelConfig(
        block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
        dropout=0.0, attn_impl="naive", remat="none",
    )
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    prompts = [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(7 + i), (5 + 3 * i,), 0, cfg.vocab_size
            )
        )
        for i in range(3)
    ]
    kw = dict(
        slots=slots, window=window, page_size=page_size,
        prefill_chunk=prefill_chunk, speculate=speculate,
        temperature=0.0, cache_dtype=jnp.float32,
    )

    def drive(telemetry):
        eng = ServingEngine(model, telemetry=telemetry, **kw)
        rids = [eng.submit(p, max_new, seed=i) for i, p in enumerate(prompts)]
        fin = eng.run()
        return eng, [list(map(int, fin[r].tokens)) for r in rids]

    eng_off, streams_off = drive(None)
    eng_on, streams_on = drive(True)
    checked = []
    for attr in ("_window_fn", "_verify_fn"):
        off_fn, on_fn = getattr(eng_off, attr), getattr(eng_on, attr)
        assert off_fn is on_fn, (
            f"{attr}: tracing selected a different program object — "
            "telemetry leaked into the program cache key"
        )
        if off_fn is not None:
            checked.append(attr)
    for bucket, fn in eng_off._chunk_fns.items():
        assert eng_on._chunk_fns.get(bucket) is fn, (
            f"prefill bucket {bucket}: tracing selected a different "
            "program object"
        )
        checked.append(f"_chunk_fns[{bucket}]")
    assert streams_on == streams_off, (
        "greedy streams diverged with tracing on — telemetry perturbed "
        "the dispatch pipeline"
    )
    n_events = len(eng_on.telemetry.events)
    assert n_events > 0, "traced run recorded no events (vacuous pass)"
    return {
        "ok": True,
        "programs_identical": checked,
        "streams_identical": True,
        "requests": len(prompts),
        "events_recorded": n_events,
        "dispatch_records": len(eng_on.telemetry.dispatches),
    }
