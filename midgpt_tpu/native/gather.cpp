// Native batch gather for the token-stream data pipeline.
//
// The reference's get_batch (/root/reference/src/train.py:56-66) gathers
// block_size windows from a memmapped uint16 stream with numpy fancy
// indexing — single-threaded, and it materializes an int64 index matrix of
// the same size as the output. This library does the gather directly:
// multi-threaded over sequences, uint16 -> int32 widening in-flight, no
// index materialization, and x/y (shift-by-one) produced in one pass over
// the source window.
//
// Exposed C ABI (ctypes-friendly, no pybind11 dependency):
//   dg_gather(tokens, n_tokens, offsets, n_seqs, block_size, x_out, y_out,
//             n_threads)
//     tokens:   const uint16_t*  token stream (memmap or RAM)
//     offsets:  const int64_t*   n_seqs window start positions
//     x_out:    int32_t*         [n_seqs, block_size]
//     y_out:    int32_t*         [n_seqs, block_size]  (= x shifted by one)
//   returns 0 on success, -1 if any window would run past n_tokens.

#include <cstdint>
#include <atomic>
#include <thread>
#include <vector>

extern "C" {

int dg_gather(const uint16_t* tokens, int64_t n_tokens,
              const int64_t* offsets, int64_t n_seqs, int64_t block_size,
              int32_t* x_out, int32_t* y_out, int n_threads) {
  // validate every window before touching output (full batch or nothing)
  for (int64_t s = 0; s < n_seqs; ++s) {
    if (offsets[s] < 0 || offsets[s] + block_size + 1 > n_tokens) return -1;
  }
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_seqs) n_threads = static_cast<int>(n_seqs);

  std::atomic<int64_t> next_seq{0};
  auto worker = [&]() {
    for (;;) {
      const int64_t s = next_seq.fetch_add(1, std::memory_order_relaxed);
      if (s >= n_seqs) return;
      const uint16_t* src = tokens + offsets[s];
      int32_t* x = x_out + s * block_size;
      int32_t* y = y_out + s * block_size;
      // one pass over block_size+1 source tokens fills both x and y
      int32_t prev = static_cast<int32_t>(src[0]);
      for (int64_t t = 0; t < block_size; ++t) {
        const int32_t cur = static_cast<int32_t>(src[t + 1]);
        x[t] = prev;
        y[t] = cur;
        prev = cur;
      }
    }
  };

  if (n_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> threads;
    threads.reserve(n_threads);
    for (int i = 0; i < n_threads; ++i) threads.emplace_back(worker);
    for (auto& t : threads) t.join();
  }
  return 0;
}

}  // extern "C"
