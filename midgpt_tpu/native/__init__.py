"""Native (C++) runtime components, bound via ctypes.

The compute path is JAX/XLA/Pallas; the host-side runtime around it —
batch gather for the data feed — is C++ (midgpt_tpu/native/gather.cpp),
built on first use with g++ (no pybind11 required). Every native entry
point has a numpy fallback so the framework runs where no toolchain
exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import typing as tp

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "gather.cpp")
_LIB = os.path.join(_HERE, "libdatagather.so")

_lock = threading.Lock()
_lib: tp.Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # build to a process-unique temp path and rename into place: publication
    # is atomic, so concurrent builders can't hand a half-written .so to a
    # loader, and a rebuild never truncates a file another process has
    # already dlopen'd
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-pthread", _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_library() -> tp.Optional[ctypes.CDLL]:
    """The compiled gather library, building it on first call; None if no
    toolchain is available (callers fall back to numpy)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.dg_gather.restype = ctypes.c_int
        lib.dg_gather.argtypes = [
            ctypes.c_void_p,  # tokens
            ctypes.c_int64,  # n_tokens
            ctypes.c_void_p,  # offsets
            ctypes.c_int64,  # n_seqs
            ctypes.c_int64,  # block_size
            ctypes.c_void_p,  # x_out
            ctypes.c_void_p,  # y_out
            ctypes.c_int,  # n_threads
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_library() is not None


def gather_windows(
    tokens: np.ndarray,  # 1-D uint16
    offsets: np.ndarray,  # 1-D int
    block_size: int,
    n_threads: tp.Optional[int] = None,
) -> tp.Tuple[np.ndarray, np.ndarray]:
    """(x, y) int32 [n_seqs, block_size] windows; y shifted by one.

    Native multi-threaded gather when the library is available, else the
    numpy path (same recipe as the reference's get_batch, train.py:61-62).
    """
    assert tokens.dtype == np.uint16 and tokens.ndim == 1
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n_seqs = len(offsets)
    lib = load_library()
    if lib is not None and tokens.flags["C_CONTIGUOUS"]:
        x = np.empty((n_seqs, block_size), dtype=np.int32)
        y = np.empty((n_seqs, block_size), dtype=np.int32)
        if n_threads is None:
            n_threads = min(os.cpu_count() or 1, 16)
        rc = lib.dg_gather(
            tokens.ctypes.data, len(tokens),
            offsets.ctypes.data, n_seqs, block_size,
            x.ctypes.data, y.ctypes.data, n_threads,
        )
        if rc == 0:
            return x, y
        raise IndexError("gather window out of range")
    # numpy fallback
    if np.any(offsets < 0) or np.any(offsets + block_size + 1 > len(tokens)):
        raise IndexError("gather window out of range")
    idx = offsets[:, None] + np.arange(block_size + 1)[None, :]
    windows = np.take(tokens, idx, axis=0).astype(np.int32)
    return windows[:, :-1], windows[:, 1:]
