"""Async, sharding-aware checkpointing on Orbax.

Capability parity with the reference (/root/reference/src/train.py:139-145,
179-187, 215, 225: AsyncCheckpointer + CheckpointManager, max_to_keep=1,
save-every-eval-interval, sharding-aware restore, local disk or GCS),
redesigned per SURVEY.md 5.4's critique:

- saves STRUCTURED state as named composite items (params / opt_state /
  extra + JSON metadata: step, loader state, config fingerprint) instead
  of bare tree leaves, so checkpoints don't silently couple to code
  structure;
- restore takes abstract templates built from the live (sharded) state,
  so every leaf lands on devices with its target NamedSharding directly
  (no host staging), including after mesh-shape changes;
- partial restore is first-class: sampling restores only the ``params``
  item — no Adam-moment memory (the reference rebuilds a dummy optimizer
  just to match the checkpoint tree, sample.py:111-131);
- data-loader state IS checkpointed (the reference's isn't — resume there
  changes data order).
"""

from __future__ import annotations

import json
import typing as tp

import jax
import orbax.checkpoint as ocp


class Checkpointer:
    def __init__(
        self,
        rundir: str,
        *,
        keep: int = 1,
        save_interval_steps: int = 1000,
        async_save: bool = True,
    ):
        import os

        path = rundir if rundir.startswith("gs://") else os.path.abspath(rundir)
        self._mngr = ocp.CheckpointManager(
            path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
            ),
        )

    def latest_step(self) -> tp.Optional[int]:
        return self._mngr.latest_step()

    def save(
        self,
        step: int,
        items: tp.Mapping[str, tp.Any],
        meta: tp.Mapping[str, tp.Any],
        force: bool = False,
    ) -> bool:
        """Async save of named pytree items + JSON metadata; the manager
        no-ops between save intervals (parity: train.py:214-215 calling save
        every iteration). ``force=True`` saves regardless of the interval
        (end-of-run checkpoint)."""
        assert "meta" not in items, "'meta' is reserved for the JSON metadata"
        if force and step in self._mngr.all_steps():
            # already durable (e.g. Orbax saves step 0 regardless of the
            # interval; a preemption force-save of the same step would
            # raise StepAlreadyExistsError)
            return False
        return self._mngr.save(
            step,
            args=ocp.args.Composite(
                meta=ocp.args.JsonSave(dict(meta)),
                **{k: ocp.args.StandardSave(v) for k, v in items.items()},
            ),
            force=force,
        )

    def restore(
        self,
        templates: tp.Mapping[str, tp.Any],
        step: tp.Optional[int] = None,
    ) -> tp.Tuple[tp.Dict[str, tp.Any], tp.Dict[str, tp.Any]]:
        """Restore the named items in ``templates`` into the shardings their
        template leaves carry (parity: train.py:179-187). Items present in
        the checkpoint but not in ``templates`` are skipped — that's the
        params-only sampling path."""
        step = step if step is not None else self._mngr.latest_step()
        assert step is not None, "no checkpoint to restore"
        default = jax.sharding.SingleDeviceSharding(jax.devices()[0])

        def _abstract(x):
            sharding = getattr(x, "sharding", None)
            if not isinstance(sharding, jax.sharding.Sharding):
                sharding = default  # abstract templates (eval_shape) carry none
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                meta=ocp.args.JsonRestore(),
                **{
                    k: ocp.args.StandardRestore(jax.tree.map(_abstract, v))
                    for k, v in templates.items()
                },
            ),
        )
        items = {k: restored[k] for k in templates}
        return items, dict(restored["meta"])

    def has_item(self, name: str, step: tp.Optional[int] = None) -> bool:
        """Whether the checkpoint at ``step`` (default latest) stores an
        item called ``name`` — how loaders pick between the training
        ``params`` tree and a pre-quantized ``params_q8`` serving tree
        (midgpt_tpu.quant) without reading any array data."""
        step = step if step is not None else self._mngr.latest_step()
        if step is None:
            return False
        # items are step-directory children (works for local and gs://
        # paths via epath); item_metadata can't resolve items a fresh
        # manager has no registered handler for
        return (self._mngr.directory / str(step) / name).exists()

    def item_metadata(self, step: tp.Optional[int] = None) -> tp.Any:
        """Shape/dtype metadata of the stored items WITHOUT reading array
        data — lets callers adapt a config to what a checkpoint actually
        contains (e.g. pin an MLP width) before building restore templates."""
        step = step if step is not None else self._mngr.latest_step()
        assert step is not None, "no checkpoint to inspect"
        return self._mngr.item_metadata(step)

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def config_fingerprint(config_dict: tp.Mapping[str, tp.Any]) -> str:
    """Stable hash of the experiment config for resume-compatibility checks."""
    import hashlib

    blob = json.dumps(config_dict, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]
