"""Pytree-dataclass module system.

TPU-first replacement for the reference's Equinox module idiom
(/root/reference/src/layers.py:13-99): a module is a frozen dataclass
registered with ``jax.tree_util.register_dataclass``. Array-valued fields are
pytree leaves (parameters / sub-modules); fields declared with ``static()``
are auxiliary data baked into the treedef (hashable, trace-time constants).

This gives the same "params are just a pytree" property the reference gets
from ``eqx.partition`` (/root/reference/src/train.py:82) without a partition /
combine step: the whole model is directly jit-able, vmap-able and shardable.
"""

from __future__ import annotations

import dataclasses
import typing as tp

import jax
import jax.numpy as jnp

_T = tp.TypeVar("_T")


def static(default: tp.Any = dataclasses.MISSING, **kwargs) -> tp.Any:
    """Declare a dataclass field as static (treedef aux data, not a leaf)."""
    metadata = dict(kwargs.pop("metadata", {}) or {})
    metadata["pytree_static"] = True
    if default is not dataclasses.MISSING:
        kwargs["default"] = default
    return dataclasses.field(metadata=metadata, **kwargs)


def module(cls: tp.Type[_T]) -> tp.Type[_T]:
    """Class decorator: frozen dataclass + pytree registration."""
    cls = dataclasses.dataclass(frozen=True)(cls)
    data_fields, meta_fields = [], []
    for f in dataclasses.fields(cls):
        if f.metadata.get("pytree_static", False):
            meta_fields.append(f.name)
        else:
            data_fields.append(f.name)
    jax.tree_util.register_dataclass(
        cls, data_fields=data_fields, meta_fields=meta_fields
    )
    return cls


def is_array(x: tp.Any) -> bool:
    return isinstance(x, (jax.Array,)) or hasattr(x, "shape") and hasattr(x, "dtype")


def cast_floating(tree: tp.Any, dtype: tp.Any) -> tp.Any:
    """Cast all inexact (floating) array leaves to ``dtype``.

    Mixed-precision boundary, equivalent of ``cast_pytree``
    (/root/reference/src/train.py:47-53): params live in float32, compute
    runs in bfloat16.
    """

    def _cast(x):
        if is_array(x) and jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
            return jnp.asarray(x, dtype=dtype)
        return x

    return jax.tree.map(_cast, tree)


def count_params(tree: tp.Any) -> int:
    """Total number of array elements in the tree."""
    return sum(
        x.size for x in jax.tree.leaves(tree) if is_array(x)
    )


def tree_paths(tree: tp.Any) -> tp.List[tp.Tuple[str, tp.Any]]:
    """Flatten a pytree into ("a/b/c", leaf) pairs using field/key names."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for keypath, leaf in flat:
        parts = []
        for k in keypath:
            if isinstance(k, jax.tree_util.GetAttrKey):
                parts.append(k.name)
            elif isinstance(k, jax.tree_util.DictKey):
                parts.append(str(k.key))
            elif isinstance(k, jax.tree_util.SequenceKey):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        out.append(("/".join(parts), leaf))
    return out
