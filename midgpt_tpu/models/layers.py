"""Primitive NN layers, batched-native.

Capability parity with /root/reference/src/layers.py, redesigned TPU-first:
every op works on full ``[..., T, D]`` batches (big MXU-friendly matmuls)
instead of the reference's per-token modules vmapped by the caller
(/root/reference/src/model.py:104). Weights are stored ``(in, out)`` so the
forward is a plain ``x @ W`` contraction XLA maps straight onto the MXU.

Numerics preserved exactly (SURVEY.md 2.3):
- Linear: truncated-normal init in [-2, 2] scaled 1/sqrt(fan_in)
  (layers.py:49-50), no bias.
- RMSNorm: x * rsqrt(mean(x^2) + eps), optional learned scale
  (layers.py:60-75); weightless for block and final norms.
- QK-norm: mean-subtracting LayerNorm with weight, no bias, eps 1e-6
  (model.py:52-53).
- RoPE: GPT-J interleaved rotate-every-two, base 10000, tables precomputed
  in NumPy at trace time so they constant-fold (layers.py:79-99).
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.pytree import module, static

KeyArray = jax.Array
Array = jax.Array


@module
class Embedding:
    """Token-id -> vector gather (parity: layers.py:13-34)."""

    weight: Array  # [V, D]

    @staticmethod
    def init(key: KeyArray, vocab_size: int, dim: int, std: float) -> "Embedding":
        w = std * jax.random.normal(key, (vocab_size, dim), dtype=jnp.float32)
        return Embedding(weight=w)

    def __call__(self, tokens: Array) -> Array:  # [...] int -> [..., D]
        with jax.named_scope("embedding"):
            return jnp.take(self.weight, tokens, axis=0)


@module
class Linear:
    """Bias-free linear, weight stored (in, out) (parity: layers.py:37-57,
    transposed for x @ W)."""

    weight: Array  # [in, out]

    @staticmethod
    def init(key: KeyArray, in_features: int, out_features: int) -> "Linear":
        w = (1 / math.sqrt(in_features)) * jax.random.truncated_normal(
            key, lower=-2, upper=2, shape=(in_features, out_features), dtype=jnp.float32
        )
        return Linear(weight=w)

    def __call__(self, x: Array) -> Array:  # [..., in] -> [..., out]
        with jax.named_scope("linear"):
            return x @ self.weight


@module
class RMSNorm:
    """x * rsqrt(mean(x^2, -1) + eps) [* weight] (parity: layers.py:60-75).

    impl: "jnp" (XLA-fused elementwise chain) | "fused" (Pallas one-pass
    kernel, midgpt_tpu.ops.fused_norm) | "auto" (= jnp, by measurement).
    The fused path needs D % 128 == 0 and a TPU; otherwise it silently
    falls back to jnp.

    Why auto == jnp: measured on a v5e-class chip
    (scripts/bench_kernels.py, r2): fused fwd is slightly faster
    (6.5us vs 10.2us at [16,1024,768]) but its custom-VJP backward costs
    236us vs jnp's 10us — XLA fuses the jnp backward into neighboring ops
    while the Pallas backward is a separate kernel launch + extra HBM
    round trip. Training always takes the jnp path; "fused" remains a
    tested oracle and a forward-only/inference option.
    """

    weight: tp.Optional[Array]  # [D] or None
    eps: float = static(default=1e-6)
    impl: str = static(default="auto")

    @staticmethod
    def init(
        dim: int, use_weight: bool = False, eps: float = 1e-6,
        impl: str = "auto",
    ) -> "RMSNorm":
        w = jnp.ones((dim,), dtype=jnp.float32) if use_weight else None
        return RMSNorm(weight=w, eps=eps, impl=impl)

    def __call__(self, x: Array) -> Array:
        from midgpt_tpu.utils.platform import is_tpu_backend

        with jax.named_scope("rmsnorm"):
            if (
                self.impl == "fused"
                and x.shape[-1] % 128 == 0
                and is_tpu_backend()
            ):
                from midgpt_tpu.ops.fused_norm import fused_rms_norm

                w = (
                    self.weight.astype(x.dtype)
                    if self.weight is not None
                    else None
                )
                return fused_rms_norm(x, w, self.eps)
            out = x * jax.lax.rsqrt(
                jnp.mean(jnp.square(x), axis=-1, keepdims=True) + self.eps
            )
            if self.weight is not None:
                out = out * self.weight.astype(out.dtype)
            return out


@module
class LayerNorm:
    """Mean-subtracting LayerNorm, learned scale, no bias. Used for per-head
    QK normalization (parity: model.py:52-53, eqx.nn.LayerNorm(C, eps=1e-6,
    use_weight=True, use_bias=False))."""

    weight: Array  # [D]
    eps: float = static(default=1e-6)

    @staticmethod
    def init(dim: int, eps: float = 1e-6) -> "LayerNorm":
        return LayerNorm(weight=jnp.ones((dim,), dtype=jnp.float32), eps=eps)

    def __call__(self, x: Array) -> Array:
        with jax.named_scope("layernorm"):
            mean = jnp.mean(x, axis=-1, keepdims=True)
            centered = x - mean
            var = jnp.mean(jnp.square(centered), axis=-1, keepdims=True)
            out = centered * jax.lax.rsqrt(var + self.eps)
            return out * self.weight.astype(out.dtype)


# ---------------------------------------------------------------------------
# Rotary position embeddings (interleaved / GPT-J style)
# ---------------------------------------------------------------------------


def rope_tables(
    head_dim: int, seq_len: int, base: float = 10000.0
) -> tp.Tuple[np.ndarray, np.ndarray]:
    """Precompute sin/cos tables [T, head_dim//2] in NumPy at trace time so
    XLA constant-folds them (parity: layers.py:79-82)."""
    inv_freq = 1.0 / (base ** (np.arange(0, head_dim, 2) / head_dim))
    angles = np.einsum("i,j->ij", np.arange(seq_len), inv_freq)
    return np.sin(angles), np.cos(angles)


def rotate_every_two(x: Array) -> Array:
    """[a b c d] -> [-b a -d c] (parity: layers.py:85-89).

    Reference form (kept as the oracle for tests); apply_rotary uses the
    matmul form below on the hot path."""
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    y = jnp.stack((-x2, x1), axis=-1)
    return jnp.reshape(y, x.shape)


@functools.lru_cache(maxsize=None)
def _rotation_matrix(c: int, dtype_name: str) -> np.ndarray:
    """[C, C] constant R with (x @ R) == rotate_every_two(x).

    Strided even/odd slicing on the minor (lane) dim lowers to a gather on
    TPU — and its transpose (the VJP) to a scatter-add, which profiling
    showed as a top copy cost in the train step. As a signed permutation
    matrix the op runs on the MXU instead, and its VJP is x @ R.T (another
    matmul). Each output element receives exactly one +-x term, so the
    result is bit-identical to the slicing form in any dtype."""
    r = np.zeros((c, c), dtype=np.float32)
    idx = np.arange(0, c, 2)
    r[idx + 1, idx] = -1.0  # y[2i] = -x[2i+1]
    r[idx, idx + 1] = 1.0  # y[2i+1] = x[2i]
    return r.astype(dtype_name)


def _duplicate_interleaved(t: Array) -> Array:
    """[..., D/2] -> [..., D] duplicating each column across even/odd lanes."""
    y = jnp.stack((t, t), axis=-1)
    return jnp.reshape(y, t.shape[:-1] + (t.shape[-1] * 2,))


def apply_rotary(
    x: Array,
    sin: tp.Union[Array, np.ndarray],
    cos: tp.Union[Array, np.ndarray],
) -> Array:
    """Apply interleaved RoPE. ``x``: [..., T, C]; sin/cos: [T, C//2]
    (parity: layers.py:92-99)."""
    with jax.named_scope("rope"):
        sin = jnp.asarray(sin, dtype=x.dtype)
        cos = jnp.asarray(cos, dtype=x.dtype)
        sin_full = _duplicate_interleaved(sin)  # [T, C]
        cos_full = _duplicate_interleaved(cos)
        rot = jnp.asarray(_rotation_matrix(x.shape[-1], x.dtype.name))
        return x * cos_full + (x @ rot) * sin_full


# ---------------------------------------------------------------------------
# Dropout (functional)
# ---------------------------------------------------------------------------


def dropout(
    x: Array,
    rate: float,
    key: tp.Optional[KeyArray],
    deterministic: bool,
) -> Array:
    """Inverted dropout; no-op when deterministic or rate == 0."""
    if deterministic or rate == 0.0:
        return x
    assert key is not None, "dropout in training mode requires a PRNG key"
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, p=keep, shape=x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))
