from midgpt_tpu.models.gpt import GPT, GPT_PARAM_RULES, Attention, Block, MLP, count_params
from midgpt_tpu.models import layers

__all__ = ["GPT", "GPT_PARAM_RULES", "Attention", "Block", "MLP", "count_params", "layers"]
