"""Decoder-only transformer (GPT / Llama family), batched-native.

Capability parity with /root/reference/src/model.py (GPT: RoPE, weightless
RMSNorm pre-norms, per-head QK-LayerNorm, GELU MLP, scan-over-layers with
whole-block remat, init-shared wte/lm_head), redesigned TPU-first:

- operates on whole ``[B, T]`` batches (one big MXU matmul per projection)
  instead of per-sequence modules vmapped by the caller (model.py:140-158);
- fused QKV projection sized ``(H + 2*Hkv) * C`` so GQA (Llama family,
  BASELINE.json configs) falls out of the same code path;
- optional SwiGLU MLP and weighted RMSNorms for the Llama-style family;
- activation shardings tagged with logical axis names
  (midgpt_tpu.parallel.sharding) so DP/FSDP/SP/TP are rule-table entries;
- attention is dispatched (naive oracle / Pallas flash / ring) via
  midgpt_tpu.ops.attention.

Layer stacking: blocks are created with ``jax.vmap`` over the layer axis and
iterated with ``lax.scan`` (+ configurable remat) for O(1) compile time in
depth (parity: model.py:130-155).
"""

from __future__ import annotations

import math
import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.compat import shard_map
from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.layers import (
    Embedding,
    LayerNorm,
    Linear,
    RMSNorm,
    apply_rotary,
    dropout,
    rope_tables,
)
from midgpt_tpu.ops.attention import attention
from midgpt_tpu.parallel.sharding import current_mesh, shard_act
from midgpt_tpu.pytree import module, static

Array = jax.Array
KeyArray = jax.Array


def _fused_attention_sharded(qkv, wq, wk, sin, cos, h, hkv, eps):
    """Run the fused kernel per shard. Under a live multi-device mesh a
    bare ``pallas_call`` (an opaque custom call) would make GSPMD gather
    the sharded activations onto every device; wrapping in ``shard_map``
    keeps each device's kernel on its local batch — and, under TP, on its
    local HEADS: tensor shards the head dim, each shard running the
    split-input kernel with H/tp (and Hkv/tp) heads. T stays whole (the
    SP case takes the ring path, _use_fused)."""
    from midgpt_tpu.ops.fused_attn import fused_attention, fused_attention_qkv
    from midgpt_tpu.parallel.sharding import current_mesh, shard_act

    mesh = current_mesh()
    data_axes = ("replica", "fsdp")
    tp = mesh.shape.get("tensor", 1) if mesh is not None else 1
    if mesh is None or (
        tp == 1 and all(mesh.shape.get(a, 1) == 1 for a in data_axes)
    ):
        return fused_attention_qkv(qkv, wq, wk, sin, cos, h, hkv, True, eps)

    from jax.sharding import PartitionSpec as P

    if tp == 1:
        fn = lambda q_, wq_, wk_, s_, c_: fused_attention_qkv(  # noqa: E731
            q_, wq_, wk_, s_, c_, h, hkv, True, eps
        )
        return shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(data_axes), P(), P(), P(), P()),
            out_specs=P(data_axes),
            check_vma=False,
        )(qkv, wq, wk, sin, cos)

    # TP: split q/k/v (GSPMD reshards each slice head-contiguous per the
    # "heads" rule) and run the split-entry kernel with local head counts
    c = qkv.shape[-1] // (h + 2 * hkv)
    q = shard_act(qkv[..., : h * c], "batch", "seq", "heads")
    k = shard_act(qkv[..., h * c : (h + hkv) * c], "batch", "seq", "kv_heads")
    v = shard_act(qkv[..., (h + hkv) * c :], "batch", "seq", "kv_heads")

    fn = lambda q_, k_, v_, wq_, wk_, s_, c_: fused_attention(  # noqa: E731
        q_, k_, v_, wq_, wk_, s_, c_, h // tp, hkv // tp, True, None, None, eps
    )
    act = P(data_axes, None, "tensor")
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(act, act, act, P(), P(), P(), P()),
        out_specs=act,
        check_vma=False,
    )(q, k, v, wq, wk, sin, cos)


def _gathered_pool_view(pool_x, scale_x, bt, layer):
    """The block-table gather: the slots' pages as a logical KV view
    ``[S, Hkv, C, W = Pmax*PS]`` in page order. Float pools return the
    gathered pages in POOL dtype — byte-for-byte the pre-existing path
    (downstream ``.astype(f32)`` upcasts are where the choreography
    fixes the arithmetic). Int8 pools dequantize at the view:
    ``f32(codes) * scale`` with the per-(page, KV-head) po2 scale
    broadcast to its page's columns — EXACT (|code| <= 127, po2 scale;
    midgpt_tpu.quant's KV grid contract), so every downstream consumer
    sees precisely the grid values a bf16 pool would have held.
    ``mode="clip"``, NOT "fill": block-table pads carry the out-of-range
    sentinel, and fill-mode NaNs would poison the score sum straight
    through the additive mask (0 * NaN = NaN); clipped garbage is erased
    by the -inf mask before the softmax."""
    pk_l = jnp.take(pool_x[layer], bt, axis=0, mode="clip")
    s_, pmax, hkv, c, ps = pk_l.shape
    ck = jnp.transpose(pk_l, (0, 2, 3, 1, 4)).reshape(s_, hkv, c, pmax * ps)
    if scale_x is None:
        return ck
    sc = jnp.take(scale_x[layer], bt, axis=0, mode="clip")  # [S, Pmax, Hkv]
    scw = jnp.transpose(sc, (0, 2, 1))[:, :, None, :, None]
    scw = jnp.broadcast_to(
        scw, (s_, hkv, 1, pmax, ps)
    ).reshape(s_, hkv, 1, pmax * ps)
    return ck.astype(jnp.float32) * scw


def _gathered_pool_scales(scale_x, bt, layer):
    """Per-slot per-page scale gather ``[S, Pmax, Hkv]`` for the Pallas
    kernels (which dequantize in-kernel and only need the tiny scale
    planes gathered, never the payload)."""
    if scale_x is None:
        return None
    return jnp.take(scale_x[layer], bt, axis=0, mode="clip")


def _paged_kernel_dispatch(kind: str, layer: int, tensors, scales):
    """Run a serving paged-attention kernel (ops.paged_attn), wrapped in
    ``shard_map`` under a live TP mesh: a bare ``pallas_call`` is an
    opaque custom call, and GSPMD would gather the KV-head-sharded pool
    onto every device (the same trap ``_fused_attention_sharded``
    documents). Each shard runs the kernel on its own Hkv/tp heads —
    the pool's page/time dims stay whole per shard, block tables and
    lengths ride replicated, so the walk is shard-local exactly like
    the XLA gather it replaces."""
    from midgpt_tpu.ops.paged_attn import (
        paged_decode_attention,
        paged_verify_attention,
    )

    mesh = current_mesh()
    tp = mesh.shape.get("tensor", 1) if mesh is not None else 1
    quant = scales[0] is not None
    sc = tuple(scales) if quant else ()

    if kind == "decode":
        q, pool_k, pool_v, bt, pooled_len, rkl, rvl, r = tensors
        call = lambda *a: paged_decode_attention(  # noqa: E731
            a[0], a[1], a[2], a[3], a[4], a[5], a[6], a[7], layer,
            *(a[8:] or (None, None)),
        )
        specs = [
            ("tensor", 1), ("tensor", 2), ("tensor", 2), (None, None),
            (None, None), ("tensor", 1), ("tensor", 1), (None, None),
        ]
    else:
        q, kc, vc, pool_k, pool_v, bt, start = tensors
        call = lambda *a: paged_verify_attention(  # noqa: E731
            a[0], a[1], a[2], a[3], a[4], a[5], a[6], layer,
            *(a[7:] or (None, None)),
        )
        specs = [
            ("tensor", 1), ("tensor", 1), ("tensor", 1), ("tensor", 2),
            ("tensor", 2), (None, None), (None, None),
        ]
    args = tuple(tensors) + sc
    if mesh is None or tp == 1:
        return call(*args)

    from jax.sharding import PartitionSpec as P

    def spec_for(arr, axis_pos):
        name, pos = axis_pos
        if name is None:
            return P(*([None] * arr.ndim))
        entries = [None] * arr.ndim
        entries[pos] = name
        return P(*entries)

    if quant:
        specs = specs + [("tensor", 2), ("tensor", 2)]  # [S, Pmax, Hkv]
    in_specs = tuple(spec_for(a, sp) for a, sp in zip(args, specs))
    out_spec = P(None, "tensor", *([None] * (args[0].ndim - 2)))
    return shard_map(
        call,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_spec,
        check_vma=False,
    )(*args)


@module
class Attention:
    """Causal self-attention with QK-norm + RoPE (parity: model.py:34-81)."""

    wqkv: Linear  # [D, (H + 2*Hkv) * C]
    wo: Linear  # [H*C, D]
    q_norm: tp.Optional[LayerNorm]
    k_norm: tp.Optional[LayerNorm]
    n_head: int = static()
    n_kv_head: int = static()
    dropout_rate: float = static(default=0.0)
    ring_schedule: str = static(default="zigzag")

    @staticmethod
    def init(key: KeyArray, cfg: ModelConfig) -> "Attention":
        k1, k2 = jax.random.split(key)
        c = cfg.head_dim
        hkv = cfg.kv_heads
        qkv_out = (cfg.n_head + 2 * hkv) * c
        return Attention(
            wqkv=Linear.init(k1, cfg.n_embd, qkv_out),
            wo=Linear.init(k2, cfg.n_head * c, cfg.n_embd),
            q_norm=LayerNorm.init(c, eps=1e-6) if cfg.qk_norm else None,
            k_norm=LayerNorm.init(c, eps=1e-6) if cfg.qk_norm else None,
            n_head=cfg.n_head,
            n_kv_head=hkv,
            dropout_rate=cfg.dropout,
            ring_schedule=cfg.ring_schedule,
        )

    def __call__(
        self,
        x: Array,  # [B, T, D]
        sin,
        cos,
        *,
        impl: str = "naive",
        key: tp.Optional[KeyArray] = None,
        deterministic: bool = True,
        return_kv: bool = False,
    ) -> tp.Union[Array, tp.Tuple[Array, tp.Tuple[Array, Array]]]:
        b, t, d = x.shape
        h, hkv = self.n_head, self.n_kv_head
        c = d // h
        adrop_key, pdrop_key = (
            jax.random.split(key) if key is not None else (None, None)
        )
        if impl == "fused" and (return_kv or self.q_norm is None):
            # return_kv needs per-head K/V (prefill), and the kernel requires
            # qk-norm; same math either way, so degrade to auto dispatch
            impl = "auto"
        if self._use_fused(impl, t, deterministic) and not return_kv:
            return self._fused_call(x, sin, cos, pdrop_key, deterministic)
        with jax.named_scope("attention"):
            qkv = self.wqkv(x)  # [B, T, (H + 2Hkv) C]
            q = qkv[..., : h * c].reshape(b, t, h, c)
            k = qkv[..., h * c : (h + hkv) * c].reshape(b, t, hkv, c)
            v = qkv[..., (h + hkv) * c :].reshape(b, t, hkv, c)
            if self.q_norm is not None:
                q = self.q_norm(q)
                k = self.k_norm(k)
            # [B, H, T, C]
            q = jnp.transpose(q, (0, 2, 1, 3))
            k = jnp.transpose(k, (0, 2, 1, 3))
            v = jnp.transpose(v, (0, 2, 1, 3))
            q = apply_rotary(q, sin, cos)
            k = apply_rotary(k, sin, cos)
            q = shard_act(q, "batch", "heads", "seq", "head_dim")
            k = shard_act(k, "batch", "kv_heads", "seq", "head_dim")
            v = shard_act(v, "batch", "kv_heads", "seq", "head_dim")
            if impl == "ulysses":
                from midgpt_tpu.parallel.sharding import current_mesh
                from midgpt_tpu.parallel.ulysses import ulysses_attention

                mesh = current_mesh()
                assert mesh is not None, (
                    "attn_impl='ulysses' requires running inside "
                    "axis_rules(mesh)"
                )
                if self.dropout_rate > 0.0 and not deterministic:
                    u_seed = jax.random.randint(
                        adrop_key, (), -(2**31), 2**31 - 1, dtype=jnp.int32
                    )
                    out = ulysses_attention(
                        q, k, v, mesh,
                        dropout_rate=self.dropout_rate, dropout_seed=u_seed,
                    )
                else:
                    out = ulysses_attention(q, k, v, mesh)
            elif impl == "ring":
                from midgpt_tpu.parallel.ring import ring_attention
                from midgpt_tpu.parallel.sharding import current_mesh

                mesh = current_mesh()
                assert mesh is not None, (
                    "attn_impl='ring' requires running inside axis_rules(mesh)"
                )
                schedule = self.ring_schedule
                if schedule == "zigzag" and t % (2 * mesh.shape["sequence"]):
                    schedule = "standard"  # zigzag needs T | 2S
                if self.dropout_rate > 0.0 and not deterministic:
                    # in-hop counter-hash dropout at global coordinates
                    # (ring.py); zigzag interleaves half-chunks, which the
                    # scalar hash offsets can't express — degrade to the
                    # standard schedule (r5; the only dropout configs are
                    # the small shakespeare family)
                    seed = jax.random.randint(
                        adrop_key, (), -(2**31), 2**31 - 1, dtype=jnp.int32
                    )
                    out = ring_attention(
                        q, k, v, mesh, schedule="standard",
                        dropout_rate=self.dropout_rate, dropout_seed=seed,
                    )
                else:
                    out = ring_attention(q, k, v, mesh, schedule=schedule)
            else:
                out = attention(
                    q,
                    k,
                    v,
                    impl=impl,
                    causal=True,
                    dropout_rate=self.dropout_rate,
                    dropout_key=adrop_key,
                    deterministic=deterministic,
                )
            out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, t, h * c)
            out = self.wo(out)
            out = dropout(out, self.dropout_rate, pdrop_key, deterministic)
            out = shard_act(out, "batch", "seq", "embed")
            if return_kv:
                # post-norm, post-rope K and raw V [B, Hkv, T, C] — exactly
                # what the KV cache stores (decode() writes the same)
                return out, (k, v)
            return out


    def _use_fused(self, impl: str, t: int, deterministic: bool) -> bool:
        """Route to the projection-natural fused kernel (ops/fused_attn):
        QK-LN + RoPE + flash in one Pallas call, no [B,H,T,C] intermediates.
        impl="fused" forces it (tests, via the interpret fixture); "auto"
        takes it on TPU under the same conditions flash requires."""
        from midgpt_tpu.ops.fused_attn import supported

        if impl not in ("fused", "auto") or self.q_norm is None:
            return False
        shape_ok = (
            supported(self.n_head, self.n_kv_head, self.head_dim())
            and t >= 128
            and t % 128 == 0
            and (self.dropout_rate == 0.0 or deterministic)
        )
        mesh = current_mesh()
        tp = mesh.shape.get("tensor", 1) if mesh is not None else 1
        sp = mesh.shape.get("sequence", 1) if mesh is not None else 1
        pp = mesh.shape.get("pipeline", 1) if mesh is not None else 1
        # TP is fine when every shard keeps whole supported heads (each
        # device runs the split-entry kernel with H/tp, Hkv/tp heads);
        # SP shards T, which the kernel grid cannot see — ring territory
        tp_ok = (
            tp == 1
            or (
                self.n_head % tp == 0
                and self.n_kv_head % tp == 0
                and supported(
                    self.n_head // tp, self.n_kv_head // tp, self.head_dim()
                )
            )
        )
        # pipeline: the stages already run inside a shard_map over
        # 'pipeline'; _fused_attention_sharded's in_specs would declare the
        # activations replicated over that axis and force GSPMD to regather
        # them (ADVICE r3) — the flash/naive path handles PP meshes
        mesh_unsupported = sp > 1 or pp > 1 or not tp_ok
        if impl == "fused":
            assert shape_ok, (
                "attn_impl='fused' requires qk-norm, T % 128 == 0, no "
                "attention dropout, and a supported head shape "
                "(C % 128 == 0, or C == 64 with MHA)"
            )
            assert not mesh_unsupported, (
                "attn_impl='fused' cannot run under a sequence-sharded "
                "mesh, or a tensor sharding that breaks the per-shard "
                "head shape; use attn_impl='auto' (falls back) or 'ring'"
            )
            return True
        from midgpt_tpu.utils.platform import is_tpu_backend

        if mesh_unsupported:
            return False
        return shape_ok and is_tpu_backend()

    def head_dim(self) -> int:
        # static: wo is [H*C, D]
        return self.wo.weight.shape[0] // self.n_head

    def _fused_call(self, x, sin, cos, pdrop_key, deterministic):
        from midgpt_tpu.models.layers import _duplicate_interleaved

        h, hkv = self.n_head, self.n_kv_head
        with jax.named_scope("fused_attention"):
            qkv = self.wqkv(x)  # [B, T, (H + 2Hkv) C]
            # single-device / data-sharded meshes take the packed entry
            # (lane-offset reads, no slice copies, no pad+add VJP); TP
            # meshes split q/k/v and run per head shard — both inside
            # _fused_attention_sharded.
            qkv = shard_act(qkv, "batch", "seq", None)
            sin_full = _duplicate_interleaved(jnp.asarray(sin, jnp.float32))
            cos_full = _duplicate_interleaved(jnp.asarray(cos, jnp.float32))
            out = _fused_attention_sharded(
                qkv, self.q_norm.weight, self.k_norm.weight,
                sin_full, cos_full, h, hkv, self.q_norm.eps,
            )
            out = self.wo(out)
            out = dropout(out, self.dropout_rate, pdrop_key, deterministic)
            return shard_act(out, "batch", "seq", "embed")

    def _decode_qkv(
        self, x: Array, sin_row: Array, cos_row: Array
    ) -> tp.Tuple[Array, Array, Array]:
        """Project one token's q/k/v (+ optional QK-norm + rope at the
        token's absolute position). q: [B, H, 1, C]; k/v: [B, Hkv, 1, C]."""
        b, one, d = x.shape
        h, hkv = self.n_head, self.n_kv_head
        c = d // h
        qkv = self.wqkv(x)  # [B, 1, (H+2Hkv)C]
        q = qkv[..., : h * c].reshape(b, 1, h, c)
        k = qkv[..., h * c : (h + hkv) * c].reshape(b, 1, hkv, c)
        v = qkv[..., (h + hkv) * c :].reshape(b, 1, hkv, c)
        if self.q_norm is not None:
            q = self.q_norm(q)
            k = self.k_norm(k)
        q = jnp.transpose(q, (0, 2, 1, 3))  # [B, H, 1, C]
        k = jnp.transpose(k, (0, 2, 1, 3))  # [B, Hkv, 1, C]
        v = jnp.transpose(v, (0, 2, 1, 3))
        q = apply_rotary(q, sin_row, cos_row)
        k = apply_rotary(k, sin_row, cos_row)
        # no sharding constraints HERE: this helper is shared with the
        # fixed-batch sampler's ring paths (decode_at/decode_recent_at),
        # which run under the TRAINING rule table with the batch dim
        # sharded — a serving-style batch-replicated pin would force a
        # per-layer-per-token reshard there. The paged serving caller
        # (decode_paged_at) applies its whole-head TP constraints itself.
        return q, k, v

    def decode_at(
        self,
        x: Array,  # [B, 1, D] — one new token per sequence
        cache_k: Array,  # [L, B, Hkv, C, W] FULL stacked ring buffer (time-minor)
        cache_v: Array,  # [L, B, Hkv, C, W]
        layer: int,  # STATIC layer index into the stacked cache
        slot: Array,  # [] int32 — ring slot to write (pos % W)
        mask: Array,  # [W] f32 additive mask over cache slots (0 / -inf)
        sin_row: Array,  # [1, C//2] rope row at the token's ABSOLUTE position
        cos_row: Array,
    ) -> tp.Tuple[Array, Array, Array]:
        """Single-token incremental attention against a ring-buffer KV cache.

        The reference has no decode path (sample.py:72-94 re-runs the full
        forward per token); this is the TPU-native replacement: O(W) per
        token, static shapes, jit/scan-friendly. Keys are roped at absolute
        positions, so evicting the oldest slot implements the reference's
        sliding window (sample.py:74 ``idx[:, -block_size:]``) exactly:
        attention scores depend only on position DIFFERENCES (RoPE shift
        invariance, tests/test_layers.py).

        Takes the WHOLE stacked cache and a static ``layer``: the write is
        one [B, Hkv, 1, C] dynamic_update_slice row that XLA aliases in
        place, and the read is a static slice that fuses into the attention
        einsums — nothing copies or re-stacks the [L, ...] cache (the old
        scan-over-layers decode re-materialized all L·B·Hkv·W·C elements of
        both caches per token: ~300 MB/step at the 124M shape, the dominant
        term in the measured 2.9 ms/token, PERF.md 'Serving bench')."""
        b, one, d = x.shape
        h, hkv = self.n_head, self.n_kv_head
        c = d // h
        q, k, v = self._decode_qkv(x, sin_row, cos_row)
        # cache is time-minor ([B, Hkv, C, W] per layer — see KVCache): the
        # new row lands as a single-lane column write
        kc = jnp.transpose(k, (0, 1, 3, 2))  # [B, Hkv, C, 1]
        vc = jnp.transpose(v, (0, 1, 3, 2))
        zero = jnp.zeros((), slot.dtype)
        at = (jnp.asarray(layer, slot.dtype), zero, zero, zero, slot)
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, kc.astype(cache_k.dtype)[None], at
        )
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, vc.astype(cache_v.dtype)[None], at
        )
        ck, cv = cache_k[layer], cache_v[layer]  # [B, Hkv, C, W] views
        # single-query attention as broadcast-multiply + reduce, NOT
        # dot_general: a [1, C] x [C, W] matvec uses one MXU row per pass
        # and measured ~160 GB/s; the VPU form streams the cache at full
        # rate (profiled 1.26 -> ~0.3 ms/step at 124M W=1024, PERF.md r4).
        # f32 casts fuse into the reduce — nothing materializes at [.., C, W].
        qg = q.reshape(b, hkv, h // hkv, 1, c)
        qcw = jnp.transpose(qg, (0, 1, 2, 4, 3))  # [B, Hkv, G, C, 1]
        scores = jnp.sum(
            qcw.astype(jnp.float32) * ck[:, :, None].astype(jnp.float32),
            axis=-2,
        )  # [B, Hkv, G, W]
        probs = jax.nn.softmax(
            (scores + mask) / math.sqrt(c), axis=-1
        )  # [B, Hkv, G, W] f32 — reduces over W must accumulate in f32
        out = jnp.sum(
            probs[:, :, :, None, :] * cv[:, :, None].astype(jnp.float32),
            axis=-1,
        ).astype(x.dtype)  # [B, Hkv, G, C]
        out = out[:, :, :, None, :]  # [B, Hkv, G, 1, C]
        out = out.reshape(b, h, 1, c)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, 1, h * c)
        return self.wo(out), cache_k, cache_v

    def decode_paged_at(
        self,
        x: Array,  # [S, 1, D] — one new token per decode SLOT
        pool_k: Array,  # [L, NP, Hkv, C, PS] page pool, READ-ONLY here
        pool_v: Array,  # [L, NP, Hkv, C, PS]
        bt: Array,  # [S, Pmax] int32 per-slot block tables (page ids)
        rk: Array,  # [L, S, Hkv, R, C] recent-K write buffer (row writes)
        rv: Array,  # [L, S, Hkv, R, C]
        layer: int,  # STATIC layer index
        r: Array,  # [] int32 — step index within the decode window
        mask_pool: Array,  # [S, W=Pmax*PS] additive f32 over paged slots
        mask_rec: Array,  # [R] additive f32 over recent rows
        sin_rows: Array,  # [S, 1, 1, C//2] per-slot rope rows (positions differ)
        cos_rows: Array,
        pooled_len: tp.Optional[Array] = None,  # [S] int32 (kernel / kv-quant)
        pool_sk: tp.Optional[Array] = None,  # [L, NP, Hkv] f32 (int8 pool)
        pool_sv: tp.Optional[Array] = None,
        paged_kernel: str = "xla",
    ) -> tp.Tuple[Array, Array, Array]:
        """Single-token attention against a PAGED KV pool read through
        per-slot block tables, plus the write-combining recent buffer.

        The serving variant of :meth:`decode_recent_at`: instead of one
        contiguous per-batch ring cache, every slot (request) owns a list
        of fixed-size pages in a shared pool (``midgpt_tpu.serving``) —
        its logical KV is the concatenation of its block-table pages. The
        gather through ``bt`` is the only new op; the two-part joint
        softmax (exact, not an approximation) and the read-only-pool /
        bulk-merge write discipline are identical to the chunked sampler's
        (PERF.md r4 'Serving': per-token scattered column writes into the
        big time-minor cache either flip its layout or pay scattered RMW).
        Positions differ PER SLOT (continuous batching mixes requests at
        different depths), hence per-slot rope rows and a [S, W] mask.

        ``paged_kernel="pallas"`` replaces the gather + two-part softmax
        with the ragged Pallas kernel (ops.paged_attn): the block table
        is walked IN-KERNEL over each slot's ``pooled_len``, pages
        stream from HBM exactly once, and no ``[S, Pmax*PS, ...]``
        gathered intermediate exists — BITWISE the same result (the
        kernel mirrors this method's op sequence; tested). An int8 pool
        (``pool_sk``/``pool_sv`` given) dequantizes per (page, KV-head)
        po2 scale — in-kernel on the kernel path, at the gathered view
        here — and this step's K/V row is rounded through its target
        page's grid BEFORE the recent buffer sees it, so in-window reads
        and post-flush pool reads of the same position are
        indistinguishable (the invariance the token-identity matrix
        rests on)."""
        b, one, d = x.shape
        h, hkv = self.n_head, self.n_kv_head
        c = d // h
        q, k, v = self._decode_qkv(x, sin_rows, cos_rows)
        # whole-head TP (serving meshes, serving_logical_rules): the
        # slot dim stays replicated — DP is shared-nothing engine
        # replicas, not a sharded slot axis — and every per-head tensor
        # splits over 'tensor'. No-ops outside an axis_rules scope.
        q = shard_act(q, None, "heads", None, None)
        k = shard_act(k, None, "kv_heads", None, None)
        v = shard_act(v, None, "kv_heads", None, None)
        quant = pool_sk is not None
        ps = pool_k.shape[-1]
        zero = jnp.zeros((), r.dtype)
        if quant:
            # round this step's row through its page's int8 grid before
            # ANY reader (the recent buffer, this very step's scores)
            # sees it. The page scale: derived from this row when the
            # page is born at this position, from the page's in-window
            # birth row (already rounded — derivation is rounding-
            # stable) when born earlier in this window, else the pool's
            # recorded scale.
            from midgpt_tpu.quant import round_kv_rows_to_grid
            from midgpt_tpu.serving.paged import kv_row_scales

            # the scale-derivation rows enter in COMPUTE dtype, exactly
            # like verify's candidate rows: the recent buffer's bf16
            # grid values upcast exactly, and matching operand dtypes
            # keep the decode and verify attention traces op-identical
            # (the choreography prover compares them record for record)
            at4 = (zero, zero, r, zero)
            tmp_k = jax.lax.dynamic_update_slice(
                rk[layer].astype(k.dtype), k, at4
            )
            tmp_v = jax.lax.dynamic_update_slice(
                rv[layer].astype(v.dtype), v, at4
            )
            sk_all, sv_all = kv_row_scales(
                tmp_k, tmp_v, pooled_len, bt, pool_sk[layer],
                pool_sv[layer], ps,
            )  # [S, Hkv, R]
            sk_r = jax.lax.dynamic_slice_in_dim(sk_all, r, 1, axis=2)
            sv_r = jax.lax.dynamic_slice_in_dim(sv_all, r, 1, axis=2)
            k = round_kv_rows_to_grid(k, sk_r)  # [S, Hkv, 1, C]
            v = round_kv_rows_to_grid(v, sv_r)
        at = (jnp.asarray(layer, r.dtype), zero, zero, r, zero)
        rk = jax.lax.dynamic_update_slice(rk, k.astype(rk.dtype)[None], at)
        rv = jax.lax.dynamic_update_slice(rv, v.astype(rv.dtype)[None], at)
        rk = shard_act(rk, None, None, "kv_heads", None, None)
        rv = shard_act(rv, None, None, "kv_heads", None, None)
        rkl, rvl = rk[layer], rv[layer]  # [S, Hkv, R, C]
        if paged_kernel == "pallas":
            # the ragged in-kernel block-table walk (ops.paged_attn):
            # bitwise this method's arithmetic, none of its HBM gather
            qs = shard_act(
                q.reshape(b, hkv, h // hkv, c), None, "kv_heads", None, None
            )
            out = _paged_kernel_dispatch(
                "decode", layer,
                (qs, pool_k, pool_v, bt, pooled_len, rkl, rvl, r),
                (_gathered_pool_scales(pool_sk, bt, layer),
                 _gathered_pool_scales(pool_sv, bt, layer)),
            )  # [S, Hkv, G, C]
            out = shard_act(out, None, "kv_heads", None, None)
            out = out.reshape(b, h, 1, c)
        else:
            # gather this layer's pages through the block tables: the
            # slot's logical KV [S, Hkv, C, W] in page order (int8 pools
            # dequantize at the view — see _gathered_pool_view)
            ck = _gathered_pool_view(pool_k, pool_sk, bt, layer)
            cv = _gathered_pool_view(pool_v, pool_sv, bt, layer)
            # the block-table gather indexes the (replicated) page dim of
            # a KV-head-sharded pool, so it is shard-local: each device
            # gathers its own heads' pages. Pin the gathered view so the
            # partitioner can never "help" by regathering heads (the
            # batch-allgather footgun the
            # no-batch-allgather-in-page-gather audit rule gates).
            ck = shard_act(ck, None, "kv_heads", None, None)
            cv = shard_act(cv, None, "kv_heads", None, None)
            qg = q.reshape(b, hkv, h // hkv, 1, c)
            qcw = jnp.transpose(qg, (0, 1, 2, 4, 3))  # [S, Hkv, G, C, 1]
            s_pool = jnp.sum(
                qcw.astype(jnp.float32) * ck[:, :, None].astype(jnp.float32),
                axis=-2,
            )  # [S, Hkv, G, W]
            s_rec = jnp.sum(
                qg.astype(jnp.float32) * rkl[:, :, None].astype(jnp.float32),
                axis=-1,
            )  # [S, Hkv, G, R]
            s_all = jnp.concatenate(
                [s_pool + mask_pool[:, None, None, :], s_rec + mask_rec],
                axis=-1,
            )
            probs = jax.nn.softmax(s_all / math.sqrt(c), axis=-1)
            p_pool = probs[..., : s_pool.shape[-1]]
            p_rec = probs[..., s_pool.shape[-1]:]
            # PV accumulation in the banded kernel's pinned
            # ascending-band order (ops.paged_attn.banded_fold, same
            # band plan): f32 addition is not associative, so matching
            # the kernel's chunked reduction order IS what keeps
            # kernel == XLA bitwise at long contexts. One band (every
            # small geometry) folds to exactly the pre-banding single
            # reduce — the trace is unchanged there.
            from midgpt_tpu.ops.paged_attn import (
                banded_fold, resolved_band_pages,
            )
            w_pool = s_pool.shape[-1]
            bw = resolved_band_pages(
                bt.shape[1], ps, c, jnp.dtype(pool_k.dtype).itemsize
            ) * ps
            if bw >= w_pool:
                o_pool = jnp.sum(
                    p_pool[:, :, :, None, :]
                    * cv[:, :, None].astype(jnp.float32),
                    axis=-1,
                )  # [S, Hkv, G, C]
            else:
                # plain lax slices (NOT mixed None+slice indexing,
                # which lowers to a gather and hides the band start
                # from the choreo prover's order extractor)
                o_pool = banded_fold([
                    jnp.sum(
                        jax.lax.slice_in_dim(
                            p_pool, lo, lo + bw, axis=-1
                        )[:, :, :, None, :]
                        * jax.lax.slice_in_dim(
                            cv, lo, lo + bw, axis=-1
                        )[:, :, None].astype(jnp.float32),
                        axis=-1,
                    )
                    for lo in range(0, w_pool, bw)
                ])  # [S, Hkv, G, C]
            o_rec = jnp.sum(
                p_rec[..., None] * rvl[:, :, None].astype(jnp.float32),
                axis=-2,
            )
            out = (o_pool + o_rec).astype(x.dtype)
            out = out.reshape(b, h, 1, c)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, 1, h * c)
        # merged [.., H*C] stays head-contiguous tensor-sharded: wo is
        # row-parallel (GPT_PARAM_RULES), so the contraction runs on
        # local heads and GSPMD inserts ONE psum on the [.., D] result
        out = shard_act(out, None, None, "heads")
        return self.wo(out), rk, rv

    def prefill_paged_at(
        self,
        x: Array,  # [1, T, D] — the prefill chunk's hidden states
        pool_k: Array,  # [L, NP, Hkv, C, PS] page pool, READ-ONLY here
        pool_v: Array,  # [L, NP, Hkv, C, PS]
        bt: Array,  # [1, Pmax] int32 — the slot's block table
        layer: int,  # STATIC layer index
        mask_pool: Array,  # [W = Pmax*PS] additive f32 (0 where pos < start)
        mask_self: Array,  # [T, T] additive causal f32 within the chunk
        sin_rows: Array,  # [T, C//2] rope rows at the chunk's positions
        cos_rows: Array,
        start: tp.Optional[Array] = None,  # [] int32 (kv-quant only)
        pool_sk: tp.Optional[Array] = None,  # [L, NP, Hkv] f32 (int8 pool)
        pool_sv: tp.Optional[Array] = None,
    ) -> tp.Tuple[Array, Array, Array]:
        """Multi-query attention for a PREFILL CHUNK over a pre-populated
        block table: the chunk's T tokens attend jointly to the slot's
        already-resident pages (positions < chunk start — the cached
        prefix and/or earlier chunks) and to themselves (causal). The
        suffix-only prefill path of the prefix cache: a request whose
        prompt prefix is already in the pool computes only this chunk's
        FLOPs, and chunked prefill resumes a long prompt mid-stream from
        whatever the block table already holds. Joint softmax over
        [pages | chunk] — exact, same two-part discipline as
        :meth:`decode_paged_at`. Returns (out, k, v) with k/v the chunk's
        post-rope K / raw V [1, Hkv, T, C] for the page write.

        The score/probs arithmetic deliberately MIRRORS
        ops.attention.naive_attention op for op: compute-dtype operands
        with f32 einsum accumulation, additive mask applied before the
        in-softmax scale, probs cast to the value dtype before the PV
        contraction. With an empty pool part the whole computation is
        then bitwise what ``model.hidden`` + naive attention produces,
        so a bf16-cache engine stays greedy-token-identical to the
        fixed-batch sampler (a cast-to-f32-early variant drifted by ~2
        bf16 ulps in the pool K/V — enough to flip near-tied greedy
        argmaxes on a real checkpoint, caught by the sample.py --serve
        verify drive). This contract is MACHINE-CHECKED: the
        choreography prover (midgpt_tpu.analysis.choreo, CI
        serving-choreo job) normalizes this subgraph's op-and-dtype
        trace out of the compiled chunk program's jaxpr and asserts its
        softmax core equals naive_attention's — a cast-early edit here
        turns that gate red before anything compiles."""
        b, t, d = x.shape
        h, hkv = self.n_head, self.n_kv_head
        c = d // h
        qkv = self.wqkv(x)  # [1, T, (H+2Hkv)C]
        q = qkv[..., : h * c].reshape(b, t, h, c)
        k = qkv[..., h * c : (h + hkv) * c].reshape(b, t, hkv, c)
        v = qkv[..., (h + hkv) * c :].reshape(b, t, hkv, c)
        if self.q_norm is not None:
            q = self.q_norm(q)
            k = self.k_norm(k)
        q = jnp.transpose(q, (0, 2, 1, 3))  # [1, H, T, C]
        k = jnp.transpose(k, (0, 2, 1, 3))  # [1, Hkv, T, C]
        v = jnp.transpose(v, (0, 2, 1, 3))
        q = apply_rotary(q, sin_rows, cos_rows)
        k = apply_rotary(k, sin_rows, cos_rows)
        # whole-head TP: per-head tensors split over 'tensor', the slot
        # dim replicated (see _decode_qkv)
        q = shard_act(q, None, "heads", None, None)
        k = shard_act(k, None, "kv_heads", None, None)
        v = shard_act(v, None, "kv_heads", None, None)
        if pool_sk is not None:
            # int8 pool: round the chunk's own K/V rows through their
            # target pages' grids BEFORE the in-chunk self-attention.
            # Without this, a later chunk would read these positions
            # from the pool (grid values) while the monolithic prefill
            # read them in-chunk un-rounded — chunked vs monolithic
            # streams would diverge under kv-quant. With it, every
            # reader of a position sees one value, whatever the chunk
            # grid. (The bf16 pool keeps the naive-attention contract
            # un-rounded — rounding there is the identity at serving
            # dtype, and the choreography prover pins that path.)
            from midgpt_tpu.quant import round_kv_rows_to_grid
            from midgpt_tpu.serving.paged import kv_row_scales

            ps_ = pool_k.shape[-1]
            sk_all, sv_all = kv_row_scales(
                k, v, jnp.reshape(start, (1,)).astype(jnp.int32), bt,
                pool_sk[layer], pool_sv[layer], ps_,
            )  # [1, Hkv, T]
            k = round_kv_rows_to_grid(k, sk_all)
            v = round_kv_rows_to_grid(v, sv_all)
        # gather the slot's pages (clip-mode for the same NaN reason as
        # decode_paged_at) -> logical KV [1, Hkv, C, W] in page order;
        # int8 pools dequantize at the view (_gathered_pool_view)
        ck = _gathered_pool_view(pool_k, pool_sk, bt, layer)
        cv = _gathered_pool_view(pool_v, pool_sv, bt, layer)
        ck = shard_act(ck, None, "kv_heads", None, None)
        cv = shard_act(cv, None, "kv_heads", None, None)
        qg = q.reshape(b, hkv, h // hkv, t, c)
        s_pool = jnp.einsum(
            "bhgtc,bhcw->bhgtw", qg, ck.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        )  # [1, Hkv, G, T, W]
        s_self = jnp.einsum(
            "bhgtc,bhsc->bhgts", qg, k,
            preferred_element_type=jnp.float32,
        )  # [1, Hkv, G, T, T]
        s_all = jnp.concatenate(
            [s_pool + mask_pool, s_self + mask_self], axis=-1
        )
        scale = 1.0 / jnp.sqrt(c).astype(jnp.float32)
        probs = jax.nn.softmax(s_all * scale, axis=-1)
        probs = probs.astype(v.dtype)
        p_pool = probs[..., : s_pool.shape[-1]]
        p_self = probs[..., s_pool.shape[-1]:]
        o_pool = jnp.einsum("bhgtw,bhcw->bhgtc", p_pool, cv.astype(v.dtype))
        o_self = jnp.einsum("bhgts,bhsc->bhgtc", p_self, v)
        out = (o_pool + o_self).reshape(b, h, t, c)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, t, h * c)
        # head-contiguous merged dim feeds the row-parallel wo (one psum)
        out = shard_act(out, None, None, "heads")
        return self.wo(out.astype(x.dtype)), k, v

    def verify_paged_at(
        self,
        x: Array,  # [S, T, D] — the verify dispatch's candidate rows
        pool_k: Array,  # [L, NP, Hkv, C, PS] page pool, READ-ONLY here
        pool_v: Array,  # [L, NP, Hkv, C, PS]
        bt: Array,  # [S, Pmax] int32 per-slot block tables
        layer: int,  # STATIC layer index
        mask_pool: Array,  # [S, 1, 1, 1, W] additive f32 (0 where resident)
        mask_self: Array,  # [T, T] additive causal f32 within the rows
        sin_rows: Array,  # [S, 1, T, C//2] per-slot rope rows
        cos_rows: Array,
        start: tp.Optional[Array] = None,  # [S] int32 (kernel / kv-quant)
        pool_sk: tp.Optional[Array] = None,  # [L, NP, Hkv] f32 (int8 pool)
        pool_sv: tp.Optional[Array] = None,
        paged_kernel: str = "xla",
    ) -> tp.Tuple[Array, Array, Array]:
        """Multi-query attention for SPECULATIVE VERIFICATION: all T
        candidate rows of every slot attend jointly to the slot's
        resident pages plus themselves (causal), one joint softmax.

        The dtype choreography deliberately MIRRORS
        :meth:`decode_paged_at` op for op — f32 upcast BEFORE the
        score multiply-sums, f32 probs through the PV contraction,
        mask added before the in-softmax ``/ sqrt(c)`` — NOT the
        prefill chunk's naive_attention choreography. Acceptance
        compares the verify logits' argmax against what the decode
        window would have sampled; on a real bf16 checkpoint the two
        choreographies disagree by ~2 bf16 ulps, enough to flip
        near-tied greedy argmaxes (caught by the sample.py --serve
        --serve_spec verify drive on a trained checkpoint — the same
        class of flip PR 4 hit with a cast-early prefill variant).
        Mirroring the decode arithmetic pins spec-on to the decode
        path at f32-reduction granularity, the same equivalence class
        as the tested K=4 vs K=1 window invariance. This contract is
        MACHINE-CHECKED: the choreography prover
        (midgpt_tpu.analysis.choreo, CI serving-choreo job) asserts
        the verify program's normalized attention trace equals the
        decode window's OP FOR OP — a prefill-flavored edit here turns
        that gate red before anything compiles."""
        b, t, d = x.shape
        h, hkv = self.n_head, self.n_kv_head
        c = d // h
        qkv = self.wqkv(x)  # [S, T, (H+2Hkv)C]
        q = qkv[..., : h * c].reshape(b, t, h, c)
        k = qkv[..., h * c : (h + hkv) * c].reshape(b, t, hkv, c)
        v = qkv[..., (h + hkv) * c :].reshape(b, t, hkv, c)
        if self.q_norm is not None:
            q = self.q_norm(q)
            k = self.k_norm(k)
        q = jnp.transpose(q, (0, 2, 1, 3))  # [S, H, T, C]
        k = jnp.transpose(k, (0, 2, 1, 3))  # [S, Hkv, T, C]
        v = jnp.transpose(v, (0, 2, 1, 3))
        q = apply_rotary(q, sin_rows, cos_rows)
        k = apply_rotary(k, sin_rows, cos_rows)
        # whole-head TP: per-head tensors split over 'tensor', the slot
        # dim replicated (see _decode_qkv)
        q = shard_act(q, None, "heads", None, None)
        k = shard_act(k, None, "kv_heads", None, None)
        v = shard_act(v, None, "kv_heads", None, None)
        quant = pool_sk is not None
        ps = pool_k.shape[-1]
        row_dt = jnp.bfloat16 if pool_k.dtype == jnp.int8 else pool_k.dtype
        if quant:
            # round the candidate rows through their target pages' int8
            # grids: the verify self-reads, the decode window's recent-
            # buffer reads, and the post-flush pool reads of the same
            # positions must all see the identical grid values, or
            # near-tied acceptance argmaxes flip between spec-on and
            # spec-off (the PR 5 bug class, int8 edition). Rows past the
            # watermark never land (flush mask) — rounding them is
            # harmless.
            from midgpt_tpu.quant import round_kv_rows_to_grid
            from midgpt_tpu.serving.paged import kv_row_scales

            sk_all, sv_all = kv_row_scales(
                k, v, start, bt, pool_sk[layer], pool_sv[layer], ps
            )  # [S, Hkv, T]
            k = round_kv_rows_to_grid(k, sk_all)
            v = round_kv_rows_to_grid(v, sv_all)
        qg = q.reshape(b, hkv, h // hkv, t, c)  # [S, Hkv, G, T, C]
        # the decode window stores each step's K/V into the CACHE-dtype
        # recent buffer and reads it back for the in-window scores — so
        # rows see cache-rounded self keys/values. Mirror that rounding
        # (an identity when cache dtype == compute dtype, but an f32
        # model over a bf16 pool would otherwise score un-rounded self
        # keys and flip near-tied acceptance argmaxes)
        kc = k.astype(row_dt)
        vc = v.astype(row_dt)
        if paged_kernel == "pallas":
            # the ragged in-kernel block-table walk (ops.paged_attn):
            # bitwise this method's arithmetic, none of its HBM gather
            qg = shard_act(qg, None, "kv_heads", None, None, None)
            out = _paged_kernel_dispatch(
                "verify", layer,
                (qg, kc, vc, pool_k, pool_v, bt, start),
                (_gathered_pool_scales(pool_sk, bt, layer),
                 _gathered_pool_scales(pool_sv, bt, layer)),
            )  # [S, Hkv, G, T, C]
            out = shard_act(out, None, "kv_heads", None, None, None)
            out = out.reshape(b, h, t, c)
        else:
            # gather the slots' pages (clip-mode for the same NaN reason
            # as decode_paged_at) -> logical KV [S, Hkv, C, W] in page
            # order; int8 pools dequantize at the view
            ck = _gathered_pool_view(pool_k, pool_sk, bt, layer)
            cv = _gathered_pool_view(pool_v, pool_sv, bt, layer)
            ck = shard_act(ck, None, "kv_heads", None, None)
            cv = shard_act(cv, None, "kv_heads", None, None)
            # scores as f32 broadcast-multiply + reduce, exactly the
            # decode VPU form — q upcast first, cache upcast first, sum
            # over C
            s_pool = jnp.sum(
                qg[..., :, None].astype(jnp.float32)
                * ck[:, :, None, None].astype(jnp.float32),
                axis=-2,
            )  # [S, Hkv, G, T, W]
            s_self = jnp.sum(
                qg[:, :, :, :, None, :].astype(jnp.float32)
                * kc[:, :, None, None].astype(jnp.float32),
                axis=-1,
            )  # [S, Hkv, G, T, T]
            s_all = jnp.concatenate(
                [s_pool + mask_pool, s_self + mask_self], axis=-1
            )
            probs = jax.nn.softmax(s_all / math.sqrt(c), axis=-1)  # f32
            p_pool = probs[..., : s_pool.shape[-1]]
            p_self = probs[..., s_pool.shape[-1]:]
            # PV fold in the banded kernel's pinned ascending-band
            # order — same contract (and same band plan) as
            # decode_paged_at's XLA branch; one band degenerates to
            # the pre-banding single reduce, trace unchanged.
            from midgpt_tpu.ops.paged_attn import (
                banded_fold, resolved_band_pages,
            )
            w_pool = s_pool.shape[-1]
            bw = resolved_band_pages(
                bt.shape[1], ps, c, jnp.dtype(pool_k.dtype).itemsize
            ) * ps
            if bw >= w_pool:
                o_pool = jnp.sum(
                    p_pool[:, :, :, :, None, :]
                    * cv[:, :, None, None].astype(jnp.float32),
                    axis=-1,
                )  # [S, Hkv, G, T, C]
            else:
                # plain lax slices — see decode_paged_at's banded fold
                o_pool = banded_fold([
                    jnp.sum(
                        jax.lax.slice_in_dim(
                            p_pool, lo, lo + bw, axis=-1
                        )[:, :, :, :, None, :]
                        * jax.lax.slice_in_dim(
                            cv, lo, lo + bw, axis=-1
                        )[:, :, None, None].astype(jnp.float32),
                        axis=-1,
                    )
                    for lo in range(0, w_pool, bw)
                ])  # [S, Hkv, G, T, C]
            o_self = jnp.sum(
                p_self[..., None] * vc[:, :, None, None].astype(jnp.float32),
                axis=-2,
            )  # [S, Hkv, G, T, C]
            out = (o_pool + o_self).astype(x.dtype)
            out = out.reshape(b, h, t, c)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, t, h * c)
        # head-contiguous merged dim feeds the row-parallel wo (one psum)
        out = shard_act(out, None, None, "heads")
        return self.wo(out), k, v

    def decode_recent_at(
        self,
        x: Array,  # [B, 1, D]
        cache_k: Array,  # [L, B, Hkv, C, W] — READ-ONLY within the chunk
        cache_v: Array,  # [L, B, Hkv, C, W]
        rk: Array,  # [L, B, Hkv, R, C] recent-K write buffer (row writes)
        rv: Array,  # [L, B, Hkv, R, C]
        layer: int,  # STATIC layer index
        r: Array,  # [] int32 — step index within the chunk (recent row)
        mask_big: Array,  # [W] additive f32 over merged cache slots
        mask_rec: Array,  # [R] additive f32 over recent rows
        sin_row: Array,
        cos_row: Array,
    ) -> tp.Tuple[Array, Array, Array]:
        """Two-part single-token attention: merged ring cache + a small
        write-combining 'recent' buffer.

        Why the split (PERF.md r4 'Serving'): a per-step write into the big
        time-minor cache is a 1-lane column scattered over ~768 (8,128)
        tiles — XLA either flips the cache layout to make that write cheap
        (halving read bandwidth; reads are ~6x the writes) or pays ~24 us
        of scattered RMW per cache per layer. Writing instead into a small
        time-MAJOR buffer is one contiguous tile row per (b, kv-head); the
        big cache stays read-only (keeps its streaming-friendly layout) and
        absorbs the recent rows in one bulk aligned merge per chunk
        (``merge_recent``). Softmax runs jointly over both parts — exact,
        not an approximation."""
        b, one, d = x.shape
        h, hkv = self.n_head, self.n_kv_head
        c = d // h
        q, k, v = self._decode_qkv(x, sin_row, cos_row)
        zero = jnp.zeros((), r.dtype)
        at = (jnp.asarray(layer, r.dtype), zero, zero, r, zero)
        rk = jax.lax.dynamic_update_slice(rk, k.astype(rk.dtype)[None], at)
        rv = jax.lax.dynamic_update_slice(rv, v.astype(rv.dtype)[None], at)
        ck, cv = cache_k[layer], cache_v[layer]  # [B, Hkv, C, W]
        rkl, rvl = rk[layer], rv[layer]  # [B, Hkv, R, C]
        qg = q.reshape(b, hkv, h // hkv, 1, c)
        qcw = jnp.transpose(qg, (0, 1, 2, 4, 3))  # [B, Hkv, G, C, 1]
        s_big = jnp.sum(
            qcw.astype(jnp.float32) * ck[:, :, None].astype(jnp.float32),
            axis=-2,
        )  # [B, Hkv, G, W]
        s_rec = jnp.sum(
            qg.astype(jnp.float32) * rkl[:, :, None].astype(jnp.float32),
            axis=-1,
        )  # [B, Hkv, G, R]  (qg [.., 1, C] x rkl [.., R, C] summed over C)
        s = jnp.concatenate([s_big + mask_big, s_rec + mask_rec], axis=-1)
        probs = jax.nn.softmax(s / math.sqrt(c), axis=-1)  # [B, Hkv, G, W+R]
        p_big, p_rec = probs[..., : s_big.shape[-1]], probs[..., s_big.shape[-1]:]
        o_big = jnp.sum(
            p_big[:, :, :, None, :] * cv[:, :, None].astype(jnp.float32),
            axis=-1,
        )  # [B, Hkv, G, C]
        o_rec = jnp.sum(
            p_rec[..., None] * rvl[:, :, None].astype(jnp.float32), axis=-2
        )  # [B, Hkv, G, C]
        out = (o_big + o_rec).astype(x.dtype)
        out = out.reshape(b, h, 1, c)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, 1, h * c)
        return self.wo(out), rk, rv


def mlp_hidden_dim(cfg: ModelConfig) -> int:
    """MLP hidden width. Fractional ratios (SwiGLU's 8/3) round UP to a
    multiple of 256 — int(8/3 * 4096) = 10922 is not even lane-aligned
    and tiles terribly on the 128-wide MXU, while 256-rounding gives
    exactly Llama's published 11008 (the same rule Llama uses:
    multiple_of=256). Integral products (GELU 4x) are untouched;
    cfg.mlp_hidden pins an exact width (e.g. for old checkpoints)."""
    if cfg.mlp_hidden is not None:
        return cfg.mlp_hidden
    f = cfg.mlp_ratio * cfg.n_embd
    if f == int(f):
        return int(f)
    return 256 * -(-int(f) // 256)


def maybe_pin_mlp_hidden(cfg: ModelConfig, stored_params_meta: tp.Any) -> ModelConfig:
    """Reconcile ``cfg`` with a checkpoint's stored MLP width.

    Checkpoints written before fractional SwiGLU widths rounded up to a
    multiple of 256 hold ``int(mlp_ratio * n_embd)``-wide tensors; a config
    with ``mlp_hidden=None`` would now resolve to the rounded width and the
    restore templates would mismatch. Given the checkpoint's param METADATA
    (``Checkpointer.item_metadata()[...]["params"]`` — shapes only, no array
    reads), pin ``cfg.mlp_hidden`` to whatever width the checkpoint actually
    holds. No-op when the widths already agree or ``mlp_hidden`` is pinned."""
    import dataclasses

    if cfg.mlp_hidden is not None:
        return cfg
    stored = None
    for path, leaf in jax.tree_util.tree_flatten_with_path(stored_params_meta)[0]:
        keys = [getattr(k, "key", getattr(k, "name", None)) for k in path]
        if "w_down" in keys:
            # blocks are layer-stacked: w_down.weight is [L, F, D]
            stored = int(leaf.shape[-2])
            break
    if stored is None or stored == mlp_hidden_dim(cfg):
        return cfg
    return dataclasses.replace(cfg, mlp_hidden=stored)


def pin_mlp_hidden_from_ckpt(cfg: ModelConfig, ckpt: tp.Any) -> ModelConfig:
    """The restore-time entry point for ``maybe_pin_mlp_hidden``: no-op
    unless the width is fractional and unpinned (the only case the
    256-rounding rule changed), so ordinary restores skip the checkpoint
    metadata read (and its Orbax handler warnings). ``ckpt`` is anything
    with ``item_metadata()`` returning a ``{"params": ...}`` metadata tree
    (midgpt_tpu.checkpoint.Checkpointer)."""
    if cfg.mlp_hidden is not None:
        return cfg
    if cfg.mlp_ratio * cfg.n_embd == int(cfg.mlp_ratio * cfg.n_embd):
        return cfg
    return maybe_pin_mlp_hidden(cfg, ckpt.item_metadata()["params"])


@module
class MLP:
    """GELU MLP (parity: model.py:17-31) or SwiGLU (Llama family)."""

    w_up: Linear  # [D, F]
    w_down: Linear  # [F, D]
    w_gate: tp.Optional[Linear]  # [D, F] (SwiGLU only)
    dropout_rate: float = static(default=0.0)

    @staticmethod
    def init(key: KeyArray, cfg: ModelConfig) -> "MLP":
        k1, k2, k3 = jax.random.split(key, 3)
        f = mlp_hidden_dim(cfg)
        if cfg.mlp == "swiglu":
            gate = Linear.init(k3, cfg.n_embd, f)
        elif cfg.mlp == "gelu":
            gate = None
        else:
            raise ValueError(f"unknown mlp kind {cfg.mlp!r}")
        return MLP(
            w_up=Linear.init(k1, cfg.n_embd, f),
            w_down=Linear.init(k2, f, cfg.n_embd),
            w_gate=gate,
            dropout_rate=cfg.dropout,
        )

    def __call__(
        self,
        x: Array,
        *,
        key: tp.Optional[KeyArray] = None,
        deterministic: bool = True,
    ) -> Array:
        with jax.named_scope("mlp"):
            up = self.w_up(x)
            if self.w_gate is not None:
                hidden = jax.nn.silu(self.w_gate(x)) * up
            else:
                hidden = jax.nn.gelu(up)
            hidden = shard_act(hidden, "batch", "seq", "mlp")
            out = self.w_down(hidden)
            out = dropout(out, self.dropout_rate, key, deterministic)
            return shard_act(out, "batch", "seq", "embed")


@module
class MoEMLP:
    """Switch-style top-1 mixture of GELU experts (Switch Transformer,
    arXiv:2101.03961) — the expert-parallel (ep) MLP variant. Absent from
    the reference (its MLP is dense, model.py:17-31); built TPU-first:

    - routing/dispatch as DENSE one-hot einsums with STATIC shapes — the
      canonical TPU MoE formulation (no sorts, no ragged gathers, every
      FLOP on the MXU); capacity is per batch row and scales with top_k
      (K claims per token share the buffers): C = ceil(cf * top_k * T / E).
    - experts stacked [E, D, F]/[E, F, D] and sharded over the 'tensor'
      mesh axis (GPT_PARAM_RULES): each shard computes its local experts'
      [B, E/tp, C, *] blocks and GSPMD inserts the psum on the combine
      contraction — expert parallelism without any hand-written
      collective.
    - the load-balance auxiliary loss (E * sum_e f_e * p_e; 1.0 when
      perfectly balanced) is returned next to the output and threaded to
      the trainer through the layer scan (GPT.hidden(return_aux=True)).

    Tokens overflowing an expert's capacity are dropped (contribute zero;
    the residual connection passes them through) — standard Switch
    semantics. At top_k > 1 capacity slots fill in TOKEN order with first
    and second choices interleaved (one cumsum over the combined
    assignment matrix) — a deliberate deviation from GShard, which fills
    every first choice before admitting any second choice. The single
    cumsum keeps the fill one static-shaped pass; the difference only
    shows under overflow, where GShard would evict a late token's FIRST
    choice in favor of an early token's second choice slightly less
    often. Router runs in f32 for a stable softmax."""

    router: Linear  # [D, E]
    expert_up: Array  # [E, D, F]
    expert_down: Array  # [E, F, D]
    capacity_factor: float = static(default=1.25)
    dropout_rate: float = static(default=0.0)
    top_k: int = static(default=1)  # 1 = Switch, 2 = GShard-style

    @staticmethod
    def init(key: KeyArray, cfg: ModelConfig) -> "MoEMLP":
        kr, ku, kd = jax.random.split(key, 3)
        e, d, f = cfg.moe_experts, cfg.n_embd, mlp_hidden_dim(cfg)
        # per-expert init identical to Linear.init (truncated normal,
        # lecun scaling) so experts start like the dense MLP they replace
        up = (1.0 / jnp.sqrt(d)) * jax.random.truncated_normal(
            ku, lower=-2, upper=2, shape=(e, d, f), dtype=jnp.float32
        )
        down = (1.0 / jnp.sqrt(f)) * jax.random.truncated_normal(
            kd, lower=-2, upper=2, shape=(e, f, d), dtype=jnp.float32
        )
        assert 1 <= cfg.moe_top_k <= e, cfg.moe_top_k
        return MoEMLP(
            router=Linear.init(kr, d, e),
            expert_up=up.astype(jnp.float32),
            expert_down=down.astype(jnp.float32),
            capacity_factor=cfg.moe_capacity,
            dropout_rate=cfg.dropout,
            top_k=cfg.moe_top_k,
        )

    @property
    def n_experts(self) -> int:
        return self.expert_up.shape[0]

    def __call__(
        self,
        x: Array,  # [B, T, D]
        *,
        key: tp.Optional[KeyArray] = None,
        deterministic: bool = True,
        return_dropped: bool = False,
    ) -> tp.Tuple[Array, ...]:
        """(y, aux) — with ``return_dropped`` also the dropped-claim
        fraction: routing claims past their expert's capacity contribute
        zero output (standard Switch drop semantics), and that fraction
        is the one silent failure mode of the subsystem — a collapsed
        router looks fine in the loss curve while most tokens pass
        through the residual untouched (VERDICT r5 Next #7)."""
        b, t, d = x.shape
        e = self.n_experts
        # GShard capacity: K claims per token share the buffers, so C
        # scales with top_k — at K=2 an unscaled C would drop ~(2-cf)/2
        # of all claims even under perfect balance (code review r5)
        cap = int(-(-self.capacity_factor * self.top_k * t // e))  # ceil
        cap = max(1, min(cap, t))
        k = self.top_k
        with jax.named_scope("moe"):
            # f32 router (tiny [D, E] matmul; softmax stability)
            logits = self.router(x.astype(jnp.float32))  # [B, T, E]
            # keep every routing tensor batch/seq-sharded: unconstrained,
            # GSPMD re-shards the [B,T,E] probs around top_k with a
            # batch all-gather (caught by the HLO audit)
            logits = shard_act(logits, "batch", "seq", None)
            probs = jax.nn.softmax(logits, axis=-1)
            # K iterative argmax extractions instead of lax.top_k: XLA's
            # TopK lowering under GSPMD replicates the batch dim (a
            # full-batch all-gather, caught by the HLO audit); max/argmax
            # reductions partition cleanly, and K is static and tiny
            vals, idxs = [], []
            remaining = probs
            for _ in range(k):
                vals.append(jnp.max(remaining, axis=-1))
                ix = jnp.argmax(remaining, axis=-1)
                idxs.append(ix)
                remaining = remaining * (
                    1.0 - jax.nn.one_hot(ix, e, dtype=probs.dtype)
                )
            topv = jnp.stack(vals, axis=-1)  # [B, T, K]
            topi = jnp.stack(idxs, axis=-1)
            topv = shard_act(topv, "batch", "seq", None)
            topi = shard_act(topi, "batch", "seq", None)
            # chosen-expert assignment matrix (<= K ones per token) and
            # per-(token, expert) combine weight: top-1 keeps the raw
            # Switch prob; K > 1 renormalizes the chosen gates to sum 1
            # (GShard) so identical experts reproduce the dense MLP
            choice_oh = jax.nn.one_hot(topi, e, dtype=jnp.float32)  # [B,T,K,E]
            choice_oh = shard_act(choice_oh, "batch", "seq", None, None)
            assign = jnp.sum(choice_oh, axis=2)  # [B, T, E] in {0, 1}
            assign = shard_act(assign, "batch", "seq", None)
            gates = topv / jnp.sum(topv, axis=-1, keepdims=True) if k > 1 else topv
            w = jnp.einsum("btke,btk->bte", choice_oh, gates)  # [B, T, E]
            w = shard_act(w, "batch", "seq", None)

            # load-balance aux (Switch eq. 4) over FIRST choices
            first = choice_oh[:, :, 0]  # [B, T, E]
            frac = jnp.mean(first, axis=1)  # [B, E]
            pmean = jnp.mean(probs, axis=1)  # [B, E]
            aux = e * jnp.mean(jnp.sum(frac * pmean, axis=-1))

            # position of each (token, expert) claim within the expert's
            # capacity buffer — columns are independent, so one cumsum
            # covers any K. NOTE: this fills slots in token order with
            # 1st/2nd choices interleaved, NOT GShard's
            # first-choices-first order (see the class docstring) — a
            # deliberate trade of fill-priority fidelity for a single
            # static-shaped pass
            pos = jnp.cumsum(assign, axis=1) * assign  # [B, T, E], 1-based
            pos = shard_act(pos, "batch", "seq", None)
            keep = (assign * (pos <= cap)).astype(x.dtype)  # [B, T, E]
            keep = shard_act(keep, "batch", "seq", None)
            pos0 = jnp.clip(pos.astype(jnp.int32) - 1, 0, cap - 1)
            slot_oh = jax.nn.one_hot(pos0, cap, dtype=x.dtype)  # [B,T,E,C]

            # dispatch -> [B,E,C,D] (one-hot einsums: all static shapes,
            # all MXU)
            disp = keep[..., None] * slot_oh  # [B, T, E, C]
            disp = shard_act(disp, "batch", "seq", "expert", None)
            xe = jnp.einsum("btec,btd->becd", disp, x)
            xe = shard_act(xe, "batch", "expert", None, "embed")
            h = jax.nn.gelu(
                jnp.einsum(
                    "becd,edf->becf", xe, self.expert_up.astype(x.dtype)
                )
            )
            # NOT "mlp" on the last dim: it aliases 'tensor', which the
            # expert dim already occupies
            h = shard_act(h, "batch", "expert", None, None)
            ye = jnp.einsum(
                "becf,efd->becd", h, self.expert_down.astype(x.dtype)
            )
            # combine scaled by the per-expert gate (router grad path)
            comb = disp * w.astype(x.dtype)[..., None]
            y = jnp.einsum("btec,becd->btd", comb, ye)
            y = dropout(y, self.dropout_rate, key, deterministic)
            y = shard_act(y, "batch", "seq", "embed")
            if not return_dropped:
                return y, aux
            # fraction of routing claims past capacity (dropped): scalar
            # reductions partition cleanly under any mesh
            n_claims = jnp.sum(assign.astype(jnp.float32))
            n_kept = jnp.sum(keep.astype(jnp.float32))
            dropped = 1.0 - n_kept / jnp.maximum(n_claims, 1.0)
            return y, aux, dropped


def make_mlp(key: KeyArray, cfg: ModelConfig):
    """MLP factory: dense (gelu/swiglu) or MoE by cfg.mlp."""
    if cfg.mlp == "moe":
        return MoEMLP.init(key, cfg)
    return MLP.init(key, cfg)


def mlp_call(mlp, x, *, key=None, deterministic=True, with_stats=False):
    """(y, aux) for either MLP kind — dense returns aux = 0. With
    ``with_stats``: (y, aux, dropped_frac), dense dropped = 0."""
    if with_stats:
        if isinstance(mlp, MoEMLP):
            return mlp(
                x, key=key, deterministic=deterministic, return_dropped=True
            )
        y = mlp(x, key=key, deterministic=deterministic)
        zero = jnp.zeros((), jnp.float32)
        return y, zero, zero
    out = mlp(x, key=key, deterministic=deterministic)
    if isinstance(mlp, MoEMLP):
        return out
    return out, jnp.zeros((), jnp.float32)


@module
class Block:
    """Pre-norm residual block (parity: model.py:84-105)."""

    attn: Attention
    mlp: tp.Union[MLP, "MoEMLP"]
    ln1: RMSNorm
    ln2: RMSNorm

    @staticmethod
    def init(key: KeyArray, cfg: ModelConfig) -> "Block":
        k1, k2 = jax.random.split(key)
        return Block(
            attn=Attention.init(k1, cfg),
            mlp=make_mlp(k2, cfg),
            # weightless block norms (model.py:94-95, layers.py:64-68)
            ln1=RMSNorm.init(cfg.n_embd, use_weight=False, impl=cfg.norm_impl),
            ln2=RMSNorm.init(cfg.n_embd, use_weight=False, impl=cfg.norm_impl),
        )

    def __call__(
        self,
        x: Array,
        sin,
        cos,
        *,
        impl: str = "naive",
        key: tp.Optional[KeyArray] = None,
        deterministic: bool = True,
        return_kv: bool = False,
        return_aux: bool = False,
    ) -> tp.Union[Array, tp.Tuple[Array, tp.Tuple[Array, Array]]]:
        attn_key, mlp_key = (
            jax.random.split(key) if key is not None else (None, None)
        )
        attn_out = self.attn(
            self.ln1(x), sin, cos, impl=impl, key=attn_key,
            deterministic=deterministic, return_kv=return_kv,
        )
        kv = None
        if return_kv:
            attn_out, kv = attn_out
        x = x + attn_out
        y, aux = mlp_call(
            self.mlp, self.ln2(x), key=mlp_key, deterministic=deterministic
        )
        x = x + y
        if return_aux:
            return ((x, aux), kv) if return_kv else (x, aux)
        return (x, kv) if return_kv else x

    def decode_at(self, x, cache_k, cache_v, layer, slot, mask, sin_row, cos_row):
        attn_out, cache_k, cache_v = self.attn.decode_at(
            self.ln1(x), cache_k, cache_v, layer, slot, mask, sin_row, cos_row
        )
        x = x + attn_out
        x = x + mlp_call(self.mlp, self.ln2(x))[0]
        return x, cache_k, cache_v

    def decode_recent_at(
        self, x, cache_k, cache_v, rk, rv, layer, r, mask_big, mask_rec,
        sin_row, cos_row,
    ):
        attn_out, rk, rv = self.attn.decode_recent_at(
            self.ln1(x), cache_k, cache_v, rk, rv, layer, r,
            mask_big, mask_rec, sin_row, cos_row,
        )
        x = x + attn_out
        x = x + mlp_call(self.mlp, self.ln2(x))[0]
        return x, rk, rv

    def decode_paged_at(
        self, x, pool_k, pool_v, bt, rk, rv, layer, r, mask_pool, mask_rec,
        sin_rows, cos_rows, pooled_len=None, pool_sk=None, pool_sv=None,
        paged_kernel="xla",
    ):
        attn_out, rk, rv = self.attn.decode_paged_at(
            self.ln1(x), pool_k, pool_v, bt, rk, rv, layer, r,
            mask_pool, mask_rec, sin_rows, cos_rows, pooled_len=pooled_len,
            pool_sk=pool_sk, pool_sv=pool_sv, paged_kernel=paged_kernel,
        )
        x = x + attn_out
        x = x + mlp_call(self.mlp, self.ln2(x))[0]
        return x, rk, rv

    def prefill_paged_at(
        self, x, pool_k, pool_v, bt, layer, mask_pool, mask_self,
        sin_rows, cos_rows, start=None, pool_sk=None, pool_sv=None,
        sp=False,
    ):
        if not sp:
            attn_out, k, v = self.attn.prefill_paged_at(
                self.ln1(x), pool_k, pool_v, bt, layer, mask_pool,
                mask_self, sin_rows, cos_rows, start=start,
                pool_sk=pool_sk, pool_sv=pool_sv,
            )
            x = x + attn_out
            x = x + mlp_call(self.mlp, self.ln2(x))[0]
            return x, k, v
        # Sequence-parallel prefill (Megatron-SP style): the per-token
        # segments that tensor parallelism leaves REPLICATED — ln1/ln2,
        # both residual adds — run with the chunk's T rows sharded over
        # 'tensor' (the 'sp' logical axis), and a pure all-gather of
        # rows restores full T before each parallel region. Every
        # floating-point op keeps its exact off-path operands: a row's
        # layernorm reduces over D inside that row, the gathers move
        # bytes without touching values, and the attention/matmul block
        # below is the IDENTICAL head-parallel arithmetic (one joint
        # softmax — the choreo prover checks the same signature either
        # way). Pinning attn/mlp outputs replicated BEFORE re-sharding
        # rows keeps the row-parallel psum an all-reduce — left free,
        # GSPMD may fuse it to reduce-scatter, whose partial-sum order
        # is not contractually the all-reduce's (the PR 9 lse-merge
        # lesson, one level down). That is what makes sp=True bitwise
        # against sp=False by construction rather than by tolerance.
        x = shard_act(x, None, "sp", None)
        h1 = shard_act(self.ln1(x), None, None, None)  # gather rows
        attn_out, k, v = self.attn.prefill_paged_at(
            h1, pool_k, pool_v, bt, layer, mask_pool, mask_self,
            sin_rows, cos_rows, start=start, pool_sk=pool_sk,
            pool_sv=pool_sv,
        )
        attn_out = shard_act(attn_out, None, None, None)  # pin the psum
        x = x + shard_act(attn_out, None, "sp", None)
        h2 = shard_act(self.ln2(x), None, None, None)  # gather rows
        mlp_out = shard_act(mlp_call(self.mlp, h2)[0], None, None, None)
        x = x + shard_act(mlp_out, None, "sp", None)
        return x, k, v

    def verify_paged_at(
        self, x, pool_k, pool_v, bt, layer, mask_pool, mask_self,
        sin_rows, cos_rows, start=None, pool_sk=None, pool_sv=None,
        paged_kernel="xla",
    ):
        attn_out, k, v = self.attn.verify_paged_at(
            self.ln1(x), pool_k, pool_v, bt, layer, mask_pool, mask_self,
            sin_rows, cos_rows, start=start, pool_sk=pool_sk,
            pool_sv=pool_sv, paged_kernel=paged_kernel,
        )
        x = x + attn_out
        x = x + mlp_call(self.mlp, self.ln2(x))[0]
        return x, k, v


def embed_tokens(wte: Embedding, tokens: Array) -> Array:
    """Token embedding that stays SPMD-friendly under tensor parallelism.

    When the vocab dim is tensor-sharded (GPT_PARAM_RULES), a jnp.take
    whose indexed dim is sharded forces SPMD into involuntary full
    rematerialization; the TPU-native embedding under TP is a one-hot
    contraction — GSPMD turns the vocab-sharded einsum into a partial
    matmul + psum over 'tensor', and the MXU eats it. With an unsharded
    vocab the plain gather is cheaper. Shared by the batched forward and
    the KV-cache decode path."""
    mesh = current_mesh()
    if mesh is not None and mesh.shape.get("tensor", 1) > 1:
        one_hot = jax.nn.one_hot(
            tokens, wte.weight.shape[0], dtype=wte.weight.dtype
        )
        one_hot = shard_act(one_hot, "batch", "seq", "vocab")
        return one_hot @ wte.weight
    return wte(tokens)


@module
class GPT:
    """The full model. ``blocks`` leaves carry a leading n_layer axis."""

    wte: Embedding  # [V, D]
    blocks: Block  # stacked: every leaf [L, ...]
    ln_f: RMSNorm
    lm_head: tp.Optional[Linear]  # [D, V]; None when tie_embeddings
    config: ModelConfig = static()

    @staticmethod
    def init(key: KeyArray, cfg: ModelConfig) -> "GPT":
        block_key, head_key = jax.random.split(key)
        block_keys = jax.random.split(block_key, cfg.n_layer)
        blocks = jax.vmap(lambda k: Block.init(k, cfg))(block_keys)
        embed_std = 1 / math.sqrt(cfg.n_embd)
        wte_wt = embed_std * jax.random.normal(
            head_key, (cfg.vocab_size, cfg.n_embd), dtype=jnp.float32
        )
        if cfg.tie_embeddings:
            lm_head = None  # reuse wte.weight.T at the head
        else:
            # reference semantics: same init array, independent params
            # (model.py:134-138; SURVEY.md 2.3 "init-only tying")
            lm_head = Linear(weight=wte_wt.T)
        return GPT(
            wte=Embedding(weight=wte_wt),
            blocks=blocks,
            ln_f=RMSNorm.init(
                cfg.n_embd, use_weight=False, eps=1e-5, impl=cfg.norm_impl
            ),
            lm_head=lm_head,
            config=cfg,
        )

    def hidden(
        self,
        tokens: Array,  # [B, T] int32
        *,
        key: tp.Optional[KeyArray] = None,
        deterministic: bool = True,
        attn_impl: tp.Optional[str] = None,
        return_kv: bool = False,
        return_aux: bool = False,
    ) -> tp.Union[Array, tp.Tuple[Array, tp.Tuple[Array, Array]]]:
        """[B, T, D] final (ln_f-normalized) hidden states; with
        ``return_kv`` also the per-layer post-rope K / raw V stacked
        [L, B, Hkv, T, C] (collected as scan ys — the prefill path).
        ``return_aux`` additionally returns the MoE load-balance loss
        SUMMED over layers (the scan carries ``aux_in + aux``; 0.0 for
        dense MLPs) — the trainer consumes it when cfg.mlp == "moe"
        (train.loss_fn scales the sum by ``moe_aux_weight``, so the
        effective per-layer weight shrinks as 1/n_layer relative to a
        mean; Switch's own formulation also sums over layers)."""
        cfg = self.config
        impl = attn_impl if attn_impl is not None else cfg.attn_impl
        b, t = tokens.shape
        assert t <= cfg.block_size, f"sequence {t} > block_size {cfg.block_size}"
        sin, cos = rope_tables(cfg.head_dim, t, cfg.rope_base)

        drop_key, scan_keys = (None, None)
        if key is not None:
            drop_key, block_key = jax.random.split(key)
            scan_keys = jax.random.split(block_key, cfg.n_layer)

        with jax.named_scope("gpt"):
            h = embed_tokens(self.wte, tokens)  # [B, T, D]
            h = dropout(h, cfg.dropout, drop_key, deterministic)
            h = shard_act(h, "batch", "seq", "embed")

            def body(carry, layer):
                block, k = layer
                if return_aux:
                    h_in, aux_in = carry
                    out = block(
                        h_in, sin, cos, impl=impl, key=k,
                        deterministic=deterministic, return_kv=return_kv,
                        return_aux=True,
                    )
                    if return_kv:
                        (h_out, aux), kv = out
                        return (h_out, aux_in + aux), kv
                    h_out, aux = out
                    return (h_out, aux_in + aux), None
                out = block(
                    carry, sin, cos, impl=impl, key=k,
                    deterministic=deterministic, return_kv=return_kv,
                )
                if return_kv:
                    return out  # (x, (k, v)) — kv stacked by scan as ys
                return out, None

            if cfg.remat == "full":
                # whole-block remat (parity: model.py:149-153)
                body = jax.checkpoint(body)
            elif cfg.remat == "dots":
                body = jax.checkpoint(
                    body,
                    policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                )
            elif cfg.remat not in ("none", "auto"):
                # "auto" reaching the model means no trainer resolved it
                # (inference/sampling) — remat is moot without gradients,
                # so it behaves as "none"; train() resolves it by HBM fit
                # (midgpt_tpu.train.resolve_auto_knobs)
                raise ValueError(f"unknown remat policy {cfg.remat!r}")

            unroll = cfg.scan_unroll if cfg.scan_unroll else cfg.n_layer
            carry0 = (h, jnp.zeros((), jnp.float32)) if return_aux else h
            carry, kvs = jax.lax.scan(
                body, carry0, (self.blocks, scan_keys), unroll=unroll
            )
            if return_aux:
                # SUM over layers (Switch eq. 4 applies alpha per layer
                # and sums) — a mean would weaken balancing pressure by
                # n_layer (code review r5)
                h, aux = carry
            else:
                h = carry
            h = self.ln_f(h)
            if return_aux:
                return ((h, kvs), aux) if return_kv else (h, aux)
            return (h, kvs) if return_kv else h

    def moe_stats(
        self, tokens: Array, *, attn_impl: tp.Optional[str] = None
    ) -> tp.Dict[str, Array]:
        """Router telemetry from one deterministic forward: the MoE
        load-balance aux (summed over layers, the training convention)
        and the dropped-claim fraction (mean over layers). Runs its own
        layer scan so the hot ``hidden`` path carries no stats plumbing;
        the trainer calls this once per eval interval (utils.metrics logs
        the two scalars) — a collapsed or overflowing router becomes
        visible the interval it happens instead of never."""
        cfg = self.config
        assert cfg.mlp == "moe", "moe_stats requires an MoE model"
        impl = attn_impl if attn_impl is not None else cfg.attn_impl
        b, t = tokens.shape
        sin, cos = rope_tables(cfg.head_dim, t, cfg.rope_base)

        with jax.named_scope("moe_stats"):
            h = embed_tokens(self.wte, tokens)
            h = shard_act(h, "batch", "seq", "embed")

            def body(hc, block):
                attn_out = block.attn(
                    block.ln1(hc), sin, cos, impl=impl, deterministic=True
                )
                hc = hc + attn_out
                y, aux, dropped = mlp_call(
                    block.mlp, block.ln2(hc), with_stats=True
                )
                return hc + y, (aux, dropped)

            _, (auxs, droppeds) = jax.lax.scan(body, h, self.blocks)
        return {
            "aux": jnp.sum(auxs),
            "dropped_frac": jnp.mean(droppeds),
        }

    def head_weight(self, dtype) -> Array:
        """[D, V] lm-head weight in ``dtype`` (the shared wte array when
        init-only-tied/tied, SURVEY.md 2.3). Full-precision heads only —
        a quantized head has no standalone weight to hand out (the scale
        belongs in the matmul epilogue); use :meth:`project`."""
        assert not hasattr(self.lm_head, "scale"), (
            "quantized head: use GPT.project — materializing "
            "head_weight would dequantize the full [D, V] matrix"
        )
        return (
            self.wte.weight.T.astype(dtype)
            if self.lm_head is None
            else self.lm_head.weight.astype(dtype)
        )

    def project(self, h: Array) -> Array:
        """Hidden states ``[..., D]`` -> vocab logits ``[..., V]`` — the
        ONE lm-head entry point every forward/decode/prefill/verify path
        uses. For a quantized model (midgpt_tpu.quant) this fuses the
        dequant epilogue ``(h @ w_int8) * scale`` so the int8 head is
        what streams from HBM; full-precision models keep the plain
        ``h @ head_weight`` contraction (bit-identical to the
        pre-quantization code path)."""
        from midgpt_tpu.quant import QuantLinear

        if isinstance(self.lm_head, QuantLinear):
            return self.lm_head(h)
        return h @ self.head_weight(h.dtype)

    def __call__(
        self,
        tokens: Array,  # [B, T] int32
        *,
        key: tp.Optional[KeyArray] = None,
        deterministic: bool = True,
        attn_impl: tp.Optional[str] = None,
    ) -> Array:  # [B, T, V] logits in compute dtype
        h = self.hidden(
            tokens, key=key, deterministic=deterministic, attn_impl=attn_impl
        )
        logits = self.project(h)  # [B, T, V]
        return shard_act(logits, "batch", "seq", "vocab")


@module
class KVCache:
    """Per-layer KV cache; leaves carry a leading n_layer axis, matching the
    scan-stacked block params.

    TIME IS THE MINOR DIM ([..., C, W], not [..., W, C]): TPU tiles the last
    two dims to (8, 128), so a W-major cache with C=64 pads every 64-lane
    row to 128 — 2x the HBM footprint AND half the effective read bandwidth
    on the decode path, which is cache-read-bound (measured 1.33 us/slot vs
    the 0.36 us roofline, PERF.md r4). With W minor the tiles are full:
    C=64 sublanes are a legal multiple of 8 and W pads only to the next 128.
    The attention einsums contract identically either way — only the index
    order changes."""

    k: Array  # [L, B, Hkv, C, T_max]
    v: Array  # [L, B, Hkv, C, T_max]

    @staticmethod
    def init(cfg: ModelConfig, batch: int, max_len: int, dtype=jnp.bfloat16) -> "KVCache":
        shape = (cfg.n_layer, batch, cfg.kv_heads, cfg.head_dim, max_len)
        return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def decode_step(
    model: GPT,
    tokens: Array,  # [B] int32 — the newest token per sequence
    pos: Array,  # [] int32 — ABSOLUTE position of this token (tokens so far)
    cache: KVCache,
    rope_len: tp.Optional[int] = None,
) -> tp.Tuple[Array, KVCache]:
    """One incremental decoding step: logits for the next token + updated
    cache. O(W) per token vs the reference's O(T * full-forward)
    (sample.py:72-94).

    The cache is a ring buffer of W = cache length slots. While pos < W
    this is ordinary append-at-pos decoding; past W it becomes the
    reference's sliding window (sample.py:74): the new token evicts the
    oldest. ``rope_len`` sizes the rope tables (>= total generation length;
    defaults to W for the non-sliding case).

    The layer loop is STRAIGHT-LINE code over static layer slices — not a
    lax.scan. Scanning the cache through as xs/ys re-stacked every element
    of both [L, B, Hkv, W, C] caches per token (~300 MB at 124M, ~6x the
    weights); serving is HBM-bound, so that re-stack dominated the step.
    Unrolled, each layer is one in-place row write + a static-slice read,
    the block weights stream exactly once per token, and XLA fuses the
    whole layer into a handful of kernels."""
    cfg = model.config
    w = cache.k.shape[-1]
    sin_np, cos_np = rope_tables(cfg.head_dim, rope_len or w, cfg.rope_base)
    sin_t, cos_t = jnp.asarray(sin_np), jnp.asarray(cos_np)

    # ring arithmetic (all static-shape): write slot and per-slot validity.
    # slot s holds absolute position abs_s = pos - ((pos - s) mod W); it is
    # a real entry iff abs_s >= 0 — which also guarantees abs_s > pos - W
    # (in-window) and abs_s <= pos (causal).
    slot = jnp.mod(pos, w)
    idx = jnp.arange(w)
    abs_pos = pos - jnp.mod(pos - idx, w)
    mask = jnp.where(abs_pos >= 0, 0.0, -jnp.inf).astype(jnp.float32)
    sin_row = jax.lax.dynamic_slice_in_dim(sin_t, pos, 1, axis=0)
    cos_row = jax.lax.dynamic_slice_in_dim(cos_t, pos, 1, axis=0)

    h = embed_tokens(model.wte, tokens[:, None])  # [B, 1, D]
    ck, cv = cache.k, cache.v
    sin_h, cos_h = sin_row.astype(h.dtype), cos_row.astype(h.dtype)
    for i in range(cfg.n_layer):
        block = jax.tree.map(lambda a: a[i], model.blocks)  # static slices
        h, ck, cv = block.decode_at(h, ck, cv, i, slot, mask, sin_h, cos_h)
    h = model.ln_f(h)
    logits = model.project(h)[:, 0, :]  # [B, V]
    return logits, KVCache(k=ck, v=cv)


def decode_step_recent(
    model: GPT,
    tokens: Array,  # [B] int32
    pos: Array,  # [] int32 — absolute position (chunk_base + r)
    cache: KVCache,  # merged ring cache, READ-ONLY here
    rk: Array,  # [L, B, Hkv, R, C] recent-K buffer
    rv: Array,
    r: Array,  # [] int32 — step index within the chunk
    chunk_base: tp.Union[int, Array],  # absolute position of the chunk start
    window: int,  # STATIC sliding-window size (min(total, block_size))
    rope_len: int,
) -> tp.Tuple[Array, Array, Array]:
    """One decode step of the chunked sampler: attends over the merged ring
    cache (positions < chunk_base, masked to the sliding window) plus the
    recent buffer (positions chunk_base..chunk_base+r), and appends this
    token's K/V to the recent buffer. The big cache is never written — see
    ``Attention.decode_recent_at`` for why that is the fast shape of KV
    decoding on TPU. ``merge_recent`` folds the buffer in at chunk end."""
    cfg = model.config
    w = cache.k.shape[-1]
    rr = rk.shape[3]
    sin_np, cos_np = rope_tables(cfg.head_dim, rope_len, cfg.rope_base)
    sin_t, cos_t = jnp.asarray(sin_np), jnp.asarray(cos_np)

    # merged slot s holds the latest position < chunk_base congruent to s
    # (mod W'); valid iff it exists and is inside the sliding window
    idx = jnp.arange(w)
    cb1 = chunk_base - 1
    abs_pos = cb1 - jnp.mod(cb1 - idx, w)
    valid_big = (abs_pos >= 0) & (abs_pos > pos - window)
    mask_big = jnp.where(valid_big, 0.0, -jnp.inf).astype(jnp.float32)
    # recent row j holds position chunk_base + j: causal upper bound
    # (j <= r) AND the sliding-window lower bound (j > r - window) — a
    # chunk longer than the window must evict its own oldest rows too
    ridx = jnp.arange(rr)
    mask_rec = jnp.where(
        (ridx <= r) & (ridx > r - window), 0.0, -jnp.inf
    ).astype(jnp.float32)
    sin_row = jax.lax.dynamic_slice_in_dim(sin_t, pos, 1, axis=0)
    cos_row = jax.lax.dynamic_slice_in_dim(cos_t, pos, 1, axis=0)

    h = embed_tokens(model.wte, tokens[:, None])  # [B, 1, D]
    sin_h, cos_h = sin_row.astype(h.dtype), cos_row.astype(h.dtype)
    for i in range(cfg.n_layer):
        block = jax.tree.map(lambda a: a[i], model.blocks)
        h, rk, rv = block.decode_recent_at(
            h, cache.k, cache.v, rk, rv, i, r, mask_big, mask_rec,
            sin_h, cos_h,
        )
    h = model.ln_f(h)
    logits = model.project(h)[:, 0, :]  # [B, V]
    return logits, rk, rv


def decode_step_paged(
    model: GPT,
    tokens: Array,  # [S] int32 — the newest token per decode slot
    pos: Array,  # [S] int32 — PER-SLOT absolute position of this token
    pool_k: Array,  # [L, NP, Hkv, C, PS] page pool, READ-ONLY here
    pool_v: Array,  # [L, NP, Hkv, C, PS]
    bt: Array,  # [S, Pmax] int32 per-slot block tables
    rk: Array,  # [L, S, Hkv, R, C] recent buffers
    rv: Array,
    r: Array,  # [] int32 — step index within the decode window
    pooled_len: Array,  # [S] int32 — tokens already flushed to the pool
    rope_len: int,
    pool_sk: tp.Optional[Array] = None,  # [L, NP, Hkv] f32 (int8 pool)
    pool_sv: tp.Optional[Array] = None,
    paged_kernel: str = "xla",
    layer_scan: str = "off",
) -> tp.Tuple[Array, Array, Array]:
    """One decode step of the continuous-batching engine: every slot
    attends over its OWN block-table pages (positions < pooled_len[s])
    plus the shared recent buffer (window positions pooled_len[s]..r),
    and appends its token's K/V to the recent buffer. The pool is never
    written here — ``midgpt_tpu.serving.flush_recent`` folds the window's
    rows into the pages in one bulk scatter at window end (the same
    read-only-cache discipline as ``decode_step_recent``). Unlike the
    ring sampler there is no sliding window: pages are append-only and
    the engine caps each request at ``block_size`` total tokens.

    ``layer_scan="on"`` folds the layer loop into ONE ``lax.scan`` over
    the stacked block params (ROADMAP item 1: the unrolled loop
    re-dispatches the whole per-layer kernel set L times per step —
    launch overhead the [B, 1, D] decode shapes cannot hide). The
    read-only pool planes, scale planes and recent buffers ride as
    per-layer scan ``xs`` (a dynamic-slice read per iteration — nothing
    re-stacks, unlike carrying a written cache through a scan, the
    decode_step trap) and each iteration returns its layer's updated
    recent rows as ``ys``. The body calls the very same
    ``Block.decode_paged_at`` as the unrolled path, on a ``[1, ...]``
    per-layer view with ``layer=0``, so the scan BODY's arithmetic is
    op-for-op the per-layer trace — which the scan-equivalence prover
    (midgpt_tpu.analysis.fusion, CI serving-choreo job) proves
    statically and the bitwise on-vs-off token-identity matrix pins at
    runtime."""
    cfg = model.config
    s = tokens.shape[0]
    pmax = bt.shape[1]
    ps = pool_k.shape[-1]
    rr = rk.shape[3]
    # KV-head-sharded pool (TP serving): pages and the time dim stay
    # whole per shard, so every block-table gather below is shard-local
    pool_k = shard_act(pool_k, None, None, "kv_heads", None, None)
    pool_v = shard_act(pool_v, None, None, "kv_heads", None, None)
    if pool_sk is not None:
        pool_sk = shard_act(pool_sk, None, None, "kv_heads")
        pool_sv = shard_act(pool_sv, None, None, "kv_heads")
    sin_np, cos_np = rope_tables(cfg.head_dim, rope_len, cfg.rope_base)
    sin_t, cos_t = jnp.asarray(sin_np), jnp.asarray(cos_np)

    # paged slot j of the gathered [W = Pmax*PS] view holds logical
    # position j for that slot; valid iff already flushed to the pool
    idx = jnp.arange(pmax * ps)
    mask_pool = jnp.where(
        idx[None, :] < pooled_len[:, None], 0.0, -jnp.inf
    ).astype(jnp.float32)  # [S, W]
    # recent row j holds the slot's window position pooled_len + j;
    # causal bound j <= r (rows > r are unwritten). Always >= 1 valid
    # row (row r = the token itself), so empty slots never softmax over
    # an all-masked axis.
    ridx = jnp.arange(rr)
    mask_rec = jnp.where(ridx <= r, 0.0, -jnp.inf).astype(jnp.float32)
    pos_c = jnp.clip(pos, 0, rope_len - 1)
    sin_rows = jnp.take(sin_t, pos_c, axis=0)[:, None, None, :]  # [S,1,1,C/2]
    cos_rows = jnp.take(cos_t, pos_c, axis=0)[:, None, None, :]

    h = embed_tokens(model.wte, tokens[:, None])  # [S, 1, D]
    sin_h, cos_h = sin_rows.astype(h.dtype), cos_rows.astype(h.dtype)
    assert layer_scan in ("on", "off"), layer_scan
    if layer_scan == "on":
        quant = pool_sk is not None

        def body(hc, xs):
            block, pk_l, pv_l, rk_l, rv_l = xs[:5]
            sk_l = xs[5][None] if quant else None
            sv_l = xs[6][None] if quant else None
            hc, rk1, rv1 = block.decode_paged_at(
                hc, pk_l[None], pv_l[None], bt, rk_l[None], rv_l[None],
                0, r, mask_pool, mask_rec, sin_h, cos_h,
                pooled_len=pooled_len, pool_sk=sk_l, pool_sv=sv_l,
                paged_kernel=paged_kernel,
            )
            return hc, (rk1[0], rv1[0])

        xs = (model.blocks, pool_k, pool_v, rk, rv)
        if quant:
            xs = xs + (pool_sk, pool_sv)
        h, (rk, rv) = jax.lax.scan(body, h, xs)
    else:
        for i in range(cfg.n_layer):
            block = jax.tree.map(lambda a: a[i], model.blocks)
            h, rk, rv = block.decode_paged_at(
                h, pool_k, pool_v, bt, rk, rv, i, r, mask_pool, mask_rec,
                sin_h, cos_h, pooled_len=pooled_len, pool_sk=pool_sk,
                pool_sv=pool_sv, paged_kernel=paged_kernel,
            )
    h = model.ln_f(h)
    # vocab-sharded logits (TP lm head is column-parallel): nothing here
    # gathers the [S, V] row — greedy argmax partitions over 'tensor'
    logits = shard_act(model.project(h)[:, 0, :], None, "vocab")  # [S, V]
    return logits, rk, rv


def prefill_chunk_paged(
    model: GPT,
    tokens: Array,  # [1, T] int32 — one prefill chunk (right-padded)
    start: Array,  # [] int32 — absolute position of chunk token 0
    pool_k: Array,  # [L, NP, Hkv, C, PS] page pool, READ-ONLY here
    pool_v: Array,
    bt: Array,  # [1, Pmax] int32 — the slot's block table
    rope_len: int,
    pool_sk: tp.Optional[Array] = None,  # [L, NP, Hkv] f32 (int8 pool)
    pool_sv: tp.Optional[Array] = None,
    layer_scan: str = "off",
    sp: bool = False,
) -> tp.Tuple[Array, Array, Array]:
    """Suffix-only prefill of one chunk against a pre-populated block
    table: the chunk's tokens (context positions ``start .. start+T-1``)
    attend to everything already resident in the slot's pages (positions
    ``< start`` — the prefix-cache hit and/or earlier chunks of the same
    prompt) plus themselves, causally, in one joint softmax per layer.

    ``sp=True`` (ServingEngine ``prefill_sp``) is the sequence-parallel
    variant: the chunk's T rows are sharded over 'tensor' (logical axis
    'sp') through every segment tensor parallelism otherwise replicates
    — embedding output, ln1/ln2, the residual adds, ln_f — with row
    all-gathers restoring full T at each parallel-region boundary. The
    attention and matmul arithmetic is byte-for-byte the sp=False code
    (same one-joint-softmax choreography; see Block.prefill_paged_at),
    so streams are bitwise identical while the replicated O(T·D)
    per-token work and activation traffic scale 1/tp.

    This is what makes both tentpole features exact rather than
    approximate: a prefix-cache hit skips the cached pages' prefill
    compute entirely (only the suffix runs through here), and chunked
    Sarathi-style prefill resumes a long prompt mid-stream from the
    partially-built block table — in both cases the attention each token
    sees is identical to the monolithic full-prompt forward.

    Returns ``(h, ks, vs)``: the chunk's final hidden states [1, T, D]
    (logits come from the last REAL row) and the per-layer post-rope K /
    raw V [L, 1, Hkv, T, C] for the page write
    (serving.paged.write_token_rows). Pad rows beyond the chunk's real
    length are harmless: causally invisible to real rows (they sit at
    LATER positions) and their K/V rows are masked out of the write."""
    cfg = model.config
    b, t = tokens.shape
    assert b == 1, f"chunk prefill is per-slot, got batch {b}"
    pmax = bt.shape[1]
    ps = pool_k.shape[-1]
    pool_k = shard_act(pool_k, None, None, "kv_heads", None, None)
    pool_v = shard_act(pool_v, None, None, "kv_heads", None, None)
    if pool_sk is not None:
        pool_sk = shard_act(pool_sk, None, None, "kv_heads")
        pool_sv = shard_act(pool_sv, None, None, "kv_heads")
    sin_np, cos_np = rope_tables(cfg.head_dim, rope_len, cfg.rope_base)
    sin_t, cos_t = jnp.asarray(sin_np), jnp.asarray(cos_np)

    # paged slot w of the gathered [W = Pmax*PS] view holds logical
    # position w; resident (and < any chunk position) iff w < start
    idx = jnp.arange(pmax * ps)
    mask_pool = jnp.where(idx < start, 0.0, -jnp.inf).astype(jnp.float32)
    # in-chunk causal mask; row i may attend chunk rows j <= i
    ii = jnp.arange(t)
    mask_self = jnp.where(
        ii[None, :] <= ii[:, None], 0.0, -jnp.inf
    ).astype(jnp.float32)  # [T, T]
    pos = jnp.clip(start + ii, 0, rope_len - 1)  # pad tail clips harmlessly
    sin_rows = jnp.take(sin_t, pos, axis=0)  # [T, C//2]
    cos_rows = jnp.take(cos_t, pos, axis=0)

    h = embed_tokens(model.wte, tokens)  # [1, T, D]
    if sp:
        # pin the embedding's vocab psum replicated (identical all-reduce
        # to the sp=False trace) before slicing rows locally
        h = shard_act(h, None, None, None)
        h = shard_act(h, None, "sp", None)
    sin_h, cos_h = sin_rows.astype(h.dtype), cos_rows.astype(h.dtype)
    assert layer_scan in ("on", "off"), layer_scan
    if layer_scan == "on":
        # layer loop folded into one lax.scan (see decode_step_paged):
        # read-only pool/scale planes ride as xs, the chunk's per-layer
        # K/V land as scan ys — exactly the jnp.stack of the unrolled
        # loop, produced a layer at a time
        quant = pool_sk is not None

        def body(hc, xs):
            block, pk_l, pv_l = xs[:3]
            sk_l = xs[3][None] if quant else None
            sv_l = xs[4][None] if quant else None
            hc, k, v = block.prefill_paged_at(
                hc, pk_l[None], pv_l[None], bt, 0, mask_pool, mask_self,
                sin_h, cos_h, start=start, pool_sk=sk_l, pool_sv=sv_l,
                sp=sp,
            )
            return hc, (k, v)

        xs = (model.blocks, pool_k, pool_v)
        if quant:
            xs = xs + (pool_sk, pool_sv)
        h, (ks, vs) = jax.lax.scan(body, h, xs)
    else:
        ks, vs = [], []
        for i in range(cfg.n_layer):
            block = jax.tree.map(lambda a: a[i], model.blocks)  # static
            h, k, v = block.prefill_paged_at(
                h, pool_k, pool_v, bt, i, mask_pool, mask_self, sin_h,
                cos_h, start=start, pool_sk=pool_sk, pool_sv=pool_sv,
                sp=sp,
            )
            ks.append(k)
            vs.append(v)
        ks, vs = jnp.stack(ks), jnp.stack(vs)
    h = model.ln_f(h)
    if sp:
        # final ln_f ran row-sharded; gather the chunk back replicated so
        # the caller's last-real-row slice and lm-head projection are the
        # sp=False trace verbatim
        h = shard_act(h, None, None, None)
    ks = shard_act(ks, None, None, "kv_heads", None, None)
    vs = shard_act(vs, None, None, "kv_heads", None, None)
    return h, ks, vs  # ks/vs: [L, 1, Hkv, T, C]


def verify_tokens_paged(
    model: GPT,
    tokens: Array,  # [S, T] int32 — candidate rows per decode slot
    start: Array,  # [S] int32 — per-slot absolute position of row 0 (the
    # slot's write watermark: tokens already resident in the pool)
    pool_k: Array,  # [L, NP, Hkv, C, PS] page pool, READ-ONLY here
    pool_v: Array,
    bt: Array,  # [S, Pmax] int32 per-slot block tables
    rope_len: int,
    pool_sk: tp.Optional[Array] = None,  # [L, NP, Hkv] f32 (int8 pool)
    pool_sv: tp.Optional[Array] = None,
    paged_kernel: str = "xla",
    layer_scan: str = "off",
) -> tp.Tuple[Array, Array, Array]:
    """Speculative-decoding VERIFICATION forward: score every slot's
    ``[T = spec_len + 1]`` candidate rows (the true next token + the
    drafted continuation) in one batched multi-query pass over the
    resident paged KV — all slots, all rows, ONE dispatch.

    This is :func:`prefill_chunk_paged` generalized from one slot to the
    whole decode batch: each slot's rows attend to its OWN block-table
    pages (positions ``< start[s]`` — per-slot masks, continuous batching
    mixes depths) plus themselves causally, one joint softmax
    (``Attention.verify_paged_at``). The attention DTYPE CHOREOGRAPHY
    mirrors the decode window's (``decode_paged_at``), not the prefill
    chunk's: acceptance compares these logits' argmax against what the
    decode path would have sampled at the same positions, and on a real
    bf16 checkpoint the prefill choreography differs by enough bf16 ulps
    to flip near-tied greedy argmaxes (caught by the sample.py --serve
    --serve_spec drive). Mirrored, greedy acceptance decisions are the
    decisions the non-speculative engine would have made one token at a
    time.

    Returns ``(logits, ks, vs)``: per-row next-token logits [S, T, V]
    (row j scores position ``start + j + 1`` — exact whenever rows
    ``0..j`` are the true context, which is precisely what acceptance
    checks) and the rows' post-rope K / raw V [L, S, Hkv, T, C] for the
    watermark-masked page write (only accepted rows' K/V ever lands;
    rejected rows are dropped by the scatter mask — the rollback)."""
    cfg = model.config
    s, t = tokens.shape
    pmax = bt.shape[1]
    ps = pool_k.shape[-1]
    pool_k = shard_act(pool_k, None, None, "kv_heads", None, None)
    pool_v = shard_act(pool_v, None, None, "kv_heads", None, None)
    if pool_sk is not None:
        pool_sk = shard_act(pool_sk, None, None, "kv_heads")
        pool_sv = shard_act(pool_sv, None, None, "kv_heads")
    sin_np, cos_np = rope_tables(cfg.head_dim, rope_len, cfg.rope_base)
    sin_t, cos_t = jnp.asarray(sin_np), jnp.asarray(cos_np)

    # paged slot w of the gathered [W = Pmax*PS] view holds logical
    # position w; resident iff w < start[s] — per-slot, broadcast over
    # (Hkv, G, T) in the [S, Hkv, G, T, W] score tensor
    idx = jnp.arange(pmax * ps)
    mask_pool = jnp.where(
        idx[None, :] < start[:, None], 0.0, -jnp.inf
    ).astype(jnp.float32)[:, None, None, None, :]  # [S, 1, 1, 1, W]
    ii = jnp.arange(t)
    mask_self = jnp.where(
        ii[None, :] <= ii[:, None], 0.0, -jnp.inf
    ).astype(jnp.float32)  # [T, T]
    pos = jnp.clip(start[:, None] + ii[None, :], 0, rope_len - 1)  # [S, T]
    sin_rows = jnp.take(sin_t, pos, axis=0)[:, None]  # [S, 1, T, C//2]
    cos_rows = jnp.take(cos_t, pos, axis=0)[:, None]

    h = embed_tokens(model.wte, tokens)  # [S, T, D]
    sin_h, cos_h = sin_rows.astype(h.dtype), cos_rows.astype(h.dtype)
    assert layer_scan in ("on", "off"), layer_scan
    if layer_scan == "on":
        # layer loop folded into one lax.scan (see decode_step_paged):
        # the candidate rows' per-layer K/V land as scan ys
        quant = pool_sk is not None

        def body(hc, xs):
            block, pk_l, pv_l = xs[:3]
            sk_l = xs[3][None] if quant else None
            sv_l = xs[4][None] if quant else None
            hc, k, v = block.verify_paged_at(
                hc, pk_l[None], pv_l[None], bt, 0, mask_pool, mask_self,
                sin_h, cos_h, start=start, pool_sk=sk_l, pool_sv=sv_l,
                paged_kernel=paged_kernel,
            )
            return hc, (k, v)

        xs = (model.blocks, pool_k, pool_v)
        if quant:
            xs = xs + (pool_sk, pool_sv)
        h, (ks, vs) = jax.lax.scan(body, h, xs)
    else:
        ks, vs = [], []
        for i in range(cfg.n_layer):
            block = jax.tree.map(lambda a: a[i], model.blocks)  # static
            h, k, v = block.verify_paged_at(
                h, pool_k, pool_v, bt, i, mask_pool, mask_self, sin_h,
                cos_h, start=start, pool_sk=pool_sk, pool_sv=pool_sv,
                paged_kernel=paged_kernel,
            )
            ks.append(k)
            vs.append(v)
        ks, vs = jnp.stack(ks), jnp.stack(vs)
    h = model.ln_f(h)
    # vocab-sharded per-row logits (column-parallel head) — acceptance
    # argmaxes partition over 'tensor', no gathered [S, T, V] buffer
    logits = shard_act(model.project(h), None, None, "vocab")  # [S, T, V]
    ks = shard_act(ks, None, None, "kv_heads", None, None)
    vs = shard_act(vs, None, None, "kv_heads", None, None)
    return logits, ks, vs  # ks/vs: [L, S, Hkv, T, C]


def merge_recent(
    cache: KVCache, rk: Array, rv: Array, slot0: tp.Union[int, Array],
    length: int,
) -> KVCache:
    """Fold the first ``length`` recent rows into the ring cache at slots
    [slot0, slot0+length) — one bulk, statically-indexed column-block write
    per cache (the chunked sampler aligns chunk bases so the slot range
    never wraps). The small transpose relayouts ~R columns once per chunk
    instead of paying scattered column writes every token."""
    kc = jnp.transpose(rk[:, :, :, :length, :], (0, 1, 2, 4, 3))
    vc = jnp.transpose(rv[:, :, :, :length, :], (0, 1, 2, 4, 3))
    return KVCache(
        k=jax.lax.dynamic_update_slice_in_dim(
            cache.k, kc.astype(cache.k.dtype), slot0, axis=4
        ),
        v=jax.lax.dynamic_update_slice_in_dim(
            cache.v, vc.astype(cache.v.dtype), slot0, axis=4
        ),
    )


def prefill(
    model: GPT, tokens: Array, cache: KVCache
) -> tp.Tuple[Array, KVCache]:
    """Fill the cache with a whole prompt in ONE batched forward pass —
    the per-layer post-rope K / raw V come out of the same scan that runs
    the blocks (return_kv), stacked along the layer axis by lax.scan.
    O(1) passes vs the reference's O(P x full-forward) loop
    (sample.py:72-94). Returns logits after the last prompt token + the
    filled cache."""
    cfg = model.config
    b, p = tokens.shape
    t_max = cache.k.shape[-1]
    assert p <= t_max, f"prompt {p} exceeds cache length {t_max}"
    # ring needs a live mesh, and an explicit 'flash' may not divide an
    # arbitrary prompt length — 'auto' keeps the flash fast path for
    # aligned prompts and falls back to naive otherwise
    impl = (
        "auto"
        if cfg.attn_impl in ("ring", "ulysses", "flash", "fused")
        else cfg.attn_impl
    )

    h, (ks, vs) = model.hidden(
        tokens, deterministic=True, attn_impl=impl, return_kv=True
    )  # ks/vs: [L, B, Hkv, P, C]
    # one-time transpose into the time-minor cache layout (KVCache) —
    # prefill is compute-bound, the relayout is noise there
    ks = jnp.transpose(ks, (0, 1, 2, 4, 3))  # [L, B, Hkv, C, P]
    vs = jnp.transpose(vs, (0, 1, 2, 4, 3))
    cache_k = jax.lax.dynamic_update_slice_in_dim(
        cache.k, ks.astype(cache.k.dtype), 0, axis=4
    )
    cache_v = jax.lax.dynamic_update_slice_in_dim(
        cache.v, vs.astype(cache.v.dtype), 0, axis=4
    )
    logits = model.project(h[:, -1, :])  # [B, V]
    return logits, KVCache(k=cache_k, v=cache_v)


def prefill_stepwise(
    model: GPT, tokens: Array, cache: KVCache
) -> tp.Tuple[Array, KVCache]:
    """Token-by-token prefill via decode_step — the oracle the batched
    prefill is tested against."""

    def body(carry, tok):
        pos, cache = carry
        logits, cache = decode_step(model, tok, pos, cache)
        return (pos + 1, cache), logits

    b, t = tokens.shape
    (_, cache), logits_all = jax.lax.scan(
        body, (jnp.zeros((), jnp.int32), cache), jnp.transpose(tokens)
    )
    return logits_all[-1], cache


def count_params(model: GPT) -> int:
    """Non-embedding param count (parity: model.py:161-164 — subtract the
    duplicated wte/lm_head array when untied)."""
    from midgpt_tpu.pytree import count_params as _count

    total = _count(model)
    if model.lm_head is not None:
        total -= model.lm_head.weight.size
    return total


# ---------------------------------------------------------------------------
# Parameter partition rules (replaces shard_gpt's size heuristic,
# model.py:167-178). Specs are right-aligned against param rank, so the same
# rule covers stacked [L, ...] scan params and unstacked ones.
# ---------------------------------------------------------------------------

from jax.sharding import PartitionSpec as P  # noqa: E402

GPT_PARAM_RULES: tp.Sequence[tp.Tuple[str, P]] = (
    # [V, D]: vocab over tensor, embed over fsdp
    (r"wte/weight", P("tensor", "fsdp")),
    # column-parallel: [L, D, (H+2Hkv)C] — in over fsdp, out over tensor
    (r"attn/wqkv/weight", P("fsdp", "tensor")),
    # row-parallel: [L, H*C, D] — in over tensor, out over fsdp
    (r"attn/wo/weight", P("tensor", "fsdp")),
    (r"attn/(q|k)_norm/weight", P()),
    (r"mlp/w_(up|gate)/weight", P("fsdp", "tensor")),
    (r"mlp/w_down/weight", P("tensor", "fsdp")),
    # QuantLinear per-OUTPUT-channel dequant scales (midgpt_tpu.quant,
    # the int8 serving pytree): a scale vector [L, out] / [out] must
    # shard exactly like its weight's OUT dim, or the fused epilogue
    # multiply regathers the activation it scales. Column-parallel
    # weights (out over tensor) -> scale over tensor; row-parallel
    # weights (out over fsdp) -> scale over fsdp. Right-aligned, so the
    # same rule covers stacked [L, out] and the unstacked head [out].
    (r"attn/wqkv/scale", P("tensor")),
    (r"attn/wo/scale", P("fsdp")),
    (r"mlp/w_(up|gate)/scale", P("tensor")),
    (r"mlp/w_down/scale", P("fsdp")),
    (r"lm_head/scale", P("tensor")),
    # MoE (mlp="moe"): experts over 'tensor' (expert parallelism), the
    # dense dims over fsdp (ZeRO); the tiny [D, E] router replicated.
    # Right-aligned onto the stacked [L, E, D, F] / [L, E, F, D] leaves.
    (r"mlp/expert_up", P("tensor", "fsdp", None)),
    (r"mlp/expert_down", P("tensor", None, "fsdp")),
    (r"mlp/router/weight", P()),
    (r"ln_f/weight|ln1/weight|ln2/weight", P()),
    # [D, V]: embed over fsdp, vocab over tensor
    (r"lm_head/weight", P("fsdp", "tensor")),
)

# Pipeline-parallel variant: block leaves additionally shard their leading
# (stacked-layer) axis over 'pipeline' — L/S layers per stage, which is what
# parallel.pipeline's shard_map strips. Non-stacked params (wte, ln_f,
# lm_head) stay pipeline-replicated: embedding/head run outside the pipeline
# (parallel.pipeline.gpt_pipeline_hidden). Specs here are full-rank (the
# right-alignment padding in param_shardings would otherwise misplace the
# leading 'pipeline' entry).
GPT_PP_PARAM_RULES: tp.Sequence[tp.Tuple[str, P]] = (
    (r"wte/weight", P("tensor", "fsdp")),
    (r"attn/wqkv/weight", P("pipeline", "fsdp", "tensor")),
    (r"attn/wo/weight", P("pipeline", "tensor", "fsdp")),
    (r"attn/(q|k)_norm/weight", P("pipeline", None)),
    (r"mlp/w_(up|gate)/weight", P("pipeline", "fsdp", "tensor")),
    (r"mlp/w_down/weight", P("pipeline", "tensor", "fsdp")),
    (r"ln_f/weight", P()),
    (r"ln1/weight|ln2/weight", P("pipeline", None)),
    (r"lm_head/weight", P("fsdp", "tensor")),
)


def gpt_param_rules(pipeline: bool = False) -> tp.Sequence[tp.Tuple[str, P]]:
    """Partition-rule table for a GPT; ``pipeline=True`` adds the
    stacked-layer-axis sharding the PP trainer needs."""
    return GPT_PP_PARAM_RULES if pipeline else GPT_PARAM_RULES
