"""midgpt_tpu — a TPU-native LLM pretraining framework.

Capability parity with AllanYangZhou/midGPT (reference at /root/reference),
rebuilt TPU-first:

- batched-native GPT/Llama-family models (``models/``) with GQA, SwiGLU,
  QK-LN + RoPE, scan-over-layers and remat policies;
- Pallas kernels (``ops/``): flash attention (custom VJP, GQA, in-kernel
  attention dropout), the projection-natural fused QK-LN+RoPE+attention
  family, fused RMSNorm, chunked cross-entropy;
- a 5-axis (pipeline, replica, fsdp, sequence, tensor) device mesh with
  declarative sharding rules (``parallel/``), ring attention for sequence
  parallelism, GPipe pipeline parallelism, and multi-slice DCN layouts;
- the training engine (``train.py``): donated jitted step, grad
  accumulation, async Orbax checkpointing with mesh-migration restore,
  SIGTERM force-save, metrics/MFU logging;
- serving (``sampling.py``): batched prefill + chunked KV-cache decode
  with a write-combining recent buffer, multi-chip samplers.

Entry points: ``launch.py`` (training CLI), ``sample.py`` (generation),
``bench.py`` (benchmarks); see PARITY.md for the reference-parity map.
"""

from midgpt_tpu.config import (
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    get_config,
    list_configs,
)

__version__ = "0.1.0"

__all__ = [
    "ExperimentConfig",
    "MeshConfig",
    "ModelConfig",
    "get_config",
    "list_configs",
]
