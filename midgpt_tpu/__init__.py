"""midgpt_tpu — a TPU-native LLM pretraining framework.

Capability parity with AllanYangZhou/midGPT (reference at /root/reference),
rebuilt TPU-first: batched-native models, a 4-axis
(replica, fsdp, sequence, tensor) device mesh with declarative sharding
rules, and Pallas flash-attention kernels. (Planned, tracked in SURVEY.md 7:
ring attention, trainer + async Orbax checkpointing, KV-cached sampler.)
"""

from midgpt_tpu.config import (
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    get_config,
    list_configs,
)

__version__ = "0.1.0"

__all__ = [
    "ExperimentConfig",
    "MeshConfig",
    "ModelConfig",
    "get_config",
    "list_configs",
]
