"""Shared telemetry substrate: metrics registry, typed event log with
flight-recorder rings, and a Prometheus text-exposition exporter.

Extracted from ``midgpt_tpu.serving.telemetry`` (PR 12) so the training
loop can build on the same core (``midgpt_tpu.train_telemetry``) without
importing the serving stack. The split:

- **Here (domain-free, jax-free at import time)**: :class:`Counter`,
  :class:`Gauge`, :class:`Histogram`, :class:`MetricsRegistry`,
  :func:`percentile`, the :class:`Event`/:class:`DispatchRecord` record
  types, the :class:`TelemetryLog` base (bounded recency ring +
  dispatch-record ring + per-key event log + replay signature + optional
  ``jax.profiler`` window), :func:`write_json`, and
  :func:`prometheus_text`.
- **In serving.telemetry**: the serving lifecycle taxonomy
  (``EVENT_KINDS``), :class:`~midgpt_tpu.serving.telemetry.EngineTelemetry`
  (per-request derived metrics), the request/dispatch-lane Chrome trace
  exporter, and the pinned ``ENGINE_STATS_KEYS``/``CLUSTER_STATS_KEYS``
  façade contracts. Everything serving imported before the split is
  re-exported there unchanged.
- **In train_telemetry**: the training-loop taxonomy, the train-lane
  Chrome trace exporter, and the anomaly monitors.

The shared design constraint carries over verbatim: telemetry is never a
parameter of any program factory, every emission reads host-side state
the caller already holds, and wall clock lives ONLY in the ``t``/``dur``
fields — ``data`` stays deterministic so
:meth:`TelemetryLog.sequence_signature` is replay-exact.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import re
import typing as tp

__all__ = [
    "Counter",
    "DispatchRecord",
    "Event",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS_S",
    "MetricsRegistry",
    "TelemetryLog",
    "percentile",
    "prometheus_text",
    "write_json",
]


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

#: Fixed latency buckets (seconds) shared by every latency histogram:
#: sub-ms through 10 s, roughly x2.5 per step. Fixed (not adaptive) so
#: snapshots from different runs/replicas merge bucket-for-bucket.
LATENCY_BUCKETS_S: tp.Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


class Counter:
    """A monotone-by-convention integer metric. ``value`` is plainly
    assignable (the bench's warmup reset relies on it)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str, value: int = 0):
        self.name = name
        self.value = value

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """A point-in-time reading: either ``set()`` explicitly or backed by
    a zero-arg callback evaluated at snapshot time (the registry's way
    of exporting live engine state — pool occupancy, queue depth —
    without mirroring writes into the hot path)."""

    __slots__ = ("name", "fn", "value")

    def __init__(self, name: str, fn: tp.Optional[tp.Callable[[], float]] = None):
        self.name = name
        self.fn = fn
        self.value: float = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def read(self) -> float:
        return self.fn() if self.fn is not None else self.value


class Histogram:
    """A fixed-bucket histogram: ``counts[i]`` counts observations
    ``<= bounds[i]``, with one overflow bucket at the end. Bounds are
    immutable after construction so snapshots merge across replicas."""

    __slots__ = ("name", "bounds", "counts", "total", "count")

    def __init__(self, name: str, bounds: tp.Sequence[float] = LATENCY_BUCKETS_S):
        assert list(bounds) == sorted(bounds), "bucket bounds must ascend"
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        i = 0
        for b in self.bounds:
            if v <= b:
                break
            i += 1
        self.counts[i] += 1
        self.total += v
        self.count += 1

    def reset(self) -> None:
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0.0
        self.count = 0

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "buckets": list(self.bounds),
            "counts": list(self.counts),
            "count": self.count,
            "sum": self.total,
        }


class MetricsRegistry:
    """Counters + gauges + histograms under get-or-create names, with a
    JSON-exportable :meth:`snapshot`. ``attach_labels`` registers a
    labeled counter family *by reference* (e.g. the engine's
    ``reject_reasons`` dict) so the owner keeps mutating its own dict
    and the snapshot sees it live."""

    def __init__(self) -> None:
        self.counters: tp.Dict[str, Counter] = {}
        self.gauges: tp.Dict[str, Gauge] = {}
        self.histograms: tp.Dict[str, Histogram] = {}
        self._labels: tp.Dict[str, tp.Dict[str, int]] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def gauge(
        self, name: str, fn: tp.Optional[tp.Callable[[], float]] = None
    ) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge(name, fn)
        elif fn is not None:
            g.fn = fn
        return g

    def histogram(
        self, name: str, bounds: tp.Sequence[float] = LATENCY_BUCKETS_S
    ) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(name, bounds)
        return h

    def attach_labels(self, name: str, labels: tp.Dict[str, int]) -> None:
        self._labels[name] = labels

    def reset_histograms(self) -> None:
        """Zero every histogram in place (bounds kept) — bench_serving's
        post-warmup reset, next to the counter zeroing."""
        for h in self.histograms.values():
            h.reset()

    def snapshot(self) -> tp.Dict[str, tp.Any]:
        """One JSON-able view of everything: counters by value, gauges
        evaluated now, histograms with bucket arrays, labeled families
        copied. This is the superset ``stats()`` selects its façade
        from."""
        return {
            "counters": {k: c.value for k, c in sorted(self.counters.items())},
            "labeled": {k: dict(v) for k, v in sorted(self._labels.items())},
            "gauges": {k: g.read() for k, g in sorted(self.gauges.items())},
            "histograms": {
                k: h.to_dict() for k, h in sorted(self.histograms.items())
            },
        }


def percentile(sorted_vals: tp.Sequence[float], q: float) -> tp.Optional[float]:
    """Nearest-rank percentile over an ascending list (None when empty)
    — the same convention bench_serving's TTFT percentiles use."""
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Event:
    """One lifecycle event. ``step`` is the owner's deterministic step
    counter (scheduler step for serving, optimizer-step window index for
    training) and ``seq`` the per-log emission index; both are
    replay-deterministic. ``t`` is the owner clock's monotonic reading
    and is the ONLY wall-clock field — ``data`` carries deterministic
    values (slots, counts, reasons) exclusively, which is what makes
    :meth:`TelemetryLog.sequence_signature` exact across replays."""

    seq: int
    step: int
    kind: str
    rid: tp.Optional[int]
    t: float
    data: tp.Dict[str, tp.Any] = dataclasses.field(default_factory=dict)

    def signature(self) -> tp.Tuple:
        return (
            self.seq, self.step, self.kind, self.rid,
            tuple(sorted(self.data.items())),
        )

    def to_json(self) -> tp.Dict[str, tp.Any]:
        return {
            "seq": self.seq,
            "step": self.step,
            "kind": self.kind,
            "rid": self.rid,
            "t": self.t,
            **self.data,
        }


@dataclasses.dataclass
class DispatchRecord:
    """One timed span, as the host saw it: for serving, a
    compiled-program launch with ``dur`` running to the window's
    existing device->host harvest read; for training, a loop phase
    (prefetch wait, fused window launch->harvest, eval pause,
    checkpoint save) bounded by host reads the loop already performs.
    No syncs are added either way."""

    seq: int
    step: int
    kind: str
    t: float
    dur: float
    rids: tp.Tuple[int, ...]
    tokens: int
    data: tp.Dict[str, tp.Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> tp.Dict[str, tp.Any]:
        return {
            "seq": self.seq,
            "step": self.step,
            "kind": self.kind,
            "t": self.t,
            "dur": self.dur,
            "rids": list(self.rids),
            "tokens": self.tokens,
            **self.data,
        }


# ---------------------------------------------------------------------------
# TelemetryLog — the shared event-log / flight-recorder core
# ---------------------------------------------------------------------------


class TelemetryLog:
    """Typed event log + flight-recorder rings, taxonomy-parameterized.

    Two views of one stream: ``request_log`` keeps every event per key
    (request id for serving, anything the owner chooses; bounded per
    key), while ``events`` is the bounded *recency* ring the flight
    recorder dumps (``ring`` events). ``dispatches`` is the companion
    ring of the last ``dispatch_ring`` timed spans.

    ``profile_dir`` + ``profile_steps=(start, stop)`` arm the optional
    ``jax.profiler`` hooks: the owner calls :meth:`maybe_profile` at the
    top of each step so a profiler trace starts at step ``start`` and
    stops at the top of ``stop`` — a bounded window around exactly the
    steps under investigation, host-driven, with no effect on any
    compiled program.
    """

    #: Subclasses pin their taxonomy here; ``emit`` asserts membership.
    event_kinds: tp.Tuple[str, ...] = ()

    def __init__(
        self,
        *,
        ring: int = 4096,
        dispatch_ring: int = 512,
        per_request_cap: int = 4096,
        profile_dir: tp.Optional[str] = None,
        profile_steps: tp.Optional[tp.Tuple[int, int]] = None,
    ):
        assert ring >= 1 and dispatch_ring >= 1 and per_request_cap >= 1
        if profile_steps is not None:
            assert profile_dir is not None, "profile_steps needs profile_dir"
            assert profile_steps[0] < profile_steps[1], profile_steps
        self.ring_capacity = ring
        self.dispatch_ring_capacity = dispatch_ring
        self.per_request_cap = per_request_cap
        self.profile_dir = profile_dir
        self.profile_steps = profile_steps
        self._profiling = False
        self.events: tp.Deque[Event] = collections.deque(maxlen=ring)
        self.dispatches: tp.Deque[DispatchRecord] = collections.deque(
            maxlen=dispatch_ring
        )
        self.request_log: tp.Dict[int, tp.List[Event]] = {}
        self._seq = 0

    # -- recording ---------------------------------------------------------

    def emit(
        self,
        kind: str,
        *,
        step: int,
        t: float,
        rid: tp.Optional[int] = None,
        **data,
    ) -> Event:
        assert kind in self.event_kinds, kind
        ev = Event(self._seq, step, kind, rid, t, data)
        self._seq += 1
        self.events.append(ev)
        if rid is not None:
            log = self.request_log.setdefault(rid, [])
            if len(log) < self.per_request_cap:
                log.append(ev)
        return ev

    def record_dispatch(
        self,
        kind: str,
        *,
        step: int,
        t: float,
        dur: float,
        rids: tp.Sequence[int],
        tokens: int,
        **data,
    ) -> DispatchRecord:
        rec = DispatchRecord(
            self._seq, step, kind, t, dur, tuple(rids), tokens, data
        )
        # dispatch records share the event seq space so the flight dump
        # interleaves them unambiguously
        self._seq += 1
        self.dispatches.append(rec)
        return rec

    def reset(self) -> None:
        """Drop everything recorded so far (bench_serving calls this
        after warmup, next to re-arming the fault hooks, so the measured
        trace's events start at seq 0 like its fault_steps do)."""
        self.events.clear()
        self.dispatches.clear()
        self.request_log.clear()
        self._seq = 0

    # -- optional jax.profiler window --------------------------------------

    def maybe_profile(self, step: int) -> None:
        """Called by the owner at the top of each step (only when
        telemetry is attached). Starts/stops a ``jax.profiler`` trace at
        the configured step boundaries; no-op without
        ``profile_steps``."""
        if self.profile_steps is None:
            return
        import jax

        start, stop = self.profile_steps
        if not self._profiling and step == start:
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
        elif self._profiling and step >= stop:
            self.stop_profiling()

    def stop_profiling(self) -> None:
        """Stop an in-flight ``jax.profiler`` trace (idempotent). The
        owner calls this when it drains, so a workload finishing before
        the configured ``stop`` step still finalizes the trace to
        ``profile_dir`` instead of leaving the profiler armed (a
        dangling trace is unwritten AND makes the next ``start_trace``
        in the process raise). Callers driving steps manually past a
        drain should call it too."""
        if not self._profiling:
            return
        import jax

        jax.profiler.stop_trace()
        self._profiling = False

    # -- replay determinism -------------------------------------------------

    def sequence_signature(self) -> tp.Tuple[tp.Tuple, ...]:
        """The event stream minus wall-clock: what a replay must
        reproduce exactly (events are keyed to the owner's deterministic
        step counter, and every ``data`` field is deterministic under
        the owner's replay contract). Ring-bounded: compare runs whose
        event count fits ``ring``."""
        return tuple(ev.signature() for ev in self.events)

    # -- flight recorder ----------------------------------------------------

    def flight_payload(self) -> tp.Dict[str, tp.Any]:
        """The ring contents as JSON-able structures. Snapshot-copies
        under the GIL, so it is safe to call from another thread
        best-effort (the cluster's cold watchdog path — the wedged step
        thread may still append, and a dump that misses its last event
        beats no dump, which is the r4/r5 lesson this exists for)."""
        return {
            "ring_capacity": self.ring_capacity,
            "events": [ev.to_json() for ev in list(self.events)],
            "dispatches": [d.to_json() for d in list(self.dispatches)],
        }


def write_json(path: str, payload: tp.Dict[str, tp.Any]) -> str:
    """Write a JSON artifact, creating parent directories; returns the
    absolute path (what watchdog rows and flight dumps record
    in-band)."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    return path


# ---------------------------------------------------------------------------
# Prometheus text exposition (the pull-scrape view of metrics_snapshot)
# ---------------------------------------------------------------------------

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(prefix: str, name: str, suffix: str = "") -> str:
    return _PROM_NAME_RE.sub("_", f"{prefix}_{name}{suffix}")


def _prom_labels(labels: tp.Mapping[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + body + "}"


def _is_registry_snapshot(snap: tp.Mapping[str, tp.Any]) -> bool:
    return {"counters", "gauges", "histograms"} <= set(snap)


def _expand(
    snap: tp.Mapping[str, tp.Any], labels: tp.Mapping[str, str]
) -> tp.List[tp.Tuple[tp.Dict[str, str], tp.Mapping[str, tp.Any]]]:
    """Normalize one snapshot into (labels, registry_snapshot) pairs.
    A cluster-shaped snapshot (``{"cluster": ..., "replicas": [...]}``,
    see ``ServingCluster.metrics_snapshot``) expands to one pair per
    replica plus a synthesized gauge-only pair for the cluster-level
    numeric scalars."""
    if _is_registry_snapshot(snap):
        return [(dict(labels), snap)]
    if "replicas" in snap and "cluster" in snap:
        out = []
        gauges = {
            k: float(v)
            for k, v in snap["cluster"].items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        out.append((
            dict(labels, scope="cluster"),
            {"counters": {}, "labeled": {}, "gauges": gauges,
             "histograms": {}},
        ))
        for i, rep in enumerate(snap["replicas"]):
            out.extend(_expand(rep, dict(labels, replica=str(i))))
        return out
    raise ValueError(
        f"not a registry or cluster metrics snapshot: {sorted(snap)[:6]}"
    )


def prometheus_text(
    snapshots: tp.Union[
        tp.Mapping[str, tp.Any],
        tp.Sequence[tp.Tuple[tp.Mapping[str, str], tp.Mapping[str, tp.Any]]],
    ],
    prefix: str = "midgpt",
) -> str:
    """Render metrics snapshots in Prometheus text exposition format.

    Accepts a single ``MetricsRegistry.snapshot()`` dict, a
    cluster-shaped snapshot (``ServingCluster.metrics_snapshot()``), or
    an explicit sequence of ``(labels, snapshot)`` pairs (how
    bench_serving labels replicas). Conventions: counters get a
    ``_total`` suffix, labeled families render as one labeled series
    per key, histograms render cumulative ``_bucket{le=...}`` series
    plus ``_sum``/``_count``. ``# TYPE`` headers are emitted once per
    family, so concatenated replica snapshots stay parseable."""
    if isinstance(snapshots, tp.Mapping):
        pairs = _expand(snapshots, {})
    else:
        pairs = []
        for labels, snap in snapshots:
            pairs.extend(_expand(snap, labels))

    # family name -> (type, [lines])
    families: tp.Dict[str, tp.Tuple[str, tp.List[str]]] = {}

    def fam(name: str, typ: str) -> tp.List[str]:
        if name not in families:
            families[name] = (typ, [])
        return families[name][1]

    for labels, snap in pairs:
        for name, v in snap.get("counters", {}).items():
            n = _prom_name(prefix, name, "_total")
            fam(n, "counter").append(f"{n}{_prom_labels(labels)} {v}")
        for name, series in snap.get("labeled", {}).items():
            n = _prom_name(prefix, name, "_total")
            lines = fam(n, "counter")
            for key, v in sorted(series.items()):
                lines.append(
                    f"{n}{_prom_labels(dict(labels, key=str(key)))} {v}"
                )
        for name, v in snap.get("gauges", {}).items():
            n = _prom_name(prefix, name)
            fam(n, "gauge").append(f"{n}{_prom_labels(labels)} {v}")
        for name, h in snap.get("histograms", {}).items():
            n = _prom_name(prefix, name)
            lines = fam(n, "histogram")
            cum = 0
            for bound, cnt in zip(h["buckets"], h["counts"]):
                cum += cnt
                lines.append(
                    f"{n}_bucket"
                    f"{_prom_labels(dict(labels, le=repr(float(bound))))} "
                    f"{cum}"
                )
            lines.append(
                f"{n}_bucket{_prom_labels(dict(labels, le='+Inf'))} "
                f"{h['count']}"
            )
            lines.append(f"{n}_sum{_prom_labels(labels)} {h['sum']}")
            lines.append(f"{n}_count{_prom_labels(labels)} {h['count']}")

    out: tp.List[str] = []
    for name in sorted(families):
        typ, lines = families[name]
        out.append(f"# TYPE {name} {typ}")
        out.extend(lines)
    return "\n".join(out) + ("\n" if out else "")
