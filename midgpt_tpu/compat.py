"""Version compatibility shims.

``shard_map``: jax promoted ``jax.experimental.shard_map.shard_map`` to a
top-level ``jax.shard_map`` in newer releases, renaming ``check_rep`` ->
``check_vma`` and replacing the complementary ``auto=`` frozenset with
``axis_names=`` (the axes that ARE manual). This environment pins jax
0.4.37, which only has the experimental entry point. Every call site in
the package routes through :func:`shard_map` below, which presents the
NEW surface and translates down when only the old one exists — so the
code reads as current-jax and keeps working on both sides of the rename.
"""

from __future__ import annotations

import typing as tp

import jax

_HAS_TOP_LEVEL = hasattr(jax, "shard_map")


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` on any jax version (older releases call
    the same dataclass ``TPUCompilerParams``; the fields used here —
    dimension_semantics, vmem_limit_bytes — exist in both)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)

if not _HAS_TOP_LEVEL:
    from jax.experimental.shard_map import (  # noqa: F401
        shard_map as _shard_map_experimental,
    )


def shard_map(
    f: tp.Callable,
    *,
    mesh,
    in_specs,
    out_specs,
    axis_names: tp.Optional[tp.Collection[str]] = None,
    check_vma: bool = True,
) -> tp.Callable:
    """New-style ``jax.shard_map`` surface on any jax version.

    ``axis_names`` (when given) lists the MANUAL mesh axes; the old API
    expressed the same thing as its complement ``auto=``. ``check_vma``
    maps to the old ``check_rep`` — same replication check, renamed.
    """
    if _HAS_TOP_LEVEL:
        kw: tp.Dict[str, tp.Any] = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
            **kw,
        )
    check_rep = check_vma
    if axis_names is not None:
        # Partial-manual regions run FULLY manual on the old pin: 0.4.x's
        # experimental partial-auto lowering emits a PartitionId
        # instruction the SPMD partitioner rejects whenever the body
        # takes an axis_index (the PP stage id, the sharded-dropout
        # offsets). Full-manual is value-identical — the would-be-auto
        # axes just see their operands regathered at region entry per the
        # in_specs — at a memory/comms cost that only exists on the old
        # pin. The replication check predates the partial-auto semantics
        # it would have to reason about, so it stays off here.
        check_rep = False
    return _shard_map_experimental(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_rep,
    )
