"""Pallas TPU flash attention (causal, GQA-aware, custom VJP).

Replaces the reference's naive O(T^2)-memory attention
(/root/reference/src/model.py:71-79) with a blockwise online-softmax kernel:
scores never materialize in HBM; softmax runs in float32 with the 1/sqrt(C)
scale folded into the softmax argument, exactly mirroring the reference
numerics (SURVEY.md 2.3).

Layout is [B, H, T, C] — the only layout Mosaic can block per-head: the
last two block dims must be (multiple-of-8, multiple-of-128-or-full), so a
projection-natural [B, T, H, C] per-head block (1, rows, 1, C) is illegal
on hardware (measured r2; see PERF.md "transpose-free layout post-mortem").
K/V may carry fewer (grouped) heads — the grid maps each Q head to its KV
group, so tensor-parallel head sharding composes (each shard sees a
smaller H).

Forward residual is the standard (out, logsumexp) pair; backward runs two
kernels (dQ over Q blocks; dK/dV over KV blocks) plus a trivial elementwise
delta precomputation.
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.compat import tpu_compiler_params
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

_NEG_INF = -1e30  # avoids NaN from (-inf) - (-inf) in fully-masked rows


def _auto_block(t: int, cap: int = 1024) -> int:
    """Largest power-of-two block <= cap that divides T.

    Measured on a v5e-class chip (B=16, H=12, T=1024, C=64, bench_kernels.py):
    fwd 12.3ms @ 128 -> 3.2ms @ 1024; fwd+bwd 19.5ms @ 128 -> 10.2ms @ 1024.
    The dominant cost is per-grid-step matmul issue overhead at tiny blocks,
    so bigger is strictly better until the VMEM working set (~12 MB at 1024
    for the dkv kernel) nears the 16 MB scoped limit."""
    b = cap
    while b > 8 and t % b:
        b //= 2
    return min(b, t)


def _block_sizes(
    t: int, bq: tp.Optional[int], bk: tp.Optional[int], causal: bool
) -> tp.Tuple[int, int]:
    bq = _auto_block(t) if bq is None else min(bq, t)
    bk = _auto_block(t) if bk is None else min(bk, t)
    assert t % bq == 0 and t % bk == 0, (
        f"seq len {t} must be a multiple of block sizes ({bq}, {bk})"
    )
    # the causal block-skip logic compares q/k block indices directly
    assert not causal or bq == bk, (
        f"causal path requires block_q == block_k, got ({bq}, {bk})"
    )
    return bq, bk


def _causal_mask_block(iq, ik, bq: int, bk: int) -> Array:
    """Boolean [bq, bk] mask for the (iq, ik) block pair: True = visible."""
    rows = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows >= cols


def _wrap32(c: int):
    import numpy as np

    return jnp.int32(np.uint32(c).astype(np.int32))


def _hash_finalize(x: Array) -> Array:
    """murmur3-style 32-bit finalizer (good avalanche) on int32 with
    wrapping arithmetic — plain vector integer ops, so it runs identically
    under Mosaic and the Pallas CPU interpreter (pltpu.prng_* has no
    interpret-mode lowering, which would make dropout untestable here)."""
    srl = jax.lax.shift_right_logical
    x = (x ^ srl(x, 16)) * _wrap32(0x7FEB352D)
    x = (x ^ srl(x, 15)) * _wrap32(0x846CA68B)
    return x ^ srl(x, 16)


def _dropout_keep_block(
    seed, head_id, rows0, cols0, bq: int, bk: int, keep: float
) -> Array:
    """Deterministic Bernoulli(keep) over global score coordinates.

    Element (row, col) of attention head ``head_id`` keeps its probability
    iff hash(seed, head_id, row, col) falls under the keep threshold. The
    same counters regenerate the identical mask in the backward kernels —
    nothing is stored. [bq, bk] bool."""
    rows = rows0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = cols0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    x = rows * _wrap32(0x9E3779B1) + cols * _wrap32(0x85EBCA77)
    x = x ^ (seed + head_id * _wrap32(0xC2B2AE35))
    u24 = _hash_finalize(x) & jnp.int32(0x00FFFFFF)
    return u24 < jnp.int32(int(keep * (1 << 24)))


def _seed_vec(seed, row_off, col_off, bh_off=None) -> Array:
    """[4] int32 SMEM payload: dropout seed + GLOBAL anchors of this
    call's local (0, 0, 0, 0): score row/col offsets and the flat
    ``batch * H_total + head`` base. Anchors let a ring-attention hop or
    a batch/head-sharded call (parallel/ring.py) regenerate the exact
    mask a single-device call would use at the same global coordinates —
    sharded dropout is bit-identical to dense flash dropout."""
    z = jnp.zeros((), jnp.int32)
    r = z if row_off is None else jnp.asarray(row_off, jnp.int32).reshape(())
    c = z if col_off is None else jnp.asarray(col_off, jnp.int32).reshape(())
    bh = z if bh_off is None else jnp.asarray(bh_off, jnp.int32).reshape(())
    return jnp.stack([
        jnp.asarray(seed, jnp.int32).reshape(()), r, c, bh,
    ])


def _struct(shape, dtype, like) -> jax.ShapeDtypeStruct:
    """pallas_call out_shape inheriting the manual-axes vma of ``like``.

    Inside a ``check_vma=True`` shard_map region (the PP stage region,
    parallel/pipeline.py:169, and the data/TP wrap in ops/attention.py) a
    plain ShapeDtypeStruct fails pallas type-checking; carrying the input
    operand's vma keeps the output varying over the same manual axes."""
    typeof = getattr(jax, "typeof", None)  # absent (with vma) pre-0.6 jax
    vma = getattr(typeof(like), "vma", None) if typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _act_spec(rows: int, c: int, row_fn, head_fn):
    """BlockSpec for a q/k/v/o/do activation carrying ``rows`` sequence rows.

    ``row_fn(grid indices) -> row-block index``; ``head_fn(h) -> head (or KV
    group) index``. The kernel always sees a [rows, c] tile."""
    return pl.BlockSpec(
        (1, 1, rows, c),
        lambda *g: (g[0], head_fn(g[1]), row_fn(*g), 0),
    )


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    *refs,
    scale: float, causal: bool, bq: int, bk: int, nk: int,
    keep: tp.Optional[float] = None, n_head: int = 0,
):
    if keep is not None:
        seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    iq, ik = pl.program_id(2), pl.program_id(3)
    # program_id must bind OUTSIDE pl.when bodies (no interpret lowering
    # inside the cond); the flat batch-head id seeds the dropout hash
    bh = (
        seed_ref[3] + pl.program_id(0) * n_head + pl.program_id(1)
        if keep is not None
        else None
    )

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    last_k = iq if causal else nk - 1
    run = (ik <= iq) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]  # [bq, C]
        k = k_ref[0, 0]  # [bk, C]
        v = v_ref[0, 0]  # [bk, C]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        z = s * scale
        if causal:
            # only the diagonal block needs the element-level mask
            z = jnp.where(
                jnp.logical_or(ik != iq, _causal_mask_block(iq, ik, bq, bk)),
                z,
                _NEG_INF,
            )
        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(z, axis=1, keepdims=True)  # [bq, 1]
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)  # [bq, 1]
        p = jnp.exp(z - m_next)  # [bq, bk] f32
        # l (and thus lse) accumulates the UNDROPPED sum: dropout applies
        # to softmax OUTPUTS (out = (softmax(z) * mask / keep) @ v), so
        # only the value accumulation sees the mask
        l_next = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        p_acc = p
        if keep is not None:
            mask = _dropout_keep_block(
                seed_ref[0], bh,
                seed_ref[1] + iq * bq, seed_ref[2] + ik * bk, bq, bk, keep,
            )
            p_acc = jnp.where(mask, p * (1.0 / keep), 0.0)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p_acc.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_bcast = jax.lax.broadcast_in_dim(m_next, m_ref.shape, (0, 1))
        l_bcast = jax.lax.broadcast_in_dim(l_next, l_ref.shape, (0, 1))
        m_ref[:] = m_bcast
        l_ref[:] = l_bcast

    @pl.when(ik == last_k)
    def _finalize():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        # causal rows always have >= 1 visible key, so l > 0
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m + jnp.log(l)


def _flash_forward(
    q: Array, k: Array, v: Array, *, causal: bool, bq: int, bk: int,
    keep: tp.Optional[float] = None, seed: tp.Optional[Array] = None,
    row_off: tp.Optional[Array] = None, col_off: tp.Optional[Array] = None,
    bh_off: tp.Optional[Array] = None, n_head_total: tp.Optional[int] = None,
) -> tp.Tuple[Array, Array]:
    b, h, t, c = q.shape
    _, hkv, s, _ = k.shape
    assert s == t, "self-attention only (use decode path for caches)"
    groups = h // hkv
    bq, bk = _block_sizes(t, bq, bk, causal)
    nq, nk = t // bq, t // bk
    scale = 1.0 / math.sqrt(c)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
        keep=keep, n_head=n_head_total or h,
    )
    row_q = lambda b_, h_, iq, ik: iq  # noqa: E731
    # trimmed causal grid: masked (ik > iq) steps are compute-skipped
    # (pl.when); clamping their block index to the diagonal makes them
    # alias the resident block, so they trigger no DMA either (r3)
    if causal:
        row_k = lambda b_, h_, iq, ik: jnp.minimum(ik, iq)  # noqa: E731
    else:
        row_k = lambda b_, h_, iq, ik: ik  # noqa: E731
    kv_head = lambda h_: h_ // groups  # noqa: E731
    q_head = lambda h_: h_  # noqa: E731
    in_specs = [
        _act_spec(bq, c, row_q, q_head),
        _act_spec(bk, c, row_k, kv_head),
        _act_spec(bk, c, row_k, kv_head),
    ]
    operands = (q, k, v)
    if keep is not None:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
        operands = (_seed_vec(seed, row_off, col_off, bh_off),) + operands
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            _act_spec(bq, c, row_q, q_head),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        ],
        out_shape=[
            _struct((b, h, t, c), q.dtype, q),
            _struct((b, h, t, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, c), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(*operands)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    *refs,
    scale: float, causal: bool, bq: int, bk: int, nk: int,
    keep: tp.Optional[float] = None, n_head: int = 0,
):
    if keep is not None:
        (seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc = refs
    iq, ik = pl.program_id(2), pl.program_id(3)
    bh = (
        seed_ref[3] + pl.program_id(0) * n_head + pl.program_id(1)
        if keep is not None
        else None
    )

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ik <= iq) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # [bq, 1] f32
        delta = delta_ref[0, 0]  # [bq, 1] f32
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        z = s * scale
        if causal:
            z = jnp.where(
                jnp.logical_or(ik != iq, _causal_mask_block(iq, ik, bq, bk)),
                z,
                _NEG_INF,
            )
        p = jnp.exp(z - lse)  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if keep is not None:
            # out = (p * mask/keep) @ v, so dz = p * (mask/keep * dp - delta)
            # with the SAME regenerated mask (delta already absorbs out's
            # dropped entries — it is rowsum(do * out))
            mask = _dropout_keep_block(
                seed_ref[0], bh,
                seed_ref[1] + iq * bq, seed_ref[2] + ik * bk, bq, bk, keep,
            )
            dp = jnp.where(mask, dp * (1.0 / keep), 0.0)
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    last_k = iq if causal else nk - 1

    @pl.when(ik == last_k)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    *refs,
    scale: float, causal: bool, bq: int, bk: int, nq: int,
    keep: tp.Optional[float] = None, n_head: int = 0,
):
    if keep is not None:
        (seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
         dk_acc, dv_acc) = refs
    ik, iq = pl.program_id(2), pl.program_id(3)
    bh = (
        seed_ref[3] + pl.program_id(0) * n_head + pl.program_id(1)
        if keep is not None
        else None
    )

    @pl.when(iq == (ik if causal else 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (iq >= ik) if causal else (iq >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]  # [bq, C]
        k = k_ref[0, 0]  # [bk, C]
        v = v_ref[0, 0]
        do = do_ref[0, 0]  # [bq, C]
        lse = lse_ref[0, 0]  # [bq, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        z = s * scale
        if causal:
            z = jnp.where(
                jnp.logical_or(ik != iq, _causal_mask_block(iq, ik, bq, bk)),
                z,
                _NEG_INF,
            )
        p = jnp.exp(z - lse)  # [bq, bk]
        p_v = p
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if keep is not None:
            # NOTE transposed grid: this kernel's block rows start at
            # iq * bq (grid is (b, h, ik, iq))
            mask = _dropout_keep_block(
                seed_ref[0], bh,
                seed_ref[1] + iq * bq, seed_ref[2] + ik * bk, bq, bk, keep,
            )
            inv = 1.0 / keep
            p_v = jnp.where(mask, p * inv, 0.0)
            dp = jnp.where(mask, dp * inv, 0.0)
        # dv += (p * mask/keep)^T @ do  -> [bk, C]
        dv_acc[:] += jax.lax.dot_general(
            p_v.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale  # [bq, bk]
        # dk += ds^T @ q -> [bk, C]
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(
    q: Array, k: Array, v: Array, out: Array, lse: Array, do: Array,
    *, causal: bool, bq: int, bk: int, dlse: tp.Optional[Array] = None,
    keep: tp.Optional[float] = None, seed: tp.Optional[Array] = None,
    row_off: tp.Optional[Array] = None, col_off: tp.Optional[Array] = None,
    bh_off: tp.Optional[Array] = None, n_head_total: tp.Optional[int] = None,
) -> tp.Tuple[Array, Array, Array]:
    b, h, t, c = q.shape
    hkv = k.shape[1]
    groups = h // hkv
    bq, bk = _block_sizes(t, bq, bk, causal)
    nq, nk = t // bq, t // bk
    scale = 1.0 / math.sqrt(c)
    seed_ops: tp.Tuple[Array, ...] = ()
    seed_specs: tp.List[tp.Any] = []
    if keep is not None:
        seed_ops = (_seed_vec(seed, row_off, col_off, bh_off),)
        seed_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]

    # delta_i = rowsum(dO * O) — cheap elementwise, fused by XLA; stored
    # [B, H, T, 1] (tiny, consumed by the kernels only).
    # When the caller also consumes lse (flash_attention_lse), its
    # cotangent folds in exactly here: dL/dz_ij = p_ij (dp_ij - delta_i
    # + dlse_i), since dlse_i/dz_ij = p_ij — so delta_eff = delta - dlse.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    row_q34 = lambda b_, h_, iq, ik: iq  # noqa: E731 — grid (b,h,iq,ik)
    row_k43 = lambda b_, h_, ik, iq: ik  # noqa: E731 — grid (b,h,ik,iq)
    # trimmed causal grid: skipped steps alias the diagonal block (no DMA)
    if causal:
        row_k34 = lambda b_, h_, iq, ik: jnp.minimum(ik, iq)  # noqa: E731
        row_q43 = lambda b_, h_, ik, iq: jnp.maximum(iq, ik)  # noqa: E731
    else:
        row_k34 = lambda b_, h_, iq, ik: ik  # noqa: E731
        row_q43 = lambda b_, h_, ik, iq: iq  # noqa: E731
    kv_head = lambda h_: h_ // groups  # noqa: E731
    q_head = lambda h_: h_  # noqa: E731

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
            keep=keep, n_head=n_head_total or h,
        ),
        grid=(b, h, nq, nk),
        in_specs=seed_specs + [
            _act_spec(bq, c, row_q34, q_head),
            _act_spec(bk, c, row_k34, kv_head),
            _act_spec(bk, c, row_k34, kv_head),
            _act_spec(bq, c, row_q34, q_head),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        ],
        out_specs=_act_spec(bq, c, row_q34, q_head),
        out_shape=_struct((b, h, t, c), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((bq, c), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(*seed_ops, q, k, v, do, lse, delta)

    # dK/dV per Q-head (summed over GQA groups afterwards)
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nq=nq,
            keep=keep, n_head=n_head_total or h,
        ),
        grid=(b, h, nk, nq),
        in_specs=seed_specs + [
            _act_spec(bq, c, row_q43, q_head),
            _act_spec(bk, c, row_k43, kv_head),
            _act_spec(bk, c, row_k43, kv_head),
            _act_spec(bq, c, row_q43, q_head),
            pl.BlockSpec(
                (1, 1, bq, 1),
                lambda b_, h_, ik, iq: (b_, h_, row_q43(b_, h_, ik, iq), 0),
            ),
            pl.BlockSpec(
                (1, 1, bq, 1),
                lambda b_, h_, ik, iq: (b_, h_, row_q43(b_, h_, ik, iq), 0),
            ),
        ],
        out_specs=[
            _act_spec(bk, c, row_k43, q_head),
            _act_spec(bk, c, row_k43, q_head),
        ],
        out_shape=[
            _struct((b, h, t, c), k.dtype, q),
            _struct((b, h, t, c), v.dtype, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, c), jnp.float32),
            pltpu.VMEM((bk, c), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(*seed_ops, q, k, v, do, lse, delta)

    if groups > 1:
        dk = dk_h.reshape(b, hkv, groups, t, c).sum(axis=2).astype(k.dtype)
        dv = dv_h.reshape(b, hkv, groups, t, c).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------


def flash_attention(
    q: Array,
    k: Array,
    v: Array,
    causal: bool = True,
    block_q: tp.Optional[int] = None,
    block_k: tp.Optional[int] = None,
) -> Array:
    """Flash attention output only — delegates to flash_attention_lse (the
    dropped lse's cotangent instantiates to zeros, making the backward's
    ``delta - dlse`` fold a no-op), so there is a single VJP pair to
    maintain."""
    out, _ = flash_attention_lse(q, k, v, causal, block_q, block_k)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _flash_lse_core(
    q: Array,
    k: Array,
    v: Array,
    seed: Array,      # [] int32 (ignored when rate == 0.0)
    row_off: Array,   # [] int32 — global row of this call's (0,0) score
    col_off: Array,   # [] int32 — global col of this call's (0,0) score
    bh_off: Array,    # [] int32 — global batch*H_total + head of local (0,0)
    rate: float,
    causal: bool,
    block_q: tp.Optional[int],
    block_k: tp.Optional[int],
    n_head_total: tp.Optional[int],
) -> tp.Tuple[Array, Array]:
    """Single VJP pair behind every flash entry point: (out, lse) with a
    differentiable lse (cotangent folds into the backward as
    ``delta - dlse``), optional in-kernel dropout (rate > 0), and global
    score-coordinate offsets so ring hops reproduce the exact
    single-device mask (see _seed_vec)."""
    keep = None if rate == 0.0 else 1.0 - rate
    out, lse = _flash_forward(
        q, k, v, causal=causal, bq=block_q, bk=block_k,
        keep=keep, seed=seed, row_off=row_off, col_off=col_off,
        bh_off=bh_off, n_head_total=n_head_total,
    )
    return out, lse[..., 0]


def _core_vjp_fwd(
    q, k, v, seed, row_off, col_off, bh_off,
    rate, causal, block_q, block_k, n_head_total,
):
    keep = None if rate == 0.0 else 1.0 - rate
    out, lse = _flash_forward(
        q, k, v, causal=causal, bq=block_q, bk=block_k,
        keep=keep, seed=seed, row_off=row_off, col_off=col_off,
        bh_off=bh_off, n_head_total=n_head_total,
    )
    return (out, lse[..., 0]), (
        q, k, v, seed, row_off, col_off, bh_off, out, lse,
    )


def _core_vjp_bwd(rate, causal, block_q, block_k, n_head_total, residuals, cts):
    q, k, v, seed, row_off, col_off, bh_off, out, lse = residuals
    do, dlse = cts
    keep = None if rate == 0.0 else 1.0 - rate
    dq, dk, dv = _flash_backward(
        q, k, v, out, lse, do,
        causal=causal, bq=block_q, bk=block_k, dlse=dlse[..., None],
        keep=keep, seed=seed, row_off=row_off, col_off=col_off,
        bh_off=bh_off, n_head_total=n_head_total,
    )
    return dq, dk, dv, None, None, None, None


_flash_lse_core.defvjp(_core_vjp_fwd, _core_vjp_bwd)

def _z() -> Array:
    return jnp.zeros((), jnp.int32)


def flash_attention_lse(
    q: Array,
    k: Array,
    v: Array,
    causal: bool = True,
    block_q: tp.Optional[int] = None,
    block_k: tp.Optional[int] = None,
) -> tp.Tuple[Array, Array]:
    """Flash attention returning (out [B,H,T,C], lse [B,H,T]).

    The lse output is differentiable — its cotangent folds into the
    backward kernels as ``delta - dlse`` (see _flash_backward) — which is
    what lets ring attention (midgpt_tpu.parallel.ring) run this kernel
    per hop and still autodiff through the streaming LSE merge."""
    return _flash_lse_core(
        q, k, v, _z(), _z(), _z(), _z(), 0.0, causal, block_q, block_k, None
    )


def flash_attention_dropout_lse(
    q: Array,
    k: Array,
    v: Array,
    seed: Array,
    rate: float,
    causal: bool = True,
    block_q: tp.Optional[int] = None,
    block_k: tp.Optional[int] = None,
    row_off: tp.Optional[Array] = None,
    col_off: tp.Optional[Array] = None,
    bh_off: tp.Optional[Array] = None,
    n_head_total: tp.Optional[int] = None,
) -> tp.Tuple[Array, Array]:
    """(out, lse) flash attention with in-kernel dropout AND global score
    offsets — the ring-attention hop entry (parallel/ring.py): lse stays
    differentiable through the streaming merge, and (row_off, col_off)
    anchor the hop's mask in GLOBAL coordinates so the full ring pass
    drops exactly the same (head, row, col) set a single-device call
    would."""
    z = _z()
    return _flash_lse_core(
        q, k, v, seed,
        z if row_off is None else row_off,
        z if col_off is None else col_off,
        z if bh_off is None else bh_off,
        rate, causal, block_q, block_k, n_head_total,
    )


def flash_attention_reference(q, k, v, causal=True):
    """jnp oracle with identical math, for tests."""
    from midgpt_tpu.ops.attention import naive_attention

    return naive_attention(q, k, v, causal=causal)


# ---------------------------------------------------------------------------
# Attention dropout (in-kernel mask regeneration, no stored mask)
# ---------------------------------------------------------------------------


def flash_attention_dropout(
    q: Array,
    k: Array,
    v: Array,
    seed: Array,  # [] or [1] int32 — per-call dropout seed
    rate: float,
    causal: bool = True,
    block_q: tp.Optional[int] = None,
    block_k: tp.Optional[int] = None,
) -> Array:
    """Flash attention with ATTENTION dropout: out = (softmax(z) * M/keep) @ v
    with M ~ Bernoulli(keep) regenerated IN-KERNEL from (seed, b*H+h, row,
    col) by a counter-based hash (_dropout_keep_block) — no O(T^2) mask in
    HBM, and the backward kernels rebuild the identical mask from the same
    counters. This removes the last math capability the kernels lacked
    (VERDICT r3 Next #8): shakespeare_char — the only dropout config,
    /root/reference/src/model.py:78 — no longer pins training to naive
    O(T^2) attention.

    The mask stream differs from naive_attention's jax.random.bernoulli
    (different PRNG), so parity tests compare against an oracle built from
    dropout_mask_reference — same hash, dense evaluation."""
    out, _ = _flash_lse_core(
        q, k, v, jnp.asarray(seed, jnp.int32).reshape(()), _z(), _z(), _z(),
        rate, causal, block_q, block_k, None,
    )
    return out


def dropout_mask_reference(
    seed: Array, b: int, h: int, t: int, rate: float
) -> Array:
    """[B, H, T, T] boolean keep-mask — the DENSE evaluation of the exact
    hash the kernels regenerate blockwise. Test oracle only (O(T^2))."""
    keep = 1.0 - rate
    rows = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    x = rows * _wrap32(0x9E3779B1) + cols * _wrap32(0x85EBCA77)
    head_ids = jnp.arange(b * h, dtype=jnp.int32).reshape(b, h, 1, 1)
    x = x[None, None] ^ (
        jnp.asarray(seed, jnp.int32).reshape(()) + head_ids * _wrap32(0xC2B2AE35)
    )
    u24 = _hash_finalize(x) & jnp.int32(0x00FFFFFF)
    return u24 < jnp.int32(int(keep * (1 << 24)))
