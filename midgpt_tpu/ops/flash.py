"""Pallas TPU flash attention (causal, GQA-aware, custom VJP).

Replaces the reference's naive O(T^2)-memory attention
(/root/reference/src/model.py:71-79) with a blockwise online-softmax kernel:
scores never materialize in HBM; softmax runs in float32 with the 1/sqrt(C)
scale folded into the softmax argument, exactly mirroring the reference
numerics (SURVEY.md 2.3).

Layouts: ``"bhtc"`` ([B, H, T, C], the classic flash layout) or ``"bthc"``
([B, T, H, C], the projection-natural layout) — the latter lets the model
skip four [B,T,H,C]<->[B,H,T,C] transpose materializations per attention
call (q/k/v in, out; doubled again in the backward), which profiling showed
as ~8 ms/step of pure copies at the 124M bench shape. The kernel grid is
identical; only the BlockSpec index maps change. K/V may carry fewer
(grouped) heads — the grid maps each Q head to its KV group, so
tensor-parallel head sharding composes (each shard sees a smaller H).

Forward residual is the standard (out, logsumexp) pair; backward runs two
kernels (dQ over Q blocks; dK/dV over KV blocks) plus a trivial elementwise
delta precomputation.
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

_NEG_INF = -1e30  # avoids NaN from (-inf) - (-inf) in fully-masked rows


def _auto_block(t: int) -> int:
    """Largest power-of-two block <= 1024 that divides T.

    Measured on a v5e-class chip (B=16, H=12, T=1024, C=64, bench_kernels.py):
    fwd 12.3ms @ 128 -> 3.2ms @ 1024; fwd+bwd 19.5ms @ 128 -> 10.2ms @ 1024.
    The dominant cost is per-grid-step matmul issue overhead at tiny blocks,
    so bigger is strictly better until the VMEM working set (~12 MB at 1024
    for the dkv kernel) nears the 16 MB scoped limit."""
    b = 1024
    while b > 8 and t % b:
        b //= 2
    return min(b, t)


def _block_sizes(
    t: int, bq: tp.Optional[int], bk: tp.Optional[int], causal: bool
) -> tp.Tuple[int, int]:
    bq = _auto_block(t) if bq is None else min(bq, t)
    bk = _auto_block(t) if bk is None else min(bk, t)
    assert t % bq == 0 and t % bk == 0, (
        f"seq len {t} must be a multiple of block sizes ({bq}, {bk})"
    )
    # the causal block-skip logic compares q/k block indices directly
    assert not causal or bq == bk, (
        f"causal path requires block_q == block_k, got ({bq}, {bk})"
    )
    return bq, bk


def _causal_mask_block(iq, ik, bq: int, bk: int) -> Array:
    """Boolean [bq, bk] mask for the (iq, ik) block pair: True = visible."""
    rows = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows >= cols


# --- layout plumbing: "bhtc" [B,H,T,C] vs "bthc" [B,T,H,C] ----------------


def _act_spec(layout: str, rows: int, c: int, row_fn, head_fn):
    """BlockSpec for a q/k/v/o/do activation carrying ``rows`` sequence rows.

    ``row_fn(grid indices) -> row-block index``; ``head_fn(h) -> head (or KV
    group) index``. The kernel always sees a [rows, c] tile; only where that
    tile sits in the global array depends on the layout."""
    if layout == "bhtc":
        return pl.BlockSpec(
            (1, 1, rows, c),
            lambda *g: (g[0], head_fn(g[1]), row_fn(*g), 0),
        )
    assert layout == "bthc", layout
    return pl.BlockSpec(
        (1, rows, 1, c),
        lambda *g: (g[0], row_fn(*g), head_fn(g[1]), 0),
    )


def _read(layout: str, ref) -> Array:
    return ref[0, 0] if layout == "bhtc" else ref[0, :, 0, :]


def _write(layout: str, ref, value) -> None:
    if layout == "bhtc":
        ref[0, 0] = value
    else:
        ref[0, :, 0, :] = value


def _act_shape(layout: str, b: int, h: int, t: int, c: int):
    return (b, h, t, c) if layout == "bhtc" else (b, t, h, c)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, bq: int, bk: int, nk: int, layout: str,
):
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    last_k = iq if causal else nk - 1
    run = (ik <= iq) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q = _read(layout, q_ref)  # [bq, C]
        k = _read(layout, k_ref)  # [bk, C]
        v = _read(layout, v_ref)  # [bk, C]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        z = s * scale
        if causal:
            # only the diagonal block needs the element-level mask
            z = jnp.where(
                jnp.logical_or(ik != iq, _causal_mask_block(iq, ik, bq, bk)),
                z,
                _NEG_INF,
            )
        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(z, axis=1, keepdims=True)  # [bq, 1]
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)  # [bq, 1]
        p = jnp.exp(z - m_next)  # [bq, bk] f32
        l_next = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_bcast = jax.lax.broadcast_in_dim(m_next, m_ref.shape, (0, 1))
        l_bcast = jax.lax.broadcast_in_dim(l_next, l_ref.shape, (0, 1))
        m_ref[:] = m_bcast
        l_ref[:] = l_bcast

    @pl.when(ik == last_k)
    def _finalize():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        # causal rows always have >= 1 visible key, so l > 0
        _write(layout, o_ref, (acc_ref[:] / l).astype(o_ref.dtype))
        lse_ref[0, 0] = m + jnp.log(l)


def _dims(layout: str, x: Array) -> tp.Tuple[int, int, int, int]:
    """(B, H, T, C) of an activation in either layout."""
    if layout == "bhtc":
        b, h, t, c = x.shape
    else:
        b, t, h, c = x.shape
    return b, h, t, c


def _flash_forward(
    q: Array, k: Array, v: Array, *, causal: bool, bq: int, bk: int,
    layout: str = "bhtc",
) -> tp.Tuple[Array, Array]:
    b, h, t, c = _dims(layout, q)
    _, hkv, s, _ = _dims(layout, k)
    assert s == t, "self-attention only (use decode path for caches)"
    groups = h // hkv
    bq, bk = _block_sizes(t, bq, bk, causal)
    nq, nk = t // bq, t // bk
    scale = 1.0 / math.sqrt(c)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
        layout=layout,
    )
    row_q = lambda b_, h_, iq, ik: iq  # noqa: E731
    row_k = lambda b_, h_, iq, ik: ik  # noqa: E731
    kv_head = lambda h_: h_ // groups  # noqa: E731
    q_head = lambda h_: h_  # noqa: E731
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            _act_spec(layout, bq, c, row_q, q_head),
            _act_spec(layout, bk, c, row_k, kv_head),
            _act_spec(layout, bk, c, row_k, kv_head),
        ],
        out_specs=[
            _act_spec(layout, bq, c, row_q, q_head),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(_act_shape(layout, b, h, t, c), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, c), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, bq: int, bk: int, nk: int, layout: str,
):
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ik <= iq) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q = _read(layout, q_ref)
        k = _read(layout, k_ref)
        v = _read(layout, v_ref)
        do = _read(layout, do_ref)
        lse = lse_ref[0, 0]  # [bq, 1] f32
        delta = delta_ref[0, 0]  # [bq, 1] f32
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        z = s * scale
        if causal:
            z = jnp.where(
                jnp.logical_or(ik != iq, _causal_mask_block(iq, ik, bq, bk)),
                z,
                _NEG_INF,
            )
        p = jnp.exp(z - lse)  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    last_k = iq if causal else nk - 1

    @pl.when(ik == last_k)
    def _finalize():
        _write(layout, dq_ref, dq_acc[:].astype(dq_ref.dtype))


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, causal: bool, bq: int, bk: int, nq: int, layout: str,
):
    ik, iq = pl.program_id(2), pl.program_id(3)

    @pl.when(iq == (ik if causal else 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (iq >= ik) if causal else (iq >= 0)

    @pl.when(run)
    def _compute():
        q = _read(layout, q_ref)  # [bq, C]
        k = _read(layout, k_ref)  # [bk, C]
        v = _read(layout, v_ref)
        do = _read(layout, do_ref)  # [bq, C]
        lse = lse_ref[0, 0]  # [bq, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        z = s * scale
        if causal:
            z = jnp.where(
                jnp.logical_or(ik != iq, _causal_mask_block(iq, ik, bq, bk)),
                z,
                _NEG_INF,
            )
        p = jnp.exp(z - lse)  # [bq, bk]
        # dv += p^T @ do  -> [bk, C]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        ds = p * (dp - delta) * scale  # [bq, bk]
        # dk += ds^T @ q -> [bk, C]
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(iq == nq - 1)
    def _finalize():
        _write(layout, dk_ref, dk_acc[:].astype(dk_ref.dtype))
        _write(layout, dv_ref, dv_acc[:].astype(dv_ref.dtype))


def _flash_backward(
    q: Array, k: Array, v: Array, out: Array, lse: Array, do: Array,
    *, causal: bool, bq: int, bk: int, dlse: tp.Optional[Array] = None,
    layout: str = "bhtc",
) -> tp.Tuple[Array, Array, Array]:
    b, h, t, c = _dims(layout, q)
    hkv = _dims(layout, k)[1]
    groups = h // hkv
    bq, bk = _block_sizes(t, bq, bk, causal)
    nq, nk = t // bq, t // bk
    scale = 1.0 / math.sqrt(c)

    # delta_i = rowsum(dO * O) — cheap elementwise, fused by XLA; stored
    # [B, H, T, 1] in BOTH layouts (tiny, consumed by the kernels only).
    # When the caller also consumes lse (flash_attention_lse), its
    # cotangent folds in exactly here: dL/dz_ij = p_ij (dp_ij - delta_i
    # + dlse_i), since dlse_i/dz_ij = p_ij — so delta_eff = delta - dlse.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )
    if layout == "bthc":
        delta = jnp.transpose(delta, (0, 2, 1, 3))  # [B, H, T, 1]
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    row_q34 = lambda b_, h_, iq, ik: iq  # noqa: E731 — grid (b,h,iq,ik)
    row_k34 = lambda b_, h_, iq, ik: ik  # noqa: E731
    row_q43 = lambda b_, h_, ik, iq: iq  # noqa: E731 — grid (b,h,ik,iq)
    row_k43 = lambda b_, h_, ik, iq: ik  # noqa: E731
    kv_head = lambda h_: h_ // groups  # noqa: E731
    q_head = lambda h_: h_  # noqa: E731

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
            layout=layout,
        ),
        grid=(b, h, nq, nk),
        in_specs=[
            _act_spec(layout, bq, c, row_q34, q_head),
            _act_spec(layout, bk, c, row_k34, kv_head),
            _act_spec(layout, bk, c, row_k34, kv_head),
            _act_spec(layout, bq, c, row_q34, q_head),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        ],
        out_specs=_act_spec(layout, bq, c, row_q34, q_head),
        out_shape=jax.ShapeDtypeStruct(_act_shape(layout, b, h, t, c), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, c), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(q, k, v, do, lse, delta)

    # dK/dV per Q-head (summed over GQA groups afterwards)
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nq=nq,
            layout=layout,
        ),
        grid=(b, h, nk, nq),
        in_specs=[
            _act_spec(layout, bq, c, row_q43, q_head),
            _act_spec(layout, bk, c, row_k43, kv_head),
            _act_spec(layout, bk, c, row_k43, kv_head),
            _act_spec(layout, bq, c, row_q43, q_head),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, ik, iq: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, ik, iq: (b_, h_, iq, 0)),
        ],
        out_specs=[
            _act_spec(layout, bk, c, row_k43, q_head),
            _act_spec(layout, bk, c, row_k43, q_head),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(_act_shape(layout, b, h, t, c), k.dtype),
            jax.ShapeDtypeStruct(_act_shape(layout, b, h, t, c), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, c), jnp.float32),
            pltpu.VMEM((bk, c), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(q, k, v, do, lse, delta)

    if groups > 1:
        if layout == "bhtc":
            dk = dk_h.reshape(b, hkv, groups, t, c).sum(axis=2).astype(k.dtype)
            dv = dv_h.reshape(b, hkv, groups, t, c).sum(axis=2).astype(v.dtype)
        else:
            dk = dk_h.reshape(b, t, hkv, groups, c).sum(axis=3).astype(k.dtype)
            dv = dv_h.reshape(b, t, hkv, groups, c).sum(axis=3).astype(v.dtype)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------


def flash_attention(
    q: Array,
    k: Array,
    v: Array,
    causal: bool = True,
    block_q: tp.Optional[int] = None,
    block_k: tp.Optional[int] = None,
    layout: str = "bhtc",
) -> Array:
    """Flash attention output only — delegates to flash_attention_lse (the
    dropped lse's cotangent instantiates to zeros, making the backward's
    ``delta - dlse`` fold a no-op), so there is a single VJP pair to
    maintain."""
    out, _ = flash_attention_lse(q, k, v, causal, block_q, block_k, layout)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_lse(
    q: Array,
    k: Array,
    v: Array,
    causal: bool = True,
    block_q: tp.Optional[int] = None,
    block_k: tp.Optional[int] = None,
    layout: str = "bhtc",
) -> tp.Tuple[Array, Array]:
    """Flash attention returning (out in ``layout``, lse [B,H,T]).

    The lse output is differentiable — its cotangent folds into the
    backward kernels as ``delta - dlse`` (see _flash_backward) — which is
    what lets ring attention (midgpt_tpu.parallel.ring) run this kernel
    per hop and still autodiff through the streaming LSE merge."""
    out, lse = _flash_forward(
        q, k, v, causal=causal, bq=block_q, bk=block_k, layout=layout
    )
    return out, lse[..., 0]


def _lse_vjp_fwd(q, k, v, causal, block_q, block_k, layout):
    out, lse = _flash_forward(
        q, k, v, causal=causal, bq=block_q, bk=block_k, layout=layout
    )
    return (out, lse[..., 0]), (q, k, v, out, lse)


def _lse_vjp_bwd(causal, block_q, block_k, layout, residuals, cts):
    q, k, v, out, lse = residuals
    do, dlse = cts
    dq, dk, dv = _flash_backward(
        q, k, v, out, lse, do,
        causal=causal, bq=block_q, bk=block_k, dlse=dlse[..., None],
        layout=layout,
    )
    return dq, dk, dv


flash_attention_lse.defvjp(_lse_vjp_fwd, _lse_vjp_bwd)


def flash_attention_reference(q, k, v, causal=True):
    """jnp oracle with identical math, for tests."""
    from midgpt_tpu.ops.attention import naive_attention

    return naive_attention(q, k, v, causal=causal)
