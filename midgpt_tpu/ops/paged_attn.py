"""Pallas TPU ragged paged-attention kernels for serving decode/verify.

The XLA paged-attention path (models.gpt.decode_paged_at /
verify_paged_at) reads the KV pool through a block-table gather:
``jnp.take(pool[layer], bt)`` materializes a ``[S, Pmax, Hkv, C, PS]``
intermediate in HBM — the pool bytes are read once, written once into
the gathered copy, and read again by the attention contraction, i.e.
the HBM-bound decode step pays the K+V stream ~3x. These kernels walk
each slot's block table IN-KERNEL over its ragged ``pooled_len`` (the
"Ragged Paged Attention" formulation, PAPERS.md — the TPU kernel
purpose-built for exactly this paged layout): every resident page is
DMA'd from HBM into VMEM exactly once, nothing page-shaped ever lands
back in HBM, and the whole joint softmax + weighted-value contraction
runs out of VMEM. Per decode step the pool traffic drops to the
roofline minimum — each live K and V byte crosses HBM once.

BANDED STREAMING (PR 20): the walk no longer assembles every resident
page at once. The grid runs over (slot x KV head), and each program
streams its head's pages in ascending PAGE BANDS of ``band_pages``
pages, double-buffered at ``DMA_DEPTH``: while band *i* computes, band
*i+1*'s DMA is already in flight. VMEM residency is
O(DMA_DEPTH x band x page_size) per pass — independent of Pmax — so
``supported()`` now says yes at 100k-token contexts (6250 pages @
ps16) where the old whole-pool assembly needed ~940 MB. Band sizing:
``band_pages`` picks the largest divisor of Pmax whose per-band
working set (K+V band buffers at DMA depth, plus the f32 dequant/
upcast views a sub-f32 pool materializes) fits ``BAND_VMEM_BUDGET``,
capped at ``MAX_BANDS`` bands (the band loop is Python-unrolled into
the trace). No divisor fits -> ``band_pages`` returns None, the gate
reports the honest single-band cost, and ``auto`` falls back to XLA.

EXACTNESS CONTRACT (the reason this kernel looks the way it does): the
serving suite's landing gate is greedy token-identity against the XLA
path, and the repo has twice shipped attention variants that drifted by
~2 bf16 ulps and flipped near-tied greedy argmaxes on real checkpoints
(PR 4/PR 5, see analysis.choreo). A classic flash-style online-softmax
accumulator — running max with ``exp(m_old - m_new)`` rescales folded
into the accumulator — can NEVER be bitwise against the XLA joint
softmax: the rescale multiplies are extra roundings. Banding does not
change that decision (the PR 9 design decision stands): the f32 score
row for the FULL context is small (~0.4 MB per head-group at 100k) and
stays VMEM-resident, so normalization remains ONE flat f32 softmax.
Concretely the kernel makes two streaming passes per program:

  pass 1 (K): each band's scores are per-column sums over C — banding
    is invisible to them bitwise — concatenated with the recent/self
    scores into the one full score row, then the single joint softmax;
  pass 2 (V): each band's PV partial summed over its band width,
    folded in PINNED ASCENDING-BAND ORDER (``banded_fold``). The fold
    order is the ONE place banding touches f32 summation order, so
    the XLA reference path runs the IDENTICAL chunked reduction
    (models.gpt banded PV fold, same ``banded_fold``, same band plan)
    and the kernel stays BITWISE equal to the XLA path (asserted by
    tests/test_paged_attn.py down to the f32 pattern) across decode +
    verify, MHA + GQA, ragged lengths, both pool precisions, and the
    greedy/sampled token-identity matrix. The accumulation order is
    machine-checked: analysis.choreo's banded-accumulation-order
    clause extracts the fold's add-tree leaf order from the jaxpr and
    fails if any band lands out of ascending order.

INT8 KV (``scale_k``/``scale_v`` given): the pool payload is int8 with
one f32 power-of-two scale per (page, KV-head) plane
(serving.paged — the KV analogue of quant.py's po2 exactness contract).
Dequantization happens in-kernel at the VMEM boundary, per band:
``f32(q) * scale`` with ``|q| <= 127`` and a po2 scale is EXACT and
elementwise, so the band slice of the dequantized stream equals the
dequantized band slice — an int8 pool behaves like a bf16 pool whose
values happen to lie on the page grid, and the greedy token streams
stay invariant across every engine feature combination (unit-tested at
the page level).

Dtype choreography (machine-checked: analysis.choreo extracts the
kernel body's softmax signature and proves it equal to the decode
window's — a bf16-accumulating edit here turns the serving-choreo CI
gate red): bf16 Q/K products formed as f32 upcast-multiplies, f32 score
accumulation, additive mask before the in-softmax scale, one joint f32
exp per layer, f32 probs through the PV sums, output rounded to the
compute dtype once at the end.

CPU/tier-1: the kernels run under the Pallas interpreter (no TPU
required) — ``interpret`` defaults to "not on a TPU backend", so the
tier-1 suite and the CI serving gates execute the very same kernel
bodies the hardware runs. The XLA gather path stays available as a
config-selected fallback (``ServingEngine(paged_kernel="xla")``),
exactly as ops/flash.py keeps naive attention for training.
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

# The score-accumulation dtype of both kernels. Module-level so the
# choreography fault-injection test (tests/test_choreo.py) can
# monkeypatch a bf16-accumulating kernel variant and prove the prover
# catches it; the shipped value is load-bearing — f32 accumulation IS
# the decode choreography contract.
SCORE_ACC_DTYPE = jnp.float32


def _interpret_default() -> bool:
    from midgpt_tpu.utils.platform import is_tpu_backend

    return not is_tpu_backend()


# Conservative fit budget for the kernel's total VMEM working set
# (band stream buffers + the full-context f32 score/prob rows), out of
# ~16 MB/core. Module-level so the long-context gate tests can pin the
# acceptance arithmetic against the same constant the ``auto`` path
# uses.
VMEM_BUDGET = 12 * 1024 * 1024

# Double-buffer depth of the banded page stream: band i's compute
# overlaps band i+1's DMA. Depth 2 is the classic ping-pong (the
# Pallas double-buffering idiom); the band working-set arithmetic in
# ``_band_bytes`` scales with it, so raising the depth automatically
# shrinks the auto-sized band.
DMA_DEPTH = 2

# Per-pass band working-set budget: DMA_DEPTH band buffers for K and V
# at pool dtype, plus the f32 dequant/upcast views of the compute
# band. 2 MB keeps the stream buffers a small fraction of VMEM_BUDGET
# so the full-context f32 score row — the flat-softmax contract's
# residency cost — gets the rest.
BAND_VMEM_BUDGET = 2 * 1024 * 1024

# The band loop is Python-unrolled into the kernel trace (that is what
# keeps the choreography extractable and the softmax flat), so cap the
# band count: a geometry that would need more bands than this is
# rejected by the gate rather than traced into an enormous program.
MAX_BANDS = 64

# Test hook: force the band plan (pages per band) regardless of the
# VMEM arithmetic, so small-geometry tests can exercise genuinely
# multi-banded kernels. Must divide Pmax. None = auto-size.
_FORCE_BAND_PAGES: tp.Optional[int] = None

# Fault-injection hook for the choreography prover's banded-
# accumulation-order clause: "ascending" is the pinned contract; the
# choreo fault test flips this to "descending" and the prover must
# fail EXACTLY the band-order clause (both the kernel and the XLA
# reference fold through banded_fold, so bitwise kernel==XLA survives
# the flip and no other clause goes red).
_BAND_FOLD_ORDER = "ascending"


def banded_fold(parts: tp.Sequence[Array]) -> Array:
    """Fold the per-band PV partials in the PINNED ascending-band
    order (a left fold: ((o_0 + o_1) + o_2) + ...). f32 addition is
    not associative, so this order IS the bitwise contract between the
    banded kernel and the banded XLA reference — both call exactly
    this function. The recent/self partial is added AFTER the fold,
    outside it (it is not a page band)."""
    seq = list(parts)
    if _BAND_FOLD_ORDER != "ascending":
        seq = seq[::-1]
    out = seq[0]
    for p in seq[1:]:
        out = out + p
    return out


def _band_bytes(band_pages_: int, page_size: int, c: int,
                itemsize: int) -> int:
    """VMEM bytes of ONE streaming pass's band working set at this
    band size: K and V band buffers (2x) at DMA_DEPTH slots each, pool
    dtype, plus — for a sub-f32 pool (bf16, int8) — the f32
    dequant/upcast views of the K and V compute bands that
    ``_dequant_band`` materializes."""
    bw = band_pages_ * page_size
    total = 2 * DMA_DEPTH * c * bw * itemsize
    if itemsize < 4:
        total += 2 * c * bw * 4
    return total


def band_pages(pmax: int, page_size: int, c: int,
               itemsize: int) -> tp.Optional[int]:
    """Auto-size the page band: the LARGEST divisor of ``pmax`` whose
    band working set fits ``BAND_VMEM_BUDGET``, with at most
    ``MAX_BANDS`` bands (the band loop is unrolled into the trace).
    Returns None when no divisor satisfies both — e.g. a head dim so
    large even one page overflows the band budget, or a
    pathologically-factored Pmax whose only fitting divisors need too
    many bands — and the gate then reports the honest single-band
    (whole-table) cost, which is exactly the pre-banding arithmetic.
    The plan depends ONLY on (pmax, page_size, c, itemsize): never on
    head counts, groups, or spec length, so the fold order — and with
    it the f32 bit pattern — is invariant across TP degree and
    spec on/off."""
    if _FORCE_BAND_PAGES is not None:
        assert pmax % _FORCE_BAND_PAGES == 0, (
            f"_FORCE_BAND_PAGES={_FORCE_BAND_PAGES} must divide "
            f"pmax={pmax}"
        )
        return _FORCE_BAND_PAGES
    best = None
    for d in range(1, pmax + 1):
        if pmax % d:
            continue
        if _band_bytes(d, page_size, c, itemsize) <= BAND_VMEM_BUDGET:
            best = d
    if best is None or pmax // best > MAX_BANDS:
        return None
    return best


def resolved_band_pages(pmax: int, page_size: int, c: int,
                        itemsize: int) -> int:
    """The band plan the kernels AND the XLA reference fold actually
    use: the auto-sized (or test-forced) band, falling back to one
    whole-table band when no plan fits — the honest degenerate case
    the gate keeps off the ``auto`` path but a forced kernel can still
    run. Shared between ops.paged_attn and models.gpt so the two PV
    fold orders can never diverge."""
    bp = band_pages(pmax, page_size, c, itemsize)
    if bp is None:
        bp = pmax
    assert pmax % bp == 0
    return bp


def vmem_bytes(pmax: int, page_size: int, hkv: int, c: int,
               itemsize: int, groups: int = 8, spec_t: int = 1) -> int:
    """Worst-case VMEM demand of the BANDED kernel at this geometry,
    in bytes: one streaming pass's band working set (``_band_bytes``
    at the auto-sized band — K + V band buffers at DMA_DEPTH, plus the
    f32 dequant/upcast views for a sub-f32 pool), the full-context f32
    score + prob rows ([G, T, W] x2 — the flat-softmax residency
    cost), and the int8 pool's per-page f32 scale planes. ``hkv`` is
    accepted for signature stability but no longer enters the
    arithmetic: the grid runs over (slot x KV head), so per-program
    residency is head-count-free — that grid axis is half of what
    makes 100k contexts fit. When ``band_pages`` finds no plan the
    arithmetic falls back to the single whole-table band, i.e. the
    honest pre-banding cost, and the gate rejects from the byte count
    exactly as before. Exposed separately from :func:`supported` so
    the long-context tests can pin the arithmetic itself."""
    del hkv  # grid over KV heads: residency is per-head already
    bp = band_pages(pmax, page_size, c, itemsize)
    if bp is None:
        bp = pmax
    w = pmax * page_size
    # [G, T, W] f32 score row + prob row, both resident across pass 2
    scores = 2 * max(1, groups) * max(1, spec_t) * w * 4
    total = _band_bytes(bp, page_size, c, itemsize) + scores
    if itemsize == 1:
        # int8 pool: the gathered per-page scale planes ride along as
        # [Pmax]-shaped f32 VMEM blocks (K and V)
        total += 2 * pmax * 4
    return total


def supported(pmax: int, page_size: int, hkv: int, c: int,
              itemsize: int, groups: int = 8, spec_t: int = 1) -> bool:
    """Does the banded working set for this geometry fit comfortably
    in VMEM? Band stream buffers + full-context f32 score/prob rows
    (``groups`` = query heads per KV head — the [G, W] score and prob
    rows scale with it; ``spec_t`` = candidate rows per slot in the
    verify kernel, whose rows are [G, T, W] — pass ``speculate + 1``
    when speculation is on), against a conservative 12 MB budget (of
    ~16 MB/core). A sub-f32 pool (bf16, and worst int8 — 1 counted
    byte vs 4 materialized) also pays for the f32 dequant/upcast
    copies of the K and V compute bands that ``_dequant_band`` builds
    on top of the pool-dtype stream; omitting them let ``auto`` pick
    the kernel on geometries whose real VMEM demand overflowed Mosaic
    (code-review finding, PR 9 — the accounting survives banding,
    per-band). Because the band working set is O(band) rather than
    O(Pmax), this now returns True at 100k-token Pmax (6250 pages @
    ps16) for both bf16 and int8 pools — the gate that used to reject
    from a ~940 MB whole-pool assembly."""
    return vmem_bytes(
        pmax, page_size, hkv, c, itemsize, groups=groups, spec_t=spec_t
    ) <= VMEM_BUDGET


def _dequant_band(buf: Array, sc: tp.Optional[Array], b: int, bp: int,
                  ps: int) -> Array:
    """One band's VMEM buffer [C, BW] -> f32 stream values. For an
    int8 pool the band's slice of the per-page scale vector broadcasts
    to per-position columns and the dequant multiply is exact
    (|q| <= 127, po2 scale — quant.py's epilogue contract, applied to
    the KV stream). Dequantization is elementwise, so the band slice
    of the dequantized stream is bitwise the dequantized band slice —
    banding cannot perturb it."""
    if sc is None:
        return buf.astype(jnp.float32)
    sc_b = sc[b * bp:(b + 1) * bp]  # [BP] f32, static band slice
    scw = jnp.broadcast_to(sc_b[:, None], (bp, ps)).reshape(1, bp * ps)
    return buf.astype(jnp.float32) * scw


def _decode_kernel(
    # scalar prefetch
    bt_ref,      # [S, Pmax] int32
    len_ref,     # [S] int32 — pooled_len
    r_ref,       # [1] int32 — step index within the window
    # inputs
    q_ref,       # [1, 1, G, C] block — this (slot, KV head)'s queries
    rk_ref,      # [1, 1, R, C] block — recent K rows (this layer/head)
    rv_ref,      # [1, 1, R, C] block
    sk_ref,      # [1, 1, Pmax] f32 block or None (int8 pool only)
    sv_ref,
    pk_ref,      # [L, NP, Hkv, C, PS] pool K, HBM/ANY
    pv_ref,
    # outputs / scratch
    out_ref,     # [1, 1, G, C] block
    kband,       # VMEM [DMA_DEPTH, C, BP*PS] pool dtype
    vband,
    sem,         # DMA semaphores [2, DMA_DEPTH] (K row 0, V row 1)
    *,
    layer: int,
    ps: int,
    nb: int,
):
    s = pl.program_id(0)
    j = pl.program_id(1)
    _, c, bw = kband.shape
    bp = bw // ps
    w = nb * bw
    rr = rk_ref.shape[2]
    np_total = pk_ref.shape[1]
    npages = pl.cdiv(len_ref[s], ps)

    def _band_dma(pref, buf, row, b, start):
        """Start (or wait for) band ``b``'s page DMAs into buffer slot
        b % DMA_DEPTH: each live page of the band crosses HBM exactly
        once, into its [.., i*PS:(i+1)*PS] band columns. Page ids are
        clipped like the XLA path's ``mode="clip"`` gather (pads
        beyond ``npages`` are never walked; the clip is defense
        against a corrupt table, and clipped garbage is erased by the
        -inf mask before the softmax). The zero-fill on start is what
        makes un-DMA'd columns safe: masked scores become exactly
        ``0 + (-inf)`` and masked value columns contribute exactly
        ``0.0 * 0.0`` — finite, so no NaN can leak through
        ``0 * garbage``. Waits re-construct the same descriptors and
        pair one wait per started page on the band's semaphore."""
        slot = b % DMA_DEPTH
        lo = b * bp
        live = jnp.clip(npages - lo, 0, bp)
        if start:
            buf[slot] = jnp.zeros_like(buf[slot])

        def body(i, carry):
            page = jnp.clip(bt_ref[s, lo + i], 0, np_total - 1)
            cp = pltpu.make_async_copy(
                pref.at[layer, page, j],
                buf.at[slot, :, pl.ds(i * ps, ps)],
                sem.at[row, slot],
            )
            if start:
                cp.start()
            else:
                cp.wait()
            return carry

        jax.lax.fori_loop(0, live, body, 0)

    qs = q_ref[0, 0]  # [G, C]
    sc_k = None if sk_ref is None else sk_ref[0, 0]  # [Pmax] f32
    sc_v = None if sv_ref is None else sv_ref[0, 0]
    # PASS 1 (K): stream the bands, double-buffered — band b's scores
    # compute while band b+1's DMA is in flight. Each band's scores
    # are per-column sums over C, so banding is bitwise-invisible to
    # them; the masked parts concatenate into the ONE full-context f32
    # score row (the flat-softmax contract — no online rescaling).
    for d in range(min(DMA_DEPTH - 1, nb)):
        _band_dma(pk_ref, kband, 0, d, start=True)
    parts = []
    for b in range(nb):
        nxt = b + DMA_DEPTH - 1
        if nxt < nb:
            _band_dma(pk_ref, kband, 0, nxt, start=True)
        _band_dma(pk_ref, kband, 0, b, start=False)
        ck_b = _dequant_band(kband[b % DMA_DEPTH], sc_k, b, bp, ps)
        # the decode choreography, op for op (decode_paged_at): f32
        # upcast-multiplies, f32 accumulation, mask BEFORE the
        # in-softmax scale
        s_b = jnp.sum(
            qs[:, :, None].astype(SCORE_ACC_DTYPE)
            * ck_b[None].astype(SCORE_ACC_DTYPE),
            axis=-2, dtype=SCORE_ACC_DTYPE,
        )  # [G, BW]
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, bw), 1)[0] + b * bw
        mask_b = jnp.where(idx < len_ref[s], 0.0, -jnp.inf).astype(
            jnp.float32
        )
        parts.append(s_b + mask_b)
    rkl = rk_ref[0, 0]  # [R, C]
    rvl = rv_ref[0, 0]
    s_rec = jnp.sum(
        qs[:, None, :].astype(SCORE_ACC_DTYPE)
        * rkl[None].astype(SCORE_ACC_DTYPE),
        axis=-1, dtype=SCORE_ACC_DTYPE,
    )  # [G, R]
    ridx = jax.lax.broadcasted_iota(jnp.int32, (1, rr), 1)[0]
    mask_rec = jnp.where(ridx <= r_ref[0], 0.0, -jnp.inf).astype(
        jnp.float32
    )
    s_all = jnp.concatenate(parts + [s_rec + mask_rec], axis=-1)
    probs = jax.nn.softmax(s_all / math.sqrt(c), axis=-1)  # f32, joint
    # PASS 2 (V): stream the bands again (each V byte still crosses
    # HBM exactly once), each band's PV partial summed over its band
    # width, folded in PINNED ascending-band order — the one place
    # banding touches f32 summation order, matched bitwise by the XLA
    # reference's banded_fold.
    for d in range(min(DMA_DEPTH - 1, nb)):
        _band_dma(pv_ref, vband, 1, d, start=True)
    opars = []
    for b in range(nb):
        nxt = b + DMA_DEPTH - 1
        if nxt < nb:
            _band_dma(pv_ref, vband, 1, nxt, start=True)
        _band_dma(pv_ref, vband, 1, b, start=False)
        cv_b = _dequant_band(vband[b % DMA_DEPTH], sc_v, b, bp, ps)
        p_b = probs[:, b * bw:(b + 1) * bw]  # [G, BW] f32
        opars.append(
            jnp.sum(p_b[:, None, :] * cv_b[None].astype(jnp.float32),
                    axis=-1)
        )  # [G, C]
    o_pool = banded_fold(opars)
    p_rec = probs[:, w:]
    o_rec = jnp.sum(
        p_rec[..., None] * rvl[None].astype(jnp.float32), axis=-2
    )
    out_ref[0, 0] = (o_pool + o_rec).astype(out_ref.dtype)


def paged_decode_attention(
    q: Array,        # [S, Hkv, G, C] post-rope/norm queries, compute dtype
    pool_k: Array,   # [L, NP, Hkv, C, PS] pool (bf16/f32, or int8)
    pool_v: Array,
    bt: Array,       # [S, Pmax] int32 block tables
    pooled_len: Array,  # [S] int32 — ragged per-slot resident lengths
    rk_l: Array,     # [S, Hkv, R, C] recent K rows, THIS layer
    rv_l: Array,
    r: Array,        # [] int32 — step index within the window
    layer: int,      # STATIC layer index
    scale_k: tp.Optional[Array] = None,  # [S, Pmax, Hkv] f32 gathered
    scale_v: tp.Optional[Array] = None,  # per-page scales (int8 pool)
    interpret: tp.Optional[bool] = None,
) -> Array:  # [S, Hkv, G, C] compute dtype
    """One decode step's paged attention for all slots: pool part
    streamed by the banded in-kernel ragged block-table walk, recent
    part from the window's write buffer, one joint softmax — bitwise
    the (banded-fold) XLA gather path's result without the gathered
    HBM intermediate, at O(band) VMEM."""
    s, hkv, g, c = q.shape
    l, np_total, _, _, ps = pool_k.shape
    pmax = bt.shape[1]
    quant = scale_k is not None
    if interpret is None:
        interpret = _interpret_default()
    bp = resolved_band_pages(pmax, ps, c, jnp.dtype(pool_k.dtype).itemsize)
    nb = pmax // bp
    kern = functools.partial(_decode_kernel, layer=layer, ps=ps, nb=nb)
    if not quant:
        kern = _drop_scale_refs(kern, n_scalar=3)
    in_specs = [
        pl.BlockSpec((1, 1, g, c), lambda i, j, *_: (i, j, 0, 0)),
        pl.BlockSpec(
            (1, 1, rk_l.shape[2], c), lambda i, j, *_: (i, j, 0, 0)
        ),
        pl.BlockSpec(
            (1, 1, rk_l.shape[2], c), lambda i, j, *_: (i, j, 0, 0)
        ),
    ]
    args = [q, rk_l, rv_l]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, pmax), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, 1, pmax), lambda i, j, *_: (i, j, 0)),
        ]
        # [S, Pmax, Hkv] -> [S, Hkv, Pmax]: a head's scale vector as a
        # contiguous last-dim block (a [.., Pmax, 1] block would pad
        # its unit lane dim out to the tile width — ~3 MB at 100k Pmax)
        args += [
            jnp.transpose(scale_k, (0, 2, 1)),
            jnp.transpose(scale_v, (0, 2, 1)),
        ]
    in_specs += [
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    args += [pool_k, pool_v]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(s, hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, g, c), lambda i, j, *_: (i, j, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((DMA_DEPTH, c, bp * ps), pool_k.dtype),
            pltpu.VMEM((DMA_DEPTH, c, bp * ps), pool_v.dtype),
            pltpu.SemaphoreType.DMA((2, DMA_DEPTH)),
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, hkv, g, c), q.dtype),
        interpret=interpret,
    )(bt, pooled_len, jnp.reshape(r, (1,)), *args)


def _drop_scale_refs(kern, n_scalar: int):
    """Adapt a kernel written for the quantized operand list (scale
    blocks present) to the float-pool call (scales absent): insert None
    where the scale refs would sit. Positions: scalars, then 3 tensor
    blocks (q + two row buffers), then [sk, sv], then pool refs."""

    @functools.wraps(kern)
    def wrapped(*refs):
        pre = refs[: n_scalar + 3]
        post = refs[n_scalar + 3:]
        return kern(*pre, None, None, *post)

    return wrapped


def _verify_kernel(
    # scalar prefetch
    bt_ref,      # [S, Pmax] int32
    start_ref,   # [S] int32 — per-slot write watermark
    # inputs
    q_ref,       # [1, 1, G, T, C] block
    kc_ref,      # [1, 1, T, C] block — cache-rounded self K rows
    vc_ref,      # [1, 1, T, C] block
    sk_ref,      # [1, 1, Pmax] f32 block or None
    sv_ref,
    pk_ref,      # [L, NP, Hkv, C, PS] pool, HBM/ANY
    pv_ref,
    out_ref,     # [1, 1, G, T, C] block
    kband,       # VMEM [DMA_DEPTH, C, BP*PS] pool dtype
    vband,
    sem,
    *,
    layer: int,
    ps: int,
    nb: int,
):
    s = pl.program_id(0)
    j = pl.program_id(1)
    _, c, bw = kband.shape
    bp = bw // ps
    w = nb * bw
    t = kc_ref.shape[2]
    np_total = pk_ref.shape[1]
    npages = pl.cdiv(start_ref[s], ps)

    def _band_dma(pref, buf, row, b, start):
        # identical walk to _decode_kernel's _band_dma (see its
        # docstring for the clip/zero-fill contract)
        slot = b % DMA_DEPTH
        lo = b * bp
        live = jnp.clip(npages - lo, 0, bp)
        if start:
            buf[slot] = jnp.zeros_like(buf[slot])

        def body(i, carry):
            page = jnp.clip(bt_ref[s, lo + i], 0, np_total - 1)
            cp = pltpu.make_async_copy(
                pref.at[layer, page, j],
                buf.at[slot, :, pl.ds(i * ps, ps)],
                sem.at[row, slot],
            )
            if start:
                cp.start()
            else:
                cp.wait()
            return carry

        jax.lax.fori_loop(0, live, body, 0)

    qs = q_ref[0, 0]  # [G, T, C]
    kc = kc_ref[0, 0]  # [T, C]
    vc = vc_ref[0, 0]
    sc_k = None if sk_ref is None else sk_ref[0, 0]  # [Pmax] f32
    sc_v = None if sv_ref is None else sv_ref[0, 0]
    # the decode choreography over T candidate rows (verify_paged_at
    # op for op): f32 upcast-multiplies, f32 accumulation, one joint
    # exp, f32 probs through the PV sums — banded exactly like
    # _decode_kernel (pass 1 K scores, flat softmax, pass 2 V fold)
    for d in range(min(DMA_DEPTH - 1, nb)):
        _band_dma(pk_ref, kband, 0, d, start=True)
    parts = []
    for b in range(nb):
        nxt = b + DMA_DEPTH - 1
        if nxt < nb:
            _band_dma(pk_ref, kband, 0, nxt, start=True)
        _band_dma(pk_ref, kband, 0, b, start=False)
        ck_b = _dequant_band(kband[b % DMA_DEPTH], sc_k, b, bp, ps)
        s_b = jnp.sum(
            qs[..., :, None].astype(SCORE_ACC_DTYPE)
            * ck_b[None, None].astype(SCORE_ACC_DTYPE),
            axis=-2, dtype=SCORE_ACC_DTYPE,
        )  # [G, T, BW]
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, bw), 1)[0] + b * bw
        mask_b = jnp.where(idx < start_ref[s], 0.0, -jnp.inf).astype(
            jnp.float32
        )
        parts.append(s_b + mask_b)
    s_self = jnp.sum(
        qs[:, :, None, :].astype(SCORE_ACC_DTYPE)
        * kc[None, None].astype(SCORE_ACC_DTYPE),
        axis=-1, dtype=SCORE_ACC_DTYPE,
    )  # [G, T, T]
    rows = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    mask_self = jnp.where(cols <= rows, 0.0, -jnp.inf).astype(jnp.float32)
    s_all = jnp.concatenate(parts + [s_self + mask_self], axis=-1)
    probs = jax.nn.softmax(s_all / math.sqrt(c), axis=-1)  # f32
    for d in range(min(DMA_DEPTH - 1, nb)):
        _band_dma(pv_ref, vband, 1, d, start=True)
    opars = []
    for b in range(nb):
        nxt = b + DMA_DEPTH - 1
        if nxt < nb:
            _band_dma(pv_ref, vband, 1, nxt, start=True)
        _band_dma(pv_ref, vband, 1, b, start=False)
        cv_b = _dequant_band(vband[b % DMA_DEPTH], sc_v, b, bp, ps)
        p_b = probs[:, :, b * bw:(b + 1) * bw]  # [G, T, BW] f32
        opars.append(
            jnp.sum(
                p_b[:, :, None, :] * cv_b[None, None].astype(jnp.float32),
                axis=-1,
            )
        )  # [G, T, C]
    o_pool = banded_fold(opars)
    p_self = probs[:, :, w:]
    o_self = jnp.sum(
        p_self[..., None] * vc[None, None].astype(jnp.float32), axis=-2
    )  # [G, T, C]
    out_ref[0, 0] = (o_pool + o_self).astype(out_ref.dtype)


def paged_verify_attention(
    q: Array,        # [S, Hkv, G, T, C] compute dtype
    kc: Array,       # [S, Hkv, T, C] cache-rounded self K rows
    vc: Array,
    pool_k: Array,   # [L, NP, Hkv, C, PS]
    pool_v: Array,
    bt: Array,       # [S, Pmax] int32
    start: Array,    # [S] int32 — write watermark (resident tokens)
    layer: int,
    scale_k: tp.Optional[Array] = None,  # [S, Pmax, Hkv] f32 gathered
    scale_v: tp.Optional[Array] = None,
    interpret: tp.Optional[bool] = None,
) -> Array:  # [S, Hkv, G, T, C]
    """Speculative-verify paged attention: all T candidate rows of every
    slot against its ragged resident pages plus themselves (causal), one
    joint softmax, decode choreography — the kernel twin of
    ``Attention.verify_paged_at`` with the same banded in-kernel walk as
    :func:`paged_decode_attention`."""
    s, hkv, g, t, c = q.shape
    l, np_total, _, _, ps = pool_k.shape
    pmax = bt.shape[1]
    quant = scale_k is not None
    if interpret is None:
        interpret = _interpret_default()
    bp = resolved_band_pages(pmax, ps, c, jnp.dtype(pool_k.dtype).itemsize)
    nb = pmax // bp
    kern = functools.partial(_verify_kernel, layer=layer, ps=ps, nb=nb)
    if not quant:
        kern = _drop_scale_refs(kern, n_scalar=2)
    in_specs = [
        pl.BlockSpec((1, 1, g, t, c), lambda i, j, *_: (i, j, 0, 0, 0)),
        pl.BlockSpec((1, 1, t, c), lambda i, j, *_: (i, j, 0, 0)),
        pl.BlockSpec((1, 1, t, c), lambda i, j, *_: (i, j, 0, 0)),
    ]
    args = [q, kc, vc]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, pmax), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, 1, pmax), lambda i, j, *_: (i, j, 0)),
        ]
        args += [
            jnp.transpose(scale_k, (0, 2, 1)),
            jnp.transpose(scale_v, (0, 2, 1)),
        ]
    in_specs += [
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    args += [pool_k, pool_v]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, g, t, c), lambda i, j, *_: (i, j, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((DMA_DEPTH, c, bp * ps), pool_k.dtype),
            pltpu.VMEM((DMA_DEPTH, c, bp * ps), pool_v.dtype),
            pltpu.SemaphoreType.DMA((2, DMA_DEPTH)),
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, hkv, g, t, c), q.dtype),
        interpret=interpret,
    )(bt, start, *args)
