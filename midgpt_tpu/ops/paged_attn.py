"""Pallas TPU ragged paged-attention kernels for serving decode/verify.

The XLA paged-attention path (models.gpt.decode_paged_at /
verify_paged_at) reads the KV pool through a block-table gather:
``jnp.take(pool[layer], bt)`` materializes a ``[S, Pmax, Hkv, C, PS]``
intermediate in HBM — the pool bytes are read once, written once into
the gathered copy, and read again by the attention contraction, i.e.
the HBM-bound decode step pays the K+V stream ~3x. These kernels walk
each slot's block table IN-KERNEL over its ragged ``pooled_len`` (the
"Ragged Paged Attention" formulation, PAPERS.md — the TPU kernel
purpose-built for exactly this paged layout): every resident page is
DMA'd from HBM into a VMEM assembly scratch exactly once, nothing
page-shaped ever lands back in HBM, and the whole joint softmax +
weighted-value contraction runs out of VMEM. Per decode step the pool
traffic drops to the roofline minimum — each live K and V byte crosses
HBM once.

EXACTNESS CONTRACT (the reason this kernel looks the way it does): the
serving suite's landing gate is greedy token-identity against the XLA
path, and the repo has twice shipped attention variants that drifted by
~2 bf16 ulps and flipped near-tied greedy argmaxes on real checkpoints
(PR 4/PR 5, see analysis.choreo). A classic flash-style online-softmax
accumulator — running max with ``exp(m_old - m_new)`` rescales folded
into the accumulator — can NEVER be bitwise against the XLA joint
softmax: the rescale multiplies are extra roundings. So the walk here
is "online" in the streaming sense but defers normalization: pages
stream once into the VMEM assembly, the running mask/length bookkeeping
rides the walk, and the softmax itself is ONE flat f32 pass over the
VMEM-resident scores — the exact op sequence (same primitives, same
reduce extents, mask added before the in-softmax ``/ sqrt(C)`` scale,
f32 probs through the PV sums) as ``decode_paged_at``. The result is
BITWISE equal to the XLA gather path (asserted by
tests/test_paged_attn.py down to the f32 pattern), so the kernel slots
under the existing token-identity matrix instead of weakening it to a
tolerance. The VMEM cost is the assembly scratch, O(context) instead of
O(1) — at serving block sizes (<= 8K tokens) that is a few MB against
the 16 MB budget; a context long enough to break that is ring/offload
territory, not a paged decode batch.

INT8 KV (``scale_k``/``scale_v`` given): the pool payload is int8 with
one f32 power-of-two scale per (page, KV-head) plane
(serving.paged — the KV analogue of quant.py's po2 exactness contract).
Dequantization happens in-kernel at the VMEM boundary:
``f32(q) * scale`` with ``|q| <= 127`` and a po2 scale is EXACT, so the
kernel is bitwise against dequantize-then-attend — an int8 pool behaves
like a bf16 pool whose values happen to lie on the page grid, and the
greedy token streams stay invariant across every engine feature
combination (unit-tested at the page level).

Dtype choreography (machine-checked: analysis.choreo extracts the
kernel body's softmax signature and proves it equal to the decode
window's — a bf16-accumulating edit here turns the serving-choreo CI
gate red): bf16 Q/K products formed as f32 upcast-multiplies, f32 score
accumulation, additive mask before the in-softmax scale, one joint f32
exp per layer, f32 probs through the PV sums, output rounded to the
compute dtype once at the end.

CPU/tier-1: the kernels run under the Pallas interpreter (no TPU
required) — ``interpret`` defaults to "not on a TPU backend", so the
tier-1 suite and the CI serving gates execute the very same kernel
bodies the hardware runs. The XLA gather path stays available as a
config-selected fallback (``ServingEngine(paged_kernel="xla")``),
exactly as ops/flash.py keeps naive attention for training.
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

# The score-accumulation dtype of both kernels. Module-level so the
# choreography fault-injection test (tests/test_choreo.py) can
# monkeypatch a bf16-accumulating kernel variant and prove the prover
# catches it; the shipped value is load-bearing — f32 accumulation IS
# the decode choreography contract.
SCORE_ACC_DTYPE = jnp.float32


def _interpret_default() -> bool:
    from midgpt_tpu.utils.platform import is_tpu_backend

    return not is_tpu_backend()


# Conservative fit budget for the VMEM assembly + score scratch, out of
# ~16 MB/core. Module-level so the long-context gate tests can pin the
# rejection arithmetic against the same constant the ``auto`` path uses.
VMEM_BUDGET = 12 * 1024 * 1024


def vmem_bytes(pmax: int, page_size: int, hkv: int, c: int,
               itemsize: int, groups: int = 8, spec_t: int = 1) -> int:
    """Worst-case VMEM demand of the kernel at this geometry, in bytes:
    the K + V assembly scratch at pool dtype, the f32 dequant/upcast
    views ``_dequant_view`` materializes on top of a sub-f32 pool, and
    f32 score/prob headroom ([Hkv, G, T, W] x4 for scores + probs + exp
    temps). Exposed separately from :func:`supported` so the
    long-context tests can pin the arithmetic itself — at 100k-token
    Pmax the assembly alone is tens of MB and the gate must reject from
    the byte count, not from a tuned special case."""
    w = pmax * page_size
    assembly = 2 * hkv * c * w * itemsize
    if itemsize < 4:
        # f32 ck/cv views of the K and V assemblies
        assembly += 2 * hkv * c * w * 4
    # [Hkv, G, T, W] f32, x4 headroom (scores + probs + exp temps)
    scores = 4 * hkv * max(1, groups) * max(1, spec_t) * w * 4
    return assembly + scores


def supported(pmax: int, page_size: int, hkv: int, c: int,
              itemsize: int, groups: int = 8, spec_t: int = 1) -> bool:
    """Does the assembly scratch for this geometry fit comfortably in
    VMEM? K + V assembly at pool dtype plus f32 score/prob headroom
    (``groups`` = query heads per KV head — the [Hkv, G, W] score and
    prob buffers scale with it; ``spec_t`` = candidate rows per slot in
    the verify kernel, whose score/prob buffers are [Hkv, G, T, W] —
    pass ``speculate + 1`` when speculation is on), against a
    conservative 12 MB budget (of ~16 MB/core). A sub-f32 pool
    (bf16, and worst int8 — 1 counted byte vs 4 materialized) also pays
    for the f32 dequant/upcast copies of BOTH assemblies that
    ``_dequant_view`` builds on top of the pool-dtype scratch; omitting
    them let ``auto`` pick the kernel on geometries whose real VMEM
    demand overflowed Mosaic (code-review finding)."""
    return vmem_bytes(
        pmax, page_size, hkv, c, itemsize, groups=groups, spec_t=spec_t
    ) <= VMEM_BUDGET


def _dequant_view(buf: Array, scales_ref, hkv: int, pmax: int,
                  ps: int) -> Array:
    """VMEM assembly [Hkv, C, W] -> f32 stream values. For an int8 pool
    the per-page scale plane broadcasts to per-position columns and the
    dequant multiply is exact (|q| <= 127, po2 scale — quant.py's
    epilogue contract, applied to the KV stream)."""
    w = pmax * ps
    if scales_ref is None:
        return buf.astype(jnp.float32)
    sc = scales_ref[0]  # [Pmax, Hkv] f32
    scw = jnp.transpose(sc, (1, 0))[:, :, None]  # [Hkv, Pmax, 1]
    scw = jnp.broadcast_to(scw, (hkv, pmax, ps)).reshape(hkv, 1, w)
    return buf.astype(jnp.float32) * scw


def _assemble_pages(pk_ref, pv_ref, bt_ref, s, npages, layer, kbuf, vbuf,
                    sem, ps: int):
    """The in-kernel block-table walk: zero the assembly scratch, then
    DMA each resident page of slot ``s`` (K and V, this layer) from HBM
    into its [.., i*PS:(i+1)*PS] assembly columns — each page crosses
    HBM exactly once. Page ids are clipped like the XLA path's
    ``mode="clip"`` gather (pads beyond ``npages`` are never walked;
    the clip is defense against a corrupt table, and clipped garbage is
    erased by the -inf mask before the softmax). The zero-fill is what
    makes un-walked columns safe: masked scores become exactly
    ``0 + (-inf)`` and masked value columns contribute exactly
    ``0.0 * 0.0`` — finite, so no NaN can leak through ``0 * garbage``."""
    np_total = pk_ref.shape[1]
    kbuf[...] = jnp.zeros_like(kbuf)
    vbuf[...] = jnp.zeros_like(vbuf)

    def body(i, carry):
        page = jnp.clip(bt_ref[s, i], 0, np_total - 1)
        cpk = pltpu.make_async_copy(
            pk_ref.at[layer, page], kbuf.at[:, :, pl.ds(i * ps, ps)],
            sem.at[0],
        )
        cpk.start()
        cpv = pltpu.make_async_copy(
            pv_ref.at[layer, page], vbuf.at[:, :, pl.ds(i * ps, ps)],
            sem.at[1],
        )
        cpv.start()
        cpk.wait()
        cpv.wait()
        return carry

    jax.lax.fori_loop(0, npages, body, 0)


def _decode_kernel(
    # scalar prefetch
    bt_ref,      # [S, Pmax] int32
    len_ref,     # [S] int32 — pooled_len
    r_ref,       # [1] int32 — step index within the window
    # inputs
    q_ref,       # [1, Hkv, G, C] block — this slot's post-rope queries
    rk_ref,      # [1, Hkv, R, C] block — recent K rows (this layer)
    rv_ref,      # [1, Hkv, R, C] block
    sk_ref,      # [1, Pmax, Hkv] f32 block or None (int8 pool only)
    sv_ref,
    pk_ref,      # [L, NP, Hkv, C, PS] pool K, HBM/ANY
    pv_ref,
    # outputs / scratch
    out_ref,     # [1, Hkv, G, C] block
    kbuf,        # VMEM [Hkv, C, Pmax*PS] pool dtype
    vbuf,
    sem,
    *,
    layer: int,
    ps: int,
):
    s = pl.program_id(0)
    hkv, c, w = kbuf.shape
    pmax = w // ps
    rr = rk_ref.shape[2]
    npages = pl.cdiv(len_ref[s], ps)
    _assemble_pages(pk_ref, pv_ref, bt_ref, s, npages, layer, kbuf, vbuf,
                    sem, ps)
    ck = _dequant_view(kbuf[...], sk_ref, hkv, pmax, ps)  # [Hkv, C, W] f32
    cv = _dequant_view(vbuf[...], sv_ref, hkv, pmax, ps)
    qs = q_ref[0]  # [Hkv, G, C]
    # masks: identical values to the XLA path's (0 / -inf f32)
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)[0]
    mask_pool = jnp.where(idx < len_ref[s], 0.0, -jnp.inf).astype(
        jnp.float32
    )
    ridx = jax.lax.broadcasted_iota(jnp.int32, (1, rr), 1)[0]
    mask_rec = jnp.where(ridx <= r_ref[0], 0.0, -jnp.inf).astype(
        jnp.float32
    )
    # the decode choreography, op for op (decode_paged_at): f32
    # upcast-multiplies, f32 accumulation, mask BEFORE the in-softmax
    # scale, one joint exp, f32 probs through the PV sums
    qcw = qs[:, :, :, None]  # [Hkv, G, C, 1]
    s_pool = jnp.sum(
        qcw.astype(SCORE_ACC_DTYPE) * ck[:, None].astype(SCORE_ACC_DTYPE),
        axis=-2, dtype=SCORE_ACC_DTYPE,
    )  # [Hkv, G, W]
    rkl = rk_ref[0]  # [Hkv, R, C]
    rvl = rv_ref[0]
    s_rec = jnp.sum(
        qs[:, :, None, :].astype(SCORE_ACC_DTYPE)
        * rkl[:, None].astype(SCORE_ACC_DTYPE),
        axis=-1, dtype=SCORE_ACC_DTYPE,
    )  # [Hkv, G, R]
    s_all = jnp.concatenate([s_pool + mask_pool, s_rec + mask_rec], axis=-1)
    probs = jax.nn.softmax(s_all / math.sqrt(c), axis=-1)  # f32
    p_pool = probs[..., :w]
    p_rec = probs[..., w:]
    o_pool = jnp.sum(
        p_pool[:, :, None, :] * cv[:, None].astype(jnp.float32), axis=-1
    )  # [Hkv, G, C]
    o_rec = jnp.sum(
        p_rec[..., None] * rvl[:, None].astype(jnp.float32), axis=-2
    )
    out_ref[0] = (o_pool + o_rec).astype(out_ref.dtype)


def paged_decode_attention(
    q: Array,        # [S, Hkv, G, C] post-rope/norm queries, compute dtype
    pool_k: Array,   # [L, NP, Hkv, C, PS] pool (bf16/f32, or int8)
    pool_v: Array,
    bt: Array,       # [S, Pmax] int32 block tables
    pooled_len: Array,  # [S] int32 — ragged per-slot resident lengths
    rk_l: Array,     # [S, Hkv, R, C] recent K rows, THIS layer
    rv_l: Array,
    r: Array,        # [] int32 — step index within the window
    layer: int,      # STATIC layer index
    scale_k: tp.Optional[Array] = None,  # [S, Pmax, Hkv] f32 gathered
    scale_v: tp.Optional[Array] = None,  # per-page scales (int8 pool)
    interpret: tp.Optional[bool] = None,
) -> Array:  # [S, Hkv, G, C] compute dtype
    """One decode step's paged attention for all slots: pool part read
    by an in-kernel ragged block-table walk, recent part from the
    window's write buffer, one joint softmax — bitwise the XLA gather
    path's result without the gathered HBM intermediate."""
    s, hkv, g, c = q.shape
    l, np_total, _, _, ps = pool_k.shape
    pmax = bt.shape[1]
    quant = scale_k is not None
    if interpret is None:
        interpret = _interpret_default()
    kern = functools.partial(_decode_kernel, layer=layer, ps=ps)
    if not quant:
        kern = _drop_scale_refs(kern, n_scalar=3)
    in_specs = [
        pl.BlockSpec((1, hkv, g, c), lambda i, *_: (i, 0, 0, 0)),
        pl.BlockSpec(
            (1, hkv, rk_l.shape[2], c), lambda i, *_: (i, 0, 0, 0)
        ),
        pl.BlockSpec(
            (1, hkv, rk_l.shape[2], c), lambda i, *_: (i, 0, 0, 0)
        ),
    ]
    args = [q, rk_l, rv_l]
    if quant:
        in_specs += [
            pl.BlockSpec((1, pmax, hkv), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec((1, pmax, hkv), lambda i, *_: (i, 0, 0)),
        ]
        args += [scale_k, scale_v]
    in_specs += [
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    args += [pool_k, pool_v]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(s,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hkv, g, c), lambda i, *_: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, c, pmax * ps), pool_k.dtype),
            pltpu.VMEM((hkv, c, pmax * ps), pool_v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, hkv, g, c), q.dtype),
        interpret=interpret,
    )(bt, pooled_len, jnp.reshape(r, (1,)), *args)


def _drop_scale_refs(kern, n_scalar: int):
    """Adapt a kernel written for the quantized operand list (scale
    blocks present) to the float-pool call (scales absent): insert None
    where the scale refs would sit. Positions: scalars, then 3 tensor
    blocks (q + two row buffers), then [sk, sv], then pool refs."""

    @functools.wraps(kern)
    def wrapped(*refs):
        pre = refs[: n_scalar + 3]
        post = refs[n_scalar + 3:]
        return kern(*pre, None, None, *post)

    return wrapped


def _verify_kernel(
    # scalar prefetch
    bt_ref,      # [S, Pmax] int32
    start_ref,   # [S] int32 — per-slot write watermark
    # inputs
    q_ref,       # [1, Hkv, G, T, C] block
    kc_ref,      # [1, Hkv, T, C] block — cache-rounded self K rows
    vc_ref,      # [1, Hkv, T, C] block
    sk_ref,      # [1, Pmax, Hkv] f32 block or None
    sv_ref,
    pk_ref,      # [L, NP, Hkv, C, PS] pool, HBM/ANY
    pv_ref,
    out_ref,     # [1, Hkv, G, T, C] block
    kbuf,
    vbuf,
    sem,
    *,
    layer: int,
    ps: int,
):
    s = pl.program_id(0)
    hkv, c, w = kbuf.shape
    pmax = w // ps
    t = kc_ref.shape[2]
    npages = pl.cdiv(start_ref[s], ps)
    _assemble_pages(pk_ref, pv_ref, bt_ref, s, npages, layer, kbuf, vbuf,
                    sem, ps)
    ck = _dequant_view(kbuf[...], sk_ref, hkv, pmax, ps)  # [Hkv, C, W]
    cv = _dequant_view(vbuf[...], sv_ref, hkv, pmax, ps)
    qs = q_ref[0]  # [Hkv, G, T, C]
    kc = kc_ref[0]  # [Hkv, T, C]
    vc = vc_ref[0]
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)[0]
    mask_pool = jnp.where(idx < start_ref[s], 0.0, -jnp.inf).astype(
        jnp.float32
    )
    rows = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    mask_self = jnp.where(cols <= rows, 0.0, -jnp.inf).astype(jnp.float32)
    # the decode choreography over T candidate rows (verify_paged_at op
    # for op): f32 upcast-multiplies, f32 accumulation, one joint exp,
    # f32 probs through the PV sums
    s_pool = jnp.sum(
        qs[..., :, None].astype(SCORE_ACC_DTYPE)
        * ck[:, None, None].astype(SCORE_ACC_DTYPE),
        axis=-2, dtype=SCORE_ACC_DTYPE,
    )  # [Hkv, G, T, W]
    s_self = jnp.sum(
        qs[:, :, :, None, :].astype(SCORE_ACC_DTYPE)
        * kc[:, None, None].astype(SCORE_ACC_DTYPE),
        axis=-1, dtype=SCORE_ACC_DTYPE,
    )  # [Hkv, G, T, T]
    s_all = jnp.concatenate(
        [s_pool + mask_pool, s_self + mask_self], axis=-1
    )
    probs = jax.nn.softmax(s_all / math.sqrt(c), axis=-1)  # f32
    p_pool = probs[..., :w]
    p_self = probs[..., w:]
    o_pool = jnp.sum(
        p_pool[:, :, :, None, :] * cv[:, None, None].astype(jnp.float32),
        axis=-1,
    )  # [Hkv, G, T, C]
    o_self = jnp.sum(
        p_self[..., None] * vc[:, None, None].astype(jnp.float32),
        axis=-2,
    )
    out_ref[0] = (o_pool + o_self).astype(out_ref.dtype)


def paged_verify_attention(
    q: Array,        # [S, Hkv, G, T, C] compute dtype
    kc: Array,       # [S, Hkv, T, C] cache-rounded self K rows
    vc: Array,
    pool_k: Array,   # [L, NP, Hkv, C, PS]
    pool_v: Array,
    bt: Array,       # [S, Pmax] int32
    start: Array,    # [S] int32 — write watermark (resident tokens)
    layer: int,
    scale_k: tp.Optional[Array] = None,  # [S, Pmax, Hkv] f32 gathered
    scale_v: tp.Optional[Array] = None,
    interpret: tp.Optional[bool] = None,
) -> Array:  # [S, Hkv, G, T, C]
    """Speculative-verify paged attention: all T candidate rows of every
    slot against its ragged resident pages plus themselves (causal), one
    joint softmax, decode choreography — the kernel twin of
    ``Attention.verify_paged_at`` with the same in-kernel walk as
    :func:`paged_decode_attention`."""
    s, hkv, g, t, c = q.shape
    l, np_total, _, _, ps = pool_k.shape
    pmax = bt.shape[1]
    quant = scale_k is not None
    if interpret is None:
        interpret = _interpret_default()
    kern = functools.partial(_verify_kernel, layer=layer, ps=ps)
    if not quant:
        kern = _drop_scale_refs(kern, n_scalar=2)
    in_specs = [
        pl.BlockSpec((1, hkv, g, t, c), lambda i, *_: (i, 0, 0, 0, 0)),
        pl.BlockSpec((1, hkv, t, c), lambda i, *_: (i, 0, 0, 0)),
        pl.BlockSpec((1, hkv, t, c), lambda i, *_: (i, 0, 0, 0)),
    ]
    args = [q, kc, vc]
    if quant:
        in_specs += [
            pl.BlockSpec((1, pmax, hkv), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec((1, pmax, hkv), lambda i, *_: (i, 0, 0)),
        ]
        args += [scale_k, scale_v]
    in_specs += [
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    args += [pool_k, pool_v]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, hkv, g, t, c), lambda i, *_: (i, 0, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv, c, pmax * ps), pool_k.dtype),
            pltpu.VMEM((hkv, c, pmax * ps), pool_v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, hkv, g, t, c), q.dtype),
        interpret=interpret,
    )(bt, start, *args)
