"""Pallas TPU fused RMSNorm (forward + custom VJP).

The reference computes RMSNorm in plain jnp (/root/reference/src/layers.py:
60-75); XLA fuses the elementwise chain but still materializes the
normalized activation between the reduce and the consumer. This kernel does
the reduce + scale in one VMEM pass per row block and saves only the [N, 1]
reciprocal-RMS for the backward, which recomputes nothing else.

Math (identical to layers.RMSNorm, f32 accumulation):
    r  = rsqrt(mean(x^2, -1) + eps)
    y  = x * r * w            (w optional)
    g  = dy * w
    dx = r * g - x * r^3 / D * sum(g * x, -1)
    dw = sum_rows(dy * x * r)   (computed in jnp; one fused reduce)

Layout: any [..., D] input, flattened to [N, D]; D must be a multiple of
128 (lane width) — callers fall back to the jnp path otherwise.
"""

from __future__ import annotations

import functools
import typing as tp

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

DEFAULT_BLOCK_ROWS = 256


def _fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps: float, has_weight: bool):
    x = x_ref[:].astype(jnp.float32)  # [bn, D]
    r = jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=1, keepdims=True) + eps)
    y = x * r
    if has_weight:
        y = y * w_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    rstd_ref[:] = r


def _bwd_kernel(x_ref, w_ref, dy_ref, rstd_ref, dx_ref, *, has_weight: bool):
    x = x_ref[:].astype(jnp.float32)  # [bn, D]
    dy = dy_ref[:].astype(jnp.float32)
    r = rstd_ref[:]  # [bn, 1] f32
    g = dy * w_ref[:].astype(jnp.float32) if has_weight else dy
    d = x.shape[1]
    proj = jnp.sum(g * x, axis=1, keepdims=True) / d  # [bn, 1]
    dx = r * g - x * (r * r * r) * proj
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _flatten(x: Array) -> tp.Tuple[Array, tp.Tuple[int, ...]]:
    return x.reshape(-1, x.shape[-1]), x.shape


def _pad_rows(n: int, bn: int) -> int:
    return (bn - n % bn) % bn


def _run_fwd(x2: Array, w: tp.Optional[Array], eps: float, bn: int):
    n, d = x2.shape
    has_weight = w is not None
    w2 = (w if has_weight else jnp.ones((d,), x2.dtype)).reshape(1, d)
    pad = _pad_rows(n, bn)
    if pad:
        x2 = jnp.concatenate([x2, jnp.ones((pad, d), x2.dtype)], axis=0)
    grid = (x2.shape[0] // bn,)
    y, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, has_weight=has_weight),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2.shape, x2.dtype),
            jax.ShapeDtypeStruct((x2.shape[0], 1), jnp.float32),
        ],
    )(x2, w2)
    if pad:
        y, rstd = y[:n], rstd[:n]
    return y, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_rms_norm(
    x: Array,
    weight: tp.Optional[Array],
    eps: float = 1e-6,
    block_rows: int = DEFAULT_BLOCK_ROWS,
) -> Array:
    """RMSNorm over the last dim; ``weight`` is [D] or None."""
    x2, shape = _flatten(x)
    y, _ = _run_fwd(x2, weight, eps, block_rows)
    return y.reshape(shape)


def _vjp_fwd(x, weight, eps, block_rows):
    x2, shape = _flatten(x)
    y, rstd = _run_fwd(x2, weight, eps, block_rows)
    return y.reshape(shape), (x2, weight, rstd, shape)


def _vjp_bwd(eps, block_rows, residuals, dy):
    x2, weight, rstd, shape = residuals
    n, d = x2.shape
    bn = block_rows
    has_weight = weight is not None
    dy2 = dy.reshape(n, d)
    w2 = (weight if has_weight else jnp.ones((d,), x2.dtype)).reshape(1, d)
    pad = _pad_rows(n, bn)
    x_p, dy_p, rstd_p = x2, dy2, rstd
    if pad:
        x_p = jnp.concatenate([x2, jnp.ones((pad, d), x2.dtype)], axis=0)
        dy_p = jnp.concatenate([dy2, jnp.zeros((pad, d), dy2.dtype)], axis=0)
        rstd_p = jnp.concatenate(
            [rstd, jnp.ones((pad, 1), rstd.dtype)], axis=0
        )
    grid = (x_p.shape[0] // bn,)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, has_weight=has_weight),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x_p.shape, x2.dtype),
    )(x_p, w2, dy_p, rstd_p)
    if pad:
        dx = dx[:n]
    if has_weight:
        # one fused reduce; not worth a cross-block accumulation kernel
        dw = jnp.sum(
            dy2.astype(jnp.float32) * x2.astype(jnp.float32) * rstd, axis=0
        ).astype(weight.dtype)
    else:
        dw = None
    return dx.reshape(shape), dw


fused_rms_norm.defvjp(_vjp_fwd, _vjp_bwd)
