"""Projection-natural fused attention: QK-LayerNorm + RoPE + flash in one
Pallas kernel family, reading and writing ``[B, T, H*C]`` (the layout the
QKV projection produces) instead of ``[B, H, T, C]``.

Why this exists (r3, PERF.md): at the 124M shape the flash kernel itself is
at the platform ceiling, but the step pays ~38 ms of *surroundings* — the
QK-LayerNorm forward+backward and RoPE loop fusions plus four
[B,T,H,C]<->[B,H,T,C] transposes per layer. This kernel eliminates all of
it: the prologue of every block recomputes LN (f32) and RoPE (as a [C,C]
signed-permutation matmul, bit-identical to rotate-every-two — see
models/layers.py:_rotation_matrix) on the fly, and gradients flow back to
the raw projection output and the LN weights without any intermediate
[B,H,T,C] arrays existing in HBM.

Layout trick: a per-head block of a natural [B,T,H,C] array is (1, rows,
1, C) — illegal on TPU (Mosaic needs the last two block dims to be
(multiple-of-8, multiple-of-128-or-full); measured r2, PERF.md
"transpose-free post-mortem"). Treating the array as [B, T, H*C] and
blocking the LANE dim at 128 is legal — so for C=64 each grid step owns
TWO heads (a 128-lane "head pair"), and for C>=128 exactly one. Blocks
are [rows, 128] regardless of model width, so VMEM stays ~3 MB per step
even at D=4096.

Supported: C a multiple of 128 with any GQA grouping, or C == 64 with MHA
(a C=64 head-pair maps to one 128-lane KV block only when Hkv == H).
Callers fall back to ops.flash otherwise (ops/attention.py dispatch).

LN-weight grads: each backward kernel accumulates per-row partials
``sum_h dnorm * xhat`` into a [B, T, C] output resident across the head
grid dim; the [C] gradient is a cheap XLA reduction outside.

Numerics: LN and softmax in f32; RoPE in f32 before casting to the input
dtype for the MXU matmuls. The reference path (model.py:34-81 equivalent:
LayerNorm in input dtype) differs by bf16 rounding only.
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.compat import tpu_compiler_params
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from midgpt_tpu.models.layers import _rotation_matrix
from midgpt_tpu.ops.flash import _auto_block, _causal_mask_block

Array = jax.Array

_NEG_INF = -1e30


def supported(n_head: int, n_kv_head: int, head_dim: int) -> bool:
    """Shapes this kernel family handles; callers fall back to ops.flash."""
    if n_head % n_kv_head != 0:
        return False  # GQA group size must be integral (matches ops.attention)
    if head_dim % 128 == 0:
        return True
    return head_dim == 64 and n_head == n_kv_head and n_head % 2 == 0


# Per-direction block caps, keyed by heads-per-block. Measured in the full
# 124M train step (B=24, r3): 1024 blocks everywhere + a 64M vmem budget
# -> 236 ms/step; dkv capped to 512 under the default 16M budget -> 267 ms.
# The hpb==2 backward bodies keep two [bq,bk] f32 score/prob/ds sets alive
# (17.03M scoped at 1024 blocks), hence the raised vmem_limit_bytes below.
# hpb=1 (C>=128) caps at 2048: lets T=2048 (llama family) take the
# single-block COMBINED backward — measured 61.0% -> 62.7% MFU on the
# llama rung (r3); the [2048,2048] f32 temps fit the raised vmem budget.
_FWD_CAP = {1: 2048, 2: 1024}
_BWD_DQ_CAP = {1: 2048, 2: 1024}
_BWD_DKV_CAP = {1: 1024, 2: 1024}

# measurement escape hatch (r5, VERDICT r4 Next #2): raise the dkv cap
# from one env var so the llama rung's 2048-block dkv can be timed in a
# single command without editing the table. Kept out of the default path
# until a chip measurement lands — at 2048 the hpb=1 dkv body's
# [2048, 2048] f32 temps brush the raised VMEM budget.
import os as _os

if _os.environ.get("MIDGPT_DKV_CAP"):
    _BWD_DKV_CAP = {k: int(_os.environ["MIDGPT_DKV_CAP"]) for k in _BWD_DKV_CAP}


def _ln_rope(x, w_ref, sin_ref, cos_ref, rot_ref, eps: float):
    """f32 LayerNorm (mean-subtract, weight, no bias) + interleaved RoPE on
    one [rows, C] head slice. Returns (roped f32, xhat f32, rstd f32)."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    centered = x - mean
    var = jnp.mean(jnp.square(centered), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = centered * rstd
    ln = xhat * w_ref[0]
    sin = sin_ref[...]
    cos = cos_ref[...]
    rot = rot_ref[...]
    roped = ln * cos + jax.lax.dot_general(
        ln, rot, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * sin
    return roped, xhat, rstd


def _ln_rope_bwd(d_roped, xhat, rstd, w_ref, sin_ref, cos_ref, rot_ref):
    """VJP through RoPE then LN for one [rows, C] head slice.

    Returns (dx_raw f32, dw_rows f32) where dw_rows = dnorm * xhat (summed
    over heads by the caller, over rows/batch outside the kernel)."""
    sin = sin_ref[...]
    cos = cos_ref[...]
    rot = rot_ref[...]
    # roped = ln*cos + (ln@R)*sin  ->  d_ln = d*cos + (d*sin)@R^T
    d_ln = d_roped * cos + jax.lax.dot_general(
        d_roped * sin, rot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    w = w_ref[0]
    dw_rows = d_ln * xhat  # d/dw of (xhat*w), per row
    dxhat = d_ln * w
    # LN backward: dx = rstd * (dxhat - mean(dxhat) - xhat * mean(dxhat*xhat))
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = rstd * (dxhat - m1 - xhat * m2)
    return dx, dw_rows


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, wq_ref, wk_ref, sq_ref, cq_ref, sk_ref, ck_ref,
    rot_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, bq: int, bk: int, nk: int, hpb: int,
    c: int, eps: float,
):
    iq, ik = pl.program_id(1), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    last_k = iq if causal else nk - 1
    run = (ik <= iq) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q2 = q_ref[0].astype(jnp.float32)  # [bq, hpb*C]
        k2 = k_ref[0].astype(jnp.float32)  # [bk, hpb*C]
        v2 = v_ref[0]  # [bk, hpb*C] input dtype
        for a in range(hpb):
            sl = slice(a * c, (a + 1) * c)
            qh, _, _ = _ln_rope(q2[:, sl], wq_ref, sq_ref, cq_ref, rot_ref, eps)
            kh, _, _ = _ln_rope(k2[:, sl], wk_ref, sk_ref, ck_ref, rot_ref, eps)
            vh = v2[:, sl]
            s = jax.lax.dot_general(
                qh.astype(v2.dtype), kh.astype(v2.dtype),
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            )  # [bq, bk]
            z = s * scale
            if causal:
                z = jnp.where(
                    jnp.logical_or(ik != iq, _causal_mask_block(iq, ik, bq, bk)),
                    z,
                    _NEG_INF,
                )
            m_prev = m_ref[a][:, :1]
            l_prev = l_ref[a][:, :1]
            m_cur = jnp.max(z, axis=1, keepdims=True)
            m_next = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_next)
            p = jnp.exp(z - m_next)
            l_next = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:, sl] = acc_ref[:, sl] * alpha + jax.lax.dot_general(
                p.astype(vh.dtype), vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[a] = jax.lax.broadcast_in_dim(m_next, m_ref[a].shape, (0, 1))
            l_ref[a] = jax.lax.broadcast_in_dim(l_next, l_ref[a].shape, (0, 1))

    @pl.when(ik == last_k)
    def _finalize():
        for a in range(hpb):
            sl = slice(a * c, (a + 1) * c)
            m = m_ref[a][:, :1]
            l = l_ref[a][:, :1]
            o_ref[0, :, sl] = (acc_ref[:, sl] / l).astype(o_ref.dtype)
            lse_ref[0, a] = m + jnp.log(l)


def _fused_forward(q, k, v, wq, wk, sin, cos, *, n_head, n_kv_head, causal,
                   bq, bk, head_dim=None, koff=0, voff=0, eps=1e-6):
    """koff/voff: lane-block offsets of K and V inside their arrays — 0 for
    split q/k/v inputs; the packed-qkv entry passes the SAME [B,T,F] array
    as q, k and v with offsets, so no slice copies ever happen."""
    b, t, _ = q.shape
    c = head_dim if head_dim is not None else q.shape[-1] // n_head
    hpb = 2 if c == 64 else 1
    h2 = n_head // hpb
    groups = n_head // n_kv_head
    bq = _auto_block(t, _FWD_CAP[hpb]) if bq is None else min(bq, t)
    bk = _auto_block(t, _FWD_CAP[hpb]) if bk is None else min(bk, t)
    assert t % bq == 0 and t % bk == 0
    assert not causal or bq == bk
    nq, nk = t // bq, t // bk
    scale = 1.0 / math.sqrt(c)

    rot = jnp.asarray(_rotation_matrix(c, "float32"))
    sin_f = jnp.asarray(sin, jnp.float32)
    cos_f = jnp.asarray(cos, jnp.float32)
    wq2 = wq.astype(jnp.float32).reshape(1, c)
    wk2 = wk.astype(jnp.float32).reshape(1, c)

    lanes = hpb * c  # always a multiple of 128 (or full C)

    # kv head-block index for a q head-block: hpb==2 requires MHA (checked
    # in `supported`), so the pair maps 1:1; hpb==1 maps h -> h // groups.
    kv_of = (lambda g: g) if hpb == 2 else (lambda g: g // groups)
    # trimmed causal grid: steps with ik > iq are compute-skipped (pl.when);
    # clamping their data indices to the diagonal block makes them alias the
    # block already resident, so the skipped steps also trigger NO DMA.
    kclamp = (lambda ik, iq: jnp.minimum(ik, iq)) if causal else (
        lambda ik, iq: ik
    )

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
        hpb=hpb, c=c, eps=eps,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, nq, h2, nk),
        in_specs=[
            pl.BlockSpec((1, bq, lanes), lambda b_, iq, g, ik: (b_, iq, g)),
            pl.BlockSpec(
                (1, bk, lanes),
                lambda b_, iq, g, ik: (b_, kclamp(ik, iq), koff + kv_of(g)),
            ),
            pl.BlockSpec(
                (1, bk, lanes),
                lambda b_, iq, g, ik: (b_, kclamp(ik, iq), voff + kv_of(g)),
            ),
            pl.BlockSpec((1, c), lambda *g: (0, 0)),  # wq
            pl.BlockSpec((1, c), lambda *g: (0, 0)),  # wk
            pl.BlockSpec((bq, c), lambda b_, iq, g, ik: (iq, 0)),  # sin_q
            pl.BlockSpec((bq, c), lambda b_, iq, g, ik: (iq, 0)),  # cos_q
            pl.BlockSpec(
                (bk, c), lambda b_, iq, g, ik: (kclamp(ik, iq), 0)
            ),  # sin_k
            pl.BlockSpec(
                (bk, c), lambda b_, iq, g, ik: (kclamp(ik, iq), 0)
            ),  # cos_k
            pl.BlockSpec((c, c), lambda *g: (0, 0)),  # rot
        ],
        out_specs=[
            pl.BlockSpec((1, bq, lanes), lambda b_, iq, g, ik: (b_, iq, g)),
            pl.BlockSpec((1, hpb, bq, 1), lambda b_, iq, g, ik: (b_, g, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, n_head * c), q.dtype),
            jax.ShapeDtypeStruct((b, n_head, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, lanes), jnp.float32),
            pltpu.VMEM((hpb, bq, 128), jnp.float32),
            pltpu.VMEM((hpb, bq, 128), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"),
            # the hpb==2 bodies carry two [bq,bk] f32 temp sets; the default
            # 16M scoped-VMEM budget rejects 1024 blocks (17.03M measured)
            # while the chip has 128M physical VMEM. 64M keeps 1024 blocks.
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
    )(q, k, v, wq2, wk2, sin_f, cos_f, sin_f, cos_f, rot)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, wq_ref, wk_ref,
    sq_ref, cq_ref, sk_ref, ck_ref, rot_ref,
    dq_ref, dwq_ref, dq_acc, dwq_acc,
    *, scale: float, causal: bool, bq: int, bk: int, nk: int, nh2: int,
    hpb: int, c: int, eps: float,
):
    iq, g, ik = pl.program_id(1), pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(jnp.logical_and(g == 0, ik == 0))
    def _init_dw():
        dwq_acc[:] = jnp.zeros_like(dwq_acc)

    run = (ik <= iq) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q2 = q_ref[0].astype(jnp.float32)
        k2 = k_ref[0].astype(jnp.float32)
        v2 = v_ref[0]
        do2 = do_ref[0].astype(jnp.float32)
        for a in range(hpb):
            sl = slice(a * c, (a + 1) * c)
            qh, _, _ = _ln_rope(q2[:, sl], wq_ref, sq_ref, cq_ref, rot_ref, eps)
            kh, _, _ = _ln_rope(k2[:, sl], wk_ref, sk_ref, ck_ref, rot_ref, eps)
            vh = v2[:, sl]
            lse = lse_ref[0, a]  # [bq, 1]
            delta = delta_ref[0, a]
            s = jax.lax.dot_general(
                qh.astype(v2.dtype), kh.astype(v2.dtype),
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            )
            z = s * scale
            if causal:
                z = jnp.where(
                    jnp.logical_or(ik != iq, _causal_mask_block(iq, ik, bq, bk)),
                    z,
                    _NEG_INF,
                )
            p = jnp.exp(z - lse)
            dp = jax.lax.dot_general(
                do2[:, sl].astype(v2.dtype), vh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta) * scale
            dq_acc[:, sl] += jax.lax.dot_general(
                ds.astype(v2.dtype), kh.astype(v2.dtype),
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            )

    last_k = iq if causal else nk - 1

    @pl.when(ik == last_k)
    def _finalize():
        q2 = q_ref[0].astype(jnp.float32)
        for a in range(hpb):
            sl = slice(a * c, (a + 1) * c)
            _, xhat, rstd = _ln_rope(q2[:, sl], wq_ref, sq_ref, cq_ref, rot_ref, eps)
            dx, dw_rows = _ln_rope_bwd(
                dq_acc[:, sl], xhat, rstd, wq_ref, sq_ref, cq_ref, rot_ref
            )
            dq_ref[0, :, sl] = dx.astype(dq_ref.dtype)
            dwq_acc[:] += dw_rows

    @pl.when(jnp.logical_and(g == nh2 - 1, ik == last_k))
    def _flush_dw():
        dwq_ref[0] = dwq_acc[:]


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, wq_ref, wk_ref,
    sq_ref, cq_ref, sk_ref, ck_ref, rot_ref,
    dk_ref, dv_ref, dwk_ref, dk_acc, dv_acc, dwk_acc,
    *, scale: float, causal: bool, bq: int, bk: int, nq: int, nh2: int,
    hpb: int, c: int, eps: float,
):
    ik, g, iq = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    first_q = ik if causal else 0

    @pl.when(iq == first_q)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(jnp.logical_and(g == 0, iq == first_q))
    def _init_dw():
        dwk_acc[:] = jnp.zeros_like(dwk_acc)

    run = (iq >= ik) if causal else (iq >= 0)

    @pl.when(run)
    def _compute():
        q2 = q_ref[0].astype(jnp.float32)
        k2 = k_ref[0].astype(jnp.float32)
        v2 = v_ref[0]
        do2 = do_ref[0].astype(jnp.float32)
        for a in range(hpb):
            sl = slice(a * c, (a + 1) * c)
            qh, _, _ = _ln_rope(q2[:, sl], wq_ref, sq_ref, cq_ref, rot_ref, eps)
            kh, _, _ = _ln_rope(k2[:, sl], wk_ref, sk_ref, ck_ref, rot_ref, eps)
            vh = v2[:, sl]
            lse = lse_ref[0, a]
            delta = delta_ref[0, a]
            s = jax.lax.dot_general(
                qh.astype(v2.dtype), kh.astype(v2.dtype),
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            )
            z = s * scale
            if causal:
                z = jnp.where(
                    jnp.logical_or(ik != iq, _causal_mask_block(iq, ik, bq, bk)),
                    z,
                    _NEG_INF,
                )
            p = jnp.exp(z - lse)  # [bq, bk]
            doh = do2[:, sl].astype(v2.dtype)
            dv_acc[:, sl] += jax.lax.dot_general(
                p.astype(v2.dtype), doh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                doh, vh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta) * scale  # [bq, bk]
            dk_acc[:, sl] += jax.lax.dot_general(
                ds.astype(v2.dtype), qh.astype(v2.dtype),
                (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            )

    @pl.when(iq == nq - 1)
    def _finalize():
        k2 = k_ref[0].astype(jnp.float32)
        for a in range(hpb):
            sl = slice(a * c, (a + 1) * c)
            _, xhat, rstd = _ln_rope(k2[:, sl], wk_ref, sk_ref, ck_ref, rot_ref, eps)
            dx, dw_rows = _ln_rope_bwd(
                dk_acc[:, sl], xhat, rstd, wk_ref, sk_ref, ck_ref, rot_ref
            )
            dk_ref[0, :, sl] = dx.astype(dk_ref.dtype)
            dv_ref[0, :, sl] = dv_acc[:, sl].astype(dv_ref.dtype)
            dwk_acc[:] += dw_rows

    @pl.when(jnp.logical_and(g == nh2 - 1, iq == nq - 1))
    def _flush_dw():
        dwk_ref[0] = dwk_acc[:]


def _bwd_combined_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, out_ref, wq_ref, wk_ref,
    sq_ref, cq_ref, sk_ref, ck_ref, rot_ref,
    dq_ref, dk_ref, dv_ref, dwq_ref, dwk_ref, dwq_acc, dwk_acc,
    *, scale: float, causal: bool, t: int, nh2: int, hpb: int, c: int,
    eps: float,
):
    """Single-pass backward for the whole-sequence-in-one-block case
    (nq == nk == 1, i.e. T <= the block cap). Computes the score matrix and
    softmax ONCE and emits dq, dk, dv together — 5 block matmuls instead of
    the 7 the two-kernel path pays (QK^T and dO@V^T are otherwise
    recomputed), a 2/7 FLOP cut on the dominant bucket (r3 profile: the
    backward kernels are 68.5 of 236 ms at the 124M shape)."""
    g = pl.program_id(1)

    @pl.when(g == 0)
    def _init_dw():
        dwq_acc[:] = jnp.zeros_like(dwq_acc)
        dwk_acc[:] = jnp.zeros_like(dwk_acc)

    q2 = q_ref[0].astype(jnp.float32)
    k2 = k_ref[0].astype(jnp.float32)
    v2 = v_ref[0]
    do2 = do_ref[0]
    for a in range(hpb):
        sl = slice(a * c, (a + 1) * c)
        qh, q_xhat, q_rstd = _ln_rope(
            q2[:, sl], wq_ref, sq_ref, cq_ref, rot_ref, eps
        )
        kh, k_xhat, k_rstd = _ln_rope(
            k2[:, sl], wk_ref, sk_ref, ck_ref, rot_ref, eps
        )
        vh = v2[:, sl]
        doh = do2[:, sl]
        lse = lse_ref[0, a]  # [t, 1]
        # delta_i = rowsum(dO * O) for this head — computed in-kernel from
        # blocks already resident (saves the ~5 ms XLA mul/reduce pass)
        delta = jnp.sum(
            doh.astype(jnp.float32) * out_ref[0, :, sl].astype(jnp.float32),
            axis=-1,
            keepdims=True,
        )
        qh_c = qh.astype(v2.dtype)
        kh_c = kh.astype(v2.dtype)
        s = jax.lax.dot_general(
            qh_c, kh_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [t, t]
        z = s * scale
        if causal:
            z = jnp.where(_causal_mask_block(0, 0, t, t), z, _NEG_INF)
        p = jnp.exp(z - lse)
        p_c = p.astype(v2.dtype)
        dv_h = jax.lax.dot_general(
            p_c, doh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [t, C]
        dp = jax.lax.dot_general(
            doh, vh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [t, t]
        ds = p * (dp - delta) * scale
        ds_c = ds.astype(v2.dtype)
        dq_rot = jax.lax.dot_general(
            ds_c, kh_c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_rot = jax.lax.dot_general(
            ds_c, qh_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dq_raw, dwq_rows = _ln_rope_bwd(
            dq_rot, q_xhat, q_rstd, wq_ref, sq_ref, cq_ref, rot_ref
        )
        dk_raw, dwk_rows = _ln_rope_bwd(
            dk_rot, k_xhat, k_rstd, wk_ref, sk_ref, ck_ref, rot_ref
        )
        dq_ref[0, :, sl] = dq_raw.astype(dq_ref.dtype)
        dk_ref[0, :, sl] = dk_raw.astype(dk_ref.dtype)
        dv_ref[0, :, sl] = dv_h.astype(dv_ref.dtype)
        dwq_acc[:] += dwq_rows
        dwk_acc[:] += dwk_rows

    @pl.when(g == nh2 - 1)
    def _flush_dw():
        dwq_ref[0] = dwq_acc[:]
        dwk_ref[0] = dwk_acc[:]


def _fused_backward_combined(q, k, v, wq, wk, sin, cos, lse, do, out, *,
                             n_head, n_kv_head, c, hpb, koff, voff, causal,
                             eps=1e-6):
    b, t, _ = q.shape
    h2 = n_head // hpb
    groups = n_head // n_kv_head
    lanes = hpb * c
    scale = 1.0 / math.sqrt(c)

    rot = jnp.asarray(_rotation_matrix(c, "float32"))
    sin_f = jnp.asarray(sin, jnp.float32)
    cos_f = jnp.asarray(cos, jnp.float32)
    wq2 = wq.astype(jnp.float32).reshape(1, c)
    wk2 = wk.astype(jnp.float32).reshape(1, c)

    kv_of = (lambda g: g) if hpb == 2 else (lambda g: g // groups)
    wspec = pl.BlockSpec((1, c), lambda *g: (0, 0))
    rspec = pl.BlockSpec((c, c), lambda *g: (0, 0))
    tspec = pl.BlockSpec((t, c), lambda *g: (0, 0))

    act = lambda off: pl.BlockSpec(  # noqa: E731
        (1, t, lanes), lambda b_, g: (b_, 0, off(g))
    )
    dq, dk_h, dv_h, dwq_rows, dwk_rows = pl.pallas_call(
        functools.partial(
            _bwd_combined_kernel, scale=scale, causal=causal, t=t, nh2=h2,
            hpb=hpb, c=c, eps=eps,
        ),
        grid=(b, h2),
        in_specs=[
            act(lambda g: g),
            act(lambda g: koff + kv_of(g)),
            act(lambda g: voff + kv_of(g)),
            act(lambda g: g),
            pl.BlockSpec((1, hpb, t, 1), lambda b_, g: (b_, g, 0, 0)),
            pl.BlockSpec((1, t, lanes), lambda b_, g: (b_, 0, g)),  # out
            wspec, wspec, tspec, tspec, tspec, tspec, rspec,
        ],
        out_specs=[
            act(lambda g: g),
            act(lambda g: g),
            act(lambda g: g),
            pl.BlockSpec((1, t, c), lambda b_, g: (b_, 0, 0)),
            pl.BlockSpec((1, t, c), lambda b_, g: (b_, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, n_head * c), q.dtype),
            jax.ShapeDtypeStruct((b, t, n_head * c), k.dtype),
            jax.ShapeDtypeStruct((b, t, n_head * c), v.dtype),
            jax.ShapeDtypeStruct((b, t, c), jnp.float32),
            jax.ShapeDtypeStruct((b, t, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((t, c), jnp.float32),
            pltpu.VMEM((t, c), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(q, k, v, do, lse, out, wq2, wk2, sin_f, cos_f, sin_f, cos_f, rot)
    return dq, dk_h, dv_h, dwq_rows, dwk_rows


def _fused_backward(q, k, v, wq, wk, sin, cos, out, lse, do, *, n_head,
                    n_kv_head, causal, bq, bk, head_dim=None, koff=0,
                    voff=0, eps=1e-6):
    b, t, _ = q.shape
    c = head_dim if head_dim is not None else q.shape[-1] // n_head
    hpb = 2 if c == 64 else 1
    h2 = n_head // hpb
    groups = n_head // n_kv_head
    bq_dq = _auto_block(t, _BWD_DQ_CAP[hpb]) if bq is None else min(bq, t)
    bq_kv = _auto_block(t, _BWD_DKV_CAP[hpb]) if bq is None else min(bq, t)
    if causal or bk is None:
        bk_dq, bk_kv = bq_dq, bq_kv  # causal block-skip compares indices 1:1
    else:
        bk_dq = bk_kv = min(bk, t)
    scale = 1.0 / math.sqrt(c)
    lanes = hpb * c

    rot = jnp.asarray(_rotation_matrix(c, "float32"))
    sin_f = jnp.asarray(sin, jnp.float32)
    cos_f = jnp.asarray(cos, jnp.float32)
    wq2 = wq.astype(jnp.float32).reshape(1, c)
    wk2 = wk.astype(jnp.float32).reshape(1, c)

    if bq is None and bk is None and t <= _BWD_DQ_CAP[hpb]:
        # whole sequence in one block: single-pass combined kernel (which
        # also computes delta = rowsum(dO*O) in-kernel)
        dq, dk_h, dv_h, dwq_rows, dwk_rows = _fused_backward_combined(
            q, k, v, wq, wk, sin, cos, lse, do, out, n_head=n_head,
            n_kv_head=n_kv_head, c=c, hpb=hpb, koff=koff, voff=voff,
            causal=causal, eps=eps,
        )
        return _bwd_epilogue(
            dk_h, dv_h, dq, dwq_rows, dwk_rows, b, t, n_head, n_kv_head, c,
            groups, k.dtype, v.dtype, wq.dtype, wk.dtype,
        )

    # delta_i = rowsum(dO * O) per head, [B, H, T, 1] f32 (tiny)
    prod = (do.astype(jnp.float32) * out.astype(jnp.float32)).reshape(
        b, t, n_head, c
    )
    delta = jnp.transpose(prod.sum(-1), (0, 2, 1))[..., None]

    kv_of = (lambda g: g) if hpb == 2 else (lambda g: g // groups)
    # trimmed causal grid (see _fused_forward): skipped steps alias the
    # diagonal block so they cost no DMA
    kcl = (lambda ik, iq: jnp.minimum(ik, iq)) if causal else (
        lambda ik, iq: ik
    )
    qcl = (lambda iq, ik: jnp.maximum(iq, ik)) if causal else (
        lambda iq, ik: iq
    )

    wspec = pl.BlockSpec((1, c), lambda *g: (0, 0))
    rspec = pl.BlockSpec((c, c), lambda *g: (0, 0))

    # ---- dQ + dwq: grid (b, iq, h2, ik) --------------------------------
    bq, bk = bq_dq, bk_dq
    nq, nk = t // bq, t // bk
    sq_q = pl.BlockSpec((bq, c), lambda b_, iq, g, ik: (iq, 0))
    sk_q = pl.BlockSpec((bk, c), lambda b_, iq, g, ik: (kcl(ik, iq), 0))
    dq, dwq_rows = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
            nh2=h2, hpb=hpb, c=c, eps=eps,
        ),
        grid=(b, nq, h2, nk),
        in_specs=[
            pl.BlockSpec((1, bq, lanes), lambda b_, iq, g, ik: (b_, iq, g)),
            pl.BlockSpec(
                (1, bk, lanes),
                lambda b_, iq, g, ik: (b_, kcl(ik, iq), koff + kv_of(g)),
            ),
            pl.BlockSpec(
                (1, bk, lanes),
                lambda b_, iq, g, ik: (b_, kcl(ik, iq), voff + kv_of(g)),
            ),
            pl.BlockSpec((1, bq, lanes), lambda b_, iq, g, ik: (b_, iq, g)),
            pl.BlockSpec((1, hpb, bq, 1), lambda b_, iq, g, ik: (b_, g, iq, 0)),
            pl.BlockSpec((1, hpb, bq, 1), lambda b_, iq, g, ik: (b_, g, iq, 0)),
            wspec, wspec, sq_q, sq_q, sk_q, sk_q, rspec,
        ],
        out_specs=[
            pl.BlockSpec((1, bq, lanes), lambda b_, iq, g, ik: (b_, iq, g)),
            pl.BlockSpec((1, bq, c), lambda b_, iq, g, ik: (b_, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, n_head * c), q.dtype),
            jax.ShapeDtypeStruct((b, t, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, lanes), jnp.float32),
            pltpu.VMEM((bq, c), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"),
            # the hpb==2 bodies carry two [bq,bk] f32 temp sets; the default
            # 16M scoped-VMEM budget rejects 1024 blocks (17.03M measured)
            # while the chip has 128M physical VMEM. 64M keeps 1024 blocks.
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
    )(q, k, v, do, lse, delta, wq2, wk2, sin_f, cos_f, sin_f, cos_f, rot)

    # ---- dK/dV (per q-head) + dwk: grid (b, ik, h2, iq) ----------------
    bq, bk = bq_kv, bk_kv
    nq, nk = t // bq, t // bk
    sq_k = pl.BlockSpec((bq, c), lambda b_, ik, g, iq: (qcl(iq, ik), 0))
    sk_k = pl.BlockSpec((bk, c), lambda b_, ik, g, iq: (ik, 0))
    dk_h, dv_h, dwk_rows = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nq=nq,
            nh2=h2, hpb=hpb, c=c, eps=eps,
        ),
        grid=(b, nk, h2, nq),
        in_specs=[
            pl.BlockSpec(
                (1, bq, lanes), lambda b_, ik, g, iq: (b_, qcl(iq, ik), g)
            ),
            pl.BlockSpec(
                (1, bk, lanes), lambda b_, ik, g, iq: (b_, ik, koff + kv_of(g))
            ),
            pl.BlockSpec(
                (1, bk, lanes), lambda b_, ik, g, iq: (b_, ik, voff + kv_of(g))
            ),
            pl.BlockSpec(
                (1, bq, lanes), lambda b_, ik, g, iq: (b_, qcl(iq, ik), g)
            ),
            pl.BlockSpec(
                (1, hpb, bq, 1), lambda b_, ik, g, iq: (b_, g, qcl(iq, ik), 0)
            ),
            pl.BlockSpec(
                (1, hpb, bq, 1), lambda b_, ik, g, iq: (b_, g, qcl(iq, ik), 0)
            ),
            wspec, wspec, sq_k, sq_k, sk_k, sk_k, rspec,
        ],
        out_specs=[
            pl.BlockSpec((1, bk, lanes), lambda b_, ik, g, iq: (b_, ik, g)),
            pl.BlockSpec((1, bk, lanes), lambda b_, ik, g, iq: (b_, ik, g)),
            pl.BlockSpec((1, bk, c), lambda b_, ik, g, iq: (b_, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, n_head * c), k.dtype),
            jax.ShapeDtypeStruct((b, t, n_head * c), v.dtype),
            jax.ShapeDtypeStruct((b, t, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, lanes), jnp.float32),
            pltpu.VMEM((bk, lanes), jnp.float32),
            pltpu.VMEM((bk, c), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"),
            # the hpb==2 bodies carry two [bq,bk] f32 temp sets; the default
            # 16M scoped-VMEM budget rejects 1024 blocks (17.03M measured)
            # while the chip has 128M physical VMEM. 64M keeps 1024 blocks.
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
    )(q, k, v, do, lse, delta, wq2, wk2, sin_f, cos_f, sin_f, cos_f, rot)

    return _bwd_epilogue(
        dk_h, dv_h, dq, dwq_rows, dwk_rows, b, t, n_head, n_kv_head, c,
        groups, k.dtype, v.dtype, wq.dtype, wk.dtype,
    )


def _bwd_epilogue(dk_h, dv_h, dq, dwq_rows, dwk_rows, b, t, n_head,
                  n_kv_head, c, groups, k_dtype, v_dtype, wq_dtype, wk_dtype):
    if groups > 1:
        # per-q-head dk/dv -> per-kv-head (GQA, hpb==1 only)
        dk = (
            dk_h.reshape(b, t, n_kv_head, groups, c).sum(3).reshape(b, t, -1)
        ).astype(k_dtype)
        dv = (
            dv_h.reshape(b, t, n_kv_head, groups, c).sum(3).reshape(b, t, -1)
        ).astype(v_dtype)
    else:
        dk, dv = dk_h, dv_h
    dwq = dwq_rows.sum((0, 1)).astype(wq_dtype)
    dwk = dwk_rows.sum((0, 1)).astype(wk_dtype)
    return dq, dk, dv, dwq, dwk


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12))
def fused_attention(
    q: Array,  # [B, T, H*C]  raw (pre-LN, pre-RoPE) projections
    k: Array,  # [B, T, Hkv*C]
    v: Array,  # [B, T, Hkv*C]
    wq: Array,  # [C] q-LayerNorm weight
    wk: Array,  # [C] k-LayerNorm weight
    sin: Array,  # [T, C] duplicated-interleaved RoPE table
    cos: Array,  # [T, C]
    n_head: int,
    n_kv_head: int,
    causal: bool = True,
    block_q: tp.Optional[int] = None,
    block_k: tp.Optional[int] = None,
    eps: float = 1e-6,
) -> Array:
    """QK-LayerNorm + RoPE + causal flash attention, projection-natural.

    Returns [B, T, H*C] in the same layout the output projection consumes.
    Differentiable in q, k, v, wq, wk."""
    out, _ = _fused_forward(
        q, k, v, wq, wk, sin, cos, n_head=n_head, n_kv_head=n_kv_head,
        causal=causal, bq=block_q, bk=block_k, eps=eps,
    )
    return out


def _fused_vjp_fwd(q, k, v, wq, wk, sin, cos, n_head, n_kv_head, causal,
                   block_q, block_k, eps):
    out, lse = _fused_forward(
        q, k, v, wq, wk, sin, cos, n_head=n_head, n_kv_head=n_kv_head,
        causal=causal, bq=block_q, bk=block_k, eps=eps,
    )
    return out, (q, k, v, wq, wk, sin, cos, out, lse)


def _fused_vjp_bwd(n_head, n_kv_head, causal, block_q, block_k, eps, res, do):
    q, k, v, wq, wk, sin, cos, out, lse = res
    dq, dk, dv, dwq, dwk = _fused_backward(
        q, k, v, wq, wk, sin, cos, out, lse, do, n_head=n_head,
        n_kv_head=n_kv_head, causal=causal, bq=block_q, bk=block_k, eps=eps,
    )
    return dq, dk, dv, dwq, dwk, None, None


fused_attention.defvjp(_fused_vjp_fwd, _fused_vjp_bwd)


def _packed_geometry(qkv, n_head, n_kv_head):
    f = qkv.shape[-1]
    c = f // (n_head + 2 * n_kv_head)
    hpb = 2 if c == 64 else 1
    lanes = hpb * c
    assert (n_head * c) % lanes == 0 and (n_kv_head * c) % lanes == 0
    koff = (n_head * c) // lanes
    voff = koff + (n_kv_head * c) // lanes
    return c, koff, voff


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def fused_attention_qkv(
    qkv: Array,  # [B, T, (H + 2*Hkv) * C] — raw fused-projection output
    wq: Array,
    wk: Array,
    sin: Array,
    cos: Array,
    n_head: int,
    n_kv_head: int,
    causal: bool = True,
    eps: float = 1e-6,
) -> Array:
    """Packed-qkv entry: the kernels read Q, K and V straight out of the
    projection output via lane-offset block index maps — the q/k/v slice
    copies (forward) and their pad+add VJP (backward, ~16 ms/step of
    dynamic-update-slice fusions at the 124M shape, r3 profile) never
    exist. The backward emits one lane-concat of (dq, dk, dv) instead."""
    c, koff, voff = _packed_geometry(qkv, n_head, n_kv_head)
    out, _ = _fused_forward(
        qkv, qkv, qkv, wq, wk, sin, cos, n_head=n_head, n_kv_head=n_kv_head,
        causal=causal, bq=None, bk=None, head_dim=c, koff=koff, voff=voff,
        eps=eps,
    )
    return out


def _packed_vjp_fwd(qkv, wq, wk, sin, cos, n_head, n_kv_head, causal, eps):
    c, koff, voff = _packed_geometry(qkv, n_head, n_kv_head)
    out, lse = _fused_forward(
        qkv, qkv, qkv, wq, wk, sin, cos, n_head=n_head, n_kv_head=n_kv_head,
        causal=causal, bq=None, bk=None, head_dim=c, koff=koff, voff=voff,
        eps=eps,
    )
    return out, (qkv, wq, wk, sin, cos, out, lse)


def _packed_vjp_bwd(n_head, n_kv_head, causal, eps, res, do):
    qkv, wq, wk, sin, cos, out, lse = res
    c, koff, voff = _packed_geometry(qkv, n_head, n_kv_head)
    dq, dk, dv, dwq, dwk = _fused_backward(
        qkv, qkv, qkv, wq, wk, sin, cos, out, lse, do, n_head=n_head,
        n_kv_head=n_kv_head, causal=causal, bq=None, bk=None, head_dim=c,
        koff=koff, voff=voff, eps=eps,
    )
    dqkv = jnp.concatenate([dq, dk, dv], axis=-1)
    return dqkv, dwq, dwk, None, None


fused_attention_qkv.defvjp(_packed_vjp_fwd, _packed_vjp_bwd)


def fused_attention_reference(q, k, v, wq, wk, sin, cos, n_head, n_kv_head,
                              causal=True, eps=1e-6):
    """jnp oracle: the exact unfused path (LN -> transpose -> RoPE ->
    attention -> transpose back), f32 LN to match the kernel."""
    from midgpt_tpu.ops.attention import naive_attention

    b, t, _ = q.shape
    c = q.shape[-1] // n_head

    def ln(x, w):
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        cent = x32 - mean
        var = jnp.mean(jnp.square(cent), axis=-1, keepdims=True)
        return cent * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)

    rot = jnp.asarray(_rotation_matrix(c, "float32"))

    def rope(x):  # [..., T, C] f32
        return x * cos + (x @ rot) * sin

    qh = ln(q.reshape(b, t, n_head, c), wq)
    kh = ln(k.reshape(b, t, n_kv_head, c), wk)
    vh = v.reshape(b, t, n_kv_head, c)
    qh = jnp.transpose(qh, (0, 2, 1, 3))
    kh = jnp.transpose(kh, (0, 2, 1, 3))
    vh = jnp.transpose(vh, (0, 2, 1, 3))
    qh = rope(qh).astype(q.dtype)
    kh = rope(kh).astype(k.dtype)
    outh = naive_attention(qh, kh, vh, causal=causal)
    return jnp.transpose(outh, (0, 2, 1, 3)).reshape(b, t, n_head * c)
