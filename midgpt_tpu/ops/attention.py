"""Attention ops: reference (naive) implementation + impl dispatch.

The naive path is the correctness oracle, numerically mirroring
/root/reference/src/model.py:71-79: scores computed from bf16 Q/K, causal
mask applied as -inf BEFORE scaling, softmax in float32 with the 1/sqrt(C)
scale folded into the softmax argument, result cast back to the compute
dtype. O(T^2) memory — the Pallas flash kernel (midgpt_tpu.ops.flash)
replaces it on TPU; ring attention (midgpt_tpu.parallel.ring) replaces it
under sequence parallelism.

Layout: [B, H, T, C] (batch, heads, time, head_dim). GQA is supported by
passing fewer KV heads; the naive path broadcasts via reshape (no repeat
materialization).
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.compat import shard_map

Array = jax.Array


def causal_mask(t: int, dtype=jnp.float32) -> Array:
    """[T, T] additive mask: 0 on/below diagonal, -inf above."""
    mask = jnp.tril(jnp.ones((t, t), dtype=jnp.bool_))
    return jnp.where(mask, 0.0, -jnp.inf).astype(dtype)


def naive_attention(
    q: Array,  # [B, H, T, C]
    k: Array,  # [B, Hkv, T, C]
    v: Array,  # [B, Hkv, T, C]
    *,
    causal: bool = True,
    dropout_rate: float = 0.0,
    dropout_key: tp.Optional[Array] = None,
    deterministic: bool = True,
) -> Array:
    """Reference-math attention (parity: model.py:71-79)."""
    b, h, t, c = q.shape
    hkv = k.shape[1]
    assert h % hkv == 0, f"n_head {h} not divisible by n_kv_head {hkv}"
    groups = h // hkv

    with jax.named_scope("naive_attention"):
        qg = q.reshape(b, hkv, groups, t, c)
        # scores in f32 accumulate (MXU native bf16 in / f32 out)
        scores = jnp.einsum(
            "bkgqc,bkjc->bkgqj", qg, k, preferred_element_type=jnp.float32
        )
        if causal:
            scores = scores + causal_mask(t)
        # scale inside the f32 softmax argument (model.py:74-77)
        scale = 1.0 / jnp.sqrt(c).astype(jnp.float32)
        probs = jax.nn.softmax(scores * scale, axis=-1)
        if dropout_rate > 0.0 and not deterministic:
            assert dropout_key is not None
            keep = 1.0 - dropout_rate
            mask = jax.random.bernoulli(dropout_key, p=keep, shape=probs.shape)
            probs = jnp.where(mask, probs / keep, 0.0)
        probs = probs.astype(v.dtype)
        out = jnp.einsum("bkgqj,bkjc->bkgqc", probs, v)
        return out.reshape(b, h, t, c)


def resolve_impl(
    impl: str,
    seq_len: int,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
) -> str:
    """Resolve "auto" to a concrete implementation: flash on TPU when the
    sequence tiles (T % 128 == 0), else naive. Attention dropout no longer
    forces naive — the flash kernels regenerate a counter-based mask
    in-kernel (ops/flash.flash_attention_dropout), so the shakespeare_char
    family (the reference's only dropout config, model.py:78) trains on the
    kernel path too."""
    if impl != "auto":
        return impl
    from midgpt_tpu.utils.platform import is_tpu_backend

    use_flash = (
        is_tpu_backend()
        and seq_len >= 128
        and seq_len % 128 == 0
    )
    return "flash" if use_flash else "naive"


def _flash_sharded(
    q: Array, k: Array, v: Array, causal: bool,
    dropout_rate: float = 0.0, seed: tp.Optional[Array] = None,
):
    """shard_map wrapper for the flash kernel under a live data/TP mesh.

    A bare ``pallas_call`` is an opaque custom call — with batch- or
    head-sharded operands GSPMD gathers the FULL arrays onto every device
    (the r3 trap fixed for the fused kernel in
    models/gpt.py:_fused_attention_sharded; VERDICT r3 Missing #3 flagged
    this, the flash path's copy of the same hole). Runs the kernel on each
    device's local batch/head shard instead. Returns None when no wrapping
    applies (no live mesh, nothing sharded, sequence-sharded T — ring
    territory, or head counts that don't divide tp).

    Pipeline-mesh caveat (ADVICE r4, investigated r5): inside a PP stage
    (manual only over 'pipeline', pipeline.py:168) the bare kernel runs
    un-wrapped. Nesting a second partial shard_map over the data/TP axes
    there is rejected by the Shardy verifier — the flash VJP's lse
    residual picks up a free 'pipeline' dim-sharding ahead of the nested
    manual axes ("manual axes must come before free axes"). Until PP runs
    on a real pod (VERDICT r4: correct-but-unproven), the stage-local
    kernel relies on GSPMD keeping the auto batch axes sharded; audit the
    compiled HLO (tests/test_hlo_collectives.py) before production PP."""
    from midgpt_tpu.parallel.sharding import current_mesh

    mesh = current_mesh()
    if mesh is None:
        return None
    shape = dict(mesh.shape)
    data = shape.get("replica", 1) * shape.get("fsdp", 1)
    tp = shape.get("tensor", 1)
    if data == 1 and tp == 1:
        return None
    if shape.get("sequence", 1) > 1 or shape.get("pipeline", 1) > 1:
        return None
    manual_axes = {
        ax for ax in ("replica", "fsdp", "tensor") if ax in mesh.axis_names
    }
    h, hkv = q.shape[1], k.shape[1]
    if h % tp or hkv % tp or q.shape[0] % data:
        return None
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.ops.flash import flash_attention, flash_attention_dropout

    spec = P(("replica", "fsdp"), "tensor", None, None)
    if dropout_rate > 0.0:
        def body(q_, k_, v_, s_):
            # decorrelate shards: the kernel hashes LOCAL (b, h) indices,
            # so identical seeds would give every shard the same mask
            shard = jnp.zeros((), jnp.int32)
            for ax in ("replica", "fsdp", "tensor"):
                shard = shard * jnp.int32(mesh.shape.get(ax, 1)) + (
                    jax.lax.axis_index(ax)
                    if mesh.shape.get(ax, 1) > 1
                    else jnp.int32(0)
                )
            s_ = s_ + shard * jnp.int32(0x9E3779B1 & 0x7FFFFFFF)
            return flash_attention_dropout(
                q_, k_, v_, s_, dropout_rate, causal
            )

        return shard_map(
            body,
            mesh=mesh,
            in_specs=(spec, spec, spec, P()),
            out_specs=spec,
            axis_names=manual_axes,
        )(q, k, v, seed)
    return shard_map(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=manual_axes,
    )(q, k, v)


def attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    impl: str = "auto",
    causal: bool = True,
    dropout_rate: float = 0.0,
    dropout_key: tp.Optional[Array] = None,
    deterministic: bool = True,
) -> Array:
    """Dispatch between implementations.

    impl:
      auto  - flash on TPU when shapes allow (dropout included: the
              kernels regenerate the mask in-kernel), else naive
      naive - reference O(T^2) math (oracle)
      flash - Pallas blockwise online-softmax kernel
    """
    impl = resolve_impl(impl, q.shape[2], dropout_rate, deterministic)

    if impl == "naive":
        return naive_attention(
            q,
            k,
            v,
            causal=causal,
            dropout_rate=dropout_rate,
            dropout_key=dropout_key,
            deterministic=deterministic,
        )
    if impl == "flash":
        from midgpt_tpu.ops.flash import (
            flash_attention,
            flash_attention_dropout,
        )

        if dropout_rate > 0.0 and not deterministic:
            assert dropout_key is not None, "attention dropout needs a key"
            seed = jax.random.randint(
                dropout_key, (), -(2**31), 2**31 - 1, dtype=jnp.int32
            )
            sharded = _flash_sharded(
                q, k, v, causal, dropout_rate=dropout_rate, seed=seed
            )
            if sharded is not None:
                return sharded
            return flash_attention_dropout(
                q, k, v, seed, dropout_rate, causal
            )
        sharded = _flash_sharded(q, k, v, causal)
        if sharded is not None:
            return sharded
        return flash_attention(q, k, v, causal=causal)
    if impl == "ring":
        raise ValueError(
            "ring attention runs under shard_map; use "
            "midgpt_tpu.parallel.ring.ring_attention via the training step, "
            "not the per-device dispatcher"
        )
    raise ValueError(f"unknown attention impl {impl!r}")
