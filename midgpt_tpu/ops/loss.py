"""Chunked softmax cross-entropy: the lm-head projection and the loss
computed T-chunk by T-chunk, so the full ``[B, T, V]`` float32 logits
tensor never materializes in HBM.

The reference (and our dense path) computes all logits, casts to f32, and
calls softmax xent (/root/reference/src/train.py:76-77) — at B=16, T=1024,
V=50304 that is a 3.3 GB f32 intermediate, and it is what makes
remat='none' infeasible at the 124M bench config. Here a ``lax.scan`` over
T-chunks computes ``[B, tc, V]`` logits per step inside a
``jax.checkpoint`` body (recomputed in the backward), reducing peak loss
memory by T/tc while keeping the math bit-identical in structure: logits
in f32, logsumexp-minus-target-logit, mean over all tokens.

Sharding note: the scan iterates over the T axis, so this path requires
the sequence axis to be UNSHARDED (callers gate on mesh['sequence'] == 1;
under sequence parallelism per-step slicing of a sharded axis would insert
collectives every chunk). Batch and vocab sharding compose fine — the
per-chunk matmul + logsumexp reduce over a tensor-sharded V become a
partial matmul + psum under GSPMD exactly like the dense path.
"""

from __future__ import annotations

import functools
import typing as tp

import jax
import jax.numpy as jnp

Array = jax.Array


def chunked_softmax_xent(
    h: Array,  # [B, T, D] final hidden states (compute dtype)
    head_w: Array,  # [D, V] lm-head weight (compute dtype)
    targets: Array,  # [B, T] int
    *,
    chunk_t: int = 128,
    unroll: tp.Union[bool, int] = False,
) -> Array:
    """Mean cross-entropy over all B*T tokens, identical math to
    ``softmax_cross_entropy_with_integer_labels(h @ head_w -> f32, y)``.

    ``unroll`` is forwarded to the chunk ``lax.scan``: profiling the
    flagship shape (PERF.md r2) showed the rolled loop's while overhead —
    the carried [D, V] dW buffer re-read/written every backward iteration
    and the serialized chunk matmuls — costs more than the [B, tc, V]
    working set saves; unrolling keeps the memory bound (each chunk's
    logits are still checkpointed) while letting XLA overlap chunks."""
    b, t, d = h.shape
    assert t % chunk_t == 0, f"T={t} not divisible by chunk_t={chunk_t}"
    nc = t // chunk_t
    # [nc, B, tc, ...] so scan slices the leading (iteration) axis
    h_c = jnp.moveaxis(h.reshape(b, nc, chunk_t, d), 1, 0)
    y_c = jnp.moveaxis(targets.reshape(b, nc, chunk_t), 1, 0)

    from midgpt_tpu.parallel.sharding import current_mesh

    mesh = current_mesh()
    vocab_sharded = mesh is not None and dict(mesh.shape).get("tensor", 1) > 1

    @jax.checkpoint
    def body(acc, xs):
        h_i, y_i = xs  # [B, tc, D], [B, tc]
        z = (h_i @ head_w).astype(jnp.float32)  # [B, tc, V]
        lse = jax.scipy.special.logsumexp(z, axis=-1)  # [B, tc]
        if vocab_sharded:
            # target logit via a masked reduce, not take_along_axis: a
            # gather whose indexed dim is tensor-sharded would force SPMD
            # involuntary rematerialization (same reason as
            # models.gpt.embed_tokens)
            vocab_ids = jnp.arange(z.shape[-1])[None, None, :]
            z_y = jnp.sum(
                jnp.where(vocab_ids == y_i[..., None], z, 0.0), axis=-1
            )
        else:
            # unsharded vocab: a plain gather reads one element per token
            # instead of re-reading the whole [B, tc, V] block
            z_y = jnp.take_along_axis(z, y_i[..., None], axis=-1)[..., 0]
        return acc + jnp.sum(lse - z_y), None

    total, _ = jax.lax.scan(
        body, jnp.zeros((), jnp.float32), (h_c, y_c), unroll=unroll
    )
    return total / (b * t)
