"""Chunked softmax cross-entropy: the lm-head projection and the loss
computed T-chunk by T-chunk, so the full ``[B, T, V]`` float32 logits
tensor never materializes in HBM.

The reference (and our dense path) computes all logits, casts to f32, and
calls softmax xent (/root/reference/src/train.py:76-77) — at B=16, T=1024,
V=50304 that is a 3.3 GB f32 intermediate, and it is what makes
remat='none' infeasible at the 124M bench config. Here a ``lax.scan`` over
T-chunks computes the chunk's logits per step inside a ``jax.checkpoint``
body (recomputed in the backward), reducing peak loss memory by T/tc while
keeping the math bit-identical in structure: logits in f32,
logsumexp-minus-target-logit, mean over all tokens.

Sharding: batch and vocab sharding compose directly — the per-chunk
matmul + logsumexp reduce over a tensor-sharded V become a partial matmul
+ psum under GSPMD exactly like the dense path. A SHARDED sequence axis
(ring attention's long-context configs — where the [B, T, V] saving
matters most) composes too (VERDICT r3 Missing #4: the old gate fell back
to dense [B, T, V] logits exactly when T was largest): T is reshaped to
[S, T/S] with the sharded part OUTER, and the scan chunks the INNER,
unsharded part — every device scans its local tokens in lockstep, purely
under GSPMD. (A partial-manual shard_map variant hit an XLA CPU
compiler crash on this pin — bf16 boundary psums lower to an all-reduce
whose region root is a sharding_constraint, which AllReducePromotion
cannot clone; the reshape form never creates manual collectives.)
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

Array = jax.Array


def chunked_softmax_xent(
    h: Array,  # [B, T, D] final hidden states (compute dtype)
    head_w: Array,  # [D, V] lm-head weight (compute dtype)
    targets: Array,  # [B, T] int
    *,
    chunk_t: int = 128,
    unroll: tp.Union[bool, int] = False,
) -> Array:
    """Mean cross-entropy over all B*T tokens, identical math to
    ``softmax_cross_entropy_with_integer_labels(h @ head_w -> f32, y)``.

    ``unroll`` is forwarded to the chunk ``lax.scan``: profiling the
    flagship shape (PERF.md r2) showed the rolled loop's while overhead —
    the carried [D, V] dW buffer re-read/written every backward iteration
    and the serialized chunk matmuls — costs more than the per-chunk
    logits working set saves; unrolling keeps the memory bound (each
    chunk's logits are still checkpointed) while letting XLA overlap
    chunks."""
    from midgpt_tpu.parallel.sharding import current_mesh

    b, t, d = h.shape
    mesh = current_mesh()
    shape = dict(mesh.shape) if mesh is not None else {}
    vocab_sharded = shape.get("tensor", 1) > 1
    sp = shape.get("sequence", 1)

    t_local = t // sp
    if sp > 1:
        # per-shard chunk: keep the configured size when it divides the
        # local T, else the LARGEST divisor of T/S below it (gcd could
        # silently collapse to near-1-token chunks and serialize the scan)
        ct = min(chunk_t, t_local)
        while t_local % ct:
            ct -= 1
        if ct != chunk_t:
            import warnings

            warnings.warn(
                f"loss_chunk={chunk_t} does not divide the per-shard "
                f"sequence T/S={t_local}; using chunk {ct}",
                stacklevel=2,
            )
    else:
        assert t % chunk_t == 0, f"T={t} not divisible by chunk_t={chunk_t}"
        ct = chunk_t
    nc = t_local // ct

    # [B, T, D] -> [nc, B, S, ct, D]: the sharded part of T (if any) stays
    # OUTER where the sharding propagates; the scan slices the inner,
    # unsharded chunk axis — no per-step collectives, no manual psum
    h_c = jnp.moveaxis(h.reshape(b, sp, nc, ct, d), 2, 0)
    y_c = jnp.moveaxis(targets.reshape(b, sp, nc, ct), 2, 0)
    if sp > 1:
        from jax.sharding import PartitionSpec as P

        spec = P(None, ("replica", "fsdp"), "sequence", None, None)
        h_c = jax.lax.with_sharding_constraint(
            h_c, jax.sharding.NamedSharding(mesh, spec)
        )
        y_c = jax.lax.with_sharding_constraint(
            y_c, jax.sharding.NamedSharding(mesh, P(*spec[:-1]))
        )

    @jax.checkpoint
    def body(acc, xs):
        h_i, y_i = xs  # [B, S, ct, D], [B, S, ct]
        z = (h_i @ head_w).astype(jnp.float32)  # [B, S, ct, V]
        lse = jax.scipy.special.logsumexp(z, axis=-1)
        if vocab_sharded:
            # target logit via a masked reduce, not take_along_axis: a
            # gather whose indexed dim is tensor-sharded would force SPMD
            # involuntary rematerialization (same reason as
            # models.gpt.embed_tokens)
            vocab_ids = jnp.arange(z.shape[-1])
            z_y = jnp.sum(
                jnp.where(vocab_ids == y_i[..., None], z, 0.0), axis=-1
            )
        else:
            # unsharded vocab: a plain gather reads one element per token
            # instead of re-reading the whole per-chunk logits block
            z_y = jnp.take_along_axis(z, y_i[..., None], axis=-1)[..., 0]
        return acc + jnp.sum(lse - z_y), None

    total, _ = jax.lax.scan(
        body, jnp.zeros((), jnp.float32), (h_c, y_c), unroll=unroll
    )
    return total / (b * t)
