from midgpt_tpu.ops.attention import attention, causal_mask, naive_attention

__all__ = ["attention", "causal_mask", "naive_attention"]
