"""Async streaming front door over the serving stack (ROADMAP item 3).

``ServingEngine``/``ServingCluster`` are libraries driven by a
synchronous loop: ``submit()`` then ``step()`` until drained, tokens
harvested in bulk at the end. Millions of users need the four things
that loop cannot give them — and this module adds exactly those, WITHOUT
touching a single compiled program:

1. **Per-request async token streams** (:class:`TokenStream`): tokens
   surface to ``async for`` consumers at every window harvest — the
   same cadence the engine's telemetry documents honestly (K tokens per
   fused dispatch), with no added device syncs: the front door reads
   the host-side ``Request.tokens`` progress the scheduler already
   holds, through the engines' ``lookup()`` seam.

2. **Cancellation-safe teardown**: ``TokenStream.cancel()`` reclaims
   the slot and releases the pages at the next scheduler boundary (the
   only consistent point of a library-driven engine — there is no
   mid-dispatch host state to tear). Pages retire COLD through the same
   path a finish takes, so prefix-cache hits survive the cancellation;
   the speculative write watermark already guarantees no stale draft
   K/V is resident, and COW pins unwind through the normal slot
   release — the allocator identity (``free + held + cached +
   quarantined == num_pages``) and the PrefixIndex invariants hold
   after every step, property-checked when ``check_invariants=True``.

3. **Priorities + deadlines with backpressure**: ``submit(priority=,
   deadline_s=)`` feeds the engine's aging admission policy (higher
   priority first, starvation-proof aging, deadline-expired work shed
   BEFORE dispatch — serving.engine), and the bounded-queue overload
   outcomes of PR 10 map onto awaitable backpressure: a ``defer``
   outcome suspends the submitting coroutine until the queue drains
   (retrying at each scheduler boundary), a ``shed`` outcome raises the
   typed :class:`~midgpt_tpu.serving.faults.AdmissionRejected`
   immediately.

4. **A determinism contract**: scheduler decisions stay keyed to engine
   steps, deadlines read the engine's injectable clock
   (:class:`VirtualClock` for tests), and the front door adds NO
   decision state of its own — so token streams through the front door
   are bit-identical to the synchronous loop given the same admission
   order, chaos plans replay event-sequence-identically, and telemetry
   stays provably inert. :meth:`AsyncFrontDoor.pump` is the
   deterministic manual-drive seam those tests pin; the background
   driver (``async with fd:``) runs the very same round with the
   blocking ``step()`` moved to a worker thread so the event loop stays
   responsive mid-dispatch.

The engine is NOT thread-safe, so all engine access is serialized:
submissions and cancellations that arrive while a step is in flight
wait for the step boundary (an ``asyncio`` event the round flips);
everything else runs inline on the event loop.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import typing as tp

import numpy as np

from midgpt_tpu.serving.cluster import ServingCluster
from midgpt_tpu.serving.engine import Request, ServingEngine
from midgpt_tpu.serving.faults import (
    Cancelled,
    DeadlineExceeded,
    PoolOverloaded,
)

__all__ = ["AsyncFrontDoor", "TokenStream", "VirtualClock"]

Backend = tp.Union[ServingEngine, ServingCluster]

_DONE = object()  # stream terminator sentinel


class VirtualClock:
    """An injectable, deterministically-advancing clock: pass one
    instance as every engine's ``clock=`` AND read/advance it from the
    test driver, and all deadline decisions become pure functions of
    the drive schedule — the replay contract's time base. ``tick``
    optionally auto-advances per read (still deterministic: the
    engine's read count is replay-deterministic); the default 0.0
    advances only via :meth:`advance`."""

    def __init__(self, start: float = 0.0, tick: float = 0.0):
        assert tick >= 0.0, tick
        self.t = float(start)
        self.tick = float(tick)

    def __call__(self) -> float:
        t = self.t
        self.t += self.tick
        return t

    def advance(self, dt: float) -> float:
        assert dt >= 0.0, dt
        self.t += dt
        return self.t


@dataclasses.dataclass
class _Submission:
    """One accepted front-door submission's bookkeeping."""

    rid: int
    stream: "TokenStream"


class TokenStream:
    """One request's async token stream. Iterate to receive tokens as
    the engine harvests them (``async for tok in stream``); iteration
    ends when the request reaches ANY terminal outcome — read
    ``stream.outcome`` (``"finished" | "cancelled" | "expired" |
    "error"``) to tell which, or await :meth:`result` for the typed
    form (returns the full token list, raises
    :class:`~midgpt_tpu.serving.faults.Cancelled` /
    :class:`~midgpt_tpu.serving.faults.DeadlineExceeded`).

    ``tokens`` accumulates everything streamed so far — after a COLD
    cluster failover the engine recomputes a re-served request from
    scratch, and the stream's cursor deduplicates the regrown prefix
    (bit-identical by the determinism contract), so consumers see every
    token exactly once."""

    def __init__(self, fd: "AsyncFrontDoor", rid: int, *, priority: int,
                 deadline_s: tp.Optional[float]):
        self._fd = fd
        self.rid = rid
        self.priority = priority
        self.deadline_s = deadline_s
        self.tokens: tp.List[int] = []
        self.outcome: tp.Optional[str] = None
        self.request: tp.Optional[Request] = None  # set at terminal
        self._cursor = 0  # engine-side tokens already streamed
        self._q: asyncio.Queue = asyncio.Queue()
        self._buf: tp.Deque[int] = collections.deque()
        self._ended = False

    def cancel(self) -> None:
        """Request teardown: slot reclaim + page release at the next
        scheduler boundary. Idempotent; safe any time before the stream
        ends."""
        if self.outcome is None:
            self._fd.cancel(self.rid)

    def __aiter__(self) -> "TokenStream":
        return self

    async def __anext__(self) -> int:
        while not self._buf:
            if self._ended:
                raise StopAsyncIteration
            item = await self._q.get()
            if item is _DONE:
                self._ended = True
                raise StopAsyncIteration
            self._buf.extend(item)
        return self._buf.popleft()

    async def result(self) -> tp.List[int]:
        """Drain the stream and return the complete token list; raises
        the typed outcome for a cancelled/expired/errored request."""
        async for _ in self:
            pass
        if self.outcome == "cancelled":
            raise Cancelled(self.rid, len(self.tokens))
        if self.outcome == "expired":
            raise DeadlineExceeded(self.rid, len(self.tokens))
        if self.outcome == "error":
            exc = self._fd.error
            raise exc if exc is not None else RuntimeError(
                f"request {self.rid} ended without an outcome"
            )
        return list(self.tokens)

    # driver-side (event-loop thread only)

    def _push(self, new: tp.Sequence[int]) -> None:
        self.tokens.extend(int(t) for t in new)
        self._q.put_nowait([int(t) for t in new])

    def _finish(self, outcome: str, req: tp.Optional[Request]) -> None:
        self.outcome = outcome
        self.request = req
        self._q.put_nowait(_DONE)


class AsyncFrontDoor:
    """The asyncio front door over one :class:`ServingEngine` or
    :class:`ServingCluster`.

    Two drive modes, one round:

    - **Background driver** (``async with AsyncFrontDoor(backend) as
      fd:`` or ``fd.start()``): a task loops cancels → step → harvest,
      with the blocking ``step()`` in a worker thread
      (``asyncio.to_thread``) so submissions/cancellations/consumers
      stay responsive during a long dispatch. This is the serving mode
      — bench_serving's trace-replay harness drives it.
    - **Manual pump** (never call ``start()``; ``await fd.pump()`` per
      round): fully deterministic — single-task, engine stepped inline,
      scheduler decisions a pure function of the pump/submit/cancel
      schedule. The bit-identity and replay acceptance tests drive
      this seam.

    Submissions run INLINE on the event loop whenever no step is in
    flight (deterministic admission order = call order); otherwise they
    wait for the step boundary. ``PoolOverloaded`` (the PR 10 defer
    outcome) suspends the submitter until a later boundary admits it —
    awaitable retry-after backpressure; ``AdmissionRejected`` (shed and
    the permanent reasons) raises through.

    ``check_invariants=True`` re-checks the page-allocator identity and
    the PrefixIndex structural/refcount invariants on every live engine
    after EVERY scheduler round — the cancellation-safety property
    tests run with this armed.
    """

    def __init__(
        self,
        backend: Backend,
        *,
        backpressure: str = "wait",
        check_invariants: bool = False,
    ):
        assert backpressure in ("wait", "raise"), backpressure
        self.backend = backend
        self.backpressure = backpressure
        self.check_invariants = check_invariants
        self.steps = 0
        self.error: tp.Optional[BaseException] = None
        self._streams: tp.Dict[int, TokenStream] = {}
        self._cancels: tp.Deque[int] = collections.deque()
        self._stepping = False
        self._closed = False
        self._task: tp.Optional[asyncio.Task] = None
        self._boundary: asyncio.Event = asyncio.Event()
        self._wake: asyncio.Event = asyncio.Event()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Start the background driver task (idempotent)."""
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._drive(), name="serving-frontdoor"
            )

    async def __aenter__(self) -> "AsyncFrontDoor":
        self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def close(self) -> None:
        """Stop the driver after the in-flight round settles. Live
        streams are NOT cancelled — call :meth:`drain` first (or cancel
        them) if the work should complete."""
        self._closed = True
        self._wake.set()
        if self._task is not None:
            await self._task
            self._task = None
        # wake any submitter parked on a boundary (backpressure or
        # mid-step wait): it re-checks closed/error and raises instead
        # of hanging on an event no round will ever flip again
        self._flip_boundary()

    # -- submission ---------------------------------------------------------

    def _engines(self) -> tp.List[ServingEngine]:
        if isinstance(self.backend, ServingCluster):
            cl = self.backend
            return [cl.engines[i] for i in cl._alive()]
        return [self.backend]

    @property
    def live_streams(self) -> int:
        return len(self._streams)

    async def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        *,
        eos_id: tp.Optional[int] = None,
        seed: int = 0,
        priority: int = 0,
        deadline_s: tp.Optional[float] = None,
        deadline: tp.Optional[float] = None,
        backpressure: tp.Optional[str] = None,
    ) -> TokenStream:
        """Admit a request and return its :class:`TokenStream`.

        Runs inline when no engine step is in flight (admission order =
        call order — the determinism contract); suspends until the step
        boundary otherwise. On a full bounded queue: ``defer`` policy →
        this coroutine WAITS (retrying each boundary) — the typed
        backpressure of PR 10 as suspension instead of an exception;
        ``shed`` policy / permanent reasons →
        :class:`~midgpt_tpu.serving.faults.AdmissionRejected` raises
        through (``backpressure="raise"`` makes defer outcomes raise
        too, carrying ``reason="queue_full"``).

        ``deadline`` is the ABSOLUTE engine-clock form (overrides
        ``deadline_s``) — what an SLO anchored at ARRIVAL needs when
        backpressure can delay the actual admission (the trace-replay
        bench computes arrival + SLO up front, so time spent waiting in
        this coroutine counts against the deadline)."""
        bp = backpressure if backpressure is not None else self.backpressure
        assert bp in ("wait", "raise"), bp
        while True:
            if self.error is not None:
                raise self.error
            if self._closed:
                raise RuntimeError("front door is closed")
            if self._stepping:
                await self._next_boundary()
                continue
            try:
                rid = self.backend.submit(
                    prompt, max_new_tokens, eos_id=eos_id, seed=seed,
                    priority=priority, deadline_s=deadline_s,
                    deadline=deadline,
                )
            except PoolOverloaded:
                if bp == "raise":
                    raise
                # awaitable retry-after: the queue is full NOW; the
                # next scheduler boundary is the earliest it can drain
                await self._next_boundary()
                continue
            stream = TokenStream(
                self, rid, priority=priority, deadline_s=deadline_s
            )
            self._streams[rid] = stream
            self._wake.set()
            return stream

    def cancel(self, rid: int) -> None:
        """Queue a cancellation for the next scheduler boundary (the
        engine is mid-step on another thread exactly when immediacy is
        impossible anyway; at every other moment the boundary is now)."""
        self._cancels.append(rid)
        self._wake.set()
        if not self._stepping and self._task is None:
            # manual mode, engine idle: apply right away so a cancel of
            # a queued request needs no pump to land
            self._process_cancels()
            self._harvest()

    # -- the scheduler round ------------------------------------------------

    def _process_cancels(self) -> None:
        while self._cancels:
            rid = self._cancels.popleft()
            if rid in self._streams:
                self.backend.cancel(rid)

    def _flip_boundary(self) -> None:
        ev, self._boundary = self._boundary, asyncio.Event()
        ev.set()

    async def _next_boundary(self) -> None:
        await self._boundary.wait()

    def _check(self) -> None:
        for e in self._engines():
            e.alloc.check()
            if e.index is not None:
                e.index.check(e.alloc)

    def _harvest(self) -> None:
        """Push newly-emitted tokens into every live stream and resolve
        terminal outcomes — host-side reads only, through the backends'
        ``lookup`` seam."""
        be = self.backend
        done: tp.List[int] = []
        for rid, stream in self._streams.items():
            req = be.lookup(rid)
            if req is not None and len(req.tokens) > stream._cursor:
                stream._push(req.tokens[stream._cursor:])
                stream._cursor = len(req.tokens)
            if req is not None and req.outcome != "pending":
                stream._finish(req.outcome, req)
                done.append(rid)
            elif req is None and self.error is not None:
                stream._finish("error", None)
                done.append(rid)
        for rid in done:
            del self._streams[rid]

    async def pump(self) -> bool:
        """ONE deterministic scheduler round: pending cancellations →
        one backend step (inline) → harvest (+ optional invariant
        check) → boundary flip (wakes backpressured submitters).
        Returns True while streams or backend work remain. This is the
        manual-drive seam the determinism/replay tests pin; never mix
        it with a running background driver."""
        assert self._task is None, "pump() is the manual-drive seam; " \
            "the background driver is already running this round"
        await self._round(threaded=False)
        return bool(self._streams) or self.backend.has_work

    async def _round(self, threaded: bool) -> None:
        self._process_cancels()
        if self.backend.has_work:
            self._stepping = True
            try:
                if threaded:
                    await asyncio.to_thread(self.backend.step)
                else:
                    self.backend.step()
            except BaseException as exc:  # noqa: BLE001 — typed faults
                # (e.g. ClusterUnavailable) must terminate the streams,
                # not strand their consumers; re-raised from result()
                self.error = exc
            finally:
                self._stepping = False
            self.steps += 1
        self._harvest()
        if self.error is not None:
            for rid, stream in list(self._streams.items()):
                stream._finish("error", None)
                del self._streams[rid]
        if self.check_invariants:
            self._check()
        self._flip_boundary()

    async def _drive(self) -> None:
        while not self._closed:
            if self.backend.has_work or self._cancels:
                await self._round(threaded=True)
                if self.error is not None:
                    return
                # yield so same-loop consumers/submitters run between
                # rounds even when the backend stays busy
                await asyncio.sleep(0)
            else:
                self._harvest()  # e.g. cancels applied while idle
                self._wake.clear()
                if self._closed:
                    return
                await self._wake.wait()

    # -- draining + reporting ----------------------------------------------

    async def drain(self) -> None:
        """Await until every accepted stream is terminal (driver mode:
        sleeps on boundaries; manual mode: pumps)."""
        while self._streams or self.backend.has_work:
            if self.error is not None and not self._streams:
                return
            if self._task is not None:
                await self._next_boundary()
            else:
                await self.pump()

    def stats(self) -> tp.Dict[str, tp.Any]:
        """The backend's stats plus the front door's own counters."""
        st = dict(self.backend.stats())
        st["frontdoor_steps"] = self.steps
        st["frontdoor_live_streams"] = len(self._streams)
        return st
