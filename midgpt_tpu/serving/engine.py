"""Continuous-batching serving engine: paged KV pool + fused K-step decode.

The fixed-batch sampler (midgpt_tpu.sampling.generate) holds one ring
cache sized per request batch and dispatches every decode step; under real
traffic that leaves decode slots idle whenever requests finish early and
pays the full per-dispatch latency (+25-50 ms/launch on a bad relay day,
PERF.md r5) once per generated token. This engine replaces both:

- **Paged KV** (serving.paged): requests own page lists in a shared pool,
  so admission is a page allocation, eviction a free — no cache reshapes.
- **Continuous batching**: a host-side scheduler admits queued requests
  into free decode slots at every window boundary, interleaves their
  prefills with decode, and evicts (re-queues with progress kept) under
  page pressure — slots stay full under mixed traffic.
- **Fused multi-token dispatch** (the PR 2 design, ported to decode): one
  jitted, state-donating ``lax.scan`` runs K whole-model decode steps —
  all layers, sampling, and the bulk page flush — per XLA launch.
  Per-slot EOS/length masks are carried IN-SCAN: finished requests pad
  harmlessly (writes dropped, emissions masked) until the next host-side
  swap boundary. Dispatches per generated token drop from 1 per token to
  1/K per active batch.

Determinism contract: per-request sampling keys derive from
``fold_in(fold_in(key, request_seed), tokens_emitted_so_far)`` — the token
stream of a request is a function of the request alone, independent of
which slot it lands in, the window size K, batch composition, and any
mid-run eviction/re-admission.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.models.gpt import GPT, decode_step_paged
from midgpt_tpu.serving.paged import (
    PageAllocator,
    PagedKVPool,
    flush_recent,
    pages_needed,
    write_prompt_pages,
)

Array = jax.Array


# ---------------------------------------------------------------------------
# Compiled programs
# ---------------------------------------------------------------------------


def make_decode_window(
    model: GPT,
    *,
    slots: int,
    window: int,
    pmax: int,
    rope_len: int,
    pad_id: int = 0,
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
    mesh=None,
):
    """The fused K-step decode program: ONE jitted, pool/logits-donating
    ``lax.scan`` over ``window`` whole-model decode steps.

    Per scan step: sample each slot's next token from the carried logits,
    mark slots that just hit EOS/length done, run the paged decode step
    (models.gpt.decode_step_paged) for all slots SIMD-style, and collect
    (token, emit-mask, write-mask) as scan outputs. After the scan the
    window's recent K/V rows flush into the pages in one bulk scatter —
    still inside the same compiled program, so steady-state decode is
    exactly one XLA dispatch per K generated tokens per active batch.

    Finished/empty slots ride along masked: they sample pad, their page
    writes route to the drop sentinel, and their emissions are masked out
    host-side — the scan shape never depends on traffic.
    """
    from midgpt_tpu.parallel.sharding import axis_rules
    from midgpt_tpu.sampling import _sample_token

    cfg = model.config
    rshape = (cfg.n_layer, slots, cfg.kv_heads, window, cfg.head_dim)

    def window_fn(
        pool: PagedKVPool,  # DONATED
        logits: Array,  # [S, V] f32 — per-slot next-token logits; DONATED
        bt: Array,  # [S, Pmax] int32 block tables
        pooled_len: Array,  # [S] int32 — tokens resident in the pool
        done: Array,  # [S] bool — finished or empty slot
        emitted: Array,  # [S] int32 — tokens emitted so far per request
        budget: Array,  # [S] int32 — max_new_tokens per request
        eos: Array,  # [S] int32 — per-request EOS id (-1 = none)
        seeds: Array,  # [S] int32 — per-request sampling seed
        key: Array,  # base PRNG key (engine-constant)
    ):
        assert bt.shape == (slots, pmax), (
            f"block table {bt.shape} != declared geometry ({slots}, {pmax})"
        )
        with axis_rules(mesh):
            rk = jnp.zeros(rshape, pool.k.dtype)
            rv = jnp.zeros(rshape, pool.k.dtype)

            def sample(lg, em):
                if temperature == 0.0:
                    return jnp.argmax(lg, axis=-1).astype(jnp.int32)
                # per-request key stream: (seed, emitted-count) — slot-,
                # window-, and eviction-invariant
                ks = jax.vmap(
                    lambda sd, ti: jax.random.fold_in(
                        jax.random.fold_in(key, sd), ti
                    )
                )(seeds, em)
                return jax.vmap(
                    lambda l1, k1: _sample_token(
                        l1[None], k1, temperature, top_k
                    )[0]
                )(lg, ks)

            def body(carry, r):
                logits, rk, rv, done, emitted = carry
                pre_done = done
                tok = sample(logits, emitted)
                tok = jnp.where(pre_done, jnp.int32(pad_id), tok)
                emitted = emitted + (~pre_done).astype(jnp.int32)
                hit_eos = (~pre_done) & (tok == eos)
                hit_len = (~pre_done) & (emitted >= budget)
                done = pre_done | hit_eos | hit_len
                # the just-sampled token is this step's model input; its
                # K/V row is only needed if a real token can follow it
                write_valid = ~done
                pos = pooled_len + r  # per-slot absolute position
                new_logits, rk, rv = decode_step_paged(
                    model, tok, pos, pool.k, pool.v, bt, rk, rv, r,
                    pooled_len, rope_len,
                )
                # the carry is f32 regardless of compute dtype (an exact
                # widening — sampling sees the same values either way)
                new_logits = new_logits.astype(logits.dtype)
                return (
                    (new_logits, rk, rv, done, emitted),
                    (tok, ~pre_done, write_valid),
                )

            (logits, rk, rv, done, emitted), (toks, emit, wvalid) = (
                jax.lax.scan(
                    body,
                    (logits, rk, rv, done, emitted),
                    jnp.arange(window, dtype=jnp.int32),
                )
            )
            pool = flush_recent(
                pool, rk, rv, bt, pooled_len, jnp.transpose(wvalid)
            )
            new_len = pooled_len + jnp.sum(wvalid.astype(jnp.int32), axis=0)
        return pool, logits, toks, emit, done, new_len, emitted

    return jax.jit(window_fn, donate_argnums=(0, 1))


def make_prefill_program(model: GPT, *, prompt_len: int, mesh=None):
    """A prefill program for one padded prompt length: one batched forward
    collecting per-layer K/V (models.gpt prefill path), a bulk page write,
    and the admitted slot's logits row updated in place. One compile per
    padded length — the engine buckets prompts to powers-of-two page
    counts to bound recompiles."""
    from midgpt_tpu.parallel.sharding import axis_rules

    cfg = model.config
    assert prompt_len <= cfg.block_size, (prompt_len, cfg.block_size)
    impl = (
        "auto"
        if cfg.attn_impl in ("ring", "ulysses", "flash", "fused")
        else cfg.attn_impl
    )

    def prefill_fn(
        pool: PagedKVPool,  # DONATED
        logits: Array,  # [S, V] DONATED
        slot: Array,  # [] int32 — the admitted slot
        tokens: Array,  # [1, prompt_len] int32 (right-padded)
        real_len: Array,  # [] int32
        page_rows: Array,  # [prompt_len // page_size] int32 (pad = sentinel)
    ):
        with axis_rules(mesh):
            h, (ks, vs) = model.hidden(
                tokens, deterministic=True, attn_impl=impl, return_kv=True
            )  # ks/vs: [L, 1, Hkv, P, C]
            pool = write_prompt_pages(pool, ks[:, 0], vs[:, 0], page_rows)
            h_last = jax.lax.dynamic_slice_in_dim(
                h, real_len - 1, 1, axis=1
            )[:, 0]  # [1, D]
            row = (h_last @ model.head_weight(h_last.dtype)).astype(
                logits.dtype
            )[0]
            logits = jax.lax.dynamic_update_slice(
                logits, row[None], (slot, jnp.zeros((), slot.dtype))
            )
        return pool, logits

    return jax.jit(prefill_fn, donate_argnums=(0, 1))


# ---------------------------------------------------------------------------
# Requests + engine
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle record."""

    rid: int
    prompt: np.ndarray  # [p] int32 admission context (original prompt, or
    # prompt0 + generated-so-far after an eviction re-queue)
    max_new_tokens: int
    # the cropped ORIGINAL prompt — evictions rebuild the admission
    # context from this, never from an already-grown prompt
    prompt0: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0,), np.int32)
    )
    eos_id: int = -1  # -1 = no EOS (run to max_new_tokens)
    seed: int = 0
    submit_time: float = 0.0
    first_token_time: tp.Optional[float] = None
    finish_time: tp.Optional[float] = None
    tokens: tp.List[int] = dataclasses.field(default_factory=list)
    evictions: int = 0

    @property
    def done(self) -> bool:
        return self.finish_time is not None


class ServingEngine:
    """Continuous-batching scheduler over ``slots`` decode lanes.

    Every :meth:`step` is one scheduler window: admit queued requests into
    free slots (prefill + page allocation), top up page allocations for
    the coming K tokens (evicting the youngest request under pressure —
    its progress is kept and it re-queues with prompt+generated), launch
    ONE fused K-step decode dispatch for all slots, then harvest emitted
    tokens / finished requests with a single device->host read.

    Capacity contract: a request must fit its context in ``block_size``
    (prompts are cropped to ``block_size - max_new_tokens`` like the
    reference sampler crops to the window, sample.py:74).
    """

    def __init__(
        self,
        model: GPT,
        *,
        slots: int = 4,
        page_size: int = 16,  # tile-aligned at C=64; same default everywhere
        num_pages: tp.Optional[int] = None,
        window: int = 4,
        temperature: float = 0.0,
        top_k: tp.Optional[int] = None,
        cache_dtype=jnp.bfloat16,
        pad_id: int = 0,
        seed: int = 0,
        max_prefills_per_window: tp.Optional[int] = None,
        mesh=None,
        clock: tp.Callable[[], float] = time.monotonic,
    ):
        assert slots >= 1 and window >= 1 and page_size >= 1
        cfg = model.config
        # page grid must tile the context: otherwise a near-block prompt
        # padded up to the page grid exceeds block_size and prefill
        # cannot run (caught in code review)
        assert cfg.block_size % page_size == 0, (
            f"page_size {page_size} must divide block_size {cfg.block_size}"
        )
        self.model = model
        self.slots = slots
        self.window = window
        self.page_size = page_size
        self.pad_id = pad_id
        self.clock = clock
        self.block = cfg.block_size
        self.pmax = pages_needed(self.block, page_size)
        if num_pages is None:
            num_pages = slots * self.pmax  # full occupancy, no eviction
        self.alloc = PageAllocator(num_pages)
        self.pool = PagedKVPool.init(cfg, num_pages, page_size, cache_dtype)
        self.logits = jnp.zeros((slots, cfg.vocab_size), jnp.float32)
        self._key = jax.random.PRNGKey(seed)
        self._sentinel = num_pages
        self._mesh = mesh
        self._max_prefills = (
            max_prefills_per_window
            if max_prefills_per_window is not None
            else slots
        )

        # host-side slot state
        self.bt = np.full((slots, self.pmax), self._sentinel, np.int32)
        self.pooled_len = np.zeros((slots,), np.int32)
        self.done = np.ones((slots,), bool)  # empty slots ride as done
        self.emitted = np.zeros((slots,), np.int32)
        self.budget = np.zeros((slots,), np.int32)
        self.eos = np.full((slots,), -1, np.int32)
        self.seeds = np.zeros((slots,), np.int32)
        self.slot_pages: tp.List[tp.List[int]] = [[] for _ in range(slots)]
        self.slot_req: tp.List[tp.Optional[Request]] = [None] * slots

        self.queue: tp.Deque[Request] = collections.deque()
        self.finished: tp.Dict[int, Request] = {}
        self._next_rid = 0

        self._window_fn = make_decode_window(
            model,
            slots=slots,
            window=window,
            pmax=self.pmax,
            rope_len=self.block,
            pad_id=pad_id,
            temperature=temperature,
            top_k=top_k,
            mesh=mesh,
        )
        self._prefill_fns: tp.Dict[int, tp.Any] = {}

        # counters (bench_serving / tests)
        self.decode_dispatches = 0
        self.prefill_dispatches = 0
        self.tokens_generated = 0
        self.windows = 0
        self.occupancy_sum = 0
        self.evictions = 0

    # -- submission ---------------------------------------------------------

    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        *,
        eos_id: tp.Optional[int] = None,
        seed: int = 0,
    ) -> int:
        """Queue a request; returns its id. Prompts are cropped to the last
        ``block_size - max_new_tokens`` tokens so the whole context fits."""
        assert max_new_tokens >= 1, max_new_tokens
        assert max_new_tokens < self.block, (
            f"max_new_tokens {max_new_tokens} must leave room for at least "
            f"one prompt token in block_size {self.block}"
        )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        assert prompt.size >= 1, "empty prompt"
        keep = self.block - max_new_tokens
        if prompt.size > keep:
            prompt = prompt[-keep:]
        lifetime = pages_needed(
            int(prompt.size) + max_new_tokens, self.page_size
        )
        assert lifetime <= self.alloc.num_pages, (
            f"request needs {lifetime} pages over its lifetime but the pool "
            f"holds {self.alloc.num_pages}; raise num_pages"
        )
        rid = self._next_rid
        self._next_rid += 1
        self.queue.append(
            Request(
                rid=rid,
                prompt=prompt,
                prompt0=prompt,
                max_new_tokens=max_new_tokens,
                eos_id=-1 if eos_id is None else int(eos_id),
                seed=seed,
                submit_time=self.clock(),
            )
        )
        return rid

    # -- internals ----------------------------------------------------------

    def _active_slots(self) -> tp.List[int]:
        return [s for s in range(self.slots) if self.slot_req[s] is not None]

    def _prefill_bucket(self, p: int) -> int:
        """Padded prompt length: pages rounded up to a power of two, so the
        number of compiled prefill programs is O(log(block/page_size))."""
        n = pages_needed(p, self.page_size)
        n = 1 << (n - 1).bit_length()
        return min(n * self.page_size, self.pmax * self.page_size)

    def _admit(self) -> None:
        admitted = 0
        for s in range(self.slots):
            if not self.queue or admitted >= self._max_prefills:
                break
            if self.slot_req[s] is not None:
                continue
            req = self.queue[0]
            p = int(req.prompt.size)
            n_pages = pages_needed(p, self.page_size)
            if not self.alloc.can_alloc(n_pages):
                break  # head-of-line blocks: pages free up as requests end
            self.queue.popleft()
            pages = self.alloc.alloc(n_pages)
            bucket = self._prefill_bucket(p)
            toks = np.full((1, bucket), self.pad_id, np.int32)
            toks[0, :p] = req.prompt
            rows = np.full((bucket // self.page_size,), self._sentinel,
                           np.int32)
            rows[:n_pages] = pages
            if bucket not in self._prefill_fns:
                self._prefill_fns[bucket] = make_prefill_program(
                    self.model, prompt_len=bucket, mesh=self._mesh
                )
            self.pool, self.logits = self._prefill_fns[bucket](
                self.pool,
                self.logits,
                jnp.asarray(s, jnp.int32),
                jnp.asarray(toks),
                jnp.asarray(p, jnp.int32),
                jnp.asarray(rows),
            )
            self.prefill_dispatches += 1
            self.slot_req[s] = req
            self.slot_pages[s] = list(pages)
            self.bt[s, :] = self._sentinel
            self.bt[s, :n_pages] = pages
            self.pooled_len[s] = p
            self.done[s] = False
            self.emitted[s] = len(req.tokens)
            self.budget[s] = req.max_new_tokens
            self.eos[s] = req.eos_id
            self.seeds[s] = req.seed
            admitted += 1

    def _release_slot(self, s: int) -> None:
        self.alloc.free(self.slot_pages[s])
        self.slot_pages[s] = []
        self.slot_req[s] = None
        self.bt[s, :] = self._sentinel
        self.pooled_len[s] = 0
        self.done[s] = True

    def _evict(self, s: int) -> None:
        """Preempt slot ``s``: keep its progress (prompt grows by the
        generated tokens, budget shrinks to the remainder) and re-queue it
        at the FRONT so it resumes as soon as pages free up."""
        req = self.slot_req[s]
        assert req is not None
        # rebuild from the ORIGINAL prompt (a second eviction appending to
        # an already-grown prompt would duplicate the first eviction's
        # tokens — caught in code review). prompt0 <= block - max_new, so
        # prompt0 + generated always fits block - remaining: no cropping,
        # and the continuation is identical to the un-evicted run
        req.prompt = np.concatenate(
            [req.prompt0, np.asarray(req.tokens, np.int32)]
        )
        req.evictions += 1
        self._release_slot(s)
        self.queue.appendleft(req)
        self.evictions += 1

    def _ensure_growth(self) -> None:
        """Before the window, every active slot needs pages for up to K
        more tokens; allocate on demand, evicting the youngest slot (by
        admission recency ~ least progress) under pool pressure."""
        for s in self._active_slots():
            if self.slot_req[s] is None:
                continue  # evicted by an earlier slot's pressure this pass
            # growth is capped at the request's REMAINING budget, not the
            # raw window: near end-of-generation pooled_len + window can
            # point past the request's lifetime (and past the block
            # table), and demanding those pages would crash or evict
            # healthy requests for tokens that will never be written
            remaining = int(self.budget[s]) - int(self.emitted[s])
            tokens = int(self.pooled_len[s]) + min(self.window, remaining)
            need = min(
                pages_needed(tokens, self.page_size), self.pmax
            ) - len(self.slot_pages[s])
            while need > 0 and not self.alloc.can_alloc(need):
                others = [v for v in self._active_slots() if v != s]
                if not others:
                    raise MemoryError(
                        "page pool too small for a single request's window"
                    )
                # least progress loses: cheapest re-prefill on re-admission
                self._evict(min(others, key=lambda v: len(self.slot_req[v].tokens)))
            if need > 0:
                pages = self.alloc.alloc(need)
                start = len(self.slot_pages[s])
                self.slot_pages[s].extend(pages)
                self.bt[s, start : start + need] = pages

    def step(self) -> bool:
        """One scheduler window. Returns True while there is (or was) work."""
        self._admit()
        active = self._active_slots()
        if not active:
            return bool(self.queue)
        self._ensure_growth()
        active = self._active_slots()  # eviction may have changed it

        (
            self.pool, self.logits, toks, emit, done_d, new_len, emitted_d
        ) = self._window_fn(
            self.pool,
            self.logits,
            jnp.asarray(self.bt),
            jnp.asarray(self.pooled_len),
            jnp.asarray(self.done),
            jnp.asarray(self.emitted),
            jnp.asarray(self.budget),
            jnp.asarray(self.eos),
            jnp.asarray(self.seeds),
            self._key,
        )
        self.decode_dispatches += 1
        self.windows += 1
        self.occupancy_sum += len(active)

        # ONE device->host sync per window: the stacked [K, S] outputs
        toks_h = np.asarray(toks)
        emit_h = np.asarray(emit)
        # np.array (copy): zero-copy views of jax buffers are read-only,
        # and the scheduler mutates these in place
        self.done = np.array(done_d)
        self.pooled_len = np.array(new_len, np.int32)
        self.emitted = np.array(emitted_d, np.int32)
        now = self.clock()
        for s in active:
            req = self.slot_req[s]
            new = [int(t) for r in range(self.window)
                   for t in [toks_h[r, s]] if emit_h[r, s]]
            if new and req.first_token_time is None:
                req.first_token_time = now
            req.tokens.extend(new)
            self.tokens_generated += len(new)
            if self.done[s]:
                req.finish_time = now
                self.finished[req.rid] = req
                self._release_slot(s)
        return True

    def run(self, max_windows: int = 100_000) -> tp.Dict[int, Request]:
        """Drive :meth:`step` until queue and slots drain; returns the
        finished requests by id."""
        for _ in range(max_windows):
            if not self.queue and not self._active_slots():
                break
            self.step()
        else:
            raise RuntimeError(f"engine did not drain in {max_windows} windows")
        return self.finished

    # -- reporting ----------------------------------------------------------

    def stats(self) -> tp.Dict[str, float]:
        occ = self.occupancy_sum / max(1, self.windows * self.slots)
        return {
            "decode_dispatches": self.decode_dispatches,
            "prefill_dispatches": self.prefill_dispatches,
            "tokens_generated": self.tokens_generated,
            "windows": self.windows,
            "slot_occupancy": round(occ, 4),
            "evictions": self.evictions,
            "free_pages": self.alloc.free_pages,
            "tokens_per_dispatch": round(
                self.tokens_generated / max(1, self.decode_dispatches), 2
            ),
        }
